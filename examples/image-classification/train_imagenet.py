#!/usr/bin/env python
"""ImageNet-class training (ResNet/Inception/VGG/AlexNet) with Module.fit.

Analogue of the reference's example/image-classification/train_imagenet.py
(the script behind BASELINE.md's training tables). Feeds ImageRecordIter
when a RecordIO file is given (--data-train), else synthetic device-side
data at full speed. bf16 compute is on by default (MXNET_COMPUTE_DTYPE).

    python examples/image-classification/train_imagenet.py \
        --network resnet-50 --batch-size 32 --num-batches 100
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
from common import respect_jax_platforms  # noqa: E402
respect_jax_platforms()


def main():
    import logging
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--network", default="resnet-50")
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--image-shape", default="3,224,224")
    p.add_argument("--num-epochs", type=int, default=1)
    p.add_argument("--num-batches", type=int, default=100,
                   help="batches per epoch for synthetic data")
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--data-train", default=None, help=".rec file")
    p.add_argument("--model-prefix", default=None)
    p.add_argument("--dtype", default="bfloat16")
    args = p.parse_args()

    import numpy as np
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import models

    if args.dtype != "float32":
        os.environ.setdefault("MXNET_COMPUTE_DTYPE", args.dtype)

    shape = tuple(int(x) for x in args.image_shape.split(","))
    dev = (mx.Context("tpu", 0) if jax.default_backend() != "cpu"
           else mx.cpu())

    if args.data_train:
        train = mx.io.ImageRecordIter(
            path_imgrec=args.data_train, data_shape=shape,
            batch_size=args.batch_size, shuffle=True, rand_mirror=True)
    else:
        rng = np.random.RandomState(0)
        n = args.batch_size * args.num_batches
        X = rng.uniform(-1, 1, (n,) + shape).astype(np.float32)
        y = rng.randint(0, args.num_classes, n).astype(np.float32)
        train = mx.io.NDArrayIter(X, y, batch_size=args.batch_size,
                                  label_name="softmax_label")

    sym = models.get_symbol(args.network, num_classes=args.num_classes)
    # distributed runs: non-zero ranks checkpoint under prefix-<rank>
    # (reference example/image-classification/common/fit.py:29-43)
    rank = int(os.environ.get("MXNET_TPU_WORKER_RANK",
                              os.environ.get("MXNET_TPU_PROC_ID", "0")))
    if args.model_prefix and rank > 0:
        args.model_prefix = "%s-%d" % (args.model_prefix, rank)
    mod = mx.mod.Module(sym, context=dev)
    tic = time.time()
    mod.fit(train, num_epoch=args.num_epochs,
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9,
                              "wd": 1e-4},
            initializer=mx.initializer.Xavier(rnd_type="gaussian",
                                              factor_type="in",
                                              magnitude=2.0),
            batch_end_callback=[mx.callback.Speedometer(args.batch_size, 20)],
            epoch_end_callback=([mx.callback.do_checkpoint(args.model_prefix)]
                                if args.model_prefix else None),
            kvstore=None)
    dur = time.time() - tic
    total = args.num_epochs * args.num_batches * args.batch_size
    print("trained %d images in %.1fs (%.1f img/s incl. compile)"
          % (total, dur, total / dur))


if __name__ == "__main__":
    main()
