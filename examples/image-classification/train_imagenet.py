#!/usr/bin/env python
"""ImageNet-class training (ResNet/Inception/VGG/AlexNet) with Module.fit.

Analogue of the reference's example/image-classification/train_imagenet.py
(the script behind BASELINE.md's training tables). Feeds ImageRecordIter
when a RecordIO file is given (--data-train), else synthetic device-side
data at full speed. bf16 compute is on by default (MXNET_COMPUTE_DTYPE).

    python examples/image-classification/train_imagenet.py \
        --network resnet-50 --batch-size 32 --num-batches 100
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
from common import respect_jax_platforms  # noqa: E402
respect_jax_platforms()


def _make_synth_rec(path, n, shape, num_classes, quality=80):
    """Pack n random JPEGs at the training resolution into a .rec."""
    import cv2
    import numpy as np
    from mxnet_tpu import recordio

    rng = np.random.RandomState(0)
    w = recordio.MXRecordIO(path, "w")
    for i in range(n):
        img = rng.randint(0, 255, (shape[1], shape[2], 3), np.uint8)
        ok, enc = cv2.imencode(".jpg", img,
                               [int(cv2.IMWRITE_JPEG_QUALITY), quality])
        assert ok
        w.write(recordio.pack(recordio.IRHeader(0, float(i % num_classes),
                                                i, 0), enc.tobytes()))
    w.close()
    return path


def run_io_benchmark(args, shape, dev):
    """Training WITH the input pipeline in the measured loop. Reports:
    feed-only (iterator steady state), compute-only (device-resident
    batch), and with-IO (fit_step over live iterator batches) — overlap
    means with-IO tracks max(feed, compute), not their sum (the engine-
    style compute/IO pipelining of SURVEY §3.1 recreated with async
    dispatch + native prefetch threads)."""
    import tempfile
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import models

    rec = args.data_train
    if rec is None:
        # enough records that the timed window never wraps (a wrap pays a
        # full prefetcher teardown/rebuild inside the measurement)
        n_rec = max(args.io_records, (args.io_steps + 8) * args.batch_size)
        rec = os.path.join(tempfile.mkdtemp(), "synth_imagenet.rec")
        print("packing %d synthetic records at %s ..." % (n_rec, str(shape)))
        _make_synth_rec(rec, n_rec, shape, args.num_classes)

    def make_iter():
        cls = (mx.io.ImageRecordUInt8Iter if args.uint8
               else mx.io.ImageRecordIter)
        return cls(
            path_imgrec=rec, data_shape=shape, batch_size=args.batch_size,
            shuffle=True, rand_mirror=True, preprocess_threads=4,
            prefetch_buffer=4)

    sym = models.get_symbol(args.network, num_classes=args.num_classes)
    mod = mx.mod.Module(sym, context=dev)
    it = make_iter()
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.initializer.Xavier(rnd_type="gaussian",
                                          factor_type="in", magnitude=2.0))
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": args.lr,
                                         "momentum": 0.9, "wd": 1e-4})

    def sync():
        outs = mod.get_outputs()
        np.asarray(outs[0].asnumpy().reshape(-1)[0])

    def steps_over(source, n_steps, batches=None):
        done = 0
        while done < n_steps:
            if batches is not None:
                batch = batches[done % len(batches)]
            else:
                try:
                    batch = source.next()
                except StopIteration:
                    source.reset()
                    batch = source.next()
            mod.fit_step(batch)
            done += 1
        sync()


    n = args.io_steps
    # warmup: compile + fill prefetch
    first = it.next()
    resident = mx.io.DataBatch(
        [mx.nd.array(d.asnumpy().astype("float32")) for d in first.data],
        [l.copy() for l in first.label])
    steps_over(None, 3, batches=[resident])

    # compute-only: device-resident batch
    t0 = time.time()
    steps_over(None, n, batches=[resident])
    t_compute = (time.time() - t0) / n

    # feed-only: iterator steady state (fresh iterator, no training)
    feed_it = make_iter()
    feed_it.next()  # spin up decode threads
    t0 = time.time()
    got = 0
    while got < n:
        try:
            feed_it.next()
        except StopIteration:
            feed_it.reset()
            continue
        got += 1
    t_feed = (time.time() - t0) / n

    # h2d-only: host->device placement of a fresh batch (the component a
    # tunneled dev chip makes dominant; ~GB/s on a real TPU host)
    import jax as _jax

    host_batch = first.data[0].asnumpy()
    if args.uint8:
        host_batch = host_batch.astype("uint8")
    jdev = dev.jax_device()
    x = _jax.device_put(host_batch, jdev); x.block_until_ready()
    t0 = time.time()
    for _ in range(max(3, n // 3)):
        x = _jax.device_put(host_batch, jdev)
        x.block_until_ready()
    t_h2d = (time.time() - t0) / max(3, n // 3)

    # with IO: training loop fed by the live iterator through the
    # device prefetcher (decode + H2D overlap the device step)
    it.reset()
    dev_it = mx.io.DevicePrefetchIter(it, ctx=dev, depth=3,
                                      cast_dtype="float32" if args.uint8
                                      else None)
    steps_over(dev_it, 3)  # fill the device-side double buffer
    t0 = time.time()
    steps_over(dev_it, n)
    t_step = (time.time() - t0) / n
    dev_it.close()

    t_max = max(t_feed, t_h2d, t_compute)
    t_sum = t_feed + t_h2d + t_compute
    overlap = ("OVERLAPPED" if t_step < 0.75 * t_sum or t_step <= 1.2 * t_max
               else "NOT overlapped")
    print("io-bench %s bs%d: feed %.1f ms  h2d %.1f ms  compute %.1f ms  "
          "with-IO %.1f ms (max %.1f, sum %.1f) -> %s; %.1f img/s with IO"
          % (args.network, args.batch_size, t_feed * 1e3, t_h2d * 1e3,
             t_compute * 1e3, t_step * 1e3, t_max * 1e3, t_sum * 1e3,
             overlap, args.batch_size / t_step))


def main():
    import logging
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--network", default="resnet-50")
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--image-shape", default="3,224,224")
    p.add_argument("--num-epochs", type=int, default=1)
    p.add_argument("--num-batches", type=int, default=100,
                   help="batches per epoch for synthetic data")
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--data-train", default=None, help=".rec file")
    p.add_argument("--model-prefix", default=None)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--benchmark-io", action="store_true",
                   help="measure the training loop WITH the record input "
                        "pipeline: reports feed-only, compute-only and "
                        "with-IO step times (overlap = with-IO tracking "
                        "max, not sum — reference perf.md:149-155 measures "
                        "training through train_imagenet + iterator)")
    p.add_argument("--io-steps", type=int, default=30)
    p.add_argument("--io-records", type=int, default=512)
    p.add_argument("--uint8", action="store_true",
                   help="uint8 wire format (ImageRecordUInt8Iter) + "
                        "on-device cast: 4x less H2D traffic")
    args = p.parse_args()

    import numpy as np
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import models

    if args.dtype != "float32":
        os.environ.setdefault("MXNET_COMPUTE_DTYPE", args.dtype)

    shape = tuple(int(x) for x in args.image_shape.split(","))
    dev = (mx.Context("tpu", 0) if jax.default_backend() != "cpu"
           else mx.cpu())

    if args.benchmark_io:
        run_io_benchmark(args, shape, dev)
        return

    if args.data_train:
        train = mx.io.ImageRecordIter(
            path_imgrec=args.data_train, data_shape=shape,
            batch_size=args.batch_size, shuffle=True, rand_mirror=True)
    else:
        rng = np.random.RandomState(0)
        n = args.batch_size * args.num_batches
        X = rng.uniform(-1, 1, (n,) + shape).astype(np.float32)
        y = rng.randint(0, args.num_classes, n).astype(np.float32)
        train = mx.io.NDArrayIter(X, y, batch_size=args.batch_size,
                                  label_name="softmax_label")

    sym = models.get_symbol(args.network, num_classes=args.num_classes)
    # distributed runs: non-zero ranks checkpoint under prefix-<rank>
    # (reference example/image-classification/common/fit.py:29-43)
    rank = int(os.environ.get("MXNET_TPU_WORKER_RANK",
                              os.environ.get("MXNET_TPU_PROC_ID", "0")))
    if args.model_prefix and rank > 0:
        args.model_prefix = "%s-%d" % (args.model_prefix, rank)
    mod = mx.mod.Module(sym, context=dev)
    tic = time.time()
    mod.fit(train, num_epoch=args.num_epochs,
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9,
                              "wd": 1e-4},
            initializer=mx.initializer.Xavier(rnd_type="gaussian",
                                              factor_type="in",
                                              magnitude=2.0),
            batch_end_callback=[mx.callback.Speedometer(args.batch_size, 20)],
            epoch_end_callback=([mx.callback.do_checkpoint(args.model_prefix)]
                                if args.model_prefix else None),
            kvstore=None)
    dur = time.time() - tic
    total = args.num_epochs * args.num_batches * args.batch_size
    print("trained %d images in %.1fs (%.1f img/s incl. compile)"
          % (total, dur, total / dur))


if __name__ == "__main__":
    main()
