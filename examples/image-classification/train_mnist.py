#!/usr/bin/env python
"""Train an MLP or LeNet on MNIST with Module.fit.

Analogue of the reference's example/image-classification/train_mnist.py
(BASELINE config 1). Uses the MNISTIter if the idx/ubyte files are present
(``--data-dir``); otherwise falls back to a synthetic digits-like dataset
so the script is runnable anywhere.

    python examples/image-classification/train_mnist.py --network mlp \
        --num-epochs 10 --lr 0.1
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
from common import respect_jax_platforms  # noqa: E402
respect_jax_platforms()


def get_iters(args):
    import numpy as np
    import mxnet_tpu as mx

    flat = args.network == "mlp"
    train_img = os.path.join(args.data_dir, "train-images-idx3-ubyte")
    if os.path.exists(train_img):
        train = mx.io.MNISTIter(
            image=train_img,
            label=os.path.join(args.data_dir, "train-labels-idx1-ubyte"),
            batch_size=args.batch_size, shuffle=True, flat=flat)
        val = mx.io.MNISTIter(
            image=os.path.join(args.data_dir, "t10k-images-idx3-ubyte"),
            label=os.path.join(args.data_dir, "t10k-labels-idx1-ubyte"),
            batch_size=args.batch_size, shuffle=False, flat=flat)
        return train, val
    # synthetic fallback: the shared 10-gaussian-blob task
    # (mx.test_utils.synthetic_digits — same definition the CI
    # convergence bars are calibrated on)
    n = 4096
    X, y = mx.test_utils.synthetic_digits(n, flat=flat)
    split = n * 7 // 8
    train = mx.io.NDArrayIter(X[:split], y[:split].astype(np.float32),
                              batch_size=args.batch_size, shuffle=True,
                              label_name="softmax_label")
    val = mx.io.NDArrayIter(X[split:], y[split:].astype(np.float32),
                            batch_size=args.batch_size,
                            label_name="softmax_label")
    return train, val


def main():
    import logging
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--network", default="mlp", choices=["mlp", "lenet"])
    p.add_argument("--data-dir", default="mnist_data")
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--num-epochs", type=int, default=5)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--kvstore", default=None)
    p.add_argument("--model-prefix", default=None)
    p.add_argument("--load-epoch", type=int, default=None)
    args = p.parse_args()

    import jax
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import models

    np.random.seed(0)  # deterministic param init (CI quality bars)
    train, val = get_iters(args)
    sym = models.get_symbol(args.network, num_classes=10)
    dev = (mx.Context("tpu", 0) if jax.default_backend() != "cpu"
           else mx.cpu())

    arg_params = aux_params = None
    begin_epoch = 0
    if args.model_prefix and args.load_epoch is not None:
        sym, arg_params, aux_params = mx.model.load_checkpoint(
            args.model_prefix, args.load_epoch)
        begin_epoch = args.load_epoch

    mod = mx.mod.Module(sym, context=dev)
    cbs = [mx.callback.Speedometer(args.batch_size, 50)]
    epoch_cbs = ([mx.callback.do_checkpoint(args.model_prefix)]
                 if args.model_prefix else None)
    mod.fit(train, eval_data=val, num_epoch=args.num_epochs,
            arg_params=arg_params, aux_params=aux_params,
            begin_epoch=begin_epoch,
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
            initializer=mx.initializer.Xavier(),
            batch_end_callback=cbs, epoch_end_callback=epoch_cbs,
            kvstore=args.kvstore)
    print("final validation:", mod.score(val, mx.metric.create("acc")))


if __name__ == "__main__":
    main()
