#!/usr/bin/env python
"""Speech DECODING demo (reference example/speech-demo/decode_mxnet.py:
run the trained acoustic model over held-out feature archives and emit
transcriptions). The kaldi I/O of the reference is replaced by the
synthetic filterbank utterances of examples/speech_recognition (zero
egress); the demo's substance is the decode side the training example
doesn't cover: greedy CTC decoding (argmax per frame, collapse repeats,
drop blanks) and phoneme-error-rate scoring against the references.

    python examples/speech-demo/decode_mxnet.py
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
from common import respect_jax_platforms  # noqa: E402
respect_jax_platforms()

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "speech_recognition"))


def greedy_ctc_decode(logits):
    """(T, B, C) logits -> per-utterance label sequences: frame argmax,
    collapse repeats, strip blanks (class 0)."""
    import numpy as np

    path = logits.argmax(axis=2)  # (T, B)
    out = []
    for b in range(path.shape[1]):
        seq, prev = [], -1
        for t in range(path.shape[0]):
            c = int(path[t, b])
            if c != prev and c != 0:
                seq.append(c)
            prev = c
        out.append(seq)
    return out


def edit_distance(a, b):
    import numpy as np

    d = np.zeros((len(a) + 1, len(b) + 1), np.int32)
    d[:, 0] = np.arange(len(a) + 1)
    d[0, :] = np.arange(len(b) + 1)
    for i in range(1, len(a) + 1):
        for j in range(1, len(b) + 1):
            d[i, j] = min(d[i - 1, j] + 1, d[i, j - 1] + 1,
                          d[i - 1, j - 1] + (a[i - 1] != b[j - 1]))
    return int(d[len(a), len(b)])


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--train-steps", type=int, default=80)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--hidden", type=int, default=48)
    p.add_argument("--utts", type=int, default=16)
    args = p.parse_args()

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.io import DataBatch, DataDesc
    import train as sr  # examples/speech_recognition/train.py

    np.random.seed(0)
    rng = np.random.RandomState(0)
    T = max(sr.BUCKETS)
    state_shape = (2, args.batch, args.hidden)
    zeros_h = np.zeros(state_shape, np.float32)

    # --- train the acoustic model briefly (single bucket suffices) ----
    sym, data_names, label_names = sr.sym_gen_factory(args.hidden)(T)
    mod = mx.mod.Module(sym, data_names=data_names,
                        label_names=label_names, context=mx.cpu())
    ds = [DataDesc("data", (args.batch, 1, T, sr.FEAT)),
          DataDesc("rnn_state", state_shape),
          DataDesc("rnn_state_cell", state_shape)]
    ls = [DataDesc("label", (args.batch, sr.LABEL_LEN))]
    mod.bind(data_shapes=ds, label_shapes=ls)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 0.01})
    for _ in range(args.train_steps):
        x, lab = sr.make_utterance_batch(rng, args.batch, T)
        mod.forward(DataBatch([mx.nd.array(x), mx.nd.array(zeros_h),
                               mx.nd.array(zeros_h)],
                              [mx.nd.array(lab)]), is_train=True)
        mod.backward()
        mod.update()

    # --- decode held-out utterances through the LOGITS tap ------------
    # (the reference decode_mxnet.py likewise binds the acoustic model's
    # output layer and streams archives through it)
    logits_sym = sym.get_internals()["cls_output"]
    dec = mx.mod.Module(logits_sym, data_names=data_names, label_names=[],
                        context=mx.cpu())
    dec.bind(data_shapes=ds, for_training=False)
    dec.set_params(*mod.get_params())

    total_err = total_len = 0
    shown = 0
    for _ in range(args.utts // args.batch):
        x, lab = sr.make_utterance_batch(rng, args.batch, T)
        dec.forward(DataBatch([mx.nd.array(x), mx.nd.array(zeros_h),
                               mx.nd.array(zeros_h)], []), is_train=False)
        flat = dec.get_outputs()[0].asnumpy()      # (T/4 * B, C)
        logits = flat.reshape(T // 4, -1, sr.N_PHONES + 1)
        hyps = greedy_ctc_decode(logits)
        for b, hyp in enumerate(hyps):
            ref = [int(v) for v in lab[b] if v > 0]
            total_err += edit_distance(hyp, ref)
            total_len += len(ref)
            if shown < 4:
                print("utt %d  ref %s  hyp %s" % (shown, ref, hyp))
                shown += 1
    per = total_err / max(total_len, 1)
    print("decode: phoneme error rate %.2f over %d utterances"
          % (per, args.utts))
    if per > 0.5:
        raise SystemExit("decoding no better than noise")
    print("speech-demo decode OK")


if __name__ == "__main__":
    main()
