#!/usr/bin/env python
"""Sequence/context parallelism: ring attention over a device mesh.

The reference handles long sequences with bucketing + unrolling only
(SURVEY §5.7 — it predates sequence parallelism); this framework adds the
modern mechanism as a first-class citizen: ``parallel.ring_attention``
shards the sequence across a mesh axis and rotates K/V blocks around the
ring with ``ppermute`` over ICI, computing attention in an online-softmax
accumulator so the full attention matrix never materializes.

Run on a virtual mesh:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/long-context/ring_attention_demo.py --seq-len 2048
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
from common import respect_jax_platforms  # noqa: E402
respect_jax_platforms()


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--seq-len", type=int, default=1024)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--head-dim", type=int, default=32)
    p.add_argument("--batch", type=int, default=2)
    args = p.parse_args()

    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh
    from mxnet_tpu.parallel.ring_attention import ring_attention

    devs = jax.devices()
    mesh = Mesh(np.array(devs), ("seq",))
    rng = np.random.RandomState(0)
    shape = (args.batch, args.heads, args.seq_len, args.head_dim)
    q = jnp.asarray(rng.randn(*shape).astype(np.float32))
    k = jnp.asarray(rng.randn(*shape).astype(np.float32))
    v = jnp.asarray(rng.randn(*shape).astype(np.float32))

    out = ring_attention(q, k, v, mesh, causal=True)
    out = np.asarray(out)

    # reference: plain causal attention on one device
    s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(args.head_dim)
    mask = np.tril(np.ones((args.seq_len, args.seq_len), bool))
    s = np.where(mask, s, -1e30)
    p_ = np.exp(s - s.max(-1, keepdims=True))
    p_ /= p_.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bhkd->bhqd", p_, v)

    err = np.abs(out - ref).max()
    print("ring attention over %d devices, seq %d: max err vs dense %.2e"
          % (len(devs), args.seq_len, err))
    assert err < 2e-4


if __name__ == "__main__":
    main()
