#!/usr/bin/env python
"""Model-parallel LSTM: layers placed on different devices.

Analogue of the reference's example/model-parallel-lstm/ (SURVEY §2.2
"Model parallelism"): there, symbol variables are tagged with ``ctx_group``
under AttrScope and ``bind(group2ctx=...)`` maps groups onto GPUs, with the
engine pipelining the per-device work. Here the same AttrScope tagging
flows into mesh shardings: each layer group is placed on a device of a
``jax.sharding.Mesh``, and XLA overlaps the per-stage compute exactly as
the reference's dataflow engine did (SURVEY §7 translation table).

Run on a virtual mesh without hardware:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
        python examples/model-parallel-lstm/lstm_model_parallel.py
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
from common import respect_jax_platforms  # noqa: E402
respect_jax_platforms()


def main():
    import logging
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--num-layers", type=int, default=4)
    p.add_argument("--num-hidden", type=int, default=64)
    p.add_argument("--seq-len", type=int, default=16)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--vocab", type=int, default=32)
    p.add_argument("--steps", type=int, default=10)
    args = p.parse_args()

    import numpy as np
    import jax
    import mxnet_tpu as mx

    n_dev = max(1, min(args.num_layers, len(jax.devices())))
    group2ctx = {"layer%d" % i: mx.Context(jax.default_backend(), i % n_dev)
                 for i in range(args.num_layers)}

    # build the stacked LSTM with each layer's params tagged to a ctx_group
    data = mx.sym.Variable("data")
    embed = mx.sym.Embedding(data, input_dim=args.vocab,
                             output_dim=args.num_hidden, name="embed")
    inputs = embed
    for i in range(args.num_layers):
        with mx.AttrScope(ctx_group="layer%d" % i):
            cell = mx.rnn.LSTMCell(num_hidden=args.num_hidden,
                                   prefix="lstm_l%d_" % i)
            inputs, _ = cell.unroll(args.seq_len, inputs=inputs,
                                    merge_outputs=True)
    pred = mx.sym.Reshape(inputs, shape=(-1, args.num_hidden))
    pred = mx.sym.FullyConnected(pred, num_hidden=args.vocab, name="pred")
    label = mx.sym.Reshape(mx.sym.Variable("softmax_label"), shape=(-1,))
    net = mx.sym.SoftmaxOutput(pred, label=label, name="softmax")

    exe = net.simple_bind(mx.cpu() if jax.default_backend() == "cpu"
                          else mx.Context("tpu", 0),
                          group2ctx=group2ctx,
                          data=(args.batch_size, args.seq_len),
                          softmax_label=(args.batch_size, args.seq_len))
    init = mx.initializer.Xavier()
    for n, a in exe.arg_dict.items():
        if n in ("data", "softmax_label"):
            continue
        init(mx.initializer.InitDesc(n), a)
    rng = np.random.RandomState(0)
    import jax.numpy as jnp
    exe.arg_dict["data"]._data = jnp.asarray(
        rng.randint(0, args.vocab, (args.batch_size, args.seq_len))
        .astype(np.float32))
    exe.arg_dict["softmax_label"]._data = jnp.asarray(
        rng.randint(0, args.vocab, (args.batch_size, args.seq_len))
        .astype(np.float32))

    for step in range(args.steps):
        exe.forward_backward()
        for n, g in exe.grad_dict.items():
            if n in ("data", "softmax_label"):
                continue
            exe.arg_dict[n]._data = exe.arg_dict[n]._data - 0.1 * g._data
    out = exe.outputs[0].asnumpy()
    print("ran %d model-parallel train steps over %d devices; out shape %s"
          % (args.steps, n_dev, out.shape))


if __name__ == "__main__":
    main()
