#!/usr/bin/env python
"""DeepSpeech-lite: bucketed variable-length audio -> conv front-end ->
bidirectional LSTM -> CTC.

Analogue of the reference's example/speech_recognition (arch_deepspeech.py:
conv2d front-end over time x frequency, stacked BiRNNs, warp-CTC, with a
bucketing iterator over utterance lengths) — the one reference family that
exercises bucketing, CTC, and variable-length audio TOGETHER. Real
LibriSpeech is replaced by synthetic utterances (zero-egress CI): each
"phoneme" class emits a characteristic spectral band for a few frames, so
the unsegmented-sequence-labeling problem (CTC alignment over an unknown
segmentation) is the same, without the corpus.

Pipeline: synthetic (B, 1, T, F) filterbank batches bucketed by utterance
length -> BucketingModule whose sym_gen builds, per bucket T:
conv(stride 2 in time) x2 -> (T/4, B, feat) -> RNN(bidirectional lstm) ->
per-frame FC -> ctc_loss -> MakeLoss. Loss must decrease:

    python examples/speech_recognition/train.py --steps 10
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
from common import respect_jax_platforms  # noqa: E402
respect_jax_platforms()

N_PHONES = 8             # classes 1..8; 0 is the CTC blank
FEAT = 32                # filterbank bins per frame
BUCKETS = (48, 80)       # utterance lengths (frames), bucketed
LABEL_LEN = 6            # max phonemes per utterance (0-padded)


def make_utterance_batch(rng, batch, T):
    """Each phoneme holds a band of the spectrum for 6-9 frames; phones
    are separated by optional silence. (B, 1, T, F) + (B, L) labels."""
    import numpy as np

    data = np.zeros((batch, 1, T, FEAT), np.float32)
    label = np.zeros((batch, LABEL_LEN), np.float32)
    band = FEAT // N_PHONES
    n_max = min(LABEL_LEN, T // 10)
    for b in range(batch):
        n = rng.randint(2, n_max + 1)
        t = rng.randint(0, 4)
        for i in range(n):
            ph = rng.randint(0, N_PHONES)
            span = rng.randint(6, 10)
            data[b, 0, t:t + span, ph * band:(ph + 1) * band] = 1.0
            t += span + rng.randint(0, 3)
            label[b, i] = ph + 1
    data += rng.randn(*data.shape).astype(np.float32) * 0.15
    return data, label


def sym_gen_factory(hidden):
    """Per-bucket symbol: the DeepSpeech layering at lite scale."""
    import mxnet_tpu as mx

    def sym_gen(T):
        data = mx.sym.Variable("data")    # (B, 1, T, F)
        label = mx.sym.Variable("label")  # (B, L)
        # conv front-end, stride 2 in TIME on both layers (the
        # reference's conv1/conv2 time-striding that makes the RNN see
        # T/4 frames)
        h = mx.sym.Convolution(data, kernel=(5, 5), stride=(2, 2),
                               pad=(2, 2), num_filter=16, name="conv1")
        h = mx.sym.Activation(h, act_type="relu")
        h = mx.sym.Convolution(h, kernel=(5, 3), stride=(2, 1),
                               pad=(2, 1), num_filter=16, name="conv2")
        h = mx.sym.Activation(h, act_type="relu")
        t2, f2 = T // 4, FEAT // 2        # conv output time/freq extents
        # (B, C, T', F') -> (T', B, C*F') frame-major for the RNN
        h = mx.sym.transpose(h, axes=(2, 0, 1, 3))
        h = mx.sym.Reshape(h, shape=(t2, -1, 16 * f2))
        rnn = mx.sym.RNN(h, mx.sym.Variable("lstm_parameters"),
                         mx.sym.Variable("rnn_state"),
                         mx.sym.Variable("rnn_state_cell"),
                         mode="lstm", state_size=hidden, num_layers=1,
                         bidirectional=True, name="birnn")  # (T', B, 2H)
        proj = mx.sym.FullyConnected(
            mx.sym.Reshape(rnn, shape=(-1, 2 * hidden)),
            num_hidden=N_PHONES + 1, flatten=False, name="cls")
        logits = mx.sym.Reshape(proj, shape=(t2, -1, N_PHONES + 1))
        loss = mx.sym.ctc_loss(logits, label)
        net = mx.sym.MakeLoss(loss, name="ctc")
        return (net, ("data", "rnn_state", "rnn_state_cell"), ("label",))

    return sym_gen


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--hidden", type=int, default=48)
    p.add_argument("--steps", type=int, default=10,
                   help="steps PER bucket (buckets alternate)")
    p.add_argument("--lr", type=float, default=0.01)
    args = p.parse_args()

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.io import DataBatch, DataDesc

    np.random.seed(0)  # deterministic param init (CI quality bars)
    rng = np.random.RandomState(0)
    state_shape = (2, args.batch, args.hidden)  # 1 layer x 2 directions
    zeros_h = np.zeros(state_shape, np.float32)

    mod = mx.mod.BucketingModule(sym_gen_factory(args.hidden),
                                 default_bucket_key=max(BUCKETS))

    def shapes(T):
        return ([DataDesc("data", (args.batch, 1, T, FEAT)),
                 DataDesc("rnn_state", state_shape),
                 DataDesc("rnn_state_cell", state_shape)],
                [DataDesc("label", (args.batch, LABEL_LEN))])

    data_shapes, label_shapes = shapes(max(BUCKETS))
    mod.bind(data_shapes=data_shapes, label_shapes=label_shapes)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": args.lr})

    losses = {T: [] for T in BUCKETS}
    for step in range(args.steps):
        for T in BUCKETS:  # alternate buckets: every step switches
            x, lab = make_utterance_batch(rng, args.batch, T)
            ds, ls = shapes(T)
            batch = DataBatch(
                data=[mx.nd.array(x), mx.nd.array(zeros_h),
                      mx.nd.array(zeros_h)],
                label=[mx.nd.array(lab)],
                bucket_key=T, provide_data=ds, provide_label=ls)
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
            loss = float(mod.get_outputs()[0].asnumpy().mean())
            losses[T].append(loss)
            print("step %d bucket T=%d ctc loss %.4f" % (step, T, loss))

    for T in BUCKETS:
        first, last = np.mean(losses[T][:2]), np.mean(losses[T][-2:])
        print("deepspeech-lite bucket %d: loss %.4f -> %.4f (%s)"
              % (T, first, last,
                 "decreasing" if last < first else "NOT decreasing"))
        if last >= first:
            raise SystemExit("bucket %d loss did not decrease" % T)
    print("deepspeech-lite OK: %d buckets trained through one shared "
          "parameter set" % len(BUCKETS))


if __name__ == "__main__":
    main()
