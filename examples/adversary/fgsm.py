#!/usr/bin/env python
"""FGSM adversarial examples (reference example/adversary): train a small
MLP, then perturb inputs along sign(dL/dx) and show accuracy collapse —
exercises input gradients (grad_req on data) through the executor.

    python examples/adversary/fgsm.py --epsilon 0.15
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
from common import respect_jax_platforms  # noqa: E402
respect_jax_platforms()


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epsilon", type=float, default=0.15)
    p.add_argument("--epochs", type=int, default=8)
    args = p.parse_args()

    import numpy as np
    import mxnet_tpu as mx

    rng = np.random.RandomState(0)
    X = rng.uniform(-1, 1, (800, 20)).astype(np.float32)
    W = rng.uniform(-1, 1, (20, 4)).astype(np.float32)
    y = np.argmax(X @ W, axis=1).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=64, shuffle=True,
                           label_name="softmax_label")

    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=64, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net)
    mod.fit(it, num_epoch=args.epochs, optimizer="adam",
            optimizer_params={"learning_rate": 0.01},
            initializer=mx.initializer.Xavier())
    it.reset()
    clean_acc = dict(mod.score(it, "acc"))["accuracy"]

    # rebind with grad on data (the adversary's executor)
    arg_params, aux_params = mod.get_params()
    arg_shapes = {"data": (800, 20), "softmax_label": (800,)}
    grad_req = {n: ("write" if n == "data" else "null")
                for n in net.list_arguments()}
    exe = net.simple_bind(mx.cpu(), grad_req=grad_req, **arg_shapes)
    exe.copy_params_from(arg_params, aux_params)
    exe.arg_dict["data"][:] = X
    exe.arg_dict["softmax_label"][:] = y
    exe.forward(is_train=True)
    exe.backward()
    g = exe.grad_dict["data"].asnumpy()
    X_adv = X + args.epsilon * np.sign(g)

    it_adv = mx.io.NDArrayIter(X_adv, y, batch_size=64,
                               label_name="softmax_label")
    adv_acc = dict(mod.score(it_adv, "acc"))["accuracy"]
    print("clean acc %.3f -> adversarial acc %.3f (eps=%.2f)"
          % (clean_acc, adv_acc, args.epsilon))
    assert clean_acc > 0.9 and adv_acc < clean_acc - 0.1, (clean_acc, adv_acc)
    print("fgsm OK")


if __name__ == "__main__":
    main()
