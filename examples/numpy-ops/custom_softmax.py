#!/usr/bin/env python
"""Train through a numpy-implemented custom loss op (reference
example/numpy-ops).

The reference's custom_softmax.py defines the softmax loss entirely in
Python/numpy via `mx.operator.CustomOp` — no gradient from the engine
(need_top_grad=False), forward computes softmax, backward writes
``prob - one_hot`` — registers it, and trains an MLP with it as the head
(reference example/numpy-ops/custom_softmax.py:8-45,
weighted_logistic_regression.py). Same here: the host-side numpy op runs
inside the jitted graph through the pure_callback custom-op bridge, and
an MLP trains to high accuracy through it. (Requires a runtime with host
send/recv callbacks — any real TPU host, or the CPU backend; the
development tunnel's axon_pjrt lacks them and raises UNIMPLEMENTED.)

    python examples/numpy-ops/custom_softmax.py --epochs 6
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
from common import respect_jax_platforms  # noqa: E402
respect_jax_platforms()

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402


@mx.operator.register("numpy_softmax")
class NumpySoftmaxProp(mx.operator.CustomOpProp):
    def __init__(self):
        # loss layer: the head gradient is defined by the op itself
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        data_shape = in_shape[0]
        label_shape = (in_shape[0][0],)
        return [data_shape, label_shape], [data_shape], []

    def create_operator(self, ctx, shapes, dtypes):
        return NumpySoftmax()


class NumpySoftmax(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        x = x - x.max(axis=1, keepdims=True)
        e = np.exp(x)
        self.assign(out_data[0], req[0], e / e.sum(axis=1, keepdims=True))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        prob = out_data[0].asnumpy().copy()
        label = in_data[1].asnumpy().astype(int)
        prob[np.arange(label.size), label] -= 1.0
        self.assign(in_grad[0], req[0], prob / label.size)
        self.assign(in_grad[1], req[1], np.zeros_like(in_data[1].asnumpy()))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=6)
    p.add_argument("--batch-size", type=int, default=64)
    args = p.parse_args()

    rng = np.random.RandomState(0)
    centers = rng.normal(0, 3.0, (4, 16)).astype(np.float32)
    y = rng.randint(0, 4, 1536).astype(np.float32)
    x = (centers[y.astype(int)]
         + rng.normal(0, 1.0, (1536, 16))).astype(np.float32)

    it = mx.io.NDArrayIter(x[:1024], y[:1024], batch_size=args.batch_size,
                           shuffle=True)
    val = mx.io.NDArrayIter(x[1024:], y[1024:], batch_size=args.batch_size)

    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=4, name="fc2")
    net = mx.sym.Custom(data=h, label=mx.sym.Variable("softmax_label"),
                        op_type="numpy_softmax", name="softmax")

    mod = mx.mod.Module(net)
    mod.fit(it, num_epoch=args.epochs, optimizer="sgd",
            optimizer_params={"learning_rate": 0.2, "momentum": 0.9},
            initializer=mx.initializer.Xavier(), eval_metric="acc")
    acc = dict(mod.score(val, "acc"))["accuracy"]
    print("numpy-softmax custom op: val accuracy %.3f" % acc)
    assert acc > 0.9, acc
    print("numpy-ops OK")


if __name__ == "__main__":
    main()
