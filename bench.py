"""Benchmark: ResNet-50 training throughput, single chip, batch 32 —
the reference's headline number (docs/how_to/perf.md:179-188,
train_imagenet.py): P100 = 181.53 img/s. vs_baseline = ours / 181.53.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Design: the whole training step is TWO jitted XLA computations — fused
forward+backward from the symbolic graph (executor._get_fwd_bwd; the
reference's bulk-exec segments collapsed into one compilation, SURVEY §7)
and one whole-tree fused SGD-momentum update (the reference's per-weight
sgd_mom_update kernels batched into a single program).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BATCH = int(os.environ.get("BENCH_BATCH", "32"))
BASELINE = 181.53  # P100 ResNet-50 training img/s
WARMUP = 3
ITERS = int(os.environ.get("BENCH_ITERS", "100"))


def main():
    import numpy as np
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu import models

    sym = models.get_symbol("resnet-50", num_classes=1000)
    data_shape = (BATCH, 3, 224, 224)
    # bf16 compute / f32 master weights: the MXU-native mixed-precision path
    # (executor compute_dtype; override with BENCH_DTYPE=float32).
    cdtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    exe = sym.simple_bind(mx.Context("tpu", 0) if jax.default_backend() != "cpu"
                          else mx.cpu(), grad_req="write",
                          compute_dtype=cdtype,
                          data=data_shape, softmax_label=(BATCH,))
    # init weights
    init = mx.initializer.Xavier(factor_type="in", magnitude=2.0)
    for name, arr in exe.arg_dict.items():
        if name in ("data", "softmax_label"):
            continue
        init(mx.initializer.InitDesc(name), arr)

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.uniform(-1, 1, data_shape).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 1000, (BATCH,)).astype(np.float32))

    lr, momentum, wd = 0.05, 0.9, 1e-4
    param_names = [n for n in exe.arg_dict if n not in ("data", "softmax_label")]

    def sgd_all(params, grads, moms):
        new_p, new_m = {}, {}
        for n in params:
            g = grads[n] + wd * params[n]
            m = momentum * moms[n] - lr * g
            new_p[n] = params[n] + m
            new_m[n] = m
        return new_p, new_m

    # ONE fused XLA program per step (fwd+bwd+SGD, donated buffers) — the
    # whole-step bulk-exec path (Executor.make_train_step).
    step = exe.make_train_step(sgd_all)
    params = {n: exe.arg_dict[n]._data for n in param_names}
    moms = {n: jnp.zeros_like(v) for n, v in params.items()}
    feed = {"data": x, "softmax_label": y}

    def sync():
        # device->host readback of one element: a REAL sync even where
        # block_until_ready is unreliable (tunneled device platforms).
        import numpy as _np
        return _np.asarray(jnp.reshape(outs[0], (-1,))[0])

    for _ in range(WARMUP):
        outs, params, moms = step(params, moms, feed)
    sync()

    # best-of-N repeats: the shared/tunneled dev chip has run-to-run
    # contention noise; peak sustained throughput is the meaningful number
    best_dt = None
    for _ in range(max(1, int(float(os.environ.get("BENCH_REPEATS", "3"))))):
        t0 = time.perf_counter()
        for _ in range(ITERS):
            outs, params, moms = step(params, moms, feed)
        sync()
        dt = time.perf_counter() - t0
        best_dt = dt if best_dt is None else min(best_dt, dt)

    imgs_per_sec = BATCH * ITERS / best_dt
    print(json.dumps({
        "metric": "resnet50_train_imgs_per_sec_bs%d" % BATCH,
        "value": round(imgs_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(imgs_per_sec / BASELINE, 3),
    }))


if __name__ == "__main__":
    main()
