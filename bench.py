"""Benchmark: ResNet-50 + transformer-LM training, single chip — headline
metric is MFU.

The reference's headline table is img/s (docs/how_to/perf.md:179-188,
train_imagenet.py: P100 = 181.53 img/s @ bs32); this repo's north star
(BASELINE.md) is stated as MFU, so the benchmark emits both, with the FLOP
model and peak stated explicitly in the JSON:

- FLOP model: analytic 2-FLOPs-per-MAC count over the graph's matmul ops
  (mxnet_tpu/flops.py; ResNet-50 fwd = 8.18 GFLOPs/img @224^2), training
  step = 3x forward (backward = 2x forward matmul work).
- Denominator: the chip's NOMINAL bf16 peak (mxnet_tpu.flops.CHIP_PEAK_BF16
  by device_kind; override with BENCH_PEAK_TFLOPS).
- Timing: MEDIAN of BENCH_REPEATS timed blocks of BENCH_ITERS steps each
  (best-of-N over-reports under contention noise); sync = device->host
  readback of one output element before/after each block. BENCH_PER_ITER=1
  additionally reports median per-step wall time with a sync every step as
  a cross-check.

Two workloads, both through the same fused-step methodology:

- ResNet-50 @bs128 — the reference's headline table workload. On ONE v5e
  its 1x1-conv family is bandwidth-bound and the model-level ceiling is
  ~35-36% MFU (docs/perf.md roofline analysis); the 45% north star is
  stated for v5p, where the same program is compute-bound.
- Decoder transformer-LM @bs32 seq2048 (d_model 2048, GQA hkv=4, flash
  attention fwd+bwd) — dot_general-dominated, compute-bound on v5e: the
  workload that demonstrates north-star-class MFU on the chip this repo
  can measure.

The FINAL printed line (the driver's record) carries the transformer-LM
headline with the ResNet record embedded alongside ("alongside" per the
round-4 review); the ResNet full record is also printed on its own line.
Each metric appears on exactly ONE well-formed line — the LM record is
never printed both bare and embedded.
vs_baseline = MFU / 0.45 (the BASELINE.md north-star target) when
MFU is computable, else img_per_sec / 181.53 (P100 reference row).
BENCH_MODEL=resnet|transformer restricts the run (the restricted
workload's record is then the last line); BENCH_MODEL=conv runs the
per-layer conv-stack layout microbench (run_conv_config) instead.

Design: the whole training step is TWO jitted XLA computations fused into
ONE program via Executor.make_train_step — forward+backward from the
symbolic graph plus a whole-tree fused SGD-momentum update with donated
buffers (the reference's bulk-exec segments + fused sgd_mom_update kernels
collapsed into a single compilation, SURVEY §7).
"""
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# --- metric emission --------------------------------------------------------
# Every JSON record printed to stdout goes through _emit, which enforces the
# one-line-per-metric contract structurally: a metric name may be printed
# once, period — a second emission is a bench bug and raises instead of
# shipping a duplicated line (BENCH_r05.json carried the LM headline twice).
# The sweep modes' read-the-last-line contract (headline re-printed LAST) is
# the one sanctioned repeat: it must be the SAME record object, declared via
# final_repeat=True.
_EMITTED = {}
_EMIT_LOG = []  # (metric, final_repeat) per stdout line, in print order


def _emit(rec, final_repeat=False):
    name = rec.get("metric")
    prev = _EMITTED.get(name)
    if prev is not None:
        if not (final_repeat and prev is rec):
            raise RuntimeError(
                "bench bug: metric %r would be emitted twice" % name)
    else:
        if final_repeat:
            raise RuntimeError(
                "bench bug: final_repeat for never-emitted metric %r" % name)
        _EMITTED[name] = rec
    _EMIT_LOG.append((name, final_repeat))
    print(json.dumps(rec), flush=True)


def _emit_selfcheck():
    """Bench self-check: every stdout JSON line carries a unique `metric`
    key — each name printed exactly once, plus at most one declared
    final re-print (the sweep modes' last-line contract). _emit enforces
    this at print time; this re-asserts it over the full emission log and
    reports on stderr so the check shows up without touching stdout."""
    fresh = [n for n, rep in _EMIT_LOG if not rep]
    assert len(fresh) == len(set(fresh)), \
        "duplicate metric lines on stdout: %s" % fresh
    repeats = [n for n, rep in _EMIT_LOG if rep]
    assert len(repeats) <= 1 and set(repeats) <= set(fresh)
    print("bench: self-check OK — %d unique metric line(s): %s"
          % (len(set(fresh)), ", ".join(sorted(set(fresh)))),
          file=sys.stderr)

# honor JAX_PLATFORMS even where sitecustomize force-registers the TPU
# plugin (CI smoke runs set JAX_PLATFORMS=cpu)
if os.environ.get("JAX_PLATFORMS"):
    import jax as _jax
    _jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

# Default batch 128: the measured per-chip optimum on v5e (BENCH_SWEEP=1
# table in docs/perf.md — bs128 beats bs256 by ~1.4pp MFU; the reference's
# table is bs32-per-GPU and BENCH_BATCH=32 reproduces that config — every
# batch is recorded in the JSON via the metric name).
BATCH = int(os.environ.get("BENCH_BATCH", "128"))
P100_IMGS_PER_SEC = 181.53  # reference ResNet-50 training @bs32
MFU_TARGET = 0.45           # BASELINE.md north star
WARMUP = 3
ITERS = int(os.environ.get("BENCH_ITERS", "100"))
REPEATS = max(1, int(float(os.environ.get("BENCH_REPEATS", "5"))))


def run_config(batch, iters=None, repeats=None, remat=False):
    """Measure one (batch, remat) training config; returns the record
    dict. Used by the headline run and the BENCH_SWEEP table."""
    _remat_set_here = remat and not os.environ.get("MXNET_BACKWARD_DO_MIRROR")
    if _remat_set_here:
        os.environ["MXNET_BACKWARD_DO_MIRROR"] = "1"
    try:
        return _run_config_inner(batch, iters, repeats)
    finally:
        # even when the config OOMs mid-sweep, remat must not leak into
        # the next config
        if _remat_set_here:
            os.environ.pop("MXNET_BACKWARD_DO_MIRROR", None)


def _run_config_inner(batch, iters, repeats):
    import numpy as np
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu import flops as flops_mod
    from mxnet_tpu import models

    # a user-set MXNET_BACKWARD_DO_MIRROR is honored (and recorded below),
    # never silently stripped
    remat = bool(os.environ.get("MXNET_BACKWARD_DO_MIRROR"))
    iters = iters or ITERS
    repeats = repeats or REPEATS
    sym = models.get_symbol("resnet-50", num_classes=1000)
    data_shape = (batch, 3, 224, 224)
    # bf16 compute / f32 master weights: the MXU-native mixed-precision path
    # (executor compute_dtype; override with BENCH_DTYPE=float32).
    cdtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    # grad only for parameters: data/label get grad_req null, exactly like
    # Module training (a data gradient would add a full backward-data conv
    # through the stem — measurably wasted work).
    arg_names = sym.list_arguments()
    grad_req = {n: ("null" if n in ("data", "softmax_label") else "write")
                for n in arg_names}
    exe = sym.simple_bind(mx.Context("tpu", 0) if jax.default_backend() != "cpu"
                          else mx.cpu(), grad_req=grad_req,
                          compute_dtype=cdtype,
                          data=data_shape, softmax_label=(batch,))
    # init weights
    init = mx.initializer.Xavier(factor_type="in", magnitude=2.0)
    for name, arr in exe.arg_dict.items():
        if name in ("data", "softmax_label"):
            continue
        init(mx.initializer.InitDesc(name), arr)

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.uniform(-1, 1, data_shape).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 1000, (batch,)).astype(np.float32))

    lr, momentum, wd = 0.05, 0.9, 1e-4
    param_names = [n for n in exe.arg_dict if n not in ("data", "softmax_label")]

    def sgd_all(params, grads, moms):
        new_p, new_m = {}, {}
        for n in params:
            g = grads[n] + wd * params[n]
            m = momentum * moms[n] - lr * g
            new_p[n] = params[n] + m
            new_m[n] = m
        return new_p, new_m

    # ONE fused XLA program per step (fwd+bwd+SGD, donated buffers).
    # BENCH_CHAIN sub-steps run per dispatch (lax.scan bulk execution):
    # a Python dispatch costs ~1.4 ms of device idle through the dev
    # tunnel, which chaining amortizes to 1/chain — the same effect a
    # real input pipeline achieves with async prefetch ahead of the
    # device. Every reported time is per SUB-step.
    # Snapshot the weights first: step() donates its inputs, and the
    # executor's own buffers must stay live (donation contract).
    chain = max(1, int(os.environ.get("BENCH_CHAIN", "1")))
    step = exe.make_train_step(sgd_all, chain=chain)
    # BENCH_ITERS counts SUB-steps: a timed block is iters/chain
    # dispatches of chain sub-steps each
    iters = max(1, iters // chain)
    params = {n: jnp.array(exe.arg_dict[n]._data, copy=True)
              for n in param_names}
    moms = {n: jnp.zeros_like(v) for n, v in params.items()}
    feed = {"data": x, "softmax_label": y}

    def sync():
        # device->host readback of one element: a REAL sync even where
        # block_until_ready is unreliable (tunneled device platforms).
        return np.asarray(jnp.reshape(outs[0], (-1,))[0])

    for _ in range(WARMUP):
        outs, params, moms = step(params, moms, feed)
    sync()

    # median-of-N timed blocks (the shared/tunneled dev chip has
    # run-to-run contention noise; median is robust without the
    # optimistic bias of best-of-N)
    block_times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            outs, params, moms = step(params, moms, feed)
        sync()
        block_times.append(time.perf_counter() - t0)
    step_time = statistics.median(block_times) / (iters * chain)

    per_iter_ms = None
    if os.environ.get("BENCH_PER_ITER"):
        # cross-check: per-step wall time with a sync EVERY step (upper
        # bound: includes one dispatch+readback latency per step)
        ts = []
        for _ in range(min(iters, 30)):
            t0 = time.perf_counter()
            outs, params, moms = step(params, moms, feed)
            sync()
            ts.append((time.perf_counter() - t0) / chain)
        per_iter_ms = round(statistics.median(ts) * 1e3, 3)

    imgs_per_sec = batch / step_time

    fwd_flops_img = flops_mod.count_flops(
        sym, data=(1, 3, 224, 224), softmax_label=(1,))["total"]
    train_flops_img = flops_mod.training_flops(fwd_flops_img)
    peak, kind = flops_mod.chip_peak_flops()
    if os.environ.get("BENCH_PEAK_TFLOPS"):
        peak = float(os.environ["BENCH_PEAK_TFLOPS"]) * 1e12
    achieved = imgs_per_sec * train_flops_img
    # MFU only against the matching precision peak: the table is bf16, so
    # a float32 run falls back to the img/s metric instead of dividing by
    # the wrong denominator.
    mfu = achieved / peak if (peak and cdtype == "bfloat16") else None

    rec = {
        "metric": "resnet50_train_mfu_bs%d" % batch,
        "batch": batch,
        "value": round(100.0 * mfu, 2) if mfu is not None else round(imgs_per_sec, 2),
        "unit": "percent_of_bf16_peak" if mfu is not None else "images/sec",
        "vs_baseline": round(mfu / MFU_TARGET, 3) if mfu is not None
                       else round(imgs_per_sec / P100_IMGS_PER_SEC, 3),
        "img_per_sec": round(imgs_per_sec, 2),
        "vs_p100_ref": round(imgs_per_sec / P100_IMGS_PER_SEC, 3),
        "step_time_ms": round(step_time * 1e3, 3),
        "flop_formula": "2 FLOPs/MAC over Conv+FC (fwd=%.3f GF/img), "
                        "train=3x fwd=%.3f GF/img" % (
                            fwd_flops_img / 1e9, train_flops_img / 1e9),
        "chip": kind,
        "chip_peak_tflops": round(peak / 1e12, 1) if peak else None,
        "achieved_tflops": round(achieved / 1e12, 2),
        "timing": "median of %d blocks x %d dispatches x %d chained "
                  "sub-steps, readback sync" % (repeats, iters, chain),
        "chain": chain,
        "compute_dtype": cdtype,
    }
    if remat:
        rec["metric"] += "_remat"
        rec["remat"] = "MXNET_BACKWARD_DO_MIRROR segments"
    if mfu is None:
        rec["metric"] = rec["metric"].replace("_mfu_", "_imgs_per_sec_")
    if per_iter_ms is not None:
        rec["per_iter_ms_synced"] = per_iter_ms
    return rec


def run_transformer_config(batch=None, seq=None, iters=None, repeats=None,
                           model_dim=2048, num_layers=4, vocab=10000,
                           kv_heads=4):
    """Transformer-LM training MFU via the EXACT ResNet methodology:
    simple_bind + Executor.make_train_step (one fused XLA program:
    fwd+bwd+SGD, donated buffers), analytic matmul FLOPs from flops.py
    (FC projections + MultiHeadAttention at its USEFUL causal count),
    median-of-N timed blocks, nominal bf16 peak denominator.

    Default config bs32 x seq2048, d_model 2048 (16 heads x head_dim 128
    — the flash kernel's native shape), GQA hkv=4, ffn 4x: the per-chip
    MFU optimum from the docs/perf.md sweep; dot_general-dominated and
    compute-bound on v5e."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu import flops as flops_mod
    from mxnet_tpu import models

    batch = batch or int(os.environ.get("BENCH_LM_BATCH", "32"))
    seq = seq or int(os.environ.get("BENCH_LM_SEQ", "2048"))
    iters = iters or max(1, min(ITERS, 2048 // batch))
    repeats = repeats or REPEATS
    # CI smoke knobs (CPU backend): shrink the model, keep the code path
    model_dim = int(os.environ.get("BENCH_LM_DIM", model_dim))
    num_layers = int(os.environ.get("BENCH_LM_LAYERS", num_layers))
    vocab = int(os.environ.get("BENCH_LM_VOCAB", vocab))
    heads = model_dim // 128 if model_dim % 128 == 0 else max(
        1, model_dim // 64)
    kv_heads = min(kv_heads, heads)
    while heads % kv_heads:  # GQA needs heads % kv_heads == 0
        kv_heads -= 1
    sym = models.get_symbol(
        "transformer-lm", num_classes=vocab, num_layers=num_layers,
        num_heads=heads, model_dim=model_dim, ffn_dim=4 * model_dim,
        num_kv_heads=kv_heads, scalar_loss=True)
    cdtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    arg_names = sym.list_arguments()
    grad_req = {n: ("null" if n in ("data", "softmax_label") else "write")
                for n in arg_names}
    exe = sym.simple_bind(mx.Context("tpu", 0) if jax.default_backend() != "cpu"
                          else mx.cpu(), grad_req=grad_req,
                          compute_dtype=cdtype,
                          data=(batch, seq), softmax_label=(batch, seq))
    init = mx.initializer.Xavier(factor_type="in", magnitude=2.0)
    for name, arr in exe.arg_dict.items():
        if name in ("data", "softmax_label"):
            continue
        init(mx.initializer.InitDesc(name), arr)

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randint(0, vocab, (batch, seq)).astype(np.float32))
    y = jnp.asarray(rng.randint(0, vocab, (batch, seq)).astype(np.float32))

    lr, momentum, wd = 0.05, 0.9, 1e-4
    param_names = [n for n in exe.arg_dict
                   if n not in ("data", "softmax_label")]

    def sgd_all(params, grads, moms):
        new_p, new_m = {}, {}
        for n in params:
            g = grads[n] + wd * params[n]
            m = momentum * moms[n] - lr * g
            new_p[n] = params[n] + m
            new_m[n] = m
        return new_p, new_m

    chain = max(1, int(os.environ.get("BENCH_CHAIN", "1")))
    step = exe.make_train_step(sgd_all, chain=chain)
    iters = max(1, iters // chain)
    params = {n: jnp.array(exe.arg_dict[n]._data, copy=True)
              for n in param_names}
    moms = {n: jnp.zeros_like(v) for n, v in params.items()}
    feed = {"data": x, "softmax_label": y}

    def sync():
        return np.asarray(jnp.reshape(outs[0], (-1,))[0])

    for _ in range(WARMUP):
        outs, params, moms = step(params, moms, feed)
    sync()

    block_times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            outs, params, moms = step(params, moms, feed)
        sync()
        block_times.append(time.perf_counter() - t0)
    step_time = statistics.median(block_times) / (iters * chain)

    tokens_per_sec = batch * seq / step_time
    fwd_flops = flops_mod.count_flops(
        sym, data=(batch, seq), softmax_label=(batch, seq))["total"]
    train_flops = flops_mod.training_flops(fwd_flops)
    peak, kind = flops_mod.chip_peak_flops()
    if os.environ.get("BENCH_PEAK_TFLOPS"):
        peak = float(os.environ["BENCH_PEAK_TFLOPS"]) * 1e12
    achieved = train_flops / step_time
    mfu = achieved / peak if (peak and cdtype == "bfloat16") else None

    rec = {
        "metric": "transformer_lm_train_mfu_bs%d_seq%d" % (batch, seq),
        "batch": batch,
        "seq": seq,
        "value": round(100.0 * mfu, 2) if mfu is not None
                 else round(tokens_per_sec, 1),
        "unit": "percent_of_bf16_peak" if mfu is not None else "tokens/sec",
        "vs_baseline": round(mfu / MFU_TARGET, 3) if mfu is not None else None,
        "tokens_per_sec": round(tokens_per_sec, 1),
        "step_time_ms": round(step_time * 1e3, 3),
        "model": "decoder LM L=%d d_model=%d heads=%d gqa_kv=%d ffn=%d "
                 "vocab=%d, flash attention, fused train step"
                 % (num_layers, model_dim, heads, kv_heads, 4 * model_dim,
                    vocab),
        "flop_formula": "2 FLOPs/MAC over FC/attention matmuls (causal at "
                        "useful count; fwd=%.3f GF/step), train=3x fwd"
                        % (fwd_flops / 1e9),
        "chip": kind,
        "chip_peak_tflops": round(peak / 1e12, 1) if peak else None,
        "achieved_tflops": round(achieved / 1e12, 2),
        "timing": "median of %d blocks x %d dispatches x %d chained "
                  "sub-steps, readback sync" % (repeats, iters, chain),
        "compute_dtype": cdtype,
    }
    if mfu is None:
        rec["metric"] = rec["metric"].replace("_mfu_", "_tokens_per_sec_")
    return rec


def _serving_model():
    import numpy as np
    import mxnet_tpu as mx

    # wide enough that forward compute scales with batch rows (so padded
    # rows cost real time) instead of being swamped by dispatch overhead
    in_dim, hidden, classes = 512, 4096, 16
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=hidden, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=classes, name="fc2")
    sym = mx.sym.SoftmaxOutput(net, name="softmax")
    rng = np.random.RandomState(0)
    shapes, _, _ = sym.infer_shape(data=(1, in_dim))
    params = {n: rng.uniform(-0.1, 0.1, s).astype(np.float32)
              for n, s in zip(sym.list_arguments(), shapes)
              if n not in ("data", "softmax_label")}
    return sym, params, in_dim, hidden, classes


def _serving_burst(srv, in_dim, n_requests, n_threads, mix, trace=False):
    """One timed burst of the FIXED request-size mix against a running
    server: every thread walks the same deterministic rows pattern, so
    the A and B arms see identical traffic. ``trace=True`` mints a
    request-scoped trace context per request (the HTTP edge's behavior),
    so every span is stamped and teed into the flight recorder — the
    fully-traced cost arm."""
    import threading

    import numpy as np
    from mxnet_tpu import serving
    from mxnet_tpu.telemetry import context as tctx

    errors = []
    per_thread = max(1, n_requests // n_threads)

    def client(i):
        r = np.random.RandomState(100 + i)
        for k in range(per_thread):
            rows = mix[(i + k) % len(mix)]
            x = r.uniform(-1, 1, (rows, in_dim)).astype(np.float32)
            try:
                if trace:
                    with tctx.use(tctx.mint()):
                        srv.predict(data=x)
                else:
                    srv.predict(data=x)
            except serving.ServingError as e:
                errors.append(e.code)

    srv.metrics.reset()
    t0 = time.perf_counter()
    threads = [threading.Thread(target=client, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    m = dict(zip(*srv.get_metrics()))
    m["_wall"] = wall
    m["_qps"] = m["completed"] / wall
    m["_errors"] = len(errors)
    return m


def run_serving_config():
    """Serving hot-path A/B under a fixed bimodal request-size mix
    (BENCH_MODEL=serving), both arms in THIS process and run:

    - A (baseline): static bucket ladder, round-robin routing, per-
      dispatch np.zeros+concatenate assembly — the PR-2 configuration.
    - B (headline): adaptive ladder (BucketTuner retune after an
      observation phase), least-outstanding-work routing, zero-copy
      staging-buffer assembly, cross-bucket coalescing.

    The record's value is B's steady-state QPS; vs_baseline is the B/A
    QPS ratio and padding_waste_pct[_baseline] shows the padding drop.
    A telemetry spans-on burst rides along (observability overhead)."""
    import numpy as np
    from mxnet_tpu import serving, telemetry

    n_requests = int(os.environ.get("BENCH_SERVING_REQUESTS", "256"))
    n_threads = int(os.environ.get("BENCH_SERVING_THREADS", "16"))
    n_replicas = int(os.environ.get("BENCH_SERVING_REPLICAS", "2"))
    sym, params, in_dim, hidden, classes = _serving_model()
    buckets = (1, 8, 64)
    # the fixed bimodal mix: alternating 33-row and 36-row requests.
    # Two properties make this the honest adaptive-vs-static comparison:
    # the static ladder serves BOTH sizes from its 64 bucket (~46% padded
    # rows) while the tuned ladder grows exact 33/36 rungs, and any two
    # requests sum past max_batch=64 so the former produces the SAME
    # batch sequence in both arms — the ratio isolates bucket tightness
    # + routing + assembly, not batch-formation luck
    mix = (33, 36)

    def mk(cfg):
        return serving.InferenceServer(sym, params, {"data": (in_dim,)},
                                       config=cfg)

    telemetry.disable_spans()
    # --- A: static / round-robin / copy assembly -------------------------
    cfg_a = serving.ServingConfig(
        buckets=buckets, replicas=n_replicas, warm=True, router="rr",
        max_delay_ms=2.0,
        adaptive=False, zero_copy=False, coalesce_fill_pct=0.0)
    # best-of-N measured bursts per arm: one burst is ~0.7s and thread
    # scheduling jitter swings single-burst QPS by >10%, so both arms
    # report their best burst — the same estimator, so the ratio is fair
    n_bursts = int(os.environ.get("BENCH_SERVING_BURSTS", "3"))

    def best_burst(srv):
        runs = [_serving_burst(srv, in_dim, n_requests, n_threads, mix)
                for _ in range(n_bursts)]
        return max(runs, key=lambda m: m["_qps"])

    srv_a = mk(cfg_a)
    with srv_a:
        _serving_burst(srv_a, in_dim, n_requests // 2, n_threads, mix)  # warm
        a = best_burst(srv_a)

    # --- B: adaptive / least-loaded / zero-copy / coalescing -------------
    cfg_b = serving.ServingConfig(
        buckets=buckets, replicas=n_replicas, warm=True,
        router="least_loaded", adaptive=True, zero_copy=True,
        max_delay_ms=2.0,
        coalesce_fill_pct=100.0, program_budget=4,
        retune_min_samples=32, retune_interval=0)  # manual retune below
    srv_b = mk(cfg_b)
    with srv_b:
        # observation phase feeds the size histogram, then one explicit
        # retune swaps the ladder (warming the new rung off-path) BEFORE
        # the measured burst — steady-state adaptive serving
        _serving_burst(srv_b, in_dim, n_requests // 2, n_threads, mix)
        srv_b.retune_now(wait=True)
        b = best_burst(srv_b)
        # telemetry overhead rides along on the B arm: same burst with
        # serving+engine spans recording
        telemetry.enable_spans("serving,engine")
        b_on = _serving_burst(srv_b, in_dim, n_requests, n_threads, mix)
        # fully-traced arm: spans on AND a per-request trace context, so
        # every span is stamped + teed into the flight recorder — the
        # cost of the whole ISSUE 19 pipeline under load
        b_trace = _serving_burst(srv_b, in_dim, n_requests, n_threads,
                                 mix, trace=True)
        telemetry.disable_spans()
        telemetry.reset()
        from mxnet_tpu.telemetry import flight as _flight
        _flight.reset()
        # compile-witness overhead rides along too: off/on bursts
        # INTERLEAVED per repeat and the overhead taken as the median of
        # the paired ratios (the checkpoint bench's drift-immune idiom —
        # an effect this small is otherwise swamped by CPU drift). The
        # armed witness records only on fresh compiles, so the warm
        # steady-state burst must pay nothing but the surface no-ops.
        from mxnet_tpu.analysis import compile_witness as _witness
        w_prev = _witness.enable(False)
        w_pairs = []
        for _ in range(n_bursts):
            _witness.enable(False)
            w_off = _serving_burst(srv_b, in_dim, n_requests, n_threads,
                                   mix)
            _witness.enable(True)
            w_on = _serving_burst(srv_b, in_dim, n_requests, n_threads,
                                  mix)
            if w_off["_qps"] and w_on["_qps"]:
                w_pairs.append((w_off["_qps"] - w_on["_qps"])
                               / w_off["_qps"] * 100.0)
        _witness.enable(w_prev)
        _witness.reset()
        witness_overhead_pct = (sorted(w_pairs)[len(w_pairs) // 2]
                                if w_pairs else None)
        cache_b = srv_b.cache_stats()
        ladder_b = list(srv_b.current_ladder())
        version_b = srv_b.ladder_version

    # --- capture arm: B + engine capture/replay of the dispatch ----------
    # each (replica, bucket) dispatch sequence is length 1, so the QPS
    # delta is small by construction — this arm exercises the capture API
    # under real concurrent traffic + a ladder retune; the >=3x host-
    # overhead claim is carried by the engine microbench (BENCH_MODEL=
    # engine / run_engine_config)
    cfg_c = serving.ServingConfig(
        buckets=buckets, replicas=n_replicas, warm=True,
        router="least_loaded", adaptive=True, zero_copy=True,
        max_delay_ms=2.0,
        coalesce_fill_pct=100.0, program_budget=4,
        retune_min_samples=32, retune_interval=0, capture=True)
    srv_c = mk(cfg_c)
    with srv_c:
        _serving_burst(srv_c, in_dim, n_requests // 2, n_threads, mix)
        srv_c.retune_now(wait=True)
        c = best_burst(srv_c)
        replays_c = sum(cs.replays for rep in srv_c._replicas
                        for cs in rep.captures.values())

    # --- fused arm: C + trace-and-fuse of the captured dispatch ----------
    # the stabilized per-(replica, bucket) sequence lowers into one fused
    # XLA program (MXNET_ENGINE_FUSE); like C this is an API-under-load
    # arm — the >=1.3x fused-vs-replay claim is carried by the engine
    # microbench, where sequences are 64 ops deep, not 1
    cfg_d = serving.ServingConfig(
        buckets=buckets, replicas=n_replicas, warm=True,
        router="least_loaded", adaptive=True, zero_copy=True,
        max_delay_ms=2.0,
        coalesce_fill_pct=100.0, program_budget=4,
        retune_min_samples=32, retune_interval=0, capture=True,
        fuse=True)
    srv_d = mk(cfg_d)
    with srv_d:
        _serving_burst(srv_d, in_dim, n_requests // 2, n_threads, mix)
        srv_d.retune_now(wait=True)
        d = best_burst(srv_d)
        fused_runs_d = sum(cs.fused_runs for rep in srv_d._replicas
                           for cs in rep.captures.values())
        fuse_bails_d = sum(cs.fuse_bails for rep in srv_d._replicas
                           for cs in rep.captures.values())

    telemetry_rec = {
        "spans_off_qps": round(b["_qps"], 1),
        "spans_on_qps": round(b_on["_qps"], 1),
        "spans_on_overhead_pct": round(
            100.0 * (b["_qps"] - b_on["_qps"]) / b["_qps"], 2)
            if b["_qps"] else None,
        "trace_on_qps": round(b_trace["_qps"], 1),
        "trace_on_overhead_pct": round(
            100.0 * (b["_qps"] - b_trace["_qps"]) / b["_qps"], 2)
            if b["_qps"] else None,
    }
    total = cache_b["hits"] + cache_b["misses"]
    return {
        "metric": "serving_dynamic_batching_qps",
        "value": round(b["_qps"], 1),
        "unit": "requests/sec",
        # headline acceptance numbers: B vs the in-process static/rr A arm
        "vs_baseline": round(b["_qps"] / a["_qps"], 3),
        "baseline_qps": round(a["_qps"], 1),
        "latency_ms_p99": round(b["latency_ms_p99"], 3),
        "baseline_latency_ms_p99": round(a["latency_ms_p99"], 3),
        "padding_waste_pct": round(b["padding_waste_pct"], 2),
        "baseline_padding_waste_pct": round(a["padding_waste_pct"], 2),
        "padding_waste_vs_baseline": round(
            b["padding_waste_pct"] - a["padding_waste_pct"], 2),
        "requests": int(b["completed"]),
        "threads": n_threads,
        "replicas": n_replicas,
        "request_mix": "bimodal alternating %s rows" % (list(mix),),
        "latency_ms_p50": round(b["latency_ms_p50"], 3),
        "latency_ms_p95": round(b["latency_ms_p95"], 3),
        "mean_batch_occupancy": round(b["mean_batch_occupancy"], 2),
        "padding_efficiency": round(b["padding_efficiency"], 3),
        "batches": int(b["batches"]),
        "cache_hit_rate": round(cache_b["hits"] / total, 3)
                          if total else None,
        "compiles": cache_b["compiles"],
        "buckets_static": list(buckets),
        "buckets_tuned": ladder_b,
        "ladder_version": version_b,
        "config": {"adaptive": True, "router": "least_loaded",
                   "zero_copy": True, "coalesce_fill_pct": 100.0,
                   "program_budget": 4},
        "baseline_config": {"adaptive": False, "router": "rr",
                            "zero_copy": False, "coalesce_fill_pct": 0.0},
        "client_errors": b["_errors"] + a["_errors"] + c["_errors"]
                         + d["_errors"],
        "telemetry": telemetry_rec,
        # the < 1% gate: the armed compile witness must be free on the
        # steady-state serving path (negative = noise = pass); off is the
        # production default, so the pair is off-vs-on
        "witness": {
            "witness_on_overhead_pct": round(witness_overhead_pct, 2)
                                       if witness_overhead_pct is not None
                                       else None,
            "pairs": len(w_pairs),
        },
        "capture": {
            "qps": round(c["_qps"], 1),
            "vs_adaptive": round(c["_qps"] / b["_qps"], 3)
                           if b["_qps"] else None,
            "replays": replays_c,
            "config": "B + ServingConfig.capture (MXNET_ENGINE_CAPTURE)",
        },
        "fused": {
            "qps": round(d["_qps"], 1),
            "vs_capture": round(d["_qps"] / c["_qps"], 3)
                          if c["_qps"] else None,
            "fused_runs": fused_runs_d,
            "fuse_bails": fuse_bails_d,
            "config": "C + ServingConfig.fuse (MXNET_ENGINE_FUSE)",
        },
        "model": "MLP %d-%d-%d softmax" % (in_dim, hidden, classes),
    }


def run_serving_http_config():
    """HTTP front-end hop A/B (BENCH_MODEL=serving_http, ISSUE 17).

    value = the HTTP hop's p50 per-request cost (sequential p50 over
    HTTP minus the same traffic's in-process ``submit`` p50 — the
    delta is parse + route + admission + socket + the extra handler
    thread hop, measured on an otherwise idle server to isolate the
    hop) as % of the BATCH latency: the server-side p50 under the
    canonical concurrent mix (16 threads of 33-row requests — batch
    latency is a property of the loaded serving regime; a lone request
    riding an empty 64-slot batch is the idle-server latency, not
    batch latency). The request is the serving bench's canonical
    33-row size in the raw-tensor b64 form (routes.
    parse_predict_inputs: nested-list JSON float parsing alone costs
    ~6 ms at 33x512, which would measure the wire format, not the hop;
    p50_http_json_ms reports the list-form p50 for the SAME tensor
    alongside). The ISSUE 17 gate is < 10%, so vs_baseline =
    10 / overhead_pct (>= 1.0 passes; negative overhead = noise =
    pass).

    Alongside (not gated): goodput under a closed-loop 2x overload of
    batch-class requests with shedding ON (shed_pct=25: excess is a
    fast 429 at admission, the admitted subset stays near its unloaded
    latency) vs OFF (shed_pct=100: everything queues and rides the
    deep-queue latency past the SLO) — goodput counts only responses
    inside an SLO of 4x the unloaded p50, per second of wall time."""
    import http.client
    import threading

    import numpy as np
    from mxnet_tpu import serving
    from mxnet_tpu.serving.frontend import FrontendConfig, HttpFrontend

    import base64

    sym, params, in_dim, hidden, classes = _serving_model()
    n = int(os.environ.get("BENCH_HTTP_REQUESTS", "160"))
    rows = 33                  # the serving bench's canonical request
    rng = np.random.RandomState(0)
    x1 = rng.uniform(-1, 1, (rows, in_dim)).astype(np.float32)
    body_b64 = json.dumps({"encoding": "b64", "inputs": {"data": {
        "b64": base64.b64encode(np.ascontiguousarray(x1)).decode(),
        "shape": [rows, in_dim], "dtype": "float32"}}})
    body_json = json.dumps({"inputs": {"data": x1.tolist()}})

    def mk(shed_pct):
        srv = serving.InferenceServer(
            sym, params, {"data": (in_dim,)},
            config=serving.ServingConfig(buckets=(1, 8, 64), replicas=1,
                                         warm=True, max_delay_ms=2.0,
                                         queue_depth=64))
        fe = HttpFrontend(srv, FrontendConfig(port=0, max_inflight=256,
                                              shed_pct=shed_pct))
        fe.start(wait_ready=True)
        return fe, srv

    def http_predict(conn, body, headers=None):
        conn.request("POST", "/v1/predict", body,
                     {"Content-Type": "application/json",
                      **(headers or {})})
        r = conn.getresponse()
        r.read()
        return r.status

    # --- hop overhead: INTERLEAVED repeats, min-p50 per arm --------------
    # CPU drift between two monolithic blocks swings the delta by more
    # than the gate itself (the decode benches' min-vs-min idiom): each
    # repeat measures both arms back to back and each arm takes the min
    # of its per-repeat p50s
    reps = max(1, int(os.environ.get("BENCH_HTTP_REPEATS", "3")))
    fe, srv = mk(shed_pct=100.0)
    conn = http.client.HTTPConnection("127.0.0.1", fe.port, timeout=60)
    for _ in range(10):                                          # warm
        srv.predict(data=x1)
        assert http_predict(conn, body_b64) == 200

    def block(fn, k):
        lat = []
        for _ in range(k):
            t0 = time.perf_counter()
            fn()
            lat.append(time.perf_counter() - t0)
        return float(np.percentile(lat, 50))

    def http_ok(body):
        st = http_predict(conn, body)
        assert st == 200, st

    p50s_in, p50s_http, p50s_json = [], [], []
    for _ in range(reps):
        p50s_in.append(block(lambda: srv.predict(data=x1), n))
        p50s_http.append(block(lambda: http_ok(body_b64), n))
        p50s_json.append(block(lambda: http_ok(body_json),
                               max(8, n // 4)))
    conn.close()
    p50_in, p50_http, p50_json = (min(p50s_in), min(p50s_http),
                                  min(p50s_json))
    hop_ms = (p50_http - p50_in) * 1e3

    # --- the denominator: batch latency under the canonical load ---------
    # 16 concurrent HTTP clients of the same 33-row request; the server-
    # side latency_ms_p50 (submit -> result) is the batch latency of the
    # loaded regime the hop overhead is gated against
    def loaded_client(i):
        c = http.client.HTTPConnection("127.0.0.1", fe.port, timeout=120)
        try:
            for _ in range(max(4, n // 8)):
                st = http_predict(c, body_b64)
                assert st == 200, st
        finally:
            c.close()

    srv.metrics.reset()
    ts = [threading.Thread(target=loaded_client, args=(i,))
          for i in range(16)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    m = dict(zip(*srv.get_metrics()))
    batch_p50_ms = m["latency_ms_p50"]
    overhead_pct = hop_ms / batch_p50_ms * 100.0

    fe.stop(drain=True)

    # --- goodput under 2x+ overload: shed on vs off ----------------------
    # capacity is made definitional: buckets=(rows,) serves exactly ONE
    # request per batch, so N closed-loop clients hold a queue of ~N-1
    # and the per-request service time st sets all timescales. SLO =
    # 8*st (a queue position <= ~7 meets it); shed-on caps the batch-
    # class queue at 12.5% of queue_depth 32 = 4 (admitted requests ride
    # a short queue and meet the SLO, the excess is a FAST 429), shed-
    # off lets all N queue (everything rides an ~N-deep queue and
    # misses). Speed-invariant: only queue-depth ratios matter.
    n_clients = int(os.environ.get("BENCH_HTTP_OVERLOAD_CLIENTS", "24"))
    per_client = 6

    def mk_overload(shed_pct):
        srv = serving.InferenceServer(
            sym, params, {"data": (in_dim,)},
            config=serving.ServingConfig(buckets=(rows,), replicas=1,
                                         warm=True, max_delay_ms=2.0,
                                         queue_depth=32,
                                         timeout_ms=120000.0))
        fe = HttpFrontend(srv, FrontendConfig(port=0, max_inflight=256,
                                              shed_pct=shed_pct))
        fe.start(wait_ready=True)
        return fe

    def overload(fe_port, slo_s):
        lock = threading.Lock()
        stat = {"good": 0, "late": 0, "shed": 0}

        def client(i):
            c = http.client.HTTPConnection("127.0.0.1", fe_port,
                                           timeout=180)
            try:
                for _ in range(per_client):
                    t0 = time.perf_counter()
                    st = http_predict(c, body_b64,
                                      headers={"x-priority": "batch"})
                    dt = time.perf_counter() - t0
                    with lock:
                        if st != 200:
                            stat["shed"] += 1
                        elif dt <= slo_s:
                            stat["good"] += 1
                        else:
                            stat["late"] += 1
            finally:
                c.close()

        t0 = time.perf_counter()
        ts = [threading.Thread(target=client, args=(i,))
              for i in range(n_clients)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        wall = time.perf_counter() - t0
        stat["goodput_rps"] = stat["good"] / wall
        stat["wall_s"] = wall
        return stat

    def service_time_s(fe):
        c = http.client.HTTPConnection("127.0.0.1", fe.port, timeout=60)
        ref = []
        for _ in range(12):
            t0 = time.perf_counter()
            st = http_predict(c, body_b64)
            assert st == 200, st
            ref.append(time.perf_counter() - t0)
        c.close()
        return float(np.percentile(ref, 50))

    fe_on = mk_overload(shed_pct=12.5)
    slo_s = 8.0 * service_time_s(fe_on)
    on = overload(fe_on.port, slo_s)
    fe_on.stop(drain=True)
    fe_off = mk_overload(shed_pct=100.0)
    off = overload(fe_off.port, slo_s)
    fe_off.stop(drain=True)

    return {
        "metric": "serving_http",
        "value": round(overhead_pct, 3),
        "unit": "pct_http_hop_p50_of_loaded_batch_latency",
        # the < 10% gate: >= 1.0 passes (negative overhead = noise)
        "vs_baseline": round(10.0 / overhead_pct, 3)
                       if overhead_pct > 0 else 99.0,
        "hop_p50_ms": round(hop_ms, 3),
        "batch_latency_p50_ms": round(batch_p50_ms, 3),
        "p50_inprocess_ms": round(p50_in * 1e3, 3),
        "p50_http_ms": round(p50_http * 1e3, 3),
        "p50_http_json_ms": round(p50_json * 1e3, 3),
        "request_rows": rows,
        "requests": n,
        "overload": {
            "slo_ms": round(slo_s * 1e3, 1),
            "clients": n_clients, "per_client": per_client,
            "shed_on": {k: (round(v, 2) if isinstance(v, float) else v)
                        for k, v in on.items()},
            "shed_off": {k: (round(v, 2) if isinstance(v, float) else v)
                         for k, v in off.items()},
            "goodput_shed_on_vs_off": round(
                on["goodput_rps"] / off["goodput_rps"], 3)
                if off["goodput_rps"] else None,
        },
        "model": "MLP %d-%d-%d softmax" % (in_dim, hidden, classes),
    }


def run_engine_config():
    """Dispatch-overhead microbench (BENCH_MODEL=engine): host-side engine
    time per op, eager push vs captured/replayed submission, over a
    64-op/8-var chain with real RAW dependencies.

    Methodology: time ONLY the push loops — the replay's target is the
    per-op Python scheduling cost (_dedup, pending-table lock, ctypes
    marshalling, native queue insert), not op execution, so the queue is
    drained by an engine fence OUTSIDE the timed region. Median of
    BENCH_ENGINE_REPEATS timed blocks of BENCH_ENGINE_ITERS iterations.
    value = eager_us_per_op / replay_us_per_op (the >=3x gate);
    vs_baseline = value / 3.0 so >=1.0 passes."""
    from mxnet_tpu import engine

    n_ops = int(os.environ.get("BENCH_ENGINE_OPS", "64"))
    n_vars = 8
    iters = int(os.environ.get("BENCH_ENGINE_ITERS", "50"))
    repeats = max(1, int(os.environ.get("BENCH_ENGINE_REPEATS", "5")))
    vars_ = tuple(engine.new_variable() for _ in range(n_vars))
    # op i writes var i%8 and reads var (i+1)%8: a dense dependency
    # braid, so the eager arm pays real scheduler work per push
    sigs = tuple(((vars_[(i + 1) % n_vars],), (vars_[i % n_vars],),
                  "bench_op%d" % i) for i in range(n_ops))

    def nop():
        pass

    def eager_iter():
        for c, m, nm in sigs:
            engine.push(nop, const_vars=c, mutable_vars=m, name=nm)

    def drain():
        engine.fence(list(vars_), name="bench_engine_drain").wait(60)

    eager_iter()
    drain()
    eager_times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            eager_iter()
        eager_times.append(time.perf_counter() - t0)
        drain()
    eager_per_op = statistics.median(eager_times) / (iters * n_ops)

    cs = engine.CapturedSequence(name="bench_engine")

    def cap_iter():
        cs.begin_step()
        for c, m, nm in sigs:
            cs.push(nop, const_vars=c, mutable_vars=m, name=nm)
        cs.end_step()

    for _ in range(cs.warmup):
        cap_iter()
    drain()
    assert cs.state == "ready", \
        "bench bug: capture did not stabilize (%s)" % cs.state
    replay_times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            cap_iter()
        replay_times.append(time.perf_counter() - t0)
        drain()
    replay_per_op = statistics.median(replay_times) / (iters * n_ops)
    assert cs.replays >= repeats * iters and cs.bails == 0, \
        "bench bug: replay arm ran eagerly (%d replays, %d bails)" \
        % (cs.replays, cs.bails)
    speedup = eager_per_op / replay_per_op

    # --- happens-before sanitizer overhead A/B ---------------------------
    # Claim under test (docs/concurrency.md): with MXNET_ENGINE_SANITIZER
    # off, the push-path hook is one global load + is-None branch. Arm A
    # is a hook-free twin of the module push wrapper (same in-flight
    # accounting, same engine call — minus the sanitizer branch); arm B is
    # engine.push with the sanitizer disabled. Arms run BACK-TO-BACK per
    # repeat and the overhead is the median of the per-repeat paired
    # ratios (the checkpoint bench's drift-immune idiom) — gate < 1%.
    # Arm C (sanitizer ENABLED, no guards) rides along as the informative
    # cost of actually turning the tool on: per-push site capture + the
    # closure reachability scan.
    eng = engine.get()

    def push_nohook(fn, c, m, nm):
        counted = engine._inflight_begin(tuple(c) + tuple(m))
        if counted:
            fn = engine._wrap_inflight_sync(fn, counted)
        eng.push(fn, c, m, 0, nm)

    def nohook_iter():
        for c, m, nm in sigs:
            push_nohook(nop, c, m, nm)

    was_on = engine.sanitizer_enabled()
    engine.sanitizer_enable(False)
    nohook_iter()
    drain()
    san_times = {"nohook": [], "disabled": [], "enabled": []}
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            nohook_iter()
        san_times["nohook"].append(time.perf_counter() - t0)
        drain()
        t0 = time.perf_counter()
        for _ in range(iters):
            eager_iter()
        san_times["disabled"].append(time.perf_counter() - t0)
        drain()
        engine.sanitizer_enable(True)
        t0 = time.perf_counter()
        for _ in range(iters):
            eager_iter()
        san_times["enabled"].append(time.perf_counter() - t0)
        engine.sanitizer_enable(False)
        drain()
    engine.sanitizer_enable(was_on)
    san_disabled_pct = statistics.median(
        (d - n) / n * 100.0
        for d, n in zip(san_times["disabled"], san_times["nohook"]))
    san_enabled_pct = statistics.median(
        (e - n) / n * 100.0
        for e, n in zip(san_times["enabled"], san_times["nohook"]))

    # --- trace-and-fuse arm: replayed vs fused END-TO-END iteration ------
    # Same 64-op/8-var braid, but every op now carries real device work
    # (a jitted elementwise chain over a (dim, dim) register), so this
    # times the whole iteration — push + execution + drain — not just the
    # host push loop: replay still dispatches 64 separate XLA programs
    # per iteration, the fused arm runs ONE (MXNET_ENGINE_FUSE). Arms are
    # interleaved per repeat and the speedup is the median of the
    # per-repeat paired ratios (the checkpoint bench's drift-immune
    # estimator). Gate: fuse_speedup >= 1.3.
    import jax
    import jax.numpy as jnp
    import numpy as np

    # (dim, dim) f32 registers. Small on purpose: trace-and-fuse's win is
    # eliminating 63 of 64 per-op XLA dispatches, so the honest regime is
    # dispatch-dominated ops — at 128x128 this CPU's tanh compute (which
    # fusion cannot shrink, and which XLA parallelizes across the braid's
    # independent ops in the replay arm) drowns the dispatch saving
    fuse_dim = int(os.environ.get("BENCH_FUSE_DIM", "32"))
    fuse_iters = int(os.environ.get("BENCH_FUSE_ITERS", "20"))

    @jax.jit
    def fuse_kernel(c, m):
        return jnp.tanh(c * 0.999 + m * 0.001) + c * 1e-3

    def build_braid(tag, fuse_mode):
        fvars = tuple(engine.new_variable() for _ in range(n_vars))
        rng = np.random.RandomState(7)
        regs = {v: jnp.asarray(rng.randn(fuse_dim, fuse_dim)
                               .astype(np.float32)) for v in fvars}
        seq = engine.CapturedSequence(name="bench_fuse_%s" % tag,
                                      fuse=fuse_mode)
        ops = []
        for i in range(n_ops):
            cv, mv = fvars[(i + 1) % n_vars], fvars[i % n_vars]

            def work(_c=cv, _m=mv):
                regs[_m] = fuse_kernel(regs[_c], regs[_m])

            def wb(d, _m=mv):
                regs[_m] = d[_m]

            fuse = engine.FuseOp(
                lambda c, m: (fuse_kernel(c, m),),
                in_vars=(cv, mv), out_vars=(mv,),
                init={cv: (lambda _v=cv: regs[_v]),
                      mv: (lambda _v=mv: regs[_v])},
                writeback=(wb if i >= n_ops - n_vars else None),
                fingerprint="bench_fuse:v1:%d:%d" % (i, fuse_dim))
            ops.append((work, (cv,), (mv,), "bench_fuse_op%d" % i, fuse))

        def one_iter():
            seq.begin_step()
            for fn, c, m, nm, fu in ops:
                seq.push(fn, const_vars=c, mutable_vars=m, name=nm,
                         fuse=fu)
            seq.end_step()

        def drain_f():
            engine.fence(list(fvars), name="bench_fuse_drain").wait(60)
            for v in fvars:
                jax.block_until_ready(regs[v])

        return seq, regs, fvars, one_iter, drain_f

    seq_r, regs_r, _, iter_r, drain_r = build_braid("replay", False)
    seq_f, regs_f, _, iter_f, drain_ff = build_braid("fused", True)
    for _ in range(max(seq_r.warmup, seq_f.warmup) + 1):
        iter_r()
        iter_f()
    drain_r()
    drain_ff()
    assert seq_r.state == "ready" and seq_f.state == "ready", \
        "bench bug: fuse-arm capture did not stabilize (%s/%s)" \
        % (seq_r.state, seq_f.state)
    assert seq_f._fuse_state == "staged", \
        "bench bug: fused arm did not stage (%s)" % seq_f._fuse_state
    rep_times, fus_times = [], []
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(fuse_iters):
            iter_r()
        drain_r()
        rep_times.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        for _ in range(fuse_iters):
            iter_f()
        drain_ff()
        fus_times.append(time.perf_counter() - t0)
    assert seq_f.fused_runs >= repeats * fuse_iters \
        and seq_f.fuse_bails == 0, \
        "bench bug: fused arm fell back (%d fused runs, %d bails)" \
        % (seq_f.fused_runs, seq_f.fuse_bails)
    # both arms ran the same op stream over identical seeds — the fused
    # lowering must not have changed the math
    for vr, vf in zip(sorted(regs_r), sorted(regs_f)):
        assert np.allclose(np.asarray(regs_r[vr]), np.asarray(regs_f[vf]),
                           rtol=1e-5, atol=1e-6), \
            "bench bug: fused arm diverged from replay"
    fuse_speedup = statistics.median(
        r / f for r, f in zip(rep_times, fus_times))
    replay_iter_ms = statistics.median(rep_times) / fuse_iters * 1e3
    fused_iter_ms = statistics.median(fus_times) / fuse_iters * 1e3
    return {
        "metric": "engine_dispatch_overhead",
        "value": round(speedup, 2),
        "unit": "x_eager_host_us_per_op_over_replay",
        "vs_baseline": round(speedup / 3.0, 3),  # >=1.0 <=> the 3x gate
        "eager_us_per_op": round(eager_per_op * 1e6, 3),
        "replay_us_per_op": round(replay_per_op * 1e6, 3),
        "eager_pushes_per_sec": round(1.0 / eager_per_op),
        "replay_pushes_per_sec": round(1.0 / replay_per_op),
        "ops_per_sequence": n_ops,
        "n_vars": n_vars,
        "iters": iters,
        "repeats": repeats,
        "replays": cs.replays,
        # the < 1% gate: disabled sanitizer must be free on the push path
        # (negative = noise = pass); enabled cost is informative only
        "sanitizer_disabled_overhead_pct": round(san_disabled_pct, 3),
        "sanitizer_enabled_overhead_pct": round(san_enabled_pct, 3),
        # the >= 1.3x gate: one fused XLA program per iteration vs 64
        # replayed per-op dispatches, end-to-end (push + run + drain)
        "fuse_speedup": round(fuse_speedup, 2),
        "replay_iter_ms": round(replay_iter_ms, 3),
        "fused_iter_ms": round(fused_iter_ms, 3),
        "fuse_dim": fuse_dim,
        "fuse_iters": fuse_iters,
        "fused_runs": seq_f.fused_runs,
        "fuse_bails": seq_f.fuse_bails,
        "engine": type(engine.get()).__name__,
    }


def run_checkpoint_config():
    """Async-checkpoint overhead A/B (BENCH_MODEL=checkpoint): the same
    fused train-step loop with NO checkpoints (arm A), with async sharded
    checkpoints every BENCH_CKPT_INTERVAL steps (arm B, the resilience
    default: snapshot = the get_checkpoint_state host copy, serialization
    and writes in the background via the engine's file-write vars), and
    with blocking writes (arm C, what a naive save would cost). Timed region = the step loop only; the final
    drain (waiting out in-flight writes) is tail latency, reported
    separately. value = arm B overhead in % of arm A; the ISSUE 7 gate
    is < 3%, so vs_baseline = 3.0 / overhead_pct (>= 1.0 passes)."""
    import shutil
    import tempfile

    import mxnet_tpu as mx
    from mxnet_tpu.resilience import checkpoint as ckpt

    in_dim = int(os.environ.get("BENCH_CKPT_IN", "256"))
    hidden = int(os.environ.get("BENCH_CKPT_HIDDEN", "256"))
    layers = int(os.environ.get("BENCH_CKPT_LAYERS", "6"))
    # default batch 2048: the snapshot cost (asnumpy + serialize + crc)
    # is fixed per checkpoint while step compute scales with batch, so
    # the overhead ratio is batch-dependent — 2048 is where this CPU
    # microbench reflects the accelerator regime (steps >> snapshots)
    batch = int(os.environ.get("BENCH_CKPT_BATCH", "2048"))
    # every 20 steps at ~40ms/step = a checkpoint per ~0.9s of compute,
    # still orders of magnitude denser than any production cadence;
    # longer reps keep per-rep timer noise small relative to the ratio
    steps = int(os.environ.get("BENCH_CKPT_STEPS", "60"))
    interval = int(os.environ.get("BENCH_CKPT_INTERVAL", "20"))
    repeats = max(1, int(os.environ.get("BENCH_CKPT_REPEATS", "5")))
    num_shards = int(os.environ.get("BENCH_CKPT_SHARDS", "4"))

    def build():
        data = mx.sym.Variable("data")
        net = data
        for i in range(layers):
            net = mx.sym.FullyConnected(net, num_hidden=hidden,
                                        name="fc%d" % i)
            net = mx.sym.Activation(net, act_type="relu")
        net = mx.sym.FullyConnected(net, num_hidden=16, name="head")
        sym = mx.sym.SoftmaxOutput(net, name="softmax")
        mod = mx.mod.Module(sym, data_names=("data",),
                            label_names=("softmax_label",))
        mod.bind(data_shapes=[("data", (batch, in_dim))],
                 label_shapes=[("softmax_label", (batch,))])
        mod.init_params(mx.initializer.Xavier())
        mod.init_optimizer(kvstore=None, optimizer="sgd",
                           optimizer_params=(("learning_rate", 0.01),
                                             ("momentum", 0.9)))
        return mod

    import numpy as _np
    rng = _np.random.RandomState(0)
    xb = mx.nd.array(rng.uniform(-1, 1, (batch, in_dim))
                     .astype(_np.float32))
    yb = mx.nd.array(rng.randint(0, 16, (batch,)).astype(_np.float32))
    data_batch = mx.io.DataBatch(data=[xb], label=[yb])

    workdir = tempfile.mkdtemp(prefix="mxtpu_ckpt_bench_")

    def timed_loop(mod, mode, prefix):
        """One timed step loop: mode None | 'async' | 'sync'. Returns
        (loop_s, drain_s, n_ckpts)."""
        handles = []
        t0 = time.perf_counter()
        for s in range(1, steps + 1):
            mod.fit_step(data_batch)
            if mode is not None and s % interval == 0:
                arrays, meta = mod.get_checkpoint_state()
                handles.append(ckpt.save_sharded(
                    prefix, s, arrays, num_shards, opt_meta=meta,
                    async_write=(mode == "async")))
        loop_s = time.perf_counter() - t0
        t1 = time.perf_counter()
        for h in handles:
            h.wait(120)
        return loop_s, time.perf_counter() - t1, len(handles)

    # one module per arm, warmed once; each repeat runs the three arms
    # BACK-TO-BACK and the overhead is the median of the per-repeat
    # paired ratios — an overhead this small (<3% gate) is otherwise
    # dominated by machine drift on a virtualized CPU: comparing arms
    # measured minutes apart (or min-of-one-arm vs min-of-another)
    # swings the ratio by more than the gate itself
    arms = {"base": (build(), None), "async": (build(), "async"),
            "sync": (build(), "sync")}
    for mod, _ in arms.values():
        for _ in range(3):   # warmup: compile the fused step
            mod.fit_step(data_batch)
    times = {tag: [] for tag in arms}
    drain_times, n_ckpts = [], 0
    for rep in range(repeats):
        for tag, (mod, mode) in arms.items():
            prefix = os.path.join(workdir, "%s-r%d" % (tag, rep))
            loop_s, drain_s, n = timed_loop(mod, mode, prefix)
            times[tag].append(loop_s)
            if tag == "async":
                drain_times.append(drain_s)
                n_ckpts += n
    async_drain_s = statistics.median(drain_times)
    shutil.rmtree(workdir, ignore_errors=True)

    overhead_pct = statistics.median(
        (a - b) / b * 100.0
        for a, b in zip(times["async"], times["base"]))
    sync_overhead_pct = statistics.median(
        (s - b) / b * 100.0
        for s, b in zip(times["sync"], times["base"]))
    base_s, async_s, sync_s = (min(times[t])
                               for t in ("base", "async", "sync"))
    return {
        "metric": "checkpoint_overhead",
        "value": round(overhead_pct, 3),
        "unit": "pct_train_loop_slowdown_async_vs_none",
        # the <3% gate: >= 1.0 passes (negative overhead = noise = pass)
        "vs_baseline": round(3.0 / overhead_pct, 3)
                       if overhead_pct > 0 else 99.0,
        "sync_overhead_pct": round(sync_overhead_pct, 3),
        "drain_tail_s": round(async_drain_s, 4),
        "base_step_ms": round(base_s / steps * 1e3, 3),
        "async_step_ms": round(async_s / steps * 1e3, 3),
        "sync_step_ms": round(sync_s / steps * 1e3, 3),
        "steps": steps, "interval": interval,
        "checkpoints_per_arm": n_ckpts, "num_shards": num_shards,
        "model": "MLP %d-%dx%d-16 bs%d" % (in_dim, hidden, layers, batch),
        "repeats": repeats,
    }


def run_progcache_config():
    """Persistent-program-cache warm-restart A/B (BENCH_MODEL=progcache):
    time-to-first-response of a freshly built serving ladder (Predictor +
    BucketCache.warm + one forward, the restart path) with the cache
    disabled (cold arm: every bucket is a fresh XLA compile) vs enabled
    over a pre-populated dir (warm arm: every bucket is a disk load).
    The arms run BACK-TO-BACK inside each repeat and value = the median
    of the per-repeat paired ratios (the checkpoint bench's drift-
    cancelling scheme — cold and warm measured minutes apart would swing
    by more than the gate). The ISSUE 8 gate is warm ttfr >= 3x faster,
    so vs_baseline = value / 3.0 (>= 1.0 passes)."""
    import shutil
    import tempfile

    import numpy as np
    from mxnet_tpu import predict
    from mxnet_tpu.serving.bucket_cache import BucketCache

    sym, params, in_dim, hidden, classes = _serving_model()
    buckets = tuple(int(b) for b in os.environ.get(
        "BENCH_PROGCACHE_BUCKETS", "33,36").split(","))
    repeats = max(1, int(os.environ.get("BENCH_PROGCACHE_REPEATS", "5")))
    smallest = buckets[0]
    rng = np.random.RandomState(3)
    x = rng.uniform(-1, 1, (buckets[-1], in_dim)).astype(np.float32)
    symbol_json = sym.tojson()

    cachedir = tempfile.mkdtemp(prefix="mxtpu_progcache_bench_")
    saved = {k: os.environ.get(k)
             for k in ("MXNET_PROGCACHE", "MXNET_PROGCACHE_DIR")}

    def set_env(warm):
        if warm:
            os.environ.pop("MXNET_PROGCACHE", None)
            os.environ["MXNET_PROGCACHE_DIR"] = cachedir
        else:
            os.environ["MXNET_PROGCACHE"] = "0"  # kill switch: true cold
            os.environ.pop("MXNET_PROGCACHE_DIR", None)

    def arm(warm):
        """Rebuild the whole ladder from scratch (fresh Predictor — fresh
        closures, so jax's in-process jit cache cannot leak programs
        between repeats) and serve one request. Returns (ttfr_s, build_s,
        first_out, stats)."""
        set_env(warm)
        t0 = time.perf_counter()
        base = predict.Predictor(symbol_json, params,
                                 {"data": (smallest, in_dim)})
        cache = BucketCache(base, buckets)
        cache.warm()
        t1 = time.perf_counter()
        out = cache.get(buckets[-1]).forward(data=x)[0].asnumpy()
        t2 = time.perf_counter()
        return t2 - t0, t1 - t0, out, cache.stats()

    try:
        arm(True)  # populate the cache once (not timed)
        cold_t, warm_t, cold_build, warm_build = [], [], [], []
        out_c = out_w = None
        for _ in range(repeats):
            tc, bc, out_c, st_c = arm(False)
            tw, bw, out_w, st_w = arm(True)
            assert st_c["disk_hits"] == 0, st_c
            assert st_w["compiles"] == 0, \
                "warm restart performed fresh compiles: %s" % st_w
            cold_t.append(tc)
            warm_t.append(tw)
            cold_build.append(bc)
            warm_build.append(bw)
        bitwise = bool((out_c == out_w).all())
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        shutil.rmtree(cachedir, ignore_errors=True)

    speedup = statistics.median(c / w for c, w in zip(cold_t, warm_t))
    return {
        "metric": "progcache_warm_restart",
        "value": round(speedup, 2),
        "unit": "x_time_to_first_response_cold_over_warm",
        # the >=3x gate: >= 1.0 passes
        "vs_baseline": round(speedup / 3.0, 3),
        "cold_ttfr_ms": round(statistics.median(cold_t) * 1e3, 1),
        "warm_ttfr_ms": round(statistics.median(warm_t) * 1e3, 1),
        # build_s is the ladder-construction part of ttfr: all of it is
        # compile time in the cold arm, disk-load time in the warm arm
        "cold_compile_s_total": round(statistics.median(cold_build), 4),
        "warm_load_s_total": round(statistics.median(warm_build), 4),
        "bitwise_identical": bitwise,
        "buckets": list(buckets),
        "model": "MLP %d-%d-%d" % (in_dim, hidden, classes),
        "repeats": repeats,
        "timing": "median of %d paired cold/warm ttfr ratios, arms "
                  "back-to-back per repeat" % repeats,
    }


def _decode_bench_model(v, d, n_layers, h, hkv, seed=3):
    """Tiny transformer LM for the decode benches (shared by the
    continuous-batching A/B and the paged-KV A/B so both arms of both
    benches speak about the same model)."""
    import numpy as _np

    from mxnet_tpu.serving.generate import DecodeModel, DecodeSpec

    f = 2 * d
    rng = _np.random.RandomState(seed)
    dkv = d // h * hkv
    params = {"embed_weight": (rng.randn(v, d) * 0.3).astype(_np.float32)}
    for i in range(n_layers):
        pre = "layer%d" % i
        params[pre + "_ln1_gamma"] = _np.ones(d, _np.float32)
        params[pre + "_ln1_beta"] = _np.zeros(d, _np.float32)
        for nm, shape in (("q", (d, d)), ("k", (dkv, d)), ("v", (dkv, d)),
                          ("o", (d, d))):
            params["%s_%s_weight" % (pre, nm)] = (
                rng.randn(*shape) * 0.2).astype(_np.float32)
        params[pre + "_ln2_gamma"] = _np.ones(d, _np.float32)
        params[pre + "_ln2_beta"] = _np.zeros(d, _np.float32)
        params[pre + "_ffn1_weight"] = (rng.randn(f, d) * 0.2).astype(
            _np.float32)
        params[pre + "_ffn1_bias"] = _np.zeros(f, _np.float32)
        params[pre + "_ffn2_weight"] = (rng.randn(d, f) * 0.2).astype(
            _np.float32)
        params[pre + "_ffn2_bias"] = _np.zeros(d, _np.float32)
    params["lnf_gamma"] = _np.ones(d, _np.float32)
    params["lnf_beta"] = _np.zeros(d, _np.float32)
    params["pred_weight"] = (rng.randn(v, d) * 0.2).astype(_np.float32)
    params["pred_bias"] = _np.zeros(v, _np.float32)
    return DecodeModel.from_arg_params(
        params, DecodeSpec(num_heads=h, num_kv_heads=hkv))


def run_decode_config():
    """Continuous-batching decode A/B (BENCH_MODEL=decode): the same
    generate workload (BENCH_DECODE_STREAMS prompts x BENCH_DECODE_NEW
    greedy tokens on a tiny transformer LM) through arm A = the
    DecodeScheduler (iteration-level batching over slot-allocated KV
    slabs, one fixed-shape decode program) and arm B = the naive serving
    baseline (one sequence at a time, FULL-context re-prefill for every
    token — what serving autoregression costs without a KV cache). Both
    arms share compiled programs built before timing; each repeat runs
    the arms BACK-TO-BACK and value = median of the per-repeat paired
    tokens/sec ratios (checkpoint-bench idiom: paired ratios, not
    min-vs-min, or CPU drift swings the number more than the gate).
    ISSUE 9 gate: >= 2x, so vs_baseline = value / 2.0."""
    import numpy as _np

    from mxnet_tpu import telemetry
    from mxnet_tpu.serving.generate import (DecodePrograms, DecodeScheduler,
                                            GenerateConfig)

    v = int(os.environ.get("BENCH_DECODE_VOCAB", "64"))
    d = int(os.environ.get("BENCH_DECODE_DIM", "32"))
    n_layers = int(os.environ.get("BENCH_DECODE_LAYERS", "2"))
    h, hkv = 4, 2
    n_streams = int(os.environ.get("BENCH_DECODE_STREAMS", "8"))
    prompt_len = int(os.environ.get("BENCH_DECODE_PROMPT", "6"))
    new_tokens = int(os.environ.get("BENCH_DECODE_NEW", "24"))
    slots = int(os.environ.get("BENCH_DECODE_SLOTS", "4"))
    repeats = max(1, int(os.environ.get("BENCH_DECODE_REPEATS", "5")))
    max_context = prompt_len + new_tokens + 2

    rng = _np.random.RandomState(3)
    model = _decode_bench_model(v, d, n_layers, h, hkv)
    prompts = [list(rng.randint(1, v, prompt_len)) for _ in range(n_streams)]

    # arm A: scheduler built + programs compiled ONCE before timing
    bucket = 1 << (prompt_len - 1).bit_length()
    sched = DecodeScheduler(model, GenerateConfig(
        num_heads=h, num_kv_heads=hkv, slots=slots,
        max_context=max_context, prefill_buckets=(bucket,),
        max_new_tokens=new_tokens, queue_depth=max(64, 2 * n_streams)))
    sched.start()
    occ_gauge = telemetry.registry.gauge("decode_batch_occupancy_pct")

    def arm_continuous():
        t0 = time.perf_counter()
        streams = [sched.submit(p, max_new_tokens=new_tokens)
                   for p in prompts]
        max_occ = 0.0
        while not all(s.done for s in streams):
            max_occ = max(max_occ, float(occ_gauge.value))
            time.sleep(0.001)
        outs = [s.tokens(timeout=300.0) for s in streams]
        dt = time.perf_counter() - t0
        return sum(len(o) for o in outs) / dt, outs, max_occ

    # arm B: naive full-context re-prefill per token, one stream at a
    # time; its ladder (built before timing) covers the longest context
    naive_buckets = tuple(sorted({bucket, 1 << (max_context - 1)
                                  .bit_length(), max_context}))
    naive = DecodePrograms(model, slots=1, capacity=max_context,
                           prefill_buckets=naive_buckets)

    def arm_naive():
        t0 = time.perf_counter()
        outs = []
        for p in prompts:
            ctx = list(p)
            toks = []
            for _ in range(new_tokens):
                last, _k, _v = naive.prefill(ctx)
                tok = int(_np.asarray(last).argmax())
                toks.append(tok)
                ctx.append(tok)
            outs.append(toks)
        dt = time.perf_counter() - t0
        return sum(len(o) for o in outs) / dt, outs

    # warmup both arms (compiles every program incl. naive's ladder)
    arm_continuous()
    arm_naive()

    cont_tps, naive_tps, ratios = [], [], []
    max_occ = 0.0
    cont_outs = naive_outs = None
    for _ in range(repeats):
        tps_a, cont_outs, occ = arm_continuous()
        tps_b, naive_outs = arm_naive()
        cont_tps.append(tps_a)
        naive_tps.append(tps_b)
        ratios.append(tps_a / tps_b)
        max_occ = max(max_occ, occ)
    st = sched.stats()
    sched.stop(drain=True)
    # greedy decode against the cache must reproduce the re-prefill
    # tokens exactly — the two arms ran the SAME workload or the ratio
    # is meaningless
    assert cont_outs == naive_outs, "arm outputs diverged"
    # steady-state mean occupancy, derived from the scheduler's own
    # counters: each decode step emits one token per active lane
    decode_toks = n_streams * (new_tokens - 1) * (repeats + 1)
    mean_occ = 100.0 * decode_toks / max(1, st["steps"] * slots)
    speedup = statistics.median(ratios)
    return {
        "metric": "decode_continuous_batching",
        "value": round(speedup, 3),
        "unit": "tokens_per_sec_vs_reprefill_baseline",
        # the >= 2x gate: >= 1.0 passes
        "vs_baseline": round(speedup / 2.0, 3),
        "cont_tokens_per_sec": round(statistics.median(cont_tps), 1),
        "naive_tokens_per_sec": round(statistics.median(naive_tps), 1),
        "max_occupancy_pct": round(max_occ, 1),
        "mean_occupancy_pct": round(mean_occ, 1),
        "streams": n_streams, "new_tokens": new_tokens, "slots": slots,
        "prompt_len": prompt_len, "compiles": st["compiles"],
        "decode_steps": st["steps"], "repeats": repeats,
        "model": "LM V%d D%d L%dx%dh ctx%d" % (v, d, n_layers, h,
                                               max_context),
    }


def run_decode_paged_config():
    """Paged-KV decode A/B (BENCH_MODEL=decode, second record, ISSUE 13):
    a shared-system-prompt workload (every prompt = the same system
    prefix + a unique tail) through arm P = the paged scheduler
    (MXNET_DECODE_PAGED: block pool + block tables + copy-on-write
    prefix reuse) and arm U = the unpaged scheduler at the SAME usable
    KV rows (unpaged slots x max_context == paged num_blocks x
    block_tokens; the paged arm additionally carries one trash block).
    Fixed memory is the whole point: unpaged co-residency is capped at
    slots = rows/max_context, while paged admission is governed by
    free blocks actually touched plus hash-shared prefix blocks, so the
    same bytes hold more live sequences AND skip re-prefilling the
    system prompt. Each repeat runs the arms BACK-TO-BACK (paired
    ratios, same idiom as the continuous-batching record) and the two
    arms' token streams are asserted identical every repeat — paged is
    a layout change, not a numerics change. value = median paired
    tokens/sec ratio; ISSUE 13 gate: >= 1.5x end-to-end, so
    vs_baseline = value / 1.5. prefix_savings_pct (gated >= 50% in the
    CI dryrun) rides along from the scheduler's own counters."""
    import numpy as _np

    from mxnet_tpu.serving.generate import DecodeScheduler, GenerateConfig

    v = int(os.environ.get("BENCH_DECODE_VOCAB", "64"))
    d = int(os.environ.get("BENCH_DECODE_DIM", "32"))
    n_layers = int(os.environ.get("BENCH_DECODE_LAYERS", "2"))
    h, hkv = 4, 2
    n_streams = int(os.environ.get("BENCH_PAGED_STREAMS", "24"))
    # 25 = 3 full blocks + 1 token into the boundary block, so sharers
    # exercise BOTH reuse modes: whole-block aliasing AND the CoW fork
    sys_len = int(os.environ.get("BENCH_PAGED_SYS", "25"))
    new_tokens = int(os.environ.get("BENCH_PAGED_NEW", "6"))
    block_tokens = int(os.environ.get("BENCH_PAGED_BLOCK_TOKENS", "8"))
    repeats = max(1, int(os.environ.get("BENCH_PAGED_REPEATS", "5")))
    # the server is provisioned for WORST-CASE contexts (128 tokens) but
    # this traffic touches ~32 rows/stream — the shape where unpaged
    # reservation (max_context rows per slot, used or not) wastes the
    # pool and paged reservation (blocks actually touched) does not
    max_context = int(os.environ.get("BENCH_PAGED_CTX", "128"))
    unpaged_slots = int(os.environ.get("BENCH_PAGED_UNPAGED_SLOTS", "2"))
    # byte-equivalent pools: 32 blocks x 8 tokens == 2 slots x 128 rows
    # (the paged arm carries one extra trash block on top)
    num_blocks = unpaged_slots * max_context // block_tokens
    paged_slots = int(os.environ.get("BENCH_PAGED_SLOTS", "12"))

    model = _decode_bench_model(v, d, n_layers, h, hkv)
    rng = _np.random.RandomState(7)
    sys_prompt = [int(t) for t in rng.randint(1, v, sys_len)]
    prompts = [sys_prompt + [1 + (i % (v - 2))] for i in range(n_streams)]
    prompt_len = len(prompts[0])
    # suffix bucket for sharers + one full bucket for the cold prompt
    buckets = (4, 1 << (prompt_len - 1).bit_length())

    def mk(paged):
        return DecodeScheduler(model, GenerateConfig(
            num_heads=h, num_kv_heads=hkv,
            slots=paged_slots if paged else unpaged_slots,
            max_context=max_context, prefill_buckets=buckets,
            max_new_tokens=new_tokens, queue_depth=max(64, 2 * n_streams),
            paged=paged, block_tokens=block_tokens,
            num_blocks=num_blocks, prefix_share=True))

    scheds = {True: mk(True), False: mk(False)}
    for s in scheds.values():
        s.start()

    def arm(paged):
        sched = scheds[paged]
        t0 = time.perf_counter()
        streams = [sched.submit(p, max_new_tokens=new_tokens)
                   for p in prompts]
        outs = [s.tokens(timeout=300.0) for s in streams]
        dt = time.perf_counter() - t0
        return sum(len(o) for o in outs) / dt, outs

    # warmup compiles both arms' program sets before timing
    arm(True)
    arm(False)

    paged_tps, unpaged_tps, ratios = [], [], []
    for _ in range(repeats):
        tps_p, paged_outs = arm(True)
        tps_u, unpaged_outs = arm(False)
        # the headline is only meaningful if the arms ran the SAME
        # computation: paged streams must be token-identical to unpaged
        assert paged_outs == unpaged_outs, "paged/unpaged arms diverged"
        paged_tps.append(tps_p)
        unpaged_tps.append(tps_u)
        ratios.append(tps_p / tps_u)
    st_p = scheds[True].stats()
    st_u = scheds[False].stats()
    for s in scheds.values():
        s.stop(drain=True)
    # cumulative over warmup + repeats: every run resubmits the same mix
    total_prompt = n_streams * prompt_len * (repeats + 1)
    savings_pct = 100.0 * st_p["prefix_tokens_saved"] / total_prompt
    speedup = statistics.median(ratios)
    return {
        "metric": "decode_paged_kv",
        "value": round(speedup, 3),
        "unit": "tokens_per_sec_vs_unpaged_same_kv_bytes",
        # the >= 1.5x gate: >= 1.0 passes
        "vs_baseline": round(speedup / 1.5, 3),
        "paged_tokens_per_sec": round(statistics.median(paged_tps), 1),
        "unpaged_tokens_per_sec": round(statistics.median(unpaged_tps), 1),
        "prefix_savings_pct": round(savings_pct, 1),
        "prefix_hits": st_p["prefix_hits"],
        "cow_forks": st_p["cow_forks"],
        "paged_compiles": st_p["compiles"],
        "unpaged_compiles": st_u["compiles"],
        "blocks": num_blocks, "block_tokens": block_tokens,
        "paged_slots": paged_slots, "unpaged_slots": unpaged_slots,
        "streams": n_streams, "new_tokens": new_tokens,
        "prompt_len": prompt_len, "repeats": repeats,
        "model": "LM V%d D%d L%dx%dh ctx%d" % (v, d, n_layers, h,
                                               max_context),
    }


def run_decode_spec_config():
    """Speculative-decode A/B (BENCH_MODEL=decode, third record, ISSUE
    16): the shared-system-prompt mix through arm S = the paged
    scheduler with MXNET_DECODE_SPEC (int8 self-draft, k drafted tokens
    per iteration, ONE fixed-shape verify) and arm V = the identical
    paged scheduler decoding one token per step. Both arms are greedy
    and their token streams are asserted IDENTICAL every repeat —
    speculation preserves the target model's output exactly; it only
    changes how many sequence positions one scheduler iteration
    commits. The headline is therefore tokens/STEP from the scheduler's
    own counters (step_tokens / seq_steps; vanilla is exactly 1.0 by
    construction), the dispatch-bound quantity the ISSUE gates >= 2x —
    wall-clock tokens/sec rides along as paired back-to-back ratios
    (same idiom as the other decode records) for the curious, but on a
    CPU-emulated tiny model the verify's k+1-wide matmuls cost nearly
    as much as the lanes they replace, so the time ratio is reported,
    not gated."""
    import numpy as _np

    from mxnet_tpu.serving.generate import DecodeScheduler, GenerateConfig

    v = int(os.environ.get("BENCH_DECODE_VOCAB", "64"))
    d = int(os.environ.get("BENCH_DECODE_DIM", "32"))
    n_layers = int(os.environ.get("BENCH_DECODE_LAYERS", "2"))
    h, hkv = 4, 2
    n_streams = int(os.environ.get("BENCH_SPEC_STREAMS", "12"))
    sys_len = int(os.environ.get("BENCH_SPEC_SYS", "25"))
    new_tokens = int(os.environ.get("BENCH_SPEC_NEW", "12"))
    k = int(os.environ.get("BENCH_SPEC_TOKENS", "4"))
    repeats = max(1, int(os.environ.get("BENCH_SPEC_REPEATS", "5")))
    block_tokens = int(os.environ.get("BENCH_SPEC_BLOCK_TOKENS", "8"))
    max_context = int(os.environ.get("BENCH_SPEC_CTX", "64"))
    slots = int(os.environ.get("BENCH_SPEC_SLOTS", "6"))

    model = _decode_bench_model(v, d, n_layers, h, hkv)
    rng = _np.random.RandomState(7)
    sys_prompt = [int(t) for t in rng.randint(1, v, sys_len)]
    prompts = [sys_prompt + [1 + (i % (v - 2))] for i in range(n_streams)]
    prompt_len = len(prompts[0])
    buckets = (4, 1 << (prompt_len - 1).bit_length())

    def mk(spec):
        return DecodeScheduler(model, GenerateConfig(
            num_heads=h, num_kv_heads=hkv, slots=slots,
            max_context=max_context, prefill_buckets=buckets,
            max_new_tokens=new_tokens, queue_depth=max(64, 2 * n_streams),
            paged=True, block_tokens=block_tokens, num_blocks=0,
            prefix_share=True, spec=spec, spec_tokens=k,
            spec_draft="int8"))

    scheds = {True: mk(True), False: mk(False)}
    for s in scheds.values():
        s.start()

    def arm(spec):
        sched = scheds[spec]
        t0 = time.perf_counter()
        streams = [sched.submit(p, max_new_tokens=new_tokens)
                   for p in prompts]
        outs = [s.tokens(timeout=300.0) for s in streams]
        dt = time.perf_counter() - t0
        return sum(len(o) for o in outs) / dt, outs

    # warmup compiles both program sets (spec: ladder + draft + verify)
    arm(True)
    arm(False)

    spec_tps, base_tps, ratios = [], [], []
    for _ in range(repeats):
        tps_s, spec_outs = arm(True)
        tps_v, base_outs = arm(False)
        # greedy arms must emit the same computation's tokens — the
        # rejection-sampling equivalence gate, asserted every repeat
        assert spec_outs == base_outs, "spec/vanilla greedy arms diverged"
        spec_tps.append(tps_s)
        base_tps.append(tps_v)
        ratios.append(tps_s / tps_v)
    st_s = scheds[True].stats()
    st_v = scheds[False].stats()
    for s in scheds.values():
        s.stop(drain=True)
    tokens_per_step = st_s["step_tokens"] / max(1, st_s["seq_steps"])
    accept_rate = st_s["accepted_tokens"] / max(1, st_s["drafted_tokens"])
    return {
        "metric": "decode_spec",
        "value": round(tokens_per_step, 3),
        "unit": "tokens_per_seq_step_vs_1_vanilla",
        # the >= 2x tokens/step gate: >= 1.0 passes
        "vs_baseline": round(tokens_per_step / 2.0, 3),
        "accept_rate": round(accept_rate, 3),
        "drafted_tokens": st_s["drafted_tokens"],
        "accepted_tokens": st_s["accepted_tokens"],
        "time_ratio_vs_vanilla": round(statistics.median(ratios), 3),
        "spec_tokens_per_sec": round(statistics.median(spec_tps), 1),
        "vanilla_tokens_per_sec": round(statistics.median(base_tps), 1),
        "spec_compiles": st_s["compiles"],
        "vanilla_compiles": st_v["compiles"],
        "spec_k": k, "streams": n_streams, "new_tokens": new_tokens,
        "prompt_len": prompt_len, "repeats": repeats,
        "model": "LM V%d D%d L%dx%dh ctx%d" % (v, d, n_layers, h,
                                               max_context),
    }


def run_quant_weight_config():
    """Quantized-weight decode A/B (BENCH_MODEL=quant, first record,
    ISSUE 14): the same generate workload through arm Q = the
    DecodeScheduler with int8 PTQ weights (per-channel symmetric, W8A8 —
    the matmuls run int8 x int8 on the MXU's double-rate path; scales
    ride as program ARGUMENTS so the program set is unchanged) and arm F
    = the identical f32 scheduler. Model sized so decode is
    matmul-bound (D=512, 4 layers — at toy widths the host scheduler
    loop would hide the kernel speedup). Each repeat runs the arms
    BACK-TO-BACK; value = median paired tokens/sec ratio. ISSUE 14
    gate: >= 1.3x, so vs_baseline = value / 1.3. Accuracy rides along:
    every quantized stream must agree with f32 greedy on its FIRST
    token, and the pooled longest-common-prefix fraction is recorded
    (greedy forks once an argmax flips; past-fork tokens are not
    comparable)."""
    from mxnet_tpu.serving.generate import DecodeScheduler, GenerateConfig

    v = int(os.environ.get("BENCH_QUANT_VOCAB", "64"))
    d = int(os.environ.get("BENCH_QUANT_DIM", "512"))
    n_layers = int(os.environ.get("BENCH_QUANT_LAYERS", "4"))
    h, hkv = 4, 2
    n_streams = int(os.environ.get("BENCH_QUANT_STREAMS", "8"))
    prompt_len = int(os.environ.get("BENCH_QUANT_PROMPT", "6"))
    new_tokens = int(os.environ.get("BENCH_QUANT_NEW", "16"))
    slots = int(os.environ.get("BENCH_QUANT_SLOTS", "8"))
    repeats = max(1, int(os.environ.get("BENCH_QUANT_REPEATS", "3")))
    max_context = prompt_len + new_tokens + 2

    import numpy as _np
    rng = _np.random.RandomState(3)
    model = _decode_bench_model(v, d, n_layers, h, hkv)
    prompts = [list(rng.randint(1, v, prompt_len)) for _ in range(n_streams)]
    bucket = 1 << (prompt_len - 1).bit_length()

    def mk(qw):
        return DecodeScheduler(model, GenerateConfig(
            num_heads=h, num_kv_heads=hkv, slots=slots,
            max_context=max_context, prefill_buckets=(bucket,),
            max_new_tokens=new_tokens, queue_depth=max(64, 2 * n_streams),
            quant_weights=qw))

    scheds = {"int8": mk("int8"), "f32": mk("")}
    for s in scheds.values():
        s.start()

    def arm(which):
        sched = scheds[which]
        t0 = time.perf_counter()
        streams = [sched.submit(p, max_new_tokens=new_tokens)
                   for p in prompts]
        outs = [s.tokens(timeout=600.0) for s in streams]
        dt = time.perf_counter() - t0
        return sum(len(o) for o in outs) / dt, outs

    arm("int8")     # warmup compiles both program sets before timing
    arm("f32")

    q_tps, f_tps, ratios = [], [], []
    q_outs = f_outs = None
    for _ in range(repeats):
        tps_q, q_outs = arm("int8")
        tps_f, f_outs = arm("f32")
        q_tps.append(tps_q)
        f_tps.append(tps_f)
        ratios.append(tps_q / tps_f)
    st_q = scheds["int8"].stats()
    st_f = scheds["f32"].stats()
    for s in scheds.values():
        s.stop(drain=True)
    # accuracy: first-token exact per stream + pooled LCP fraction
    agree = total = first = 0
    for q, r in zip(q_outs, f_outs):
        n = 0
        while n < len(q) and n < len(r) and q[n] == r[n]:
            n += 1
        agree += n
        total += len(r)
        first += int(n >= 1)
    assert first == n_streams, \
        "an int8-weight stream diverged from f32 at its FIRST token"
    speedup = statistics.median(ratios)
    return {
        "metric": "quant_weight_decode",
        "value": round(speedup, 3),
        "unit": "tokens_per_sec_int8_weights_vs_f32",
        # the >= 1.3x gate: >= 1.0 passes
        "vs_baseline": round(speedup / 1.3, 3),
        "int8_tokens_per_sec": round(statistics.median(q_tps), 1),
        "f32_tokens_per_sec": round(statistics.median(f_tps), 1),
        "first_token_agree": "%d/%d" % (first, n_streams),
        "token_lcp_frac": round(agree / total, 3),
        "int8_compiles": st_q["compiles"], "f32_compiles": st_f["compiles"],
        "quant_weights": st_q["quant_weights"],
        "streams": n_streams, "new_tokens": new_tokens, "slots": slots,
        "repeats": repeats,
        "model": "LM V%d D%d L%dx%dh ctx%d" % (v, d, n_layers, h,
                                               max_context),
        "timing": "median of %d paired int8/f32 tokens/sec ratios, arms "
                  "back-to-back per repeat" % repeats,
    }


def run_quant_kv_config():
    """Low-precision KV capacity A/B (BENCH_MODEL=quant, second record,
    ISSUE 14): the same oversubscribed paged workload through arm F =
    f32 KV slabs and arm Q = int8 KV slabs whose block pool is sized to
    the SAME byte budget (int8 data + the per-position f32 scale slabs
    it needs — the honest accounting). Capacity is the point: at equal
    bytes the int8 pool holds ~4x the blocks, so paged admission lets
    ~4x the sequences decode CO-RESIDENT. Co-residency is measured
    causally per arm (peak overlap of [first, last]-token intervals,
    same instrument as the CI decode dryrun). value = int8 peak / f32
    peak; ISSUE 14 gate: >= 2x at byte-equivalent pools, so
    vs_baseline = value / 2.0. prefix sharing is OFF in both arms so
    admission is governed by pool capacity alone."""
    import threading

    import numpy as _np
    from mxnet_tpu.serving.generate import DecodeScheduler, GenerateConfig

    v = int(os.environ.get("BENCH_QUANT_VOCAB", "64"))
    d = int(os.environ.get("BENCH_QUANT_KV_DIM", "32"))
    n_layers = int(os.environ.get("BENCH_QUANT_KV_LAYERS", "2"))
    h, hkv = 4, 2
    n_streams = int(os.environ.get("BENCH_QUANT_KV_STREAMS", "24"))
    prompt_len = int(os.environ.get("BENCH_QUANT_KV_PROMPT", "10"))
    new_tokens = int(os.environ.get("BENCH_QUANT_KV_NEW", "6"))
    block_tokens = int(os.environ.get("BENCH_QUANT_KV_BLOCK_TOKENS", "8"))
    f32_blocks = int(os.environ.get("BENCH_QUANT_KV_BLOCKS", "8"))
    slots = int(os.environ.get("BENCH_QUANT_KV_SLOTS", "16"))
    max_context = int(os.environ.get("BENCH_QUANT_KV_CTX", "32"))

    dkv = d // h * hkv
    # per-block bytes, both sides of the parity: f32 keeps K+V rows at 4
    # bytes/elem; int8 keeps them at 1 byte/elem PLUS one f32 scale per
    # position per slab (the quantization metadata is charged to the
    # pool, not hidden)
    bytes_f32 = n_layers * 2 * block_tokens * dkv * 4
    bytes_int8 = n_layers * 2 * block_tokens * (dkv * 1 + 4)
    int8_blocks = f32_blocks * bytes_f32 // bytes_int8

    model = _decode_bench_model(v, d, n_layers, h, hkv)
    rng = _np.random.RandomState(7)
    prompts = [list(rng.randint(1, v, prompt_len)) for _ in range(n_streams)]
    bucket = 1 << (prompt_len - 1).bit_length()

    def mk(kv_dtype, blocks):
        return DecodeScheduler(model, GenerateConfig(
            num_heads=h, num_kv_heads=hkv, slots=slots,
            max_context=max_context, prefill_buckets=(bucket,),
            max_new_tokens=new_tokens, queue_depth=max(64, 2 * n_streams),
            paged=True, block_tokens=block_tokens, num_blocks=blocks,
            prefix_share=False, kv_dtype=kv_dtype))

    def arm(kv_dtype, blocks):
        """Run the full mix, consuming every stream concurrently, and
        return (peak causal co-residency, token streams)."""
        sched = mk(kv_dtype, blocks)
        sched.start()
        streams = [sched.submit(p, max_new_tokens=new_tokens)
                   for p in prompts]
        outs = [[] for _ in streams]
        spans = [[None, None] for _ in streams]

        def consume(i):
            for tok in streams[i]:
                now = time.monotonic()
                outs[i].append(tok)
                if spans[i][0] is None:
                    spans[i][0] = now
                spans[i][1] = now

        threads = [threading.Thread(target=consume, args=(i,))
                   for i in range(len(streams))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(600)
        sched.stop(drain=True)
        events = []
        for lo, hi in spans:
            assert lo is not None, "a stream produced no tokens"
            events += [(lo, 1), (hi, -1)]
        live = peak = 0
        for _t, delta in sorted(events, key=lambda e: (e[0], -e[1])):
            live += delta
            peak = max(peak, live)
        return peak, outs

    peak_f, outs_f = arm("f32", f32_blocks)
    peak_q, outs_q = arm("int8", int8_blocks)
    # int8-KV numerics must not perturb the workload's greedy tokens at
    # this scale (measured property of the drift gate, not luck — the
    # per-position scales keep attention scores inside the f32 argmax)
    agree = sum(int(a == b) for a, b in zip(outs_q, outs_f))
    ratio = peak_q / max(1, peak_f)
    return {
        "metric": "quant_kv_capacity",
        "value": round(ratio, 2),
        "unit": "x_co_resident_sequences_int8_vs_f32_same_kv_bytes",
        # the >= 2x gate: >= 1.0 passes
        "vs_baseline": round(ratio / 2.0, 3),
        "f32_co_resident_peak": peak_f, "int8_co_resident_peak": peak_q,
        "f32_blocks": f32_blocks, "int8_blocks": int8_blocks,
        "pool_bytes_f32": f32_blocks * bytes_f32,
        "pool_bytes_int8": int8_blocks * bytes_int8,
        "block_bytes_ratio": round(bytes_f32 / bytes_int8, 2),
        "streams_token_equal": "%d/%d" % (agree, n_streams),
        "streams": n_streams, "block_tokens": block_tokens,
        "slots": slots, "new_tokens": new_tokens,
        "prompt_len": prompt_len,
        "model": "LM V%d D%d L%dx%dh ctx%d" % (v, d, n_layers, h,
                                               max_context),
    }


def run_zero_config():
    """ZeRO stage A/B on the transformer LM over a dp mesh
    (BENCH_MODEL=zero, ISSUE 15): the SAME model, init, and batch
    trained through Executor.make_train_step built once per
    MXNET_SHARDED_UPDATE stage 1 / 2 / 3 — stage is read at build time,
    so each arm is its own donated XLA program over the shared mesh.

    Methodology mirrors run_quant_weight_config: all arms built and
    warmed first, then each repeat times the arms back-to-back
    (interleaved, so drift hits every arm equally) and contributes ONE
    paired ratio per comparison; the reported ratios are the MEDIAN of
    those per-repeat pairs. Alongside step time, each arm records its
    bytes/chip: param/grad bounds from the stage's layout
    (collectives.stage_train_bytes) and optimizer-state bytes measured
    off the live sharded buffers (collectives.per_device_bytes).

    value = ZeRO-3 / ZeRO-1 step-time ratio. ISSUE 15 gate: <= 1.15x,
    so vs_baseline = 1.15 / value (>= 1.0 passes)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh
    import mxnet_tpu as mx
    from mxnet_tpu import models
    from mxnet_tpu.parallel import collectives as coll

    dp = int(os.environ.get("BENCH_ZERO_DP", "0")) or min(
        4, jax.device_count())
    if dp < 2:
        raise RuntimeError(
            "BENCH_MODEL=zero needs a >1-device data axis (have %d; on "
            "CPU set XLA_FLAGS=--xla_force_host_platform_device_count=8)"
            % jax.device_count())
    mesh = Mesh(np.array(jax.devices()[:dp]), ("data",))

    batch = int(os.environ.get("BENCH_ZERO_BATCH", "16"))
    seq = int(os.environ.get("BENCH_ZERO_SEQ", "512"))
    model_dim = int(os.environ.get("BENCH_ZERO_DIM", "1024"))
    num_layers = int(os.environ.get("BENCH_ZERO_LAYERS", "4"))
    vocab = int(os.environ.get("BENCH_ZERO_VOCAB", "8000"))
    iters = max(1, min(ITERS, 2048 // batch))
    repeats = REPEATS
    heads = model_dim // 128 if model_dim % 128 == 0 else max(
        1, model_dim // 64)
    cdtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    lr, momentum, wd = 0.05, 0.9, 1e-4

    def sgd_all(params, grads, moms):
        new_p, new_m = {}, {}
        for n in params:
            g = grads[n] + wd * params[n]
            m = momentum * moms[n] - lr * g
            new_p[n] = params[n] + m
            new_m[n] = m
        return new_p, new_m

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randint(0, vocab, (batch, seq)).astype(np.float32))
    y = jnp.asarray(rng.randint(0, vocab, (batch, seq)).astype(np.float32))
    feed = {"data": x, "softmax_label": y}

    def build(stage):
        """One arm: executor + fused train step built under the stage's
        env (sharded_stage reads MXNET_SHARDED_UPDATE at build time),
        identically initialized via the seeded global RNG."""
        prev = os.environ.get("MXNET_SHARDED_UPDATE")
        os.environ["MXNET_SHARDED_UPDATE"] = str(stage)
        try:
            sym = models.get_symbol(
                "transformer-lm", num_classes=vocab, num_layers=num_layers,
                num_heads=heads, model_dim=model_dim, ffn_dim=4 * model_dim,
                num_kv_heads=min(4, heads), scalar_loss=True)
            arg_names = sym.list_arguments()
            grad_req = {n: ("null" if n in ("data", "softmax_label")
                            else "write") for n in arg_names}
            exe = sym.simple_bind(
                mx.Context("tpu", 0) if jax.default_backend() != "cpu"
                else mx.cpu(), grad_req=grad_req, compute_dtype=cdtype,
                data=(batch, seq), softmax_label=(batch, seq))
            mx.random.seed(0)
            init = mx.initializer.Xavier(factor_type="in", magnitude=2.0)
            for name, arr in exe.arg_dict.items():
                if name not in ("data", "softmax_label"):
                    init(mx.initializer.InitDesc(name), arr)
            step = exe.make_train_step(sgd_all, mesh=mesh)
            params = {n: jnp.array(exe.arg_dict[n]._data, copy=True)
                      for n in arg_names
                      if n not in ("data", "softmax_label")}
            moms = {n: jnp.zeros_like(v) for n, v in params.items()}
            pb, gb = coll.stage_train_bytes(params, stage, dp)
            return {"stage": stage, "step": step, "params": params,
                    "moms": moms, "param_bytes": pb, "grad_bytes": gb}
        finally:
            if prev is None:
                os.environ.pop("MXNET_SHARDED_UPDATE", None)
            else:
                os.environ["MXNET_SHARDED_UPDATE"] = prev

    arms = [build(stage) for stage in (1, 2, 3)]

    def run_block(arm, n):
        outs = None
        for _ in range(n):
            outs, arm["params"], arm["moms"] = arm["step"](
                arm["params"], arm["moms"], feed)
        np.asarray(jnp.reshape(outs[0], (-1,))[0])  # readback sync

    for arm in arms:
        run_block(arm, WARMUP)
        # measured AFTER the first step commits state to the stage's
        # layout — live per-chip bytes, not the analytic bound
        arm["opt_bytes"] = coll.per_device_bytes(arm["moms"])

    times = {arm["stage"]: [] for arm in arms}
    for _ in range(repeats):
        for arm in arms:  # back-to-back inside the repeat
            t0 = time.perf_counter()
            run_block(arm, iters)
            times[arm["stage"]].append((time.perf_counter() - t0) / iters)
    z2_over_z1 = statistics.median(
        b / a for a, b in zip(times[1], times[2]))
    z3_over_z1 = statistics.median(
        b / a for a, b in zip(times[1], times[3]))

    rec = {
        "metric": "zero_sharded_train_dp%d" % dp,
        "value": round(z3_over_z1, 4),
        "unit": "zero3_over_zero1_step_time_ratio",
        # the <= 1.15x gate: >= 1.0 passes
        "vs_baseline": round(1.15 / z3_over_z1, 3),
        "z2_over_z1_step_time": round(z2_over_z1, 4),
        "z3_over_z1_step_time": round(z3_over_z1, 4),
        "dp": dp,
        "model": "decoder LM L=%d d_model=%d heads=%d vocab=%d bs%d seq%d"
                 % (num_layers, model_dim, heads, vocab, batch, seq),
        "compute_dtype": cdtype,
        "timing": "interleaved arms, median of %d paired repeats x %d "
                  "steps, readback sync" % (repeats, iters),
        "gate": "ZeRO-3 step time <= 1.15x ZeRO-1 (ISSUE 15)",
    }
    for arm in arms:
        rec["zero%d" % arm["stage"]] = {
            "step_time_ms": round(
                statistics.median(times[arm["stage"]]) * 1e3, 3),
            "param_bytes_per_chip": arm["param_bytes"],
            "grad_bytes_per_chip": arm["grad_bytes"],
            "opt_bytes_per_chip": arm["opt_bytes"],
        }
    return rec




def run_conv_config(batch=None, iters=None, repeats=None):
    """Per-layer conv-stack layout microbench (BENCH_MODEL=conv,
    ISSUE 20): each representative ResNet-50 conv shape runs fwd+bwd
    under BOTH MXNET_CONV_LAYOUT arms, interleaved inside every repeat
    so the arms share thermal/clock conditions, and the record carries
    the per-shape PAIRED ratio (nchw_time / nhwc_time — > 1.0 means the
    NHWC island wins) with outputs and gradients allclose-asserted
    between arms. One JSON line per shape plus a stack headline."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    import mxnet_tpu as mx

    batch = batch or int(os.environ.get("BENCH_CONV_BATCH", min(BATCH, 64)))
    iters = iters or max(3, min(ITERS, 20))
    repeats = repeats or REPEATS
    # representative ResNet-50 @224 conv shapes, one per family: the
    # s2d-eligible stem, each stage's 3x3, and the bandwidth-bound 1x1s
    shapes = [
        ("stem7x7", 3, 224, 64, (7, 7), (2, 2), (3, 3)),
        ("s1_1x1", 64, 56, 64, (1, 1), (1, 1), (0, 0)),
        ("s1_3x3", 64, 56, 64, (3, 3), (1, 1), (1, 1)),
        ("s1_expand", 64, 56, 256, (1, 1), (1, 1), (0, 0)),
        ("s2_3x3", 128, 28, 128, (3, 3), (1, 1), (1, 1)),
        ("s3_3x3", 256, 14, 256, (3, 3), (1, 1), (1, 1)),
        ("s4_3x3", 512, 7, 512, (3, 3), (1, 1), (1, 1)),
    ]

    def build(layout, cin, hw, k, kernel, stride, pad):
        prev = os.environ.get("MXNET_CONV_LAYOUT")
        os.environ["MXNET_CONV_LAYOUT"] = layout
        try:
            data = mx.sym.Variable("data")
            sym = mx.sym.Convolution(data, kernel=kernel, stride=stride,
                                     pad=pad, num_filter=k, no_bias=True,
                                     name="conv")
            f = sym.build_eval()
        finally:
            if prev is None:
                os.environ.pop("MXNET_CONV_LAYOUT", None)
            else:
                os.environ["MXNET_CONV_LAYOUT"] = prev

        def loss(args):
            outs, _ = f(args, {}, True, jax.random.PRNGKey(0))
            return sum(jnp.sum(o * o) for o in outs)

        return jax.jit(jax.value_and_grad(loss))

    rows = []
    for name, cin, hw, k, kernel, stride, pad in shapes:
        rng = np.random.RandomState(0)
        args = {
            "data": jnp.asarray(rng.uniform(-1, 1, (batch, cin, hw, hw))
                                .astype(np.float32)),
            "conv_weight": jnp.asarray(
                rng.uniform(-0.1, 0.1, (k, cin) + tuple(kernel))
                .astype(np.float32)),
        }
        arms = {lay: build(lay, cin, hw, k, kernel, stride, pad)
                for lay in ("nchw", "nhwc")}
        # parity gate before timing: same loss, same grads
        vals = {lay: arms[lay](args) for lay in arms}
        np.testing.assert_allclose(
            float(vals["nchw"][0]), float(vals["nhwc"][0]),
            rtol=1e-4, err_msg=name)
        for key_ in vals["nchw"][1]:
            np.testing.assert_allclose(
                np.asarray(vals["nchw"][1][key_]),
                np.asarray(vals["nhwc"][1][key_]),
                rtol=5e-3, atol=5e-3, err_msg="%s %s" % (name, key_))

        def run_block(fn_, n):
            v = g = None
            for _ in range(n):
                v, g = fn_(args)
            np.asarray(jnp.reshape(next(iter(g.values())), (-1,))[0])

        for lay in arms:
            run_block(arms[lay], WARMUP)
        times = {"nchw": [], "nhwc": []}
        for _ in range(repeats):
            for lay in ("nchw", "nhwc"):  # back-to-back inside the repeat
                t0 = time.perf_counter()
                run_block(arms[lay], iters)
                times[lay].append((time.perf_counter() - t0) / iters)
        ratio = statistics.median(
            a / b for a, b in zip(times["nchw"], times["nhwc"]))
        rows.append({
            "metric": "conv_layout_r50_%s_bs%d" % (name, batch),
            "value": round(ratio, 4),
            "unit": "nchw_over_nhwc_fwdbwd_time_ratio",
            "shape": "Cin=%d HW=%d K=%d k=%s s=%s" % (
                cin, hw, k, kernel, stride),
            "nchw_ms": round(statistics.median(times["nchw"]) * 1e3, 3),
            "nhwc_ms": round(statistics.median(times["nhwc"]) * 1e3, 3),
            "timing": "interleaved arms, median of %d paired repeats x "
                      "%d fwd+bwd steps, allclose-gated" % (repeats, iters),
        })
        _emit(rows[-1])
    import math
    geo = math.exp(sum(math.log(r["value"]) for r in rows) / len(rows))
    head = {
        "metric": "conv_layout_stack_bs%d" % batch,
        "value": round(geo, 4),
        "unit": "geomean_nchw_over_nhwc_fwdbwd_time_ratio",
        "shapes": len(rows),
        "gate": "NHWC island >= NCHW per shape on TPU (ISSUE 20); "
                "> 1.0 means channels-last wins",
    }
    _emit(head)
    return head


def main():
    try:
        _main()
    finally:
        if _EMIT_LOG:
            _emit_selfcheck()


def _main():
    which = os.environ.get("BENCH_MODEL", "both")
    if which == "serving":
        _emit(run_serving_config())
        return
    if which == "serving_http":
        _emit(run_serving_http_config())
        return
    if which == "engine":
        _emit(run_engine_config())
        return
    if which == "checkpoint":
        _emit(run_checkpoint_config())
        return
    if which == "progcache":
        _emit(run_progcache_config())
        return
    if which == "decode":
        _emit(run_decode_config())
        _emit(run_decode_paged_config())
        _emit(run_decode_spec_config())
        return
    if which == "quant":
        _emit(run_quant_weight_config())
        _emit(run_quant_kv_config())
        return
    if which == "zero":
        _emit(run_zero_config())
        return
    if which == "conv":
        run_conv_config()
        return
    if os.environ.get("BENCH_LM_SWEEP"):
        # transformer (bs, seq) MFU table (docs/perf.md); one JSON line
        # per config, headline (bs32, seq2048) re-printed last
        rows = []
        for batch, seq in [(8, 2048), (16, 2048), (32, 2048),
                           (8, 4096), (16, 4096), (32, 1024)]:
            try:
                rec = run_transformer_config(batch=batch, seq=seq,
                                             repeats=3)
            except Exception as e:
                rec = {"metric": "transformer_lm_train_mfu_bs%d_seq%d"
                                 % (batch, seq),
                       "error": "%s: %s" % (type(e).__name__, e)}
            rows.append(rec)
            _emit(rec)
        ok = [r for r in rows if "error" not in r]
        head = next((r for r in ok
                     if r.get("batch") == 32 and r.get("seq") == 2048),
                    ok[0] if ok else rows[-1])
        _emit(head, final_repeat=True)
        return
    if os.environ.get("BENCH_SWEEP"):
        # MFU-vs-batch table (one JSON line per config; the HEADLINE
        # config's line is re-printed LAST so the driver's
        # read-the-last-line contract records the bs128 default, not
        # whichever sweep row happened to finish last). bs1024 needs
        # segmented remat to fit HBM (docs/note_memory.md).
        sweep = [(32, False), (128, False), (256, False), (512, False),
                 (1024, True)]
        rows = []
        for batch, remat in sweep:
            iters = max(10, min(ITERS, 8192 // batch))
            try:
                rec = run_config(batch, iters=iters, repeats=3, remat=remat)
            except Exception as e:  # OOM etc.: record, keep sweeping
                rec = {"metric": "resnet50_train_mfu_bs%d%s" % (
                           batch, "_remat" if remat else ""),
                       "batch": batch,
                       "error": "%s: %s" % (type(e).__name__, e)}
            rows.append(rec)
            _emit(rec)
        # headline = the default-BATCH row, matched on the recorded batch
        # field (metric-name suffix matching broke for _remat rows and
        # for BENCH_BATCH values outside the sweep); else the first
        # healthy row
        ok = [r for r in rows if "error" not in r]
        headline = next((r for r in ok if r.get("batch") == BATCH),
                        ok[0] if ok else rows[-1])
        if headline.get("batch") != BATCH:
            print("bench: BENCH_BATCH=%d has no healthy sweep row; "
                  "headline falls back to bs%s" % (BATCH, headline.get("batch")),
                  file=sys.stderr)
        _emit(headline, final_repeat=True)
        return
    if which == "resnet":
        _emit(run_config(BATCH))
        return
    if which == "transformer":
        _emit(run_transformer_config())
        return
    # default: BOTH workloads — ONE line per metric. The ResNet record gets
    # its own line; the driver-facing final line is the transformer-LM
    # headline (the compute-bound, north-star-class number on this chip)
    # with the ResNet record embedded alongside. The LM record is NOT also
    # printed bare: that duplicated the metric in the captured tail.
    resnet = run_config(BATCH)
    _emit(resnet)
    final = dict(run_transformer_config())
    final["resnet50"] = {k: resnet[k] for k in
                         ("metric", "value", "unit", "vs_baseline",
                          "img_per_sec", "step_time_ms") if k in resnet}
    _emit(final)


if __name__ == "__main__":
    main()
