#!/usr/bin/env bash
# CI entry point (reference: tests/travis/run_test.sh + Jenkinsfile matrix,
# SURVEY §2.7/§4.7). Stages mirror the reference's: build native libs,
# unit suite on the virtual 8-device CPU mesh, multi-chip dry-run compile,
# example smoke runs (included in the suite), lint-lite.
#
# Tiers (reference unittest-vs-nightly split, SURVEY §4):
#   ci/run_tests.sh          quick tier: everything except the exhaustive
#                            registry sweeps (completeness gates included)
#   ci/run_tests.sh --full   nightly tier: the whole suite
set -euo pipefail
cd "$(dirname "$0")/.."

TIER="quick"
if [[ "${1:-}" == "--full" ]]; then
    TIER="full"
fi

echo "== stage 1: native build =="
make -C native -j"$(nproc)"

echo "== stage 2: unit + integration suite ($TIER tier, virtual 8-device CPU mesh) =="
if [[ "$TIER" == "quick" ]]; then
    python -m pytest tests/ -q -m "not slow"
else
    python -m pytest tests/ -q
fi

echo "== stage 3: parallel tests (8-device CPU simulation, -m parallel) =="
# Dedicated pass over the multi-device tests (ZeRO-1 sharded update,
# sharding round-trips, kvstore sharded push/pull). conftest.py forces the
# 8-virtual-device CPU mesh; the explicit env makes the stage independently
# reproducible: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8
python -m pytest tests/ -q -m parallel

echo "== stage 4: multi-chip sharding dry-run (8 virtual devices) =="
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

echo "== stage 5: serving tests (dynamic batching + bucketed compile cache) =="
# Dedicated pass over the inference-server suite: concurrency-sensitive
# (batch former windows, deadlines, engine-dispatch pipelining), so it gets
# its own stage where a hang or flake is attributable. Then the end-to-end
# dry-run: concurrent clients -> occupancy/cache-hit assertions.
JAX_PLATFORMS=cpu python -m pytest tests/test_serving.py tests/test_serving_generate.py tests/test_paged_decode.py tests/test_quant.py tests/test_spec_decode.py tests/test_http_frontend.py -q
# Both end-to-end dry-runs below run with the engine happens-before
# sanitizer ON: the serving/decode dispatch paths must produce ZERO race
# reports (docs/concurrency.md sanitizer section).
JAX_PLATFORMS=cpu MXNET_ENGINE_SANITIZER=1 python -c "
import __graft_entry__ as g; g.dryrun_serving()
from mxnet_tpu import engine
assert engine.sanitizer_reports() == [], engine.sanitizer_reports()
print('sanitizer: 0 reports (serving)')"
# Continuous-batching decode gate: staggered generate streams must emit
# token streams identical to sequential generation, with fresh compiles
# bounded by the fixed program set and a clean mid-stream drain. Includes
# the paged-KV wave (ISSUE 13): shared-prefix streams at fixed KV bytes
# must run >= 2x the unpaged slot-equivalent co-residency, save >= 50% of
# prefill tokens via shared blocks, stay bitwise-identical to the unpaged
# arm, and add zero steady-state compiles — all sanitizer-clean.
# The compile witness rides along (ISSUE 18): the warm paged wave flips
# witness.steady_state() and must record ZERO fresh compiles after it.
JAX_PLATFORMS=cpu MXNET_ENGINE_SANITIZER=1 MXNET_COMPILE_WITNESS=1 python -c "
import __graft_entry__ as g; g.dryrun_decode()
from mxnet_tpu import engine
assert engine.sanitizer_reports() == [], engine.sanitizer_reports()
print('sanitizer: 0 reports (decode)')"
# Warm-restart gate (persistent progcache): a cold process populates the
# cache and tunes its ladder, then a SECOND process over the same cache
# dir must serve the same traffic with 0 fresh bucket compiles (ladder
# disk-loaded before traffic) and bitwise-identical outputs.
JAX_PLATFORMS=cpu python -c "import __graft_entry__ as g; g.dryrun_progcache()"
# Trace-and-fuse gate (MXNET_ENGINE_FUSE): the same 8 identically-seeded
# train steps run eager, captured/replayed, and captured+fused — final
# weights must be BITWISE identical across all three; the fused arms run
# under MXNET_ENGINE_SANITIZER=1 with zero reports; and a warm process
# over the same progcache dir must disk-load the fused executable with
# zero fresh fuse compiles. A second pass repeats replay/fused/warm at
# ZeRO stage 3 (ISSUE 20): the sharded step must STAGE (fused_runs > 0,
# no bail), match replay bitwise, and warm-restart from the progcache
# with 0 fresh fused compiles under MXNET_COMPILE_WITNESS=1.
JAX_PLATFORMS=cpu python -c "import __graft_entry__ as g; g.dryrun_fuse()"
# Quantized-inference gate (ISSUE 14): int8-weight + int8-KV paged decode
# streams must be bitwise-identical to sequential quantized generation and
# track the f32 arm's greedy tokens (first-token exact, LCP >= 60%) inside
# the unchanged paged program bound; the MLP serving pair must hit >= 99%
# top-5 agreement vs f32 with a warm restart disk-loading the quantized
# programs at ZERO fresh compiles — all sanitizer-clean.
JAX_PLATFORMS=cpu MXNET_ENGINE_SANITIZER=1 python -c "
import __graft_entry__ as g; g.dryrun_quant()
from mxnet_tpu import engine
assert engine.sanitizer_reports() == [], engine.sanitizer_reports()
print('sanitizer: 0 reports (quant)')"
# Speculative-decoding gate (ISSUE 16): staggered greedy spec streams
# (int8 self-draft, k=4, one fixed-shape verify) must be token-identical
# to vanilla decode inside ladder+2 programs at >= 1.5 tokens committed
# per scheduler step; sampled streams must match vanilla's per-position
# token distributions over 160 fixed seeds (rejection-sampling
# equivalence) and reproduce bitwise under the same seed; a warm restart
# over the same progcache dir serves identical streams with ZERO fresh
# compiles — all sanitizer-clean.
JAX_PLATFORMS=cpu MXNET_ENGINE_SANITIZER=1 python -c "
import __graft_entry__ as g; g.dryrun_spec()
from mxnet_tpu import engine
assert engine.sanitizer_reports() == [], engine.sanitizer_reports()
print('sanitizer: 0 reports (spec)')"
# HTTP front-end gate (ISSUE 17): a subprocess serves the predict +
# generate front-ends; concurrent HTTP clients, a 2x overload burst that
# must shed FAST with 429s (no queue-and-expire timeouts), a SIGTERM
# mid-stream drain that drops zero tokens, and a warm restart over the
# same progcache dir at ZERO fresh compiles with identical greedy
# streams. MXNET_ENGINE_SANITIZER=1 is inherited by the serve arms, and
# so is MXNET_COMPILE_WITNESS=1: the warm serve arm flips
# witness.steady_state() once ready and must report 0 compiles after it.
JAX_PLATFORMS=cpu MXNET_ENGINE_SANITIZER=1 MXNET_COMPILE_WITNESS=1 \
    python -c "import __graft_entry__ as g; g.dryrun_http()"
# Tracing + flight-recorder gate (ISSUE 19): traced traffic must leave
# assembled span trees addressable by request id with the same trace_id
# surfacing as an OpenMetrics exemplar on the latency histogram; one
# forced deadline miss must write EXACTLY one diagnostic bundle carrying
# the victim's queued span and bump
# flight_bundles_total{trigger="deadline_miss"}.
JAX_PLATFORMS=cpu MXNET_ENGINE_SANITIZER=1 \
    python -c "import __graft_entry__ as g; g.dryrun_flight()"

echo "== stage 6: import hygiene =="
python - <<'EOF'
import os
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import mxnet_tpu as mx
assert mx.libinfo.find_lib_path()
print("import OK; ops:", len(mx.ops.registry.OP_REGISTRY))
EOF

echo "== stage 7: static analysis (lock-order / engine / purity / progcache-io / racecheck / compilesurface) =="
# Pure-AST gate, independent of the pytest tiers: the shipped tree must
# produce no findings beyond ci/analysis_baseline.json (each baselined
# entry carries a written justification). Fails on ANY new finding.
# Budget: the full-tree pass must finish inside 15s (docs/static_analysis.md).
timeout -k 5 15 env JAX_PLATFORMS=cpu python -m mxnet_tpu.analysis --fail-on-new
# Self-check: the known-bad fixtures must trip the gate (a silently
# lobotomized analyzer would otherwise pass CI forever).
for bad in abba_deadlock undeclared_mutable impure_jit telemetry_in_jit \
        capture_unstable raw_write_progcache fuse_ineligible \
        undeclared_var_access unfenced_host_read var_use_after_delete \
        weight_closure stray_jit donated_arg_reuse undeclared_budget; do
    if JAX_PLATFORMS=cpu python -m mxnet_tpu.analysis \
            --root "tests/fixtures/analysis/${bad}.py" \
            --baseline none --fail-on-new >/dev/null 2>&1; then
        echo "analysis self-check FAILED: ${bad}.py not flagged" >&2
        exit 1
    fi
done
JAX_PLATFORMS=cpu python -m mxnet_tpu.analysis \
    --root tests/fixtures/analysis/clean_locks.py --baseline none --fail-on-new

echo "== stage 8: fault-injection dry-run (kill-a-rank recovery, CPU) =="
# Elastic-training gate: under a deterministic MXNET_FAULT_PLAN a
# supervised run loses rank 1 mid-training, restores the last committed
# async sharded checkpoint and replays to BIT-IDENTICAL weights; the
# dp=4 -> 2 -> 4 resharding round-trip is checked bitwise in the same
# entry point (docs/fault_tolerance.md).
# The sanitizer rides along: fault injection + recovery must not surface
# any undeclared access — races and injected faults are distinct defects.
JAX_PLATFORMS=cpu MXNET_FAULT_PLAN="kill_rank rank=1 step=5" \
    MXNET_ENGINE_SANITIZER=1 python -c "
import __graft_entry__ as g; g.dryrun_fault_tolerance()
from mxnet_tpu import engine
assert engine.sanitizer_reports() == [], engine.sanitizer_reports()
print('sanitizer: 0 reports (fault dryrun)')"
# Composed dp×pp gate (ISSUE 15): ZeRO-sharded data parallelism (data=4)
# composed with 1f1b pipeline stages (pipe=2) in one shard_map program,
# run under TrainingSupervisor with the same kill-a-rank plan — replay
# must be BITWISE identical to an uninterrupted run, and the final
# checkpoint must reshard dp=4 -> 2 -> 4 bitwise.
JAX_PLATFORMS=cpu MXNET_FAULT_PLAN="kill_rank rank=1 step=5" \
    MXNET_ENGINE_SANITIZER=1 python -c "
import __graft_entry__ as g; g.dryrun_composed_fault()
from mxnet_tpu import engine
assert engine.sanitizer_reports() == [], engine.sanitizer_reports()
print('sanitizer: 0 reports (composed dp x pp fault dryrun)')"

echo "ALL CI STAGES PASSED"
