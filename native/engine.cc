// Native host-side dependency engine.
//
// TPU-native equivalent of the reference's dependency scheduler
// (include/mxnet/engine.h:75-250, src/engine/threaded_engine.{h,cc},
// threaded_engine_perdevice.cc, naive_engine.cc — SURVEY §2.1 #1-5).
//
// Scope is deliberately narrower than the reference's: on TPU, *device*
// dependency scheduling belongs to XLA's async runtime (SURVEY §7
// translation table), so this engine only orders the host-side work XLA
// cannot see — checkpoint/file IO, data-pipeline stages, parameter-server
// style updates, metric sinks. The semantics are the reference's exactly:
// operations are closures tagged with const (read) and mutable (write)
// variable sets; conflicting ops serialize in push order, everything else
// runs concurrently on a worker pool.
//
// Dependency discipline (mirrors ThreadedVar's
// AppendRead/WriteDependency + CompleteRead/WriteDependency,
// threaded_engine.h:93-195): each Var keeps a FIFO of pending (op,is_write)
// entries plus counts of running readers / an active writer. Queue heads are
// granted when compatible; an op dispatches when ALL its vars have granted
// (atomic pending counter, the OprBlock wait count of threaded_engine.h:44).
//
// Engine types (MXNET_ENGINE_TYPE, src/engine/engine.cc:13-38):
//   0 = ThreadedEngine (worker pool, default)
//   1 = NaiveEngine    (synchronous execution in Push, for debugging —
//                       threaded_engine.h:326-338 tells users to do this)
//
// Profiling: every executed op records {name, thread, start_us, dur_us},
// dumpable as a chrome://tracing JSON via mxe_dump_profile — the analogue of
// src/engine/profiler.{h,cc} OprExecStat/DevStat.
//
// C ABI only (ctypes boundary, like include/mxnet/c_api.h).

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <functional>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

int64_t now_us() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             Clock::now().time_since_epoch())
      .count();
}

// Completion callback handed to async fns (CallbackOnComplete,
// include/mxnet/engine.h:37-54).
struct Opr;
class Engine;

typedef void (*OprFn)(void* param, void* on_complete);
typedef void (*DeleteFn)(void* param);

struct VarEntry {
  Opr* opr;
  bool is_write;
};

// ThreadedVar analogue (threaded_engine.h:93-195).
struct Var {
  std::deque<VarEntry> queue;
  int running_reads = 0;
  bool running_write = false;
};

struct Opr {
  OprFn fn;
  void* param;
  DeleteFn del;
  std::vector<int64_t> const_vars;
  std::vector<int64_t> mut_vars;
  std::atomic<int> pending{0};  // OprBlock::wait (threaded_engine.h:44-71)
  int priority = 0;
  std::string name;
  bool async = false;
  int64_t delete_var = -1;  // var to erase after completion (DeleteVariable)
  Engine* engine = nullptr;
  // NaiveEngine async support: completion just signals Push's wait.
  bool naive = false;
  std::mutex* naive_mu = nullptr;
  std::condition_variable* naive_cv = nullptr;
  bool* naive_done = nullptr;
};

struct ProfRecord {
  std::string name;
  uint32_t tid;
  int64_t start_us;
  int64_t dur_us;
};

struct ReadyCmp {
  bool operator()(Opr* a, Opr* b) const { return a->priority < b->priority; }
};

class Engine {
 public:
  Engine(int num_workers, int type) : type_(type) {
    if (num_workers <= 0) {
      unsigned hc = std::thread::hardware_concurrency();
      num_workers = hc > 2 ? static_cast<int>(hc / 2) : 2;
      if (num_workers > 8) num_workers = 8;
    }
    if (type_ == 0) {
      for (int i = 0; i < num_workers; ++i) {
        workers_.emplace_back([this, i] { WorkerLoop(i); });
      }
    }
  }

  ~Engine() {
    WaitForAll();
    {
      std::unique_lock<std::mutex> lk(ready_mu_);
      shutdown_ = true;
    }
    ready_cv_.notify_all();
    for (auto& t : workers_) t.join();
  }

  int64_t NewVar() {
    std::lock_guard<std::mutex> lk(mu_);
    int64_t id = next_var_++;
    vars_.emplace(id, Var{});
    return id;
  }

  // DeleteVariable semantics (engine.h:141-151): deletion is itself a write
  // op, so it happens after all pending uses.
  void DeleteVar(int64_t v) {
    Push(
        [](void*, void*) {}, nullptr, nullptr, nullptr, 0, &v, 1, 0,
        "delete_var", /*async=*/false, /*mark_delete=*/true);
  }

  void Push(OprFn fn, void* param, DeleteFn del, const int64_t* cvars,
            int ncvar, const int64_t* mvars, int nmvar, int priority,
            const char* name, bool async, bool mark_delete = false) {
    if (type_ == 1) {  // NaiveEngine: run inline (naive_engine.cc:16-191)
      int64_t t0 = now_us();
      if (async) {
        // synchronous semantics: block until the op's on_complete fires
        std::mutex m;
        std::condition_variable cv;
        bool done = false;
        Opr stack_op;
        stack_op.naive = true;
        stack_op.naive_mu = &m;
        stack_op.naive_cv = &cv;
        stack_op.naive_done = &done;
        fn(param, &stack_op);
        std::unique_lock<std::mutex> lk(m);
        cv.wait(lk, [&] { return done; });
      } else {
        fn(param, nullptr);  // sync fns ignore on_complete
      }
      Record(name ? name : "op", 0, t0);
      if (del) del(param);
      if (mark_delete) {
        std::lock_guard<std::mutex> lk(mu_);
        vars_.erase(mvars[0]);
      }
      return;
    }
    Opr* op = new Opr;
    op->fn = fn;
    op->param = param;
    op->del = del;
    op->priority = priority;
    op->name = name ? name : "op";
    op->engine = this;
    op->const_vars.assign(cvars, cvars + ncvar);
    op->mut_vars.assign(mvars, mvars + nmvar);
    op->async = async;
    if (mark_delete) op->delete_var = mvars[0];
    pending_total_.fetch_add(1);

    std::lock_guard<std::mutex> lk(mu_);
    op->pending.store(ncvar + nmvar + 1);
    for (int64_t v : op->const_vars) Append(v, op, false);
    for (int64_t v : op->mut_vars) Append(v, op, true);
    if (op->pending.fetch_sub(1) == 1) Enqueue(op);
  }

  // Called by async fns' completion, and by the worker for sync fns
  // (ThreadedEngine::OnComplete, threaded_engine.cc:314).
  void OnComplete(Opr* op) {
    if (op->naive) {  // stack-allocated op from the NaiveEngine async path
      std::lock_guard<std::mutex> lk(*op->naive_mu);
      *op->naive_done = true;
      op->naive_cv->notify_all();
      return;
    }
    {
      std::lock_guard<std::mutex> lk(mu_);
      for (int64_t v : op->const_vars) CompleteRead(v);
      for (int64_t v : op->mut_vars) CompleteWrite(v);
      if (op->delete_var >= 0) vars_.erase(op->delete_var);
    }
    if (op->del) op->del(op->param);
    delete op;
    if (pending_total_.fetch_sub(1) == 1) {
      std::lock_guard<std::mutex> lk(wait_mu_);
      wait_cv_.notify_all();
    }
  }

  void WaitForVar(int64_t v) {
    // WaitForVar (engine.h:183-190): push a read op and block on it.
    std::mutex m;
    std::condition_variable cv;
    bool done = false;
    struct Ctx {
      std::mutex* m;
      std::condition_variable* cv;
      bool* done;
    } ctx{&m, &cv, &done};
    Push(
        [](void* p, void*) {
          Ctx* c = static_cast<Ctx*>(p);
          std::lock_guard<std::mutex> lk(*c->m);
          *c->done = true;
          c->cv->notify_all();
        },
        &ctx, nullptr, &v, 1, nullptr, 0, 1 << 20, "wait_for_var", false);
    std::unique_lock<std::mutex> lk(m);
    cv.wait(lk, [&] { return done; });
  }

  void WaitForAll() {
    std::unique_lock<std::mutex> lk(wait_mu_);
    wait_cv_.wait(lk, [&] { return pending_total_.load() == 0; });
  }

  int PendingCount() { return pending_total_.load(); }

  // --- profiler ---------------------------------------------------------
  void SetProfiling(bool on) { profiling_ = on; }

  void Record(const std::string& name, uint32_t tid, int64_t t0) {
    if (!profiling_) return;
    std::lock_guard<std::mutex> lk(prof_mu_);
    prof_.push_back({name, tid, t0, now_us() - t0});
  }

  // Chrome trace JSON (src/engine/profiler.cc DumpProfile analogue).
  std::string DumpProfile() {
    std::lock_guard<std::mutex> lk(prof_mu_);
    std::string out = "{\"traceEvents\":[";
    bool first = true;
    for (auto& r : prof_) {
      if (!first) out += ",";
      first = false;
      char buf[512];
      snprintf(buf, sizeof(buf),
               "{\"name\":\"%s\",\"cat\":\"engine\",\"ph\":\"X\",\"ts\":%lld,"
               "\"dur\":%lld,\"pid\":0,\"tid\":%u}",
               r.name.c_str(), static_cast<long long>(r.start_us),
               static_cast<long long>(r.dur_us), r.tid);
      out += buf;
    }
    out += "]}";
    return out;
  }

  void ExecuteOpr(Opr* op, uint32_t tid) {
    int64_t t0 = now_us();
    // copy before fn: an async fn may invoke on_complete (deleting op)
    // before it returns
    bool async = op->async;
    std::string name = op->name;
    op->fn(op->param, op);  // on_complete handle = the Opr itself
    Record(name, tid, t0);
    if (!async) OnComplete(op);
  }

 private:
  void Append(int64_t v, Opr* op, bool is_write) {
    // AppendRead/WriteDependency (threaded_engine.h:109-143): try to grant
    // immediately if compatible with current holders AND nothing queued.
    Var& var = vars_[v];
    if (var.queue.empty()) {
      if (!is_write && !var.running_write) {
        ++var.running_reads;
        GrantOne(op);
        return;
      }
      if (is_write && !var.running_write && var.running_reads == 0) {
        var.running_write = true;
        GrantOne(op);
        return;
      }
    }
    var.queue.push_back({op, is_write});
  }

  void CompleteRead(int64_t v) {
    auto it = vars_.find(v);
    if (it == vars_.end()) return;
    Var& var = it->second;
    --var.running_reads;
    Advance(var);
  }

  void CompleteWrite(int64_t v) {
    auto it = vars_.find(v);
    if (it == vars_.end()) return;
    Var& var = it->second;
    var.running_write = false;
    Advance(var);
  }

  void Advance(Var& var) {
    // CompleteReadDependency/CompleteWriteDependency queue advance
    // (threaded_engine.h:146-195): grant maximal compatible prefix.
    while (!var.queue.empty()) {
      VarEntry e = var.queue.front();
      if (e.is_write) {
        if (var.running_reads == 0 && !var.running_write) {
          var.running_write = true;
          var.queue.pop_front();
          GrantOne(e.opr);
        }
        break;
      }
      if (var.running_write) break;
      ++var.running_reads;
      var.queue.pop_front();
      GrantOne(e.opr);
    }
  }

  void GrantOne(Opr* op) {
    if (op->pending.fetch_sub(1) == 1) Enqueue(op);
  }

  void Enqueue(Opr* op) {
    {
      std::lock_guard<std::mutex> lk(ready_mu_);
      ready_.push(op);
    }
    ready_cv_.notify_one();
  }

  void WorkerLoop(int tid) {
    for (;;) {
      Opr* op = nullptr;
      {
        std::unique_lock<std::mutex> lk(ready_mu_);
        ready_cv_.wait(lk, [&] { return shutdown_ || !ready_.empty(); });
        if (shutdown_ && ready_.empty()) return;
        op = ready_.top();
        ready_.pop();
      }
      ExecuteOpr(op, static_cast<uint32_t>(tid));
    }
  }

  int type_;
  std::mutex mu_;  // guards vars_
  std::unordered_map<int64_t, Var> vars_;
  int64_t next_var_ = 1;

  std::mutex ready_mu_;
  std::condition_variable ready_cv_;
  std::priority_queue<Opr*, std::vector<Opr*>, ReadyCmp> ready_;
  bool shutdown_ = false;

  std::atomic<int> pending_total_{0};
  std::mutex wait_mu_;
  std::condition_variable wait_cv_;

  std::vector<std::thread> workers_;

  bool profiling_ = false;
  std::mutex prof_mu_;
  std::vector<ProfRecord> prof_;
};

}  // namespace

extern "C" {

void* mxe_create(int num_workers, int engine_type) {
  return new Engine(num_workers, engine_type);
}

void mxe_destroy(void* e) { delete static_cast<Engine*>(e); }

int64_t mxe_new_var(void* e) { return static_cast<Engine*>(e)->NewVar(); }

void mxe_delete_var(void* e, int64_t v) {
  static_cast<Engine*>(e)->DeleteVar(v);
}

// fn(param, on_complete): sync ops must ignore on_complete (the engine
// completes on return). Async ops must eventually call
// mxe_opr_complete(engine, on_complete) from any thread.
void mxe_push(void* e, void (*fn)(void*, void*), void* param,
              void (*del)(void*), const int64_t* const_vars, int n_const,
              const int64_t* mut_vars, int n_mut, int priority,
              const char* name, int is_async) {
  static_cast<Engine*>(e)->Push(fn, param, del, const_vars, n_const, mut_vars,
                                n_mut, priority, name, is_async != 0);
}

void mxe_opr_complete(void* e, void* on_complete) {
  static_cast<Engine*>(e)->OnComplete(static_cast<Opr*>(on_complete));
}

void mxe_wait_for_var(void* e, int64_t v) {
  static_cast<Engine*>(e)->WaitForVar(v);
}

void mxe_wait_for_all(void* e) { static_cast<Engine*>(e)->WaitForAll(); }

int mxe_pending(void* e) { return static_cast<Engine*>(e)->PendingCount(); }

void mxe_set_profiling(void* e, int on) {
  static_cast<Engine*>(e)->SetProfiling(on != 0);
}

// Returns length; if buf != null copies up to buf_len bytes.
int64_t mxe_dump_profile(void* e, char* buf, int64_t buf_len) {
  std::string s = static_cast<Engine*>(e)->DumpProfile();
  if (buf && buf_len > 0) {
    int64_t n = static_cast<int64_t>(s.size()) < buf_len - 1
                    ? static_cast<int64_t>(s.size())
                    : buf_len - 1;
    memcpy(buf, s.data(), n);
    buf[n] = 0;
  }
  return static_cast<int64_t>(s.size());
}

}  // extern "C"
