// Native data plane: RecordIO + threaded image batch loader.
//
// TPU-native equivalent of the reference's C++ input pipeline
// (src/io/iter_image_recordio_2.cc ImageRecordIOParser2 + iter_prefetcher.h
// PrefetcherIter + dmlc-core recordio/InputSplit, SURVEY SS2.1 #27, SS3.5):
// a producer thread streams framed records off disk (sharded part k of n
// for multi-host input splits), a pool of decoder threads JPEG-decodes and
// augments straight into preallocated float32 NCHW batch buffers, and
// finished batches hand off through a bounded queue (double buffering) so
// host IO overlaps device compute. Exposed as a flat C ABI for ctypes
// (mxnet_tpu/native/__init__.py) -- same boundary discipline as the
// reference's C API (include/mxnet/c_api.h).
//
// Record framing matches mxnet_tpu/recordio.py (and the reference
// dmlc recordio): [kMagic u32][cflag<<29|len u32][payload][pad4].
// Image payload: IRHeader{u32 flag; f32 label; u64 id,id2}
//                [flag>0 ? flag*f32 labels] [jpeg bytes].

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cstdlib>
#include <cmath>
#include <algorithm>
#include <atomic>
#include <fstream>
#include <iterator>
#include <condition_variable>
#include <mutex>
#include <queue>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include <jpeglib.h>
#include <setjmp.h>

namespace {

constexpr uint32_t kMagic = 0xced7230a;

// ---------------------------------------------------------------- reader --
struct Reader {
  FILE* fp = nullptr;
  int part = 0, nparts = 1;
  uint64_t rec_idx = 0;
  std::vector<uint8_t> buf;

  bool NextRaw() {  // read one framed record into buf
    uint32_t head[2];
    if (fread(head, 4, 2, fp) != 2) return false;
    if (head[0] != kMagic) return false;
    uint32_t len = head[1] & ((1u << 29) - 1);
    buf.resize(len);
    if (len && fread(buf.data(), 1, len, fp) != len) return false;
    uint32_t pad = (4 - len % 4) % 4;
    if (pad) fseek(fp, pad, SEEK_CUR);
    return true;
  }

  bool Next() {  // sharded: keep records where idx % nparts == part
    while (NextRaw()) {
      bool mine = (rec_idx % (uint64_t)nparts) == (uint64_t)part;
      ++rec_idx;
      if (mine) return true;
    }
    return false;
  }

  void Reset() {
    fseek(fp, 0, SEEK_SET);
    rec_idx = 0;
  }
};

// ---------------------------------------------------------------- writer --
struct Writer {
  FILE* fp = nullptr;
  void Write(const uint8_t* data, uint64_t len) {
    uint32_t head[2] = {kMagic, (uint32_t)(len & ((1u << 29) - 1))};
    fwrite(head, 4, 2, fp);
    fwrite(data, 1, len, fp);
    uint32_t pad = (4 - len % 4) % 4;
    uint32_t zero = 0;
    if (pad) fwrite(&zero, 1, pad, fp);
  }
};

// ----------------------------------------------------------- jpeg decode --
struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jb;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  JpegErr* e = (JpegErr*)cinfo->err;
  longjmp(e->jb, 1);
}

// decode to RGB; returns false on corrupt input
bool DecodeJpeg(const uint8_t* data, size_t len, std::vector<uint8_t>* out,
                int* w, int* h) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(data), len);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  *w = cinfo.output_width;
  *h = cinfo.output_height;
  out->resize((size_t)(*w) * (*h) * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = out->data() + (size_t)cinfo.output_scanline * (*w) * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// encode RGB u8 -> jpeg bytes (libjpeg mem dest); false on failure
bool EncodeJpeg(const uint8_t* rgb, int w, int h, int quality,
                std::vector<uint8_t>* out) {
  jpeg_compress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  // volatile: modified between setjmp and a potential longjmp
  // (jpeg_mem_dest/jpeg_finish_compress reassign it); a non-volatile
  // local would be indeterminate in the error path's free(mem)
  unsigned char* volatile mem = nullptr;
  unsigned long volatile mem_size = 0;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_compress(&cinfo);
    free(mem);
    return false;
  }
  jpeg_create_compress(&cinfo);
  jpeg_mem_dest(&cinfo, const_cast<unsigned char**>(&mem),
                const_cast<unsigned long*>(&mem_size));
  cinfo.image_width = w;
  cinfo.image_height = h;
  cinfo.input_components = 3;
  cinfo.in_color_space = JCS_RGB;
  jpeg_set_defaults(&cinfo);
  jpeg_set_quality(&cinfo, quality, TRUE);
  jpeg_start_compress(&cinfo, TRUE);
  while (cinfo.next_scanline < cinfo.image_height) {
    JSAMPROW row =
        const_cast<uint8_t*>(rgb + (size_t)cinfo.next_scanline * w * 3);
    jpeg_write_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_compress(&cinfo);
  jpeg_destroy_compress(&cinfo);
  out->assign(mem, mem + mem_size);
  free(mem);
  return true;
}

// bilinear resize RGB u8
void Resize(const std::vector<uint8_t>& src, int sw, int sh,
            std::vector<uint8_t>* dst, int dw, int dh) {
  dst->resize((size_t)dw * dh * 3);
  float sx = (float)sw / dw, sy = (float)sh / dh;
  for (int y = 0; y < dh; ++y) {
    float fy = (y + 0.5f) * sy - 0.5f;
    int y0 = std::max(0, (int)fy), y1 = std::min(sh - 1, y0 + 1);
    float wy = fy - y0;
    if (wy < 0) wy = 0;
    for (int x = 0; x < dw; ++x) {
      float fx = (x + 0.5f) * sx - 0.5f;
      int x0 = std::max(0, (int)fx), x1 = std::min(sw - 1, x0 + 1);
      float wx = fx - x0;
      if (wx < 0) wx = 0;
      for (int c = 0; c < 3; ++c) {
        float v00 = src[((size_t)y0 * sw + x0) * 3 + c];
        float v01 = src[((size_t)y0 * sw + x1) * 3 + c];
        float v10 = src[((size_t)y1 * sw + x0) * 3 + c];
        float v11 = src[((size_t)y1 * sw + x1) * 3 + c];
        float v = v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                  v10 * wy * (1 - wx) + v11 * wy * wx;
        (*dst)[((size_t)y * dw + x) * 3 + c] = (uint8_t)(v + 0.5f);
      }
    }
  }
}

// ----------------------------------------------------- augment transforms --
// Rotate an RGB u8 image about its center by `angle` degrees, same output
// size, constant `fill` border (the reference affine at scale=1/shear=0:
// src/io/image_aug_default.cc:215-246). Inverse-mapped bilinear sampling
// replicating cv::warpAffine(INTER_LINEAR, BORDER_CONSTANT)'s fixed-point
// pipeline: source coordinates accumulate from per-term products rounded
// at 1/1024 px (AB_BITS=10), are re-quantized to 1/32 px (INTER_BITS=5),
// and the four tap weights are 15-bit fixed point with a
// round-to-nearest accumulate — exact-float bilinear drifts up to ±6
// counts from this path.
void RotateU8(const uint8_t* src, int w, int h, float angle, int fill,
              uint8_t* dst) {
  float a = std::cos(angle / 180.0f * (float)M_PI);
  float b = std::sin(angle / 180.0f * (float)M_PI);
  // forward M = [[a, b, tx], [-b, a, ty]] with the centering translation
  float tx = (w - (a * w + b * h)) / 2.0f;
  float ty = (h - (-b * w + a * h)) / 2.0f;
  // invert the float32 forward matrix numerically in double, exactly like
  // cv::invertAffineTransform (the analytic R^T inverse assumes det==1 and
  // flips round-to-nearest ties on ~0.03% of pixels)
  double M00 = a, M01 = b, M02 = tx, M10 = -b, M11 = a, M12 = ty;
  double D = M00 * M11 - M01 * M10;
  D = D != 0 ? 1.0 / D : 0.0;
  double i00 = M11 * D, i01 = -M01 * D, i10 = -M10 * D, i11 = M00 * D;
  double i02 = -i00 * M02 - i01 * M12;
  double i12 = -i10 * M02 - i11 * M12;
  const int AB_BITS = 10, INTER_BITS = 5;
  const double AB_SCALE = 1 << AB_BITS;
  const int ROUND_DELTA = 1 << (AB_BITS - INTER_BITS - 1);
  for (int y = 0; y < h; ++y) {
    int X0 = (int)std::lrint((i01 * y + i02) * AB_SCALE) + ROUND_DELTA;
    int Y0 = (int)std::lrint((i11 * y + i12) * AB_SCALE) + ROUND_DELTA;
    for (int x = 0; x < w; ++x) {
      int X = (X0 + (int)std::lrint(i00 * x * AB_SCALE)) >>
              (AB_BITS - INTER_BITS);
      int Y = (Y0 + (int)std::lrint(i10 * x * AB_SCALE)) >>
              (AB_BITS - INTER_BITS);
      int x0 = X >> INTER_BITS, y0 = Y >> INTER_BITS;
      float wx = (X & 31) / 32.0f, wy = (Y & 31) / 32.0f;
      int iw00 = (int)std::lrint((1 - wy) * (1 - wx) * 32768.0f);
      int iw01 = (int)std::lrint((1 - wy) * wx * 32768.0f);
      int iw10 = (int)std::lrint(wy * (1 - wx) * 32768.0f);
      int iw11 = 32768 - iw00 - iw01 - iw10;  // cv normalizes the tab sum
      uint8_t* out = dst + ((size_t)y * w + x) * 3;
      for (int c = 0; c < 3; ++c) {
        // sample with constant fill outside the source
        auto at = [&](int yy, int xx) -> int {
          if (xx < 0 || yy < 0 || xx >= w || yy >= h) return fill;
          return src[((size_t)yy * w + xx) * 3 + c];
        };
        int v = at(y0, x0) * iw00 + at(y0, x0 + 1) * iw01 +
                at(y0 + 1, x0) * iw10 + at(y0 + 1, x0 + 1) * iw11;
        v = (v + (1 << 14)) >> 15;
        out[c] = (uint8_t)(v < 0 ? 0 : (v > 255 ? 255 : v));
      }
    }
  }
}

// Additive jitter in 8-bit HLS space with clipping — the reference
// color-space augmentation (image_aug_default.cc:297-316: per-pixel add of
// (h, l, s) clipped to (180, 255, 255)). In-place on RGB u8. The RGB<->HLS
// math follows OpenCV's 8-bit convention (H in [0,180]).
void HslShiftU8(uint8_t* img, int w, int h, int dh, int ds, int dl) {
  for (size_t i = 0, n = (size_t)w * h; i < n; ++i) {
    uint8_t* p = img + i * 3;
    float r = p[0] / 255.0f, g = p[1] / 255.0f, bl = p[2] / 255.0f;
    float vmax = std::max(r, std::max(g, bl));
    float vmin = std::min(r, std::min(g, bl));
    float L = (vmax + vmin) / 2.0f;
    float H = 0, S = 0;
    float d = vmax - vmin;
    if (d > 0) {
      S = (L < 0.5f) ? d / (vmax + vmin) : d / (2.0f - vmax - vmin);
      if (vmax == r)
        H = 60.0f * (g - bl) / d;
      else if (vmax == g)
        H = 120.0f + 60.0f * (bl - r) / d;
      else
        H = 240.0f + 60.0f * (r - g) / d;
      if (H < 0) H += 360.0f;
    }
    // 8-bit HLS: H/2 in [0,180], L,S scaled to [0,255]; add + clip
    int Hi = (int)(H / 2.0f + 0.5f) + dh;
    int Li = (int)(L * 255.0f + 0.5f) + dl;
    int Si = (int)(S * 255.0f + 0.5f) + ds;
    Hi = std::max(0, std::min(180, Hi));
    Li = std::max(0, std::min(255, Li));
    Si = std::max(0, std::min(255, Si));
    // back to RGB (standard HLS->RGB, OpenCV convention)
    H = Hi * 2.0f;
    L = Li / 255.0f;
    S = Si / 255.0f;
    float c = (1.0f - std::fabs(2.0f * L - 1.0f)) * S;
    float Hp = H / 60.0f;
    float xc = c * (1.0f - std::fabs(std::fmod(Hp, 2.0f) - 1.0f));
    float r1 = 0, g1 = 0, b1 = 0;
    if (Hp < 1) { r1 = c; g1 = xc; }
    else if (Hp < 2) { r1 = xc; g1 = c; }
    else if (Hp < 3) { g1 = c; b1 = xc; }
    else if (Hp < 4) { g1 = xc; b1 = c; }
    else if (Hp < 5) { r1 = xc; b1 = c; }
    else { r1 = c; b1 = xc; }
    float m = L - c / 2.0f;
    p[0] = (uint8_t)std::max(0.0f, std::min(255.0f, (r1 + m) * 255.0f + 0.5f));
    p[1] = (uint8_t)std::max(0.0f, std::min(255.0f, (g1 + m) * 255.0f + 0.5f));
    p[2] = (uint8_t)std::max(0.0f, std::min(255.0f, (b1 + m) * 255.0f + 0.5f));
  }
}

// ------------------------------------------------------------ img loader --
struct LoaderCfg {
  int batch, H, W, C;
  int rand_crop, rand_mirror;
  float mean[3], std[3];
  int resize_shorter;  // 0 = resize directly to HxW
  // geometric/color augmentation (reference DefaultImageAugmentParam)
  int max_rotate_angle = 0;  // random angle in [-v, v]
  int rotate = -1;           // fixed angle; overrides max_rotate_angle
  int fill_value = 255;      // border fill for rotation
  int random_h = 0, random_s = 0, random_l = 0;  // HLS jitter extents
  // labels per record (reference label_width): rows of k float32s read
  // from flag>0 records' packed labels; flag==0 records fill row[0]
  int label_width = 1;
};

struct Batch {
  std::vector<float> data;    // batch*C*H*W
  std::vector<float> labels;  // batch*label_width
  int n = 0;
};

struct ImgLoader {
  LoaderCfg cfg;
  Reader reader;
  int nthreads;
  uint64_t seed;
  // streaming shuffle window (reference: ImageRecordIOParser shuffle_chunk —
  // records are drawn uniformly from a bounded pool that refills from the
  // sequential reader; 0 disables)
  int shuffle_buffer = 0;
  std::vector<std::vector<uint8_t>> shuffle_pool;
  std::mt19937_64 shuffle_rng;

  std::mutex mu;
  std::condition_variable cv_full, cv_free;
  std::queue<Batch*> ready;
  std::queue<Batch*> free_pool;
  std::vector<Batch> storage;
  std::thread producer;
  std::atomic<bool> stop{false};
  std::atomic<bool> eof{false};

  // one record's (payload copy) work item
  struct Work {
    std::vector<uint8_t> rec;
    int slot;
  };

  bool DecodeInto(const Work& w, Batch* b, std::mt19937* rng) {
    const uint8_t* p = w.rec.data();
    size_t len = w.rec.size();
    if (len < 24) return false;
    uint32_t flag;
    float label;
    memcpy(&flag, p, 4);
    memcpy(&label, p + 4, 4);
    size_t off = 24 + (flag > 0 ? (size_t)flag * 4 : 0);
    if (off >= len) return false;
    int w0, h0;
    std::vector<uint8_t> rgb, resized;
    if (!DecodeJpeg(p + off, len - off, &rgb, &w0, &h0)) return false;

    const LoaderCfg& c = cfg;
    int cw = c.W, ch = c.H;
    const std::vector<uint8_t>* src = &rgb;
    int sw = w0, sh = h0;
    if (c.resize_shorter > 0) {
      int shorter = std::min(w0, h0);
      float scale = (float)c.resize_shorter / shorter;
      int nw = (int)(w0 * scale + 0.5f), nh = (int)(h0 * scale + 0.5f);
      Resize(rgb, w0, h0, &resized, nw, nh);
      src = &resized;
      sw = nw;
      sh = nh;
    } else if (w0 != cw || h0 != ch) {
      Resize(rgb, w0, h0, &resized, cw, ch);
      src = &resized;
      sw = cw;
      sh = ch;
    }
    // rotation (reference order: affine after resize, before crop)
    std::vector<uint8_t> rotated;
    if (c.rotate > 0 || c.max_rotate_angle > 0) {
      int angle = c.rotate > 0
          ? c.rotate
          : (int)((*rng)() % (uint32_t)(2 * c.max_rotate_angle + 1)) -
                c.max_rotate_angle;
      if (angle != 0) {
        rotated.resize((size_t)sw * sh * 3);
        RotateU8(src->data(), sw, sh, (float)angle, c.fill_value,
                 rotated.data());
        src = &rotated;
      }
    }
    // crop
    int x0 = (sw - cw) / 2, y0 = (sh - ch) / 2;
    if (c.rand_crop && sw > cw) x0 = (int)((*rng)() % (uint32_t)(sw - cw + 1));
    if (c.rand_crop && sh > ch) y0 = (int)((*rng)() % (uint32_t)(sh - ch + 1));
    x0 = std::max(0, x0);
    y0 = std::max(0, y0);
    bool mirror = c.rand_mirror && ((*rng)() & 1);
    // HLS color jitter (reference order: color-space aug after crop).
    // Materialize just the crop window so the float HLS round-trip runs on
    // cw*ch pixels, not the whole resized image.
    std::vector<uint8_t> jittered;
    if (c.random_h || c.random_s || c.random_l) {
      auto draw = [&](int v) {
        return v ? (int)((*rng)() % (uint32_t)(2 * v + 1)) - v : 0;
      };
      int dh = draw(c.random_h), ds = draw(c.random_s), dl = draw(c.random_l);
      if (dh || ds || dl) {
        jittered.resize((size_t)cw * ch * 3);
        for (int y = 0; y < ch; ++y) {
          for (int x = 0; x < cw; ++x) {
            int yy = std::min(sh - 1, y0 + y), xx = std::min(sw - 1, x0 + x);
            memcpy(&jittered[((size_t)y * cw + x) * 3],
                   &(*src)[((size_t)yy * sw + xx) * 3], 3);
          }
        }
        HslShiftU8(jittered.data(), cw, ch, dh, ds, dl);
        src = &jittered;
        sw = cw;
        sh = ch;
        x0 = y0 = 0;
      }
    }

    float* dst = b->data.data() + (size_t)w.slot * c.C * ch * cw;
    for (int cc = 0; cc < c.C; ++cc) {
      for (int y = 0; y < ch; ++y) {
        for (int x = 0; x < cw; ++x) {
          int sxp = mirror ? (cw - 1 - x) : x;
          int yy = std::min(sh - 1, y0 + y), xx = std::min(sw - 1, x0 + sxp);
          float v = (*src)[((size_t)yy * sw + xx) * 3 + cc];
          dst[((size_t)cc * ch + y) * cw + x] = (v - c.mean[cc]) / c.std[cc];
        }
      }
    }
    int lw = c.label_width;
    float* lrow = b->labels.data() + (size_t)w.slot * lw;
    for (int j = 0; j < lw; ++j) lrow[j] = 0.0f;
    if (flag > 0 && len >= 24 + (size_t)flag * 4) {
      // packed multi-label record: the inline label is 0 by convention,
      // the real labels sit after the header — even label_width==1
      // readers want labels[0], not the zero placeholder
      size_t have = flag < (uint32_t)lw ? flag : (uint32_t)lw;
      memcpy(lrow, p + 24, have * 4);
    } else {
      lrow[0] = label;
    }
    return true;
  }

  // Pull the next record, optionally through the shuffle window.
  bool NextRecord(std::vector<uint8_t>* out) {
    if (shuffle_buffer <= 0) {
      if (!reader.Next()) return false;
      *out = reader.buf;
      return true;
    }
    while ((int)shuffle_pool.size() < shuffle_buffer && reader.Next()) {
      shuffle_pool.push_back(reader.buf);
    }
    if (shuffle_pool.empty()) return false;
    size_t i = shuffle_rng() % shuffle_pool.size();
    std::swap(shuffle_pool[i], shuffle_pool.back());
    *out = std::move(shuffle_pool.back());
    shuffle_pool.pop_back();
    return true;
  }

  void ProducerLoop() {
    std::vector<Work> works(cfg.batch);
    while (!stop.load()) {
      // grab a free batch buffer
      Batch* b;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_free.wait(lk, [&] { return stop.load() || !free_pool.empty(); });
        if (stop.load()) return;
        b = free_pool.front();
        free_pool.pop();
      }
      // read batch-many records (single-threaded IO, parallel decode)
      int n = 0;
      for (; n < cfg.batch; ++n) {
        if (!NextRecord(&works[n].rec)) break;
        works[n].slot = n;
      }
      if (n == 0) {
        {
          std::lock_guard<std::mutex> lk(mu);
          free_pool.push(b);
          eof.store(true);
          ready.push(nullptr);  // EOF sentinel
        }
        cv_full.notify_all();
        return;
      }
      // parallel decode; track per-slot success so corrupt records are
      // dropped, not silently fed as stale recycled-buffer pixels
      std::atomic<int> next{0};
      std::vector<char> ok(n, 0);
      auto decode_fn = [&](uint64_t tid) {
        std::mt19937 rng((uint32_t)(seed + tid * 9973 + reader.rec_idx));
        int i;
        while ((i = next.fetch_add(1)) < n)
          ok[i] = DecodeInto(works[i], b, &rng) ? 1 : 0;
      };
      if (nthreads <= 1) {
        decode_fn(0);
      } else {
        std::vector<std::thread> ts;
        for (int t = 0; t < nthreads; ++t) ts.emplace_back(decode_fn, t);
        for (auto& t : ts) t.join();
      }
      // compact failed slots out of the batch
      size_t img = (size_t)cfg.C * cfg.H * cfg.W;
      size_t lw = (size_t)cfg.label_width;
      int m = 0;
      for (int i = 0; i < n; ++i) {
        if (!ok[i]) continue;
        if (m != i) {
          memcpy(b->data.data() + (size_t)m * img,
                 b->data.data() + (size_t)i * img, img * sizeof(float));
          memcpy(b->labels.data() + (size_t)m * lw,
                 b->labels.data() + (size_t)i * lw, lw * sizeof(float));
        }
        ++m;
      }
      if (m == 0) {  // every record in this batch was corrupt — skip it
        std::lock_guard<std::mutex> lk(mu);
        free_pool.push(b);
        cv_free.notify_one();
        continue;
      }
      b->n = m;
      {
        std::lock_guard<std::mutex> lk(mu);
        ready.push(b);
      }
      cv_full.notify_all();
    }
  }

  void Start() {
    stop.store(false);
    eof.store(false);
    producer = std::thread([this] { ProducerLoop(); });
  }

  void Stop() {
    stop.store(true);
    cv_free.notify_all();
    cv_full.notify_all();
    if (producer.joinable()) producer.join();
  }
};

// ------------------------------------------------------------- im2rec ----
// Multithreaded dataset packer (the reference's tools/im2rec.cc): read a
// .lst index ("key\tlabel\t...\trelpath"), N workers load (and for
// resize > 0, decode/shrink/re-encode) images, one ordered writer frames
// IRHeader+bytes records and the .idx offsets. Ordering is preserved by a
// bounded reorder window so output is byte-deterministic regardless of
// thread timing.

#pragma pack(push, 1)
struct IRHeaderWire {  // python recordio.py _IR_FORMAT "IfQQ"
  uint32_t flag;
  float label;
  uint64_t id;
  uint64_t id2;
};
#pragma pack(pop)
static_assert(sizeof(IRHeaderWire) == 24, "IRHeader wire layout");

struct PackEntry {
  uint64_t key;
  std::vector<float> labels;  // 1 = inline (flag 0); k>1 = flag=k + floats
  std::string path;
};

int64_t Im2Rec(const char* lst_path, const char* root, const char* rec_path,
               const char* idx_path, int resize, int quality, int nthreads) {
  std::ifstream lst(lst_path);
  if (!lst) return -1;
  std::vector<PackEntry> entries;
  std::string line;
  while (std::getline(lst, line)) {
    while (!line.empty() && (line.back() == '\r' || line.back() == '\n' ||
                             line.back() == ' '))
      line.pop_back();  // CRLF-tolerant, like the Python packer's strip()
    if (line.empty()) continue;
    size_t t1 = line.find('\t');
    size_t tl = line.rfind('\t');
    if (t1 == std::string::npos || tl == t1) continue;
    PackEntry e;
    e.key = strtoull(line.substr(0, t1).c_str(), nullptr, 10);
    // every tab-separated field between key and path is a label float —
    // multi-label .lst lines (label_width > 1) pack flag=k + k floats,
    // matching recordio.py's pack() convention; parsing only the first
    // would silently drop labels 2..k
    for (size_t p = t1 + 1; p < tl + 1;) {
      size_t q = line.find('\t', p);
      if (q == std::string::npos || q > tl) q = tl;
      e.labels.push_back(strtof(line.substr(p, q - p).c_str(), nullptr));
      p = q + 1;
    }
    e.path = line.substr(tl + 1);
    entries.push_back(std::move(e));
  }
  FILE* rec = fopen(rec_path, "wb");
  if (!rec) return -1;
  std::ofstream idx(idx_path);
  if (!idx) {
    fclose(rec);
    return -1;
  }

  const size_t n = entries.size();
  std::vector<std::vector<uint8_t>> payloads(n);
  std::vector<int> state(n, 0);  // 0 pending, 1 ok, 2 skip
  std::mutex mu;
  std::condition_variable cv_done, cv_window;
  size_t write_pos = 0;
  const size_t window = std::max<size_t>(64, 4 * (size_t)nthreads);
  std::atomic<size_t> next_task{0};
  std::string rootdir = root && root[0] ? std::string(root) + "/" : "";

  auto work = [&]() {
    for (;;) {
      size_t i = next_task.fetch_add(1);
      if (i >= n) return;
      {
        // bound the reorder buffer: don't run more than `window` ahead
        // of the writer
        std::unique_lock<std::mutex> lk(mu);
        cv_window.wait(lk, [&] { return i < write_pos + window; });
      }
      std::vector<uint8_t> bytes;
      std::ifstream f(rootdir + entries[i].path, std::ios::binary);
      int ok = 0;
      if (f) {
        bytes.assign(std::istreambuf_iterator<char>(f),
                     std::istreambuf_iterator<char>());
        ok = !bytes.empty();
      }
      if (!ok) {
        fprintf(stderr, "mxio_im2rec: skip unreadable %s\n",
                entries[i].path.c_str());
      }
      if (ok && resize > 0) {
        bool is_jpeg =
            bytes.size() > 2 && bytes[0] == 0xFF && bytes[1] == 0xD8;
        std::vector<uint8_t> rgb;
        int w = 0, h = 0;
        if (is_jpeg && DecodeJpeg(bytes.data(), bytes.size(), &rgb, &w,
                                  &h)) {
          int shorter = w < h ? w : h;
          if (shorter != resize) {
            double s = (double)resize / shorter;
            int dw = (int)(w * s + 0.5), dh = (int)(h * s + 0.5);
            std::vector<uint8_t> small;
            Resize(rgb, w, h, &small, dw, dh);
            rgb.swap(small);
            w = dw;
            h = dh;
          }
          std::vector<uint8_t> enc;
          if (EncodeJpeg(rgb.data(), w, h, quality, &enc)) bytes.swap(enc);
        } else {
          // no libpng here: storing a non-JPEG verbatim would silently
          // violate the resize contract AND feed the jpeg-only native
          // loader undecodable records — skip loudly instead
          fprintf(stderr,
                  "mxio_im2rec: skip non-JPEG/corrupt %s (--resize "
                  "re-encodes and requires JPEG input)\n",
                  entries[i].path.c_str());
          ok = 0;
        }
      }
      std::vector<uint8_t> payload;
      if (ok) {
        const std::vector<float>& lab = entries[i].labels;
        size_t k = lab.size();
        if (k <= 1) {
          IRHeaderWire hd{0, k ? lab[0] : 0.0f, entries[i].key, 0};
          payload.resize(sizeof(hd) + bytes.size());
          memcpy(payload.data(), &hd, sizeof(hd));
          memcpy(payload.data() + sizeof(hd), bytes.data(), bytes.size());
        } else {
          // recordio.py pack(): flag = label count, inline label = 0,
          // k float32 labels between the header and the image bytes
          IRHeaderWire hd{(uint32_t)k, 0.0f, entries[i].key, 0};
          payload.resize(sizeof(hd) + k * 4 + bytes.size());
          memcpy(payload.data(), &hd, sizeof(hd));
          memcpy(payload.data() + sizeof(hd), lab.data(), k * 4);
          memcpy(payload.data() + sizeof(hd) + k * 4, bytes.data(),
                 bytes.size());
        }
      }
      {
        std::lock_guard<std::mutex> lk(mu);
        payloads[i] = std::move(payload);
        state[i] = ok ? 1 : 2;
      }
      cv_done.notify_one();
    }
  };

  std::vector<std::thread> pool;
  int nt = nthreads > 0 ? nthreads : 1;
  for (int t = 0; t < nt; ++t) pool.emplace_back(work);

  Writer writer;
  writer.fp = rec;
  int64_t written = 0;
  {
    std::unique_lock<std::mutex> lk(mu);
    for (size_t i = 0; i < n; ++i) {
      cv_done.wait(lk, [&] { return state[i] != 0; });
      if (state[i] == 1) {
        long off = ftell(rec);
        lk.unlock();
        writer.Write(payloads[i].data(), payloads[i].size());
        lk.lock();
        idx << entries[i].key << "\t" << off << "\n";
        ++written;
      }
      payloads[i].clear();
      payloads[i].shrink_to_fit();
      write_pos = i + 1;
      cv_window.notify_all();
    }
  }
  for (auto& t : pool) t.join();
  fclose(rec);
  idx.close();
  return written;
}

}  // namespace

extern "C" {

// ---- reader ----
void* mxio_reader_open(const char* path, int part, int nparts) {
  FILE* fp = fopen(path, "rb");
  if (!fp) return nullptr;
  Reader* r = new Reader();
  r->fp = fp;
  r->part = part;
  r->nparts = nparts;
  return r;
}

int mxio_reader_next(void* h, const uint8_t** data, uint64_t* len) {
  Reader* r = (Reader*)h;
  if (!r->Next()) return 0;
  *data = r->buf.data();
  *len = r->buf.size();
  return 1;
}

void mxio_reader_reset(void* h) { ((Reader*)h)->Reset(); }

void mxio_reader_close(void* h) {
  Reader* r = (Reader*)h;
  fclose(r->fp);
  delete r;
}

// ---- writer ----
void* mxio_writer_open(const char* path) {
  FILE* fp = fopen(path, "wb");
  if (!fp) return nullptr;
  Writer* w = new Writer();
  w->fp = fp;
  return w;
}

void mxio_writer_write(void* h, const uint8_t* data, uint64_t len) {
  ((Writer*)h)->Write(data, len);
}

void mxio_writer_close(void* h) {
  Writer* w = (Writer*)h;
  fclose(w->fp);
  delete w;
}

// ---- threaded image loader ----
// aug_params: optional int[6] {max_rotate_angle, rotate, fill_value,
// random_h, random_s, random_l} (reference DefaultImageAugmentParam);
// nullptr keeps the defaults (no rotation, no color jitter).
void* mxio_imgloader_create2(const char* path, int batch, int H, int W,
                             int C, int nthreads, int rand_crop,
                             int rand_mirror, const float* mean_rgb,
                             const float* std_rgb, int part, int nparts,
                             uint64_t seed, int resize_shorter,
                             int queue_depth, int shuffle_buffer,
                             const int* aug_params, int label_width) {
  FILE* fp = fopen(path, "rb");
  if (!fp) return nullptr;
  ImgLoader* L = new ImgLoader();
  L->reader.fp = fp;
  L->reader.part = part;
  L->reader.nparts = nparts;
  L->cfg = LoaderCfg{batch, H, W, C, rand_crop, rand_mirror,
                     {0, 0, 0}, {1, 1, 1}, resize_shorter};
  for (int i = 0; i < 3; ++i) {
    if (mean_rgb) L->cfg.mean[i] = mean_rgb[i];
    if (std_rgb) L->cfg.std[i] = std_rgb[i];
  }
  if (aug_params) {
    L->cfg.max_rotate_angle = aug_params[0];
    L->cfg.rotate = aug_params[1];
    L->cfg.fill_value = aug_params[2];
    L->cfg.random_h = aug_params[3];
    L->cfg.random_s = aug_params[4];
    L->cfg.random_l = aug_params[5];
  }
  L->cfg.label_width = label_width > 1 ? label_width : 1;
  L->nthreads = nthreads;
  L->seed = seed;
  L->shuffle_buffer = shuffle_buffer;
  L->shuffle_rng.seed(seed ? seed : 0x9e3779b97f4a7c15ull);
  if (queue_depth < 2) queue_depth = 2;
  L->storage.resize(queue_depth);
  for (auto& b : L->storage) {
    b.data.resize((size_t)batch * C * H * W);
    b.labels.resize((size_t)batch * L->cfg.label_width);
    L->free_pool.push(&b);
  }
  L->Start();
  return L;
}

void* mxio_imgloader_create(const char* path, int batch, int H, int W, int C,
                            int nthreads, int rand_crop, int rand_mirror,
                            const float* mean_rgb, const float* std_rgb,
                            int part, int nparts, uint64_t seed,
                            int resize_shorter, int queue_depth,
                            int shuffle_buffer, const int* aug_params) {
  return mxio_imgloader_create2(path, batch, H, W, C, nthreads, rand_crop,
                                rand_mirror, mean_rgb, std_rgb, part, nparts,
                                seed, resize_shorter, queue_depth,
                                shuffle_buffer, aug_params, 1);
}

int mxio_imgloader_next(void* h, float* data, float* labels) {
  ImgLoader* L = (ImgLoader*)h;
  Batch* b;
  {
    std::unique_lock<std::mutex> lk(L->mu);
    L->cv_full.wait(lk, [&] { return !L->ready.empty(); });
    b = L->ready.front();
    L->ready.pop();
  }
  if (b == nullptr) {  // EOF: re-push the sentinel so EOF is sticky and
    {                  // later calls return 0 instead of deadlocking
      std::lock_guard<std::mutex> lk(L->mu);
      L->ready.push(nullptr);
    }
    L->cv_full.notify_all();
    return 0;
  }
  memcpy(data, b->data.data(), b->data.size() * 4);
  memcpy(labels, b->labels.data(), b->labels.size() * 4);
  int n = b->n;
  {
    std::lock_guard<std::mutex> lk(L->mu);
    L->free_pool.push(b);
  }
  L->cv_free.notify_one();
  return n;
}

void mxio_imgloader_reset(void* h) {
  ImgLoader* L = (ImgLoader*)h;
  L->Stop();
  {
    std::lock_guard<std::mutex> lk(L->mu);
    while (!L->ready.empty()) {
      Batch* b = L->ready.front();
      L->ready.pop();
      if (b) L->free_pool.push(b);
    }
  }
  L->shuffle_pool.clear();
  L->reader.Reset();
  L->Start();
}

void mxio_imgloader_destroy(void* h) {
  ImgLoader* L = (ImgLoader*)h;
  L->Stop();
  fclose(L->reader.fp);
  delete L;
}

// ---- augment transforms (exported for golden tests against the Python/
// cv2 implementations of the same reference formulas) ----
void mxio_aug_rotate(const uint8_t* src, int w, int h, float angle, int fill,
                     uint8_t* dst) {
  RotateU8(src, w, h, angle, fill, dst);
}

void mxio_aug_hsl(uint8_t* img, int w, int h, int dh, int ds, int dl) {
  HslShiftU8(img, w, h, dh, ds, dl);
}

// multithreaded .lst -> .rec/.idx packer; returns records written or -1
int64_t mxio_im2rec(const char* lst_path, const char* root,
                    const char* rec_path, const char* idx_path, int resize,
                    int quality, int nthreads) {
  return Im2Rec(lst_path, root, rec_path, idx_path, resize, quality,
                nthreads);
}

}  // extern "C"
