#!/usr/bin/env python
"""Parse training logs into per-epoch tables (reference tools/parse_log.py).

Reads logs produced by Module.fit / Speedometer lines like:
  Epoch[0] Batch [50]  Speed: 4321.0 samples/sec  accuracy=0.91
  Epoch[0] Train-accuracy=0.93
  Epoch[0] Validation-accuracy=0.90
  Epoch[0] Time cost=12.3

  python tools/parse_log.py train.log [--format csv|md]
"""
import argparse
import re
import sys


EPOCH_METRIC = re.compile(
    r"Epoch\[(\d+)\]\s+(Train|Validation)-([\w-]+)=([0-9.eE+-]+)")
EPOCH_TIME = re.compile(r"Epoch\[(\d+)\]\s+Time cost=([0-9.eE+-]+)")
SPEED = re.compile(
    r"Epoch\[(\d+)\].*Speed:\s*([0-9.eE+-]+)\s*samples/sec")


def parse(lines):
    epochs = {}
    for line in lines:
        m = EPOCH_METRIC.search(line)
        if m:
            e = int(m.group(1))
            key = "%s-%s" % (m.group(2).lower(), m.group(3))
            epochs.setdefault(e, {})[key] = float(m.group(4))
            continue
        m = EPOCH_TIME.search(line)
        if m:
            epochs.setdefault(int(m.group(1)), {})["time"] = float(m.group(2))
            continue
        m = SPEED.search(line)
        if m:
            e = int(m.group(1))
            d = epochs.setdefault(e, {})
            d.setdefault("_speeds", []).append(float(m.group(2)))
    for d in epochs.values():
        sp = d.pop("_speeds", None)
        if sp:
            d["speed"] = sum(sp) / len(sp)
    return epochs


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("logfile", nargs="?", default="-")
    ap.add_argument("--format", choices=["csv", "md"], default="md")
    args = ap.parse_args()
    f = sys.stdin if args.logfile == "-" else open(args.logfile)
    epochs = parse(f)
    if not epochs:
        print("no epochs found", file=sys.stderr)
        return
    cols = sorted({k for d in epochs.values() for k in d})
    if args.format == "csv":
        print(",".join(["epoch"] + cols))
        for e in sorted(epochs):
            print(",".join([str(e)] + ["%g" % epochs[e].get(c, float("nan"))
                                       for c in cols]))
    else:
        print("| epoch | " + " | ".join(cols) + " |")
        print("|" + "---|" * (len(cols) + 1))
        for e in sorted(epochs):
            print("| %d | " % e + " | ".join(
                "%g" % epochs[e].get(c, float("nan")) for c in cols) + " |")


if __name__ == "__main__":
    main()
