#!/usr/bin/env python
"""im2rec — pack an image directory / list file into a RecordIO dataset.

Capability parity with the reference's tools/im2rec.py (+ the multithreaded
tools/im2rec.cc): builds a .lst index, then encodes images into .rec with
IRHeader framing readable by both the native C++ loader (native/recordio.cc)
and mxnet_tpu.recordio.

Usage:
  python tools/im2rec.py prefix image_root --list       # make prefix.lst
  python tools/im2rec.py prefix image_root              # pack prefix.rec
  python tools/im2rec.py prefix image_root --native --threads 8
                                  # multithreaded C++ packer (im2rec.cc)
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


EXTS = (".jpg", ".jpeg", ".png")


def make_list(prefix, root, recursive=True):
    entries = []
    label_map = {}
    for dirpath, _, files in sorted(os.walk(root)):
        for fn in sorted(files):
            if not fn.lower().endswith(EXTS):
                continue
            rel = os.path.relpath(os.path.join(dirpath, fn), root)
            cls = os.path.dirname(rel) or "."
            if cls not in label_map:
                label_map[cls] = len(label_map)
            entries.append((len(entries), label_map[cls], rel))
    with open(prefix + ".lst", "w") as f:
        for idx, label, rel in entries:
            f.write("%d\t%f\t%s\n" % (idx, label, rel))
    print("wrote %s: %d images, %d classes" % (prefix + ".lst", len(entries),
                                               len(label_map)))


def pack(prefix, root, quality=95, resize=0):
    import cv2

    from mxnet_tpu import recordio

    writer = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    n = 0
    with open(prefix + ".lst") as f:
        for line in f:
            parts = line.strip().split("\t")
            idx, rel = int(parts[0]), parts[-1]
            # all fields between key and path are labels; label_width > 1
            # packs flag=k + k float32s (recordio.pack convention) — the
            # native packer does the same
            labs = [float(v) for v in parts[1:-1]]
            label = labs[0] if len(labs) == 1 else np.asarray(labs,
                                                             np.float32)
            img = cv2.imread(os.path.join(root, rel), cv2.IMREAD_COLOR)
            if img is None:
                print("skip unreadable %s" % rel, file=sys.stderr)
                continue
            if resize:
                h, w = img.shape[:2]
                scale = resize / min(h, w)
                img = cv2.resize(img, (int(w * scale + .5), int(h * scale + .5)))
            header = recordio.IRHeader(0, label, idx, 0)
            packed = recordio.pack_img(header, img, quality=quality)
            writer.write_idx(idx, packed)
            n += 1
    writer.close()
    print("wrote %s.rec: %d records" % (prefix, n))


def pack_native(prefix, root, quality=95, resize=0, threads=4):
    """Delegate to the native multithreaded packer (native/recordio.cc
    mxio_im2rec — the reference's tools/im2rec.cc)."""
    from mxnet_tpu import native

    n = native.im2rec_pack(prefix + ".lst", root, prefix + ".rec",
                           prefix + ".idx", resize=resize, quality=quality,
                           nthreads=threads)
    with open(prefix + ".lst") as f:
        listed = sum(1 for line in f if line.strip())
    skipped = "" if n == listed else "  (%d of %d skipped — see stderr)" % (
        listed - n, listed)
    print("wrote %s.rec: %d records (native, %d threads)%s"
          % (prefix, n, threads, skipped))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("prefix")
    ap.add_argument("root")
    ap.add_argument("--list", action="store_true", help="only generate .lst")
    ap.add_argument("--quality", type=int, default=95)
    ap.add_argument("--resize", type=int, default=0)
    ap.add_argument("--native", action="store_true",
                    help="use the multithreaded C++ packer")
    ap.add_argument("--threads", type=int, default=4)
    args = ap.parse_args()
    if args.list or not os.path.exists(args.prefix + ".lst"):
        make_list(args.prefix, args.root)
    if not args.list:
        if args.native:
            pack_native(args.prefix, args.root, args.quality, args.resize,
                        args.threads)
        else:
            pack(args.prefix, args.root, args.quality, args.resize)


if __name__ == "__main__":
    main()
