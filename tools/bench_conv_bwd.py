#!/usr/bin/env python
"""Per-shape conv forward/backward microbenchmark (XLA emitters vs the
Pallas fast-path candidates).

Times every distinct ResNet-50 conv shape (at the headline batch) three
ways — forward, data-grad, weight-grad — through the same lax.conv
lowering the executor uses, bf16, NCHW (XLA:TPU relayouts internally).
This is the measurement underneath docs/perf.md's backward-conv ceiling
analysis and the selection table for the Pallas weight-grad kernel
(ops/pallas/conv_bwd.py): the fast path is only wired where this table
says XLA leaves throughput on the floor.

    python tools/bench_conv_bwd.py [--batch 128] [--json] \\
        [--layout nchw|nhwc]

--layout nhwc times the same contractions on the NHWC/HWIO resident
layout the executor's default island path (MXNET_CONV_LAYOUT=nhwc,
ops/layout.py) actually runs, with no boundary transposes in the loop —
including the Pallas wgrad candidate, which is NHWC-native and stops
paying its relayout tax on this arm.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# ResNet-50 conv inventory at 224^2: (name, C, H/W, K, kernel, stride, count)
# counts = occurrences per fwd pass (conv2..conv5 blocks; 1x1 projections
# included since their backward shares the same emitter family).
SHAPES = [
    ("stem7x7s2", 3, 224, 64, 7, 2, 1),
    ("c2_3x3", 64, 56, 64, 3, 1, 3),
    ("c2_1x1a", 64, 56, 64, 1, 1, 3),
    ("c2_1x1b", 64, 56, 256, 1, 1, 3),
    ("c2_1x1c", 256, 56, 64, 1, 1, 2),
    ("c3_3x3s2", 128, 56, 128, 3, 2, 1),
    ("c3_3x3", 128, 28, 128, 3, 1, 3),
    ("c3_1x1a", 256, 56, 128, 1, 1, 1),
    ("c3_1x1b", 128, 28, 512, 1, 1, 4),
    ("c3_1x1c", 512, 28, 128, 1, 1, 3),
    ("c4_3x3s2", 256, 28, 256, 3, 2, 1),
    ("c4_3x3", 256, 14, 256, 3, 1, 5),
    ("c4_1x1a", 512, 28, 256, 1, 1, 1),
    ("c4_1x1b", 256, 14, 1024, 1, 1, 6),
    ("c4_1x1c", 1024, 14, 256, 1, 1, 5),
    ("c5_3x3s2", 512, 14, 512, 3, 2, 1),
    ("c5_3x3", 512, 7, 512, 3, 1, 2),
    ("c5_1x1a", 1024, 14, 512, 1, 1, 1),
    ("c5_1x1b", 512, 7, 2048, 1, 1, 3),
    ("c5_1x1c", 2048, 7, 512, 1, 1, 2),
]


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=128)
    p.add_argument("--reps", type=int, default=300)
    p.add_argument("--json", action="store_true")
    p.add_argument("--only", help="substring filter on shape name")
    p.add_argument("--no-pallas", action="store_true")
    p.add_argument("--layout", choices=["nchw", "nhwc"], default="nchw",
                   help="resident layout to time (default nchw reference;"
                        " nhwc = the MXNET_CONV_LAYOUT=nhwc island path)")
    args = p.parse_args()

    import numpy as np
    import jax
    import jax.numpy as jnp

    N = args.batch
    rows = []
    for (name, C, HW, K, ksz, stride, count) in SHAPES:
        if args.only and args.only not in name:
            continue
        pad = (ksz - 1) // 2
        OH = (HW + 2 * pad - ksz) // stride + 1
        x = jnp.asarray(np.random.RandomState(0)
                        .randn(N, C, HW, HW).astype(np.float32),
                        dtype=jnp.bfloat16)
        w = jnp.asarray(np.random.RandomState(1)
                        .randn(K, C, ksz, ksz).astype(np.float32) * 0.1,
                        dtype=jnp.bfloat16)
        dy = jnp.asarray(np.random.RandomState(2)
                         .randn(N, K, OH, OH).astype(np.float32),
                         dtype=jnp.bfloat16)
        if args.layout == "nhwc":
            # same logical tensors, resident channels-last/HWIO — the
            # layout the executor's island path keeps them in
            x = jnp.transpose(x, (0, 2, 3, 1))
            w = jnp.transpose(w, (2, 3, 1, 0))
            dy = jnp.transpose(dy, (0, 2, 3, 1))
            dims = ("NHWC", "HWIO", "NHWC")
        else:
            dims = ("NCHW", "OIHW", "NCHW")
        dn = jax.lax.conv_dimension_numbers(x.shape, w.shape, dims)

        def conv(x, w):
            return jax.lax.conv_general_dilated(
                x, w, (stride, stride), [(pad, pad), (pad, pad)],
                dimension_numbers=dn,
                preferred_element_type=jnp.bfloat16)

        # Sub-ms kernels: host dispatch through the dev tunnel costs ~4 ms
        # per execution, so each measurement is a fori_loop of R chained
        # iterations INSIDE one jitted program — the op under test feeds a
        # scalar back into a (numerically inert) perturbation of x, which
        # defeats CSE/hoisting. The perturbation's own cost is measured by
        # an empty-chain baseline and subtracted.
        R = args.reps

        # Each op must be chained through an argument its VALUE depends
        # on, or XLA hoists it out of the loop (dgrad is linear in x: its
        # result depends only on (w, dy), so the chain must run through
        # dy there).
        def chained(op, carried):
            def run(x, w, dy):
                init = {"x": x, "dy": dy}[carried]
                other = {"x": (w, dy), "dy": (x, w)}[carried]

                def body(i, carry):
                    buf, s = carry
                    if carried == "x":
                        out = op(buf, other[0], other[1])
                    else:
                        out = op(other[0], other[1], buf)
                    # consume ALL of out NON-algebraically: sum(out) of a
                    # linear op strength-reduces to a trivial form (and a
                    # single-element read lets XLA slice the conv away);
                    # sum(out^2) forces full materialization
                    s2 = jnp.sum(jnp.square(out.astype(jnp.float32)))
                    # single-element in-place add on the loop carry: a
                    # real data dependence (defeats hoisting) at ~zero
                    # cost — s*1e-38 rounds away in bf16, values intact
                    buf2 = buf.at[(0,) * buf.ndim].add(
                        (s2 * 1e-38).astype(buf.dtype))
                    return (buf2, s2)
                _, s = jax.lax.fori_loop(0, R, body, (init, jnp.float32(0)))
                return s
            return jax.jit(run)

        def pallas_wgrad(x_, w_, dy_):
            # same contraction through the Pallas kernel (NHWC-native).
            # On the nchw arm the boundary transposes are part of its
            # cost, as a fast path grafted under the reference layout
            # would pay them; on the nhwc arm the operands are already
            # resident channels-last and no transpose is timed.
            from mxnet_tpu.ops.pallas.conv_bwd import conv_wgrad

            if args.layout == "nhwc":
                dw = conv_wgrad(x_, dy_, ksz, stride, pad)
                return dw.astype(w_.dtype)  # (kh,kw,C,K) == resident HWIO
            xh = jnp.transpose(x_, (0, 2, 3, 1))
            dyh = jnp.transpose(dy_, (0, 2, 3, 1))
            dw = conv_wgrad(xh, dyh, ksz, stride, pad)  # (kh,kw,C,K) f32
            return jnp.transpose(dw, (3, 2, 0, 1)).astype(w_.dtype)

        ops = {
            "fwd": (lambda x_, w_, dy_: conv(x_, w_), "x"),
            "dgrad": (lambda x_, w_, dy_: jax.vjp(
                lambda a: conv(a, w_), x_)[1](dy_)[0], "dy"),
            "wgrad": (lambda x_, w_, dy_: jax.vjp(
                lambda a: conv(x_, a), w_)[1](dy_)[0], "x"),
            "plwg": (pallas_wgrad, "x"),
        }

        def timeit(f):
            np.asarray(f(x, w, dy))
            best = None
            for _ in range(3):
                t0 = time.perf_counter()
                np.asarray(f(x, w, dy))
                t = (time.perf_counter() - t0) / R
                best = t if best is None else min(best, t)
            return best

        # measured time includes the sum(out^2) consumer; subtract its
        # analytic bandwidth cost (one read of out at ~700 GB/s measured
        # effective) so absolute TF/s stay honest — the XLA-vs-Pallas
        # COMPARISON is unaffected either way (same harness both sides)
        def est_sum(n_elems):
            return n_elems * 2 / 700e9

        t_f = max(1e-9, timeit(chained(*ops["fwd"])) - est_sum(dy.size))
        t_d = max(1e-9, timeit(chained(*ops["dgrad"])) - est_sum(x.size))
        t_w = max(1e-9, timeit(chained(*ops["wgrad"])) - est_sum(w.size))
        t_p = None
        if ksz == 3 and not args.no_pallas:
            try:
                t_p = max(1e-9,
                          timeit(chained(*ops["plwg"])) - est_sum(w.size))
            except Exception as e:
                print("  pallas wgrad failed for %s: %s" % (name, e))
        flops = 2.0 * N * OH * OH * C * K * ksz * ksz
        row = dict(name=name, layout=args.layout,
                   C=C, HW=HW, K=K, k=ksz, s=stride, count=count,
                   fwd_ms=round(t_f * 1e3, 3), fwd_tf=round(flops / t_f / 1e12, 1),
                   dgrad_ms=round(t_d * 1e3, 3), dgrad_tf=round(flops / t_d / 1e12, 1),
                   wgrad_ms=round(t_w * 1e3, 3), wgrad_tf=round(flops / t_w / 1e12, 1))
        if t_p is not None:
            row["plwg_ms"] = round(t_p * 1e3, 3)
            row["plwg_tf"] = round(flops / t_p / 1e12, 1)
        rows.append(row)
        if args.json:
            print(json.dumps(row), flush=True)
        else:
            extra = ("" if t_p is None else
                     " | PALLAS wgrad %6.2fms %5.1fTF (%.2fx)"
                     % (row["plwg_ms"], row["plwg_tf"], t_w / t_p))
            print("%-10s C=%-4d HW=%-3d K=%-4d k=%d s=%d x%d | "
                  "fwd %6.2fms %5.1fTF | dgrad %6.2fms %5.1fTF | "
                  "wgrad %6.2fms %5.1fTF%s"
                  % (name, C, HW, K, ksz, stride, count,
                     row["fwd_ms"], row["fwd_tf"], row["dgrad_ms"],
                     row["dgrad_tf"], row["wgrad_ms"], row["wgrad_tf"],
                     extra), flush=True)

    tot = {"fwd": 0.0, "dgrad": 0.0, "wgrad": 0.0}
    fl = 0.0
    for r in rows:
        tot["fwd"] += r["fwd_ms"] * r["count"]
        tot["dgrad"] += r["dgrad_ms"] * r["count"]
        tot["wgrad"] += r["wgrad_ms"] * r["count"]
        fl += 2.0 * N * (r["HW"] // r["s"]) ** 2 * r["C"] * r["K"] * r["k"] ** 2 \
            * r["count"]
    print("totals (%s, weighted by count): fwd %.1f ms, dgrad %.1f ms, "
          "wgrad %.1f ms; conv FLOPs/step %.2f TF"
          % (args.layout, tot["fwd"], tot["dgrad"], tot["wgrad"],
             fl / 1e12))


if __name__ == "__main__":
    main()
