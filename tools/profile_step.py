#!/usr/bin/env python
"""Profile the fused ResNet-50 training step on the real chip and print
the device-time breakdown by HLO category (+ top loop fusions with
achieved bandwidth).

This is the harness behind docs/perf.md's ceiling analysis: capture a
jax.profiler trace of N steps, then parse the xplane directly
(tensorflow.tsl xplane proto — the tensorboard plugin converter in this
image has a proto-version mismatch) and aggregate the "XLA Ops" lane by
the hlo_category stat, with model_flops/bytes_accessed for achieved
TF/s / GB/s.

    PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python \
        python tools/profile_step.py [--batch 128] [--steps 5]
"""
import argparse
import collections
import glob
import os
import shutil
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def capture(batch, steps, logdir):
    import numpy as np
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu import models

    sym = models.get_symbol("resnet-50", num_classes=1000)
    grad_req = {n: ("null" if n in ("data", "softmax_label") else "write")
                for n in sym.list_arguments()}
    exe = sym.simple_bind(mx.Context("tpu", 0), grad_req=grad_req,
                          compute_dtype="bfloat16",
                          data=(batch, 3, 224, 224), softmax_label=(batch,))
    init = mx.initializer.Xavier(factor_type="in", magnitude=2.0)
    for name, arr in exe.arg_dict.items():
        if name not in ("data", "softmax_label"):
            init(mx.initializer.InitDesc(name), arr)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.uniform(-1, 1, (batch, 3, 224, 224))
                    .astype(np.float32))
    y = jnp.asarray(rng.randint(0, 1000, (batch,)).astype(np.float32))
    lr, momentum, wd = 0.05, 0.9, 1e-4
    pn = [n for n in exe.arg_dict if n not in ("data", "softmax_label")]

    def sgd_all(params, grads, moms):
        np_, nm = {}, {}
        for n in params:
            g = grads[n] + wd * params[n]
            m = momentum * moms[n] - lr * g
            np_[n] = params[n] + m
            nm[n] = m
        return np_, nm

    step = exe.make_train_step(sgd_all)
    params = {n: jnp.array(exe.arg_dict[n]._data, copy=True) for n in pn}
    moms = {n: jnp.zeros_like(v) for n, v in params.items()}
    feed = {"data": x, "softmax_label": y}
    for _ in range(3):
        outs, params, moms = step(params, moms, feed)
    np.asarray(jnp.reshape(outs[0], (-1,))[0])
    shutil.rmtree(logdir, ignore_errors=True)
    with jax.profiler.trace(logdir):
        for _ in range(steps):
            outs, params, moms = step(params, moms, feed)
        np.asarray(jnp.reshape(outs[0], (-1,))[0])


def report(logdir, steps):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    xs = sorted(glob.glob(logdir + "/**/*.xplane.pb", recursive=True))
    if not xs:
        raise SystemExit("no xplane.pb found under %r — did the capture "
                         "run on a real TPU?" % logdir)
    space = xplane_pb2.XSpace()
    with open(xs[0], "rb") as f:
        space.ParseFromString(f.read())
    found = False
    for plane in space.planes:
        if plane.name != "/device:TPU:0":
            continue
        found = True
        stat_names = {k: v.name for k, v in plane.stat_metadata.items()}
        md = {}
        for k, v in plane.event_metadata.items():
            d = {"name": v.name}
            for st in v.stats:
                sn = stat_names.get(st.metadata_id, "")
                if sn == "hlo_category":
                    d["cat"] = st.str_value
                elif sn == "model_flops":
                    d["flops"] = st.int64_value
                elif sn == "bytes_accessed":
                    d["bytes"] = st.int64_value
            md[k] = d
        for line in plane.lines:
            if line.name != "XLA Ops":
                continue
            cat = collections.Counter()
            fl = collections.Counter()
            loops = collections.Counter()
            lbytes = {}
            total = 0.0
            for ev in line.events:
                m = md[ev.metadata_id]
                c = m.get("cat", "uncategorized")
                dur = ev.duration_ps / 1e9
                cat[c] += dur
                fl[c] += m.get("flops", 0)
                total += dur
                if c == "loop fusion":
                    # key by FULL name: truncated keys can collide and
                    # merge distinct fusions' durations
                    loops[m["name"]] += dur
                    lbytes[m["name"]] = lbytes.get(m["name"], 0) \
                        + m.get("bytes", 0)
            print("device total %.2f ms/step" % (total / steps))
            for k, v in cat.most_common(12):
                tf_s = (fl[k] / steps) / (v / steps * 1e-3) / 1e12 if v else 0
                print("  %-32s %7.2f ms/step (%4.1f%%)  %6.1f TF/s"
                      % (k, v / steps, 100 * v / total, tf_s))
            print("top loop fusions (elementwise; achieved GB/s):")
            for k, v in loops.most_common(8):
                bw = (lbytes[k] / steps) / (v / steps * 1e-3) / 1e9 if v else 0
                print("  %6.3f ms/step %5.0f GB/s  %s"
                      % (v / steps, bw, k[:90]))
    _check_found(found)


def _check_found(found):
    if not found:
        raise SystemExit(
            "no '/device:TPU:0' plane with an 'XLA Ops' line in the trace "
            "— was the capture taken on a real single-chip TPU backend?")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=128)
    p.add_argument("--steps", type=int, default=5)
    p.add_argument("--logdir", default="/tmp/mxtpu_profile")
    p.add_argument("--report-only", action="store_true")
    args = p.parse_args()
    if not args.report_only:
        capture(args.batch, args.steps, args.logdir)
    report(args.logdir, args.steps)


if __name__ == "__main__":
    main()
