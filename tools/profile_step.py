#!/usr/bin/env python
"""Profile the fused ResNet-50 training step on the real chip and print
the device-time breakdown by HLO category (+ top loop fusions with
achieved bandwidth).

This is the harness behind docs/perf.md's ceiling analysis: capture a
jax.profiler trace of N steps, then parse the xplane directly
(tensorflow.tsl xplane proto — the tensorboard plugin converter in this
image has a proto-version mismatch) and aggregate the "XLA Ops" lane by
the hlo_category stat, with model_flops/bytes_accessed for achieved
TF/s / GB/s.

    PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python \
        python tools/profile_step.py [--batch 128] [--steps 5]
"""
import argparse
import collections
import glob
import os
import shutil
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def capture(batch, steps, logdir, chain=1):
    import numpy as np
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu import models

    sym = models.get_symbol("resnet-50", num_classes=1000)
    grad_req = {n: ("null" if n in ("data", "softmax_label") else "write")
                for n in sym.list_arguments()}
    exe = sym.simple_bind(mx.Context("tpu", 0), grad_req=grad_req,
                          compute_dtype="bfloat16",
                          data=(batch, 3, 224, 224), softmax_label=(batch,))
    init = mx.initializer.Xavier(factor_type="in", magnitude=2.0)
    for name, arr in exe.arg_dict.items():
        if name not in ("data", "softmax_label"):
            init(mx.initializer.InitDesc(name), arr)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.uniform(-1, 1, (batch, 3, 224, 224))
                    .astype(np.float32))
    y = jnp.asarray(rng.randint(0, 1000, (batch,)).astype(np.float32))
    lr, momentum, wd = 0.05, 0.9, 1e-4
    pn = [n for n in exe.arg_dict if n not in ("data", "softmax_label")]

    def sgd_all(params, grads, moms):
        np_, nm = {}, {}
        for n in params:
            g = grads[n] + wd * params[n]
            m = momentum * moms[n] - lr * g
            np_[n] = params[n] + m
            nm[n] = m
        return np_, nm

    step = exe.make_train_step(sgd_all, chain=chain)
    params = {n: jnp.array(exe.arg_dict[n]._data, copy=True) for n in pn}
    moms = {n: jnp.zeros_like(v) for n, v in params.items()}
    feed = {"data": x, "softmax_label": y}
    for _ in range(3):
        outs, params, moms = step(params, moms, feed)
    np.asarray(jnp.reshape(outs[0], (-1,))[0])
    shutil.rmtree(logdir, ignore_errors=True)
    with jax.profiler.trace(logdir):
        for _ in range(steps):
            outs, params, moms = step(params, moms, feed)
        np.asarray(jnp.reshape(outs[0], (-1,))[0])


_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "bf16": 2, "f16": 2,
                "s16": 2, "u16": 2, "f32": 4, "s32": 4, "u32": 4,
                "f64": 8, "s64": 8, "u64": 8}


def _hbm_split(hlo_name):
    """Split an HLO instruction's operand/output bytes into (hbm_bytes,
    onchip_bytes) by parsing the shapes out of the instruction text.

    XLA's memory-space-assignment promotes hot operands into the chip's
    alternate memory (the ``S(1)`` suffix inside the layout braces);
    those reads never touch HBM, which is how a fusion's cost-analysis
    ``bytes_accessed`` can imply > HBM-peak "bandwidth". Counting S(1)
    operands separately is the reuse term that makes the table obey the
    roofline."""
    import re

    hbm = onchip = 0
    for m in re.finditer(r"(\w+)\[([\d,]*)\]\{([^}]*)\}", hlo_name):
        dt, dims, layout = m.groups()
        if dt not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        b = n * _DTYPE_BYTES[dt]
        if "S(1)" in layout:
            onchip += b
        else:
            hbm += b
    return hbm, onchip


def report(logdir):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    xs = sorted(glob.glob(logdir + "/**/*.xplane.pb", recursive=True))
    if not xs:
        raise SystemExit("no xplane.pb found under %r — did the capture "
                         "run on a real TPU?" % logdir)
    space = xplane_pb2.XSpace()
    with open(xs[0], "rb") as f:
        space.ParseFromString(f.read())
    found = False
    for plane in space.planes:
        if plane.name != "/device:TPU:0":
            continue
        found = True
        stat_names = {k: v.name for k, v in plane.stat_metadata.items()}
        md = {}
        for k, v in plane.event_metadata.items():
            d = {"name": v.name}
            for st in v.stats:
                sn = stat_names.get(st.metadata_id, "")
                if sn == "hlo_category":
                    d["cat"] = st.str_value
                elif sn == "model_flops":
                    d["flops"] = st.int64_value
                elif sn == "bytes_accessed":
                    d["bytes"] = st.int64_value
            md[k] = d
        for line in plane.lines:
            if line.name != "XLA Ops":
                continue
            # normalize PER OP from its own event count (an op inside a
            # chained lax.scan executes steps*chain times, one outside
            # only steps times — trusting a CLI step count skews both);
            # ms values below are per EXECUTION of each op.
            per = {}
            for ev in line.events:
                d, c = per.get(ev.metadata_id, (0.0, 0))
                per[ev.metadata_id] = (d + ev.duration_ps / 1e9, c + 1)
            cat = collections.Counter()
            fl = collections.Counter()
            loops = collections.Counter()
            lbytes = {}
            total = 0.0
            for eid, (dur, cnt) in per.items():
                m = md[eid]
                c = m.get("cat", "uncategorized")
                if c in ("while", "conditional"):
                    # control-flow umbrella events envelop their whole
                    # body: counting them double-counts every op inside
                    continue
                cat[c] += dur / cnt
                fl[c] += m.get("flops", 0)
                total += dur / cnt
                if c == "loop fusion":
                    # key by FULL name: truncated keys can collide and
                    # merge distinct fusions' durations
                    loops[m["name"]] += dur / cnt
                    lbytes[m["name"]] = lbytes.get(m["name"], 0) \
                        + m.get("bytes", 0)
            print("device total %.2f ms/step" % total)
            for k, v in cat.most_common(12):
                tf_s = fl[k] / (v * 1e-3) / 1e12 if v else 0
                print("  %-32s %7.2f ms/step (%4.1f%%)  %6.1f TF/s"
                      % (k, v, 100 * v / total, tf_s))
            print("top loop fusions (elementwise; HBM vs on-chip split):")
            for k, ms in loops.most_common(8):
                hbm_b, chip_b = _hbm_split(k)
                hbm_bw = hbm_b / (ms * 1e-3) / 1e9 if ms else 0
                raw_bw = lbytes[k] / (ms * 1e-3) / 1e9 if ms else 0
                print("  %6.3f ms/step  HBM %5.0f GB/s (%5.1f MB)"
                      "  on-chip %5.1f MB  [cost-analysis %4.0f GB/s]  %s"
                      % (ms, hbm_bw, hbm_b / 1e6, chip_b / 1e6, raw_bw,
                         k[:70]))
    _check_found(found)


def _check_found(found):
    if not found:
        raise SystemExit(
            "no '/device:TPU:0' plane with an 'XLA Ops' line in the trace "
            "— was the capture taken on a real single-chip TPU backend?")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=128)
    p.add_argument("--steps", type=int, default=5)
    p.add_argument("--logdir", default="/tmp/mxtpu_profile")
    p.add_argument("--report-only", action="store_true")
    p.add_argument("--chain", type=int, default=1)
    args = p.parse_args()
    if not args.report_only:
        capture(args.batch, args.steps, args.logdir, args.chain)
    report(args.logdir)


if __name__ == "__main__":
    main()
