#!/usr/bin/env python
"""Kill stray training processes on this host.

Capability parity with tools/kill-mxnet.py in the reference: after a
crashed distributed run, worker/server processes can linger; this greps
the process table for python processes running the given program (default:
anything importing mxnet_tpu) and SIGKILLs them, sparing itself.

Usage: python tools/kill_mxnet.py [program_substring]
"""
import os
import signal
import subprocess
import sys


def main():
    pattern = sys.argv[1] if len(sys.argv) > 1 else "mxnet_tpu"
    me = os.getpid()
    out = subprocess.run(["ps", "axo", "pid,command"], capture_output=True,
                         text=True).stdout
    killed = []
    for line in out.splitlines()[1:]:
        line = line.strip()
        if not line:
            continue
        pid_str, _, cmd = line.partition(" ")
        try:
            pid = int(pid_str)
        except ValueError:
            continue
        if pid == me or "kill_mxnet" in cmd:
            continue
        if "python" in cmd and pattern in cmd:
            try:
                os.kill(pid, signal.SIGKILL)
                killed.append((pid, cmd))
            except OSError:
                pass
    for pid, cmd in killed:
        print("killed %d: %s" % (pid, cmd[:100]))
    if not killed:
        print("no matching processes")


if __name__ == "__main__":
    main()
