#!/usr/bin/env python
"""Collective bus-bandwidth measurement harness.

TPU-native analogue of the reference's tools/bandwidth/ kvstore
bus-bandwidth tool (cited by docs/how_to/perf.md "Multiple Devices"):
measures the all-reduce bandwidth the gradient-sync path actually achieves
over a mesh axis (ICI on a slice; ICI+DCN across hosts), for a sweep of
message sizes. The reference's guidance applies unchanged: per-batch
communication time must stay below per-batch compute time.

  python tools/bandwidth.py                   # defaults: data axis, 1-256MB
  python tools/bandwidth.py --sizes-mb 4 64 --axis data
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--axis", default="data")
    ap.add_argument("--sizes-mb", type=float, nargs="+",
                    default=[1, 4, 16, 64, 256])
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--json", action="store_true",
                    help="print one JSON line instead of a table")
    args = ap.parse_args()

    import jax
    if os.environ.get("JAX_PLATFORMS"):
        # honor the env var even where sitecustomize force-registers a
        # different default platform
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh
    from mxnet_tpu.parallel import collectives

    devs = jax.devices()
    mesh = Mesh(np.array(devs).reshape(len(devs)), (args.axis,))
    rows = []
    for mb in args.sizes_mb:
        gbps = collectives.bus_bandwidth(mesh, args.axis, size_mb=mb,
                                         iters=args.iters,
                                         dtype=jnp.dtype(args.dtype))
        rows.append({"size_mb": mb, "bus_gbps": round(gbps, 3)})
    if args.json:
        print(json.dumps({"devices": len(devs), "axis": args.axis,
                          "results": rows}))
    else:
        print("devices=%d axis=%s dtype=%s" % (len(devs), args.axis,
                                               args.dtype))
        print("%10s %12s" % ("size(MB)", "bus GB/s"))
        for r in rows:
            print("%10g %12.3f" % (r["size_mb"], r["bus_gbps"]))


if __name__ == "__main__":
    main()
