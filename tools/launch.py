#!/usr/bin/env python
"""Cluster launcher.

TPU-native analogue of the reference's tools/launch.py (which delegates to
dmlc-core trackers: local/ssh/mpi/sge/yarn — tools/launch.py:33-60,
SURVEY §2.7). The reference starts scheduler + server + worker OS
processes; here every process is a worker and the "scheduler" is the
jax.distributed coordinator (SURVEY §5.8), so launching means: start N
copies of the training script with MXNET_TPU_{COORDINATOR,NUM_PROCS,
PROC_ID} set, then `mxnet_tpu.parallel.dist.init()` inside the script wires
them into one mesh.

Modes:
  --launcher local  spawn N local processes (the dmlc "local" tracker;
                    multi-process CPU emulation or one-host multi-chip)
  --launcher ssh    one process per host listed in --hostfile
                    (the dmlc "ssh" tracker)
  --launcher mpi    delegate process placement to mpirun; per-rank
                    identity comes from the MPI env (OMPI_COMM_WORLD_* /
                    PMI_*) which dist.init() reads once the launcher has
                    pinned the coordinator (the dmlc "mpi" tracker)
  --launcher sge    submit a qsub array job whose tasks derive their rank
                    from SGE_TASK_ID (the dmlc "sge" tracker)
  --launcher yarn   print the YARN distributed-shell submission with the
                    coordinator env wired (the dmlc "yarn" tracker; like
                    the tpu mode, cluster submission runs via the
                    cluster's own CLI)
  --launcher tpu    print the gcloud command that runs the script on every
                    worker of a TPU pod slice (pods launch via the cloud
                    CLI, not raw ssh)

--dry-run prints the exact command/script any launcher would run without
executing it.

Example:
  python tools/launch.py -n 4 --launcher local python train.py --epochs 1
"""
import argparse
import os
import shlex
import subprocess
import sys


def _coord(host="127.0.0.1"):
    """coordinator address `host:port` — the one place the default port
    and MXNET_TPU_PORT override live."""
    return "%s:%d" % (host, int(os.environ.get("MXNET_TPU_PORT", "12975")))


def _read_hostfile(path):
    with open(path) as f:
        return [h.strip().split()[0] for h in f if h.strip()]


def launch_local(n, cmd, env_extra=None, n_servers=0):
    """Local multi-process launch (dmlc local tracker analogue). With
    n_servers > 0, also spawns that many parameter-server processes and
    wires every process with the comma-separated MXNET_TPU_PS_URI list
    (the reference's `launch.py -n W -s S` worker/server topology; big
    arrays shard across the whole server group, kvstore_dist.h:276-314)."""
    import socket

    procs = []
    servers = []
    coord = _coord()
    ps_uri = None
    if n_servers > 0:
        ports = []
        for _ in range(n_servers):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            ports.append(s.getsockname()[1])
            s.close()
        ps_uri = ",".join("127.0.0.1:%d" % p for p in ports)
        for sid in range(n_servers):
            env = dict(os.environ)
            env.update(env_extra or {})
            env["MXNET_TPU_ROLE"] = "server"
            env["MXNET_TPU_SERVER_ID"] = str(sid)
            env["MXNET_TPU_PS_URI"] = ps_uri
            env["MXNET_TPU_NUM_WORKERS"] = str(n)
            servers.append(subprocess.Popen(cmd, env=env))
    for rank in range(n):
        env = dict(os.environ)
        env.update(env_extra or {})
        env["MXNET_TPU_COORDINATOR"] = coord
        env["MXNET_TPU_NUM_PROCS"] = str(n)
        env["MXNET_TPU_PROC_ID"] = str(rank)
        if ps_uri:
            env["MXNET_TPU_ROLE"] = "worker"
            env["MXNET_TPU_WORKER_RANK"] = str(rank)
            env["MXNET_TPU_PS_URI"] = ps_uri
            env["MXNET_TPU_NUM_WORKERS"] = str(n)
        procs.append(subprocess.Popen(cmd, env=env))
    rc = 0
    # Port pre-allocation above is bind-then-close, so another process can
    # steal a port before the server binds it (TOCTOU). Rather than letting
    # the group hang on 60s connect retries, fail fast: a server exiting
    # while workers still run means it never came up.
    import time

    running = list(procs)
    while running:
        for p in list(running):
            if p.poll() is not None:
                running.remove(p)
                rc = rc or p.returncode
        for s in (servers if running else ()):
            # rc 0 is a clean stop_server() exit (stragglers may still be
            # finishing); nonzero while workers run means the server never
            # came up (e.g. lost its pre-allocated port to a bind race).
            # Skipped once all workers are reaped: a server dying during
            # shutdown must not fail a successful job.
            if s.poll() is not None and s.returncode != 0:
                sys.stderr.write(
                    "launch.py: server process exited early (rc=%s) while "
                    "workers are running — likely lost its pre-allocated "
                    "port; killing the group\n" % s.returncode)
                for p in running + [x for x in servers if x.poll() is None]:
                    p.terminate()
                for p in running + servers:
                    try:
                        p.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        p.kill()
                        p.wait()
                # a worker failure already recorded in rc stays the verdict
                # (workers define success); the server rc is the fallback
                return rc or s.returncode or 1
        if running:
            time.sleep(0.2)
    # servers only exit on a kv.stop_server() RPC; whether or not the
    # workers sent one, shut the group down now. Server exit status does
    # NOT fold into the launcher rc — workers define success (the
    # reference tracker likewise tears servers down after workers).
    for p in servers:
        if p.poll() is None:
            p.terminate()
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()
    return rc


def launch_ssh(hosts, cmd, repo_dir):
    """One process per host over ssh (dmlc ssh tracker analogue)."""
    coord = _coord(hosts[0])
    procs = []
    for rank, host in enumerate(hosts):
        envs = ("MXNET_TPU_COORDINATOR=%s MXNET_TPU_NUM_PROCS=%d "
                "MXNET_TPU_PROC_ID=%d" % (coord, len(hosts), rank))
        remote = "cd %s && %s %s" % (shlex.quote(repo_dir), envs,
                                     " ".join(shlex.quote(c) for c in cmd))
        procs.append(subprocess.Popen(["ssh", "-o",
                                       "StrictHostKeyChecking=no", host,
                                       remote]))
    rc = 0
    for p in procs:
        p.wait()
        rc = rc or p.returncode
    return rc


def _mpi_env_flags(var, value):
    """mpirun flags exporting var=value to every rank, in the installed
    MPI's dialect: OpenMPI takes `-x VAR=val`, MPICH/hydra and Intel MPI
    take `-genv VAR val` (hydra aborts on an unknown `-x`). Flavor is
    sniffed from `mpirun --version`; unknown/absent mpirun defaults to
    the OpenMPI form."""
    flavor = ""
    try:
        out = subprocess.run(["mpirun", "--version"], capture_output=True,
                             text=True, timeout=10)
        flavor = (out.stdout or "") + (out.stderr or "")
    except (FileNotFoundError, subprocess.TimeoutExpired):
        pass
    if "HYDRA" in flavor or "Intel" in flavor or "MPICH" in flavor:
        return ["-genv", var, value]
    return ["-x", "%s=%s" % (var, value)]


def launch_mpi(n, cmd, hostfile=None, dry_run=False):
    """Delegate placement to mpirun (dmlc mpi tracker analogue,
    reference tools/launch.py:33-60). mpirun exports per-rank identity
    (OMPI_COMM_WORLD_RANK/SIZE or PMI_RANK/SIZE) which
    `mxnet_tpu.parallel.dist.init()` reads; the launcher's job is only
    to pin the coordinator address every rank should dial.

    Coordinator placement ASSUMES mpirun's default by-slot mapping puts
    rank 0 on the first hostfile entry. With custom mappings (--map-by
    node, rankfiles, relative slot counts) rank 0 can land elsewhere —
    set MXNET_TPU_COORD_HOST to the host that will run rank 0 and it is
    honored verbatim."""
    host = os.environ.get("MXNET_TPU_COORD_HOST") or "127.0.0.1"
    if hostfile and not os.environ.get("MXNET_TPU_COORD_HOST"):
        hosts = _read_hostfile(hostfile)
        if hosts:
            host = hosts[0]
    coord = _coord(host)
    mpi_cmd = ["mpirun", "-np", str(n)]
    if hostfile:
        mpi_cmd += ["--hostfile", hostfile]
    # NUM_PROCS rides along for scripts that read it directly (rank
    # itself comes from the MPI env: OMPI_COMM_WORLD_RANK / PMI_RANK)
    mpi_cmd += (_mpi_env_flags("MXNET_TPU_COORDINATOR", coord)
                + _mpi_env_flags("MXNET_TPU_NUM_PROCS", str(n)) + cmd)
    if dry_run:
        print(" ".join(shlex.quote(c) for c in mpi_cmd))
        return 0
    env = dict(os.environ, MXNET_TPU_COORDINATOR=coord)
    try:
        return subprocess.call(mpi_cmd, env=env)
    except FileNotFoundError:
        sys.stderr.write("launch.py: mpirun not found on PATH\n")
        return 127


def sge_job_script(n, cmd):
    """The qsub array-job script text: N tasks, rank = SGE_TASK_ID - 1
    (dist.init reads SGE_TASK_ID/FIRST/STEPSIZE/LAST).

    Coordinator placement: jax.distributed's coordinator service is
    HOSTED BY RANK 0 — SGE task 1 — which the scheduler places on an
    arbitrary exec host (the submit host would only be right by luck;
    the reference's dmlc sge tracker could pin the submit host because
    its rendezvous ran there as a separate process, which
    jax.distributed does not do). So task 1 publishes its own hostname
    to a shared-FS rendezvous file under -cwd (SGE jobs share the
    submit cwd) and the other tasks poll for it before exec'ing the
    command. MXNET_TPU_COORD_HOST overrides: set it to the exec host
    that will run task 1 and the file dance is skipped."""
    joined = " ".join(shlex.quote(c) for c in cmd)
    port = int(os.environ.get("MXNET_TPU_PORT", "12975"))
    lines = [
        "#!/bin/bash",
        "#$ -cwd",
        "#$ -t 1-%d" % n,
        "#$ -S /bin/bash",
    ]
    coord_host = os.environ.get("MXNET_TPU_COORD_HOST")
    if coord_host:
        # resolved NOW, at generation time: a shell $(hostname) would
        # expand per-task on each execution host and every rank would
        # dial a different address
        lines.append("export MXNET_TPU_COORDINATOR=%s" % _coord(coord_host))
    else:
        lines += [
            'RDV=".mxnet_tpu_coord.$JOB_ID"',
            'if [ "$SGE_TASK_ID" = "1" ]; then',
            # write-then-rename so pollers never read a partial file;
            # task 1 owns the file's lifetime (trap removes it on exit —
            # without it every job litters the shared cwd, and a
            # qsub -r y rerun of task 1 on a NEW host could hand peers
            # the dead previous host). The rerun case also rewrites
            # unconditionally, so late-joining peers see the new host.
            '  trap \'rm -f "$RDV"\' EXIT',
            '  hostname -f > "$RDV.tmp" && mv "$RDV.tmp" "$RDV"',
            "fi",
            "for _i in $(seq 600); do",
            '  [ -f "$RDV" ] && break',
            "  sleep 1",
            "done",
            'if [ ! -f "$RDV" ]; then',
            '  echo "launch.py[sge]: rendezvous file $RDV never appeared'
            ' (is -cwd on a shared filesystem?)" >&2',
            "  exit 1",
            "fi",
            'export MXNET_TPU_COORDINATOR="$(cat "$RDV"):%d"' % port,
        ]
    lines += [joined, ""]
    return "\n".join(lines)


def launch_sge(n, cmd, dry_run=False):
    """Submit the array job via qsub (dmlc sge tracker analogue)."""
    script = sge_job_script(n, cmd)
    if dry_run:
        print(script)
        return 0
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".sh", delete=False) as f:
        f.write(script)
        path = f.name
    try:
        return subprocess.call(["qsub", "-sync", "y", path])
    except FileNotFoundError:
        sys.stderr.write("launch.py: qsub not found on PATH\n")
        return 127


def launch_yarn(n, cmd):
    """Print the YARN distributed-shell submission (dmlc yarn tracker
    analogue). Like the tpu mode, the cluster's own CLI performs the
    submission. Rank identity: the distributed-shell exports no task
    index, but every container's CONTAINER_ID ends in a dense 1-based
    ordinal where _000001 is the application master — worker rank =
    ordinal - 2."""
    coord = _coord(os.environ.get("MXNET_TPU_COORD_HOST")
                   or "$COORD_HOST")
    joined = " ".join(shlex.quote(c) for c in cmd)
    shell = ("export MXNET_TPU_PROC_ID=$(( 10#${CONTAINER_ID##*_} - 2 )); "
             + joined)
    print("# Submit via the YARN distributed-shell application:")
    print("yarn jar $HADOOP_HOME/share/hadoop/yarn/"
          "hadoop-yarn-applications-distributedshell-*.jar "
          "-jar $HADOOP_HOME/share/hadoop/yarn/"
          "hadoop-yarn-applications-distributedshell-*.jar "
          "-num_containers %d "
          "-shell_env MXNET_TPU_COORDINATOR=%s "
          "-shell_env MXNET_TPU_NUM_PROCS=%d "
          "-shell_command %s"
          % (n, coord, n, shlex.quote(shell)))
    return 0


def launch_tpu_pod(args, cmd):
    """Print the pod-slice launch command; TPU pods are driven by the cloud
    CLI (every worker runs the same script; jax initializes from pod
    metadata, no MXNET_TPU_* env needed)."""
    joined = " ".join(shlex.quote(c) for c in cmd)
    print("# Run on every worker of the pod slice:")
    print("gcloud compute tpus tpu-vm ssh %s --worker=all "
          "--command=%s" % (args.tpu_name or "$TPU_NAME",
                            shlex.quote("cd %s && %s"
                                        % (os.getcwd(), joined))))
    return 0


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-n", "--num-workers", type=int, default=1)
    ap.add_argument("-s", "--num-servers", type=int, default=0,
                    help="parameter-server processes (local launcher)")
    ap.add_argument("--launcher",
                    choices=["local", "ssh", "mpi", "sge", "yarn", "tpu"],
                    default="local")
    ap.add_argument("--hostfile",
                    help="one host per line (ssh/mpi launchers)")
    ap.add_argument("--tpu-name", help="TPU pod name (tpu launcher)")
    ap.add_argument("--dry-run", action="store_true",
                    help="print what would run without executing")
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    if not args.command:
        ap.error("no command given")
    cmd = args.command
    if args.launcher == "local":
        sys.exit(launch_local(args.num_workers, cmd,
                              n_servers=args.num_servers))
    elif args.launcher == "ssh":
        if not args.hostfile:
            ap.error("--hostfile required for ssh launcher")
        with open(args.hostfile) as f:
            hosts = [h.strip() for h in f if h.strip()]
        sys.exit(launch_ssh(hosts[:args.num_workers] if args.num_workers > 1
                            else hosts, cmd, os.getcwd()))
    elif args.launcher == "mpi":
        sys.exit(launch_mpi(args.num_workers, cmd, hostfile=args.hostfile,
                            dry_run=args.dry_run))
    elif args.launcher == "sge":
        sys.exit(launch_sge(args.num_workers, cmd, dry_run=args.dry_run))
    elif args.launcher == "yarn":
        sys.exit(launch_yarn(args.num_workers, cmd))
    else:
        sys.exit(launch_tpu_pod(args, cmd))


if __name__ == "__main__":
    main()
