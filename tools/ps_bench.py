#!/usr/bin/env python
"""Parameter-server push/pull latency micro-benchmark.

Measures round-trip push+pull against one in-process server for a
range of tensor sizes, and compares the wire path (raw-frame tensor
payloads, kvstore_server.send_msg/recv_msg) against the former
pickle-everything framing (reconstructed here for the comparison).

    python tools/ps_bench.py
"""
import os
import pickle
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mxnet_tpu import kvstore_server as ps  # noqa: E402


def main():
    addr = "/tmp/mxtpu_psbench.sock"  # AF_UNIX: avoids loopback-TCP delayed-ACK artifacts
    server = ps.KVStoreServer(address=addr, n_workers=1, sync_mode=False)
    server.start_background()
    client = ps.PSClient([addr])

    print("%10s  %12s  %14s  %12s" % ("elements", "rtt (framed)",
                                      "pickle-only*", "speedup"))
    for n in (1 << 10, 1 << 16, 1 << 20, 1 << 24):
        v = np.random.RandomState(0).rand(n).astype(np.float32)
        client.init("k%d" % n, v)
        client.push("k%d" % n, v)   # warmup (incl. first-connect cost)
        client.pull("k%d" % n)
        reps = max(3, min(50, (1 << 24) // n))
        t0 = time.perf_counter()
        for _ in range(reps):
            client.push("k%d" % n, v)
            client.pull("k%d" % n)
        framed = (time.perf_counter() - t0) / reps

        # counterfactual: the serialize+deserialize cost the old framing
        # added on top of the same socket traffic (pickle round-trips of
        # the request and reply payloads, 2x per push+pull)
        t0 = time.perf_counter()
        for _ in range(reps):
            for _ in range(2):
                pickle.loads(pickle.dumps(("push", "k", v),
                                          protocol=pickle.HIGHEST_PROTOCOL))
        pickled = (time.perf_counter() - t0) / reps + framed
        print("%10d  %9.3f ms  %11.3f ms  %11.2fx"
              % (n, framed * 1e3, pickled * 1e3, pickled / framed))
    client.stop()


if __name__ == "__main__":
    main()
