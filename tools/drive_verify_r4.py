"""Round-4 verify drive: exercises this round's fixes on the REAL backend.

Run from /root/repo: python tools/drive_verify_r4.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

print("backend:", jax.default_backend())

# 1. Flash attention causal cross-length (compiled on TPU when available)
from mxnet_tpu.ops.pallas.flash_attention import flash_attention
from mxnet_tpu.ops.attention import dot_product_attention

rng = np.random.RandomState(0)
B, H, D = 1, 4, 128
for tq, tk in ((1024, 2048), (2048, 2048)):
    q = jnp.asarray(rng.randn(B, H, tq, D).astype(np.float32), jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, H, tk, D).astype(np.float32), jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, H, tk, D).astype(np.float32), jnp.bfloat16)
    got = np.asarray(flash_attention(q, k, v, causal=True), np.float32)
    want = np.asarray(dot_product_attention(q, k, v, causal=True), np.float32)
    err = np.abs(got - want).max()
    assert np.isfinite(got).all(), (tq, tk)
    assert err < 3e-2, (tq, tk, err)
    print("flash causal tq=%d tk=%d max_err=%.4f OK" % (tq, tk, err))

# tq > tk causal routes to the (finite) XLA fallback even on TPU
q = jnp.asarray(rng.randn(B, H, 2048, D).astype(np.float32), jnp.bfloat16)
k = jnp.asarray(rng.randn(B, H, 1024, D).astype(np.float32), jnp.bfloat16)
v = jnp.asarray(rng.randn(B, H, 1024, D).astype(np.float32), jnp.bfloat16)
out = np.asarray(flash_attention(q, k, v, causal=True), np.float32)
assert np.isfinite(out).all()
print("flash causal tq>tk fallback finite OK")

# 2. DevicePrefetchIter close guard on the real backend
import mxnet_tpu as mx

X = np.arange(8 * 3, dtype=np.uint8).reshape(8, 3)
y = np.arange(8, dtype=np.float32)
it = mx.io.DevicePrefetchIter(mx.io.NDArrayIter(X, y, batch_size=2),
                              depth=2, cast_dtype="float32")
n = sum(1 for _ in it)
assert n == 4, n
it.close()
it.close()
try:
    it.reset()
    raise AssertionError("reset after close must raise")
except RuntimeError as e:
    assert "closed" in str(e)
print("DevicePrefetchIter close guard OK")

print("ALL OK")
