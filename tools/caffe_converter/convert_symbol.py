#!/usr/bin/env python
"""Convert a Caffe .prototxt network definition into a framework Symbol.

Analogue of the reference's tools/caffe_converter (SURVEY §2.7): parses the
protobuf *text format* directly (no caffe/protobuf schema needed) and maps
the common layer types onto the op registry:

Convolution, Pooling(MAX/AVE), InnerProduct, ReLU, Dropout, LRN, Concat,
Eltwise(SUM), Flatten, BatchNorm(+Scale folded), Softmax/SoftmaxWithLoss.

    python tools/caffe_converter/convert_symbol.py lenet.prototxt out.json
"""
import re
import sys
import os

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))


def parse_prototxt(text):
    """Parse protobuf text format into nested dicts (repeated fields ->
    lists)."""
    # strip comments, but not '#' inside quoted strings
    text = re.sub(r'("[^"]*")|#[^\n]*',
                  lambda m: m.group(1) or "", text)
    tokens = re.findall(r'[\w.+-]+|"[^"]*"|[{}:]', text)
    pos = 0

    def parse_block():
        nonlocal pos
        out = {}
        while pos < len(tokens) and tokens[pos] != "}":
            key = tokens[pos]
            pos += 1
            if tokens[pos] == ":":
                pos += 1
                val = tokens[pos]
                pos += 1
                if val.startswith('"'):
                    val = val[1:-1]
                else:
                    try:
                        val = int(val)
                    except ValueError:
                        try:
                            val = float(val)
                        except ValueError:
                            pass  # enum / bool string
            elif tokens[pos] == "{":
                pos += 1
                val = parse_block()
                assert tokens[pos] == "}"
                pos += 1
            else:
                raise ValueError("parse error at %r" % tokens[pos:pos + 4])
            if key in out:
                if not isinstance(out[key], list):
                    out[key] = [out[key]]
                out[key].append(val)
            else:
                out[key] = val
        return out

    return parse_block()


def _hw(v, default):
    """(h, w) from a scalar or per-axis repeated field: 'kernel_size: 3'
    -> (3, 3); 'kernel_size: 3 kernel_size: 5' -> (3, 5)."""
    if v is None:
        return (int(default), int(default))
    if isinstance(v, list):
        if len(v) != 2:
            raise NotImplementedError(
                "repeated spatial field with %d entries" % len(v))
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def _kernel_hw(p, default):
    """kernel size as (h, w): kernel_size (scalar or per-axis repeated) or
    kernel_h/kernel_w, as Caffe allows."""
    if "kernel_h" in p or "kernel_w" in p:
        return int(p.get("kernel_h", default)), int(p.get("kernel_w", default))
    return _hw(p.get("kernel_size"), default)


def _pair(p, field, default):
    if field + "_h" in p or field + "_w" in p:
        return (int(p.get(field + "_h", default)),
                int(p.get(field + "_w", default)))
    return _hw(p.get(field), default)


def _as_list(v):
    if v is None:
        return []
    return v if isinstance(v, list) else [v]


def convert(text):
    """prototxt text -> (Symbol, input_name)."""
    import mxnet_tpu as mx

    net = parse_prototxt(text)
    layers = _as_list(net.get("layer") or net.get("layers"))
    blobs = {}
    input_name = net.get("input", "data")
    if isinstance(input_name, list):
        input_name = input_name[0]
    blobs[input_name] = mx.sym.Variable(input_name)

    def get_bottom(l):
        bots = _as_list(l.get("bottom", input_name))
        return [blobs[b] for b in bots]

    for l in layers:
        ltype = str(l.get("type", "")).upper()
        name = l.get("name", ltype.lower())
        tops = _as_list(l.get("top", name))
        if ltype in ("DATA", "INPUT", "HDF5DATA", "IMAGEDATA"):
            for t in tops:
                blobs[t] = blobs.get(input_name) or mx.sym.Variable(t)
            continue
        bot = get_bottom(l)
        if ltype == "CONVOLUTION":
            p = l.get("convolution_param", {})
            kh, kw = _kernel_hw(p, 1)
            out = mx.sym.Convolution(
                bot[0], num_filter=int(p.get("num_output")),
                kernel=(kh, kw),
                stride=_pair(p, "stride", 1),
                pad=_pair(p, "pad", 0),
                num_group=int(p.get("group", 1)),
                no_bias=str(p.get("bias_term", "true")).lower() == "false",
                name=name)
        elif ltype == "POOLING":
            p = l.get("pooling_param", {})
            kh, kw = _kernel_hw(p, 2)
            pool = "max" if str(p.get("pool", "MAX")).upper() == "MAX" else "avg"
            gp = str(p.get("global_pooling", "false")).lower() == "true"
            # Caffe computes pooling output sizes ceil-mode; 'full' is the
            # matching convention (reference convert_symbol.py
            # _convert_pooling_param emits it unconditionally).
            out = mx.sym.Pooling(
                bot[0], kernel=(kh, kw), pool_type=pool,
                stride=_pair(p, "stride", 1),
                pad=_pair(p, "pad", 0),
                pooling_convention="full",
                global_pool=gp, name=name)
        elif ltype == "INNERPRODUCT":
            p = l.get("inner_product_param", {})
            out = mx.sym.FullyConnected(
                mx.sym.Flatten(bot[0]),
                num_hidden=int(p.get("num_output")),
                no_bias=str(p.get("bias_term", "true")).lower() == "false",
                name=name)
        elif ltype == "RELU":
            out = mx.sym.Activation(bot[0], act_type="relu", name=name)
        elif ltype == "SIGMOID":
            out = mx.sym.Activation(bot[0], act_type="sigmoid", name=name)
        elif ltype == "TANH":
            out = mx.sym.Activation(bot[0], act_type="tanh", name=name)
        elif ltype == "DROPOUT":
            p = l.get("dropout_param", {})
            out = mx.sym.Dropout(bot[0], p=float(p.get("dropout_ratio", 0.5)),
                                 name=name)
        elif ltype == "LRN":
            p = l.get("lrn_param", {})
            out = mx.sym.LRN(bot[0], nsize=int(p.get("local_size", 5)),
                             alpha=float(p.get("alpha", 1e-4)),
                             beta=float(p.get("beta", 0.75)), name=name)
        elif ltype == "CONCAT":
            out = mx.sym.Concat(*bot, name=name)
        elif ltype == "ELTWISE":
            ep = l.get("eltwise_param", {})
            op = str(ep.get("operation", "SUM")).upper()
            if "coeff" in ep:
                raise NotImplementedError(
                    "eltwise coeff weights are not supported")
            out = bot[0]
            for b in bot[1:]:
                if op == "SUM":
                    out = out + b
                elif op == "PROD":
                    out = out * b
                elif op == "MAX":
                    out = mx.sym.maximum(out, b)
                else:
                    raise NotImplementedError("eltwise operation %s" % op)
        elif ltype == "FLATTEN":
            out = mx.sym.Flatten(bot[0], name=name)
        elif ltype == "BATCHNORM":
            p = l.get("batch_norm_param", {})
            # Caffe BN has no gamma/beta (a Scale layer follows); set
            # Caffe's eps directly (default 1e-5) — no variance
            # eps-correction dance needed, unlike the reference's
            # convert_model.py:144-150
            out = mx.sym.BatchNorm(bot[0], fix_gamma=False,
                                   eps=float(p.get("eps", 1e-5)),
                                   use_global_stats=True, name=name)
        elif ltype == "SCALE":
            out = bot[0]  # folded into the preceding BatchNorm's gamma/beta
        elif ltype in ("SOFTMAX", "SOFTMAXWITHLOSS"):
            out = mx.sym.SoftmaxOutput(bot[0], name="softmax")
        elif ltype == "ACCURACY":
            continue
        else:
            raise NotImplementedError("caffe layer type %s" % ltype)
        for t in tops:
            blobs[t] = out

    return out, input_name


def input_dim(text):
    """The deploy-prototxt input shape: `input_dim:` repeated 4x,
    `input_shape { dim: ... }`, or a data layer's shape block."""
    net = parse_prototxt(text)
    if "input_dim" in net:
        dims = [int(d) for d in _as_list(net["input_dim"])]
        # multi-input deploy files repeat input_dim per input (4 each);
        # only the FIRST input is converted
        return tuple(dims[:4]) if len(dims) > 4 else tuple(dims)
    if "input_shape" in net:
        shp = _as_list(net["input_shape"])[0]
        return tuple(int(d) for d in _as_list(shp.get("dim")))
    for l in _as_list(net.get("layer") or net.get("layers")):
        if str(l.get("type", "")).upper() in ("INPUT", "DATA"):
            ip = l.get("input_param", {})
            if "shape" in ip:
                shp = _as_list(ip["shape"])[0]
                return tuple(int(d) for d in _as_list(shp.get("dim")))
    raise ValueError("prototxt declares no input shape")


def main():
    if len(sys.argv) < 2:
        print(__doc__)
        sys.exit(1)
    text = open(sys.argv[1]).read()
    sym, input_name = convert(text)
    out_path = sys.argv[2] if len(sys.argv) > 2 else sys.argv[1] + ".json"
    sym.save(out_path)
    print("wrote %s (input: %s, args: %d)"
          % (out_path, input_name, len(sym.list_arguments())))


if __name__ == "__main__":
    main()
