#!/usr/bin/env python
"""Convert a trained Caffe model (.prototxt + .caffemodel) into a
framework checkpoint — topology AND weights, no caffe dependency (the
binary protobuf is decoded by caffe_parser.py).

Mapping (the semantics of the reference's tools/caffe_converter/
convert_model.py:49-160, re-expressed):
- Convolution / InnerProduct / PReLU blobs -> <name>_weight/_bias
  (/_gamma), reshaped to the inferred arg shapes; the FIRST conv's
  input channels are swapped BGR->RGB when the net takes 3/4-channel
  images (Caffe datasets are BGR).
- BatchNorm blobs (mean, var, scale_factor) -> <name>_moving_mean/var
  divided by the scale factor. Caffe's eps is set on the symbol at
  conversion time (convert_symbol.py), so no variance correction term.
- Scale blobs -> <bn_name>_gamma/_beta of the preceding BatchNorm
  (layer named scale* pairs with bn*).

    python tools/caffe_converter/convert_model.py deploy.prototxt \
        net.caffemodel out_prefix
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import caffe_parser  # noqa: E402
from convert_symbol import convert, input_dim  # noqa: E402


def convert_model(prototxt_fname, caffemodel_fname, output_prefix=None):
    """Returns (sym, arg_params, aux_params, input_dim)."""
    import mxnet_tpu as mx

    text = open(prototxt_fname).read()
    sym, input_name = convert(text)
    in_dim = input_dim(text)
    arg_shapes, _, aux_shapes = sym.infer_shape(**{input_name: in_dim})
    arg_shape_dic = dict(zip(sym.list_arguments(), arg_shapes))
    aux_shape_dic = dict(zip(sym.list_auxiliary_states(), aux_shapes))

    arg_params, aux_params = {}, {}
    first_conv = True
    for layer in caffe_parser.read_caffemodel(caffemodel_fname):
        name, ltype, blobs = layer["name"], layer["type"], layer["blobs"]
        if not blobs:
            continue
        if ltype in ("Convolution", "InnerProduct"):
            wmat = np.asarray(blobs[0], np.float32)
            wname = name + "_weight"
            if wname not in arg_shape_dic:
                print("skipping %s: %s not in symbol" % (name, wname))
                continue
            wmat = wmat.reshape(arg_shape_dic[wname])
            if (first_conv and ltype == "Convolution"
                    and wmat.shape[1] in (3, 4)):
                wmat = wmat.copy()
                wmat[:, [0, 2]] = wmat[:, [2, 0]]   # BGR -> RGB
            arg_params[wname] = mx.nd.array(wmat)
            if len(blobs) > 1:
                bname = name + "_bias"
                arg_params[bname] = mx.nd.array(
                    np.asarray(blobs[1], np.float32).reshape(
                        arg_shape_dic[bname]))
            if ltype == "Convolution":
                first_conv = False
        elif ltype == "PReLU":
            gname = name + "_gamma"
            if gname not in arg_shape_dic:
                print("skipping %s: %s not in symbol" % (name, gname))
                continue
            arg_params[gname] = mx.nd.array(
                np.asarray(blobs[0], np.float32).reshape(
                    arg_shape_dic[gname]))
        elif ltype == "BatchNorm":
            if ("%s_moving_mean" % name) not in aux_shape_dic:
                print("skipping %s: not in symbol" % name)
                continue
            if len(blobs) < 3:
                print("skipping %s: %d blobs (expected mean/var/scale)"
                      % (name, len(blobs)))
                continue
            sf = float(np.asarray(blobs[2], np.float32).ravel()[0])
            sf = 1.0 / sf if sf != 0 else 0.0
            for key, blob in (("moving_mean", blobs[0]),
                              ("moving_var", blobs[1])):
                full = "%s_%s" % (name, key)
                aux_params[full] = mx.nd.array(
                    np.asarray(blob, np.float32).reshape(
                        aux_shape_dic[full]) * sf)
        elif ltype == "Scale":
            bn_name = name.replace("scale", "bn")
            # bias_term defaults to false in caffe.proto: a Scale layer
            # may carry only gamma — beta then stays at the zero default
            pairs = [("gamma", blobs[0])]
            if len(blobs) > 1:
                pairs.append(("beta", blobs[1]))
            for key, blob in pairs:
                full = "%s_%s" % (bn_name, key)
                if full not in arg_shape_dic:
                    print("skipping %s: %s not in symbol" % (name, full))
                    break
                arg_params[full] = mx.nd.array(
                    np.asarray(blob, np.float32).reshape(
                        arg_shape_dic[full]))
        else:
            print("skipping layer %s of type %s (%d blobs)"
                  % (name, ltype, len(blobs)))

    # BatchNorms with no Scale partner: identity gamma/beta
    for aname, shp in arg_shape_dic.items():
        if aname not in arg_params and aname != input_name:
            if aname.endswith("_gamma"):
                arg_params[aname] = mx.nd.array(np.ones(shp, np.float32))
            elif aname.endswith("_beta"):
                arg_params[aname] = mx.nd.array(np.zeros(shp, np.float32))

    if output_prefix is not None:
        sym.save(output_prefix + "-symbol.json")
        payload = {"arg:%s" % k: v for k, v in arg_params.items()}
        payload.update({"aux:%s" % k: v for k, v in aux_params.items()})
        mx.nd.save(output_prefix + "-0000.params", payload)
    return sym, arg_params, aux_params, in_dim


def main():
    if len(sys.argv) < 3:
        print(__doc__)
        sys.exit(1)
    prefix = sys.argv[3] if len(sys.argv) > 3 else "converted"
    sym, arg_params, aux_params, in_dim = convert_model(
        sys.argv[1], sys.argv[2], prefix)
    print("wrote %s-symbol.json / %s-0000.params (input %s, %d args, "
          "%d aux)" % (prefix, prefix, in_dim, len(arg_params),
                       len(aux_params)))


if __name__ == "__main__":
    main()
