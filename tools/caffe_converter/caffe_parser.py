"""Minimal protobuf *wire-format* reader for .caffemodel files — no
caffe or protobuf dependency (the reference's caffe_parser.py imports
pycaffe / compiled caffe_pb2; here the handful of NetParameter fields
the converter needs are decoded straight from the wire encoding).

Field numbers (public caffe.proto):
  NetParameter: name=1, layers(V1)=2, input=3, input_dim=4, layer=100
  LayerParameter:   name=1, type=2(string), blobs=7
  V1LayerParameter: name=4, type=5(enum),  blobs=6
  BlobProto: num=1 channels=2 height=3 width=4 (legacy 4D),
             data=5 (packed float), shape=7 (BlobShape), double_data=9
  BlobShape: dim=1 (packed int64)
"""
from __future__ import annotations

import struct

import numpy as np

# V1LayerType enum -> layer-name strings (upstream caffe.proto; V1
# predates BatchNorm/Scale, so those only appear in the new format)
V1_TYPE_NAMES = {1: "Accuracy", 3: "Concat", 4: "Convolution", 5: "Data",
                 6: "Dropout", 8: "Flatten", 12: "ImageData",
                 14: "InnerProduct", 15: "LRN", 17: "Pooling", 18: "ReLU",
                 19: "Sigmoid", 20: "Softmax", 21: "SoftmaxWithLoss",
                 23: "TanH", 25: "Eltwise", 39: "Deconvolution"}


def _varint(buf, o):
    x = 0
    shift = 0
    while True:
        b = buf[o]
        o += 1
        x |= (b & 0x7F) << shift
        if not b & 0x80:
            return x, o
        shift += 7


def walk(buf):
    """Yield (field_number, wire_type, value) over one message's fields.
    wire 0 -> int, 1 -> 8 raw bytes, 2 -> bytes, 5 -> 4 raw bytes."""
    o = 0
    n = len(buf)
    while o < n:
        key, o = _varint(buf, o)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, o = _varint(buf, o)
        elif wire == 1:
            v = buf[o:o + 8]
            o += 8
        elif wire == 2:
            ln, o = _varint(buf, o)
            v = buf[o:o + ln]
            o += ln
        elif wire == 5:
            v = buf[o:o + 4]
            o += 4
        else:
            raise ValueError("unsupported wire type %d (field %d)"
                             % (wire, field))
        yield field, wire, v


def _packed(wire_payloads, scalar_fmt):
    """Decode a repeated scalar field that may be packed (one
    length-delimited payload) or unpacked (one 4/8-byte entry per
    element)."""
    out = []
    for wire, v in wire_payloads:
        out.append(np.frombuffer(bytes(v), dtype=scalar_fmt))
    return (np.concatenate(out) if out
            else np.zeros(0, np.dtype(scalar_fmt)))


def _parse_blob(buf):
    shape = None
    legacy = {}
    data_parts, ddata_parts = [], []
    for field, wire, v in walk(buf):
        if field == 7 and wire == 2:              # BlobShape
            dims = []
            for f2, w2, v2 in walk(v):
                if f2 == 1:
                    if w2 == 2:                   # packed int64 varints
                        o = 0
                        while o < len(v2):
                            d, o = _varint(v2, o)
                            dims.append(d)
                    else:
                        dims.append(v2)
            shape = tuple(dims)
        elif field == 5:                          # data (float)
            data_parts.append((wire, v))
        elif field == 9:                          # double_data
            ddata_parts.append((wire, v))
        elif field in (1, 2, 3, 4) and wire == 0:  # legacy num/c/h/w
            legacy[field] = v
    if ddata_parts:
        data = _packed(ddata_parts, "<f8").astype(np.float32)
    else:
        data = np.asarray(_packed(data_parts, "<f4"))
    if shape is None and legacy:
        shape = tuple(legacy.get(k, 1) for k in (1, 2, 3, 4))
    if shape is not None and int(np.prod(shape)) == data.size:
        data = data.reshape(shape)
    return data


def _parse_layer(buf, v1):
    name, ltype = "", ""
    blobs = []
    f_name, f_type, f_blobs = (4, 5, 6) if v1 else (1, 2, 7)
    for field, wire, v in walk(buf):
        if field == f_name and wire == 2:
            name = v.decode()
        elif field == f_type:
            if v1:
                ltype = V1_TYPE_NAMES.get(int(v), str(int(v)))
            else:
                ltype = v.decode()
        elif field == f_blobs and wire == 2:
            blobs.append(_parse_blob(v))
    return {"name": name, "type": ltype, "blobs": blobs}


def read_caffemodel(fname_or_bytes):
    """Parse a .caffemodel binary NetParameter. Returns a list of
    {"name", "type", "blobs": [np.ndarray, ...]} in file order (layers
    without learned blobs included, blobs empty)."""
    if isinstance(fname_or_bytes, bytes):
        data = fname_or_bytes
    else:
        with open(fname_or_bytes, "rb") as f:
            data = f.read()
    layers = []
    for field, wire, v in walk(data):
        if field == 100 and wire == 2:            # LayerParameter
            layers.append(_parse_layer(v, v1=False))
        elif field == 2 and wire == 2:            # V1LayerParameter
            layers.append(_parse_layer(v, v1=True))
    return layers
