"""mxnet_tpu.analysis — static concurrency/purity checks for the package.

Three pure-``ast`` checkers (no module under analysis is imported):

- :mod:`.lockorder`     global lock-acquisition graph: cycles, declared-
                        hierarchy violations, callbacks under locks
- :mod:`.engine_lint`   push_async const/mutable-vars discipline,
                        waitall()/drain loops used as fences
- :mod:`.trace_purity`  impure calls and state mutation inside
                        jit/shard_map-traced functions and pure_callback
                        callbacks
- :mod:`.progcache_io`  persistent-cache commit discipline: every write
                        in a progcache module goes through the atomic
                        tmp+``os.replace`` helper (no raw
                        ``open(path, 'wb')`` commits)
- :mod:`.racecheck`     happens-before discipline: undeclared state
                        touched by pushed closures (interprocedural,
                        through aliases/helpers), host reads of pushed
                        state with no fence between, engine-var use
                        after ``delete_variable`` — the static half of
                        the ``MXNET_ENGINE_SANITIZER`` pair
- :mod:`.compilesurface` bounded-program invariant: jit sites outside
                        the sanctioned surfaces, weights closed over by
                        traced fns, donated buffers dereferenced after
                        the call, sanctioned surfaces missing a
                        :data:`PROGRAM_BUDGETS` bound — the static half
                        of the ``MXNET_COMPILE_WITNESS`` pair

Run ``python -m mxnet_tpu.analysis --fail-on-new`` (the CI gate) or use
:func:`run_analysis` programmatically. Findings carry stable fingerprints;
``ci/analysis_baseline.json`` allowlists justified ones. The runtime
complements are :class:`.witness.LockOrderWitness` and
:mod:`.compile_witness`.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from . import compile_witness
from .compilesurface import PROGRAM_BUDGETS, SANCTIONED_SURFACES
from .core import (Finding, SourceModule, dedupe, diff_against_baseline,
                   load_baseline, load_modules, write_baseline)
from .lockorder import LOCK_HIERARCHY
from .witness import LockOrderWitness

CHECKERS = ("lockorder", "engine", "purity", "progcache_io", "racecheck",
            "compilesurface")


def run_analysis(root: str,
                 checks: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run the selected checkers (default: all) over every ``*.py`` under
    ``root`` and return deduped, location-sorted findings."""
    from . import (compilesurface, engine_lint, lockorder, progcache_io,
                   racecheck, trace_purity)
    checks = tuple(checks) if checks else CHECKERS
    modules = load_modules(root)
    findings: List[Finding] = []
    if "lockorder" in checks:
        findings += lockorder.check(modules)
    if "engine" in checks:
        findings += engine_lint.check(modules)
    if "purity" in checks:
        findings += trace_purity.check(modules)
    if "progcache_io" in checks:
        findings += progcache_io.check(modules)
    if "racecheck" in checks:
        findings += racecheck.check(modules)
    if "compilesurface" in checks:
        findings += compilesurface.check(modules)
    return dedupe(findings)


__all__ = ["Finding", "SourceModule", "LockOrderWitness", "LOCK_HIERARCHY",
           "CHECKERS", "PROGRAM_BUDGETS", "SANCTIONED_SURFACES",
           "compile_witness", "run_analysis", "load_modules",
           "load_baseline", "write_baseline", "diff_against_baseline",
           "dedupe"]
