"""Lock-order analyzer.

Builds a global lock-acquisition graph over the package: every
``threading.Lock/RLock/Condition`` assigned to a module global or a
``self.<attr>`` is a node; acquiring B (``with``-block or ``acquire()``)
while holding A is an edge A -> B, including edges discovered
*interprocedurally* (holding A and calling a function that may acquire B).
Findings:

- ``lock-cycle``          the edge graph has a cycle (the ABBA shape)
- ``lock-hierarchy``      an edge contradicts the declared hierarchy
                          (:data:`LOCK_HIERARCHY`): acquiring a lower- or
                          equal-ranked lock while holding a higher one.
                          Equal ranks declare PEER locks — no nesting in
                          either direction (the serving former/metrics
                          contract from PR 2).
- ``callback-under-lock`` a value called while a lock/condition is held
                          resolves to *user-supplied code* (a callable
                          attribute, parameter, or local non-def), directly
                          or through callees — the exact shape of both PR 2
                          serving deadlocks.
- ``lock-self-deadlock``  re-acquiring a held non-reentrant Lock/Condition
                          (directly or through a callee)
- ``lock-group-multi-acquire``  acquiring members of a lock *group* (a
                          list of locks under one attribute) in a loop —
                          safe only under a total order; must be justified
                          in the baseline.

Resolution is deliberately conservative: ``self.x.m()`` only creates call
edges when ``x``'s class is known (ctor assignment, parameter annotation,
or the assigning method's return annotation); unknown receivers create no
edges and no findings, keeping false positives near zero at the cost of
missing exotic aliasing.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, SourceModule, dotted, import_aliases, unparse

LOCK_CTORS = {
    "threading.Lock": "lock", "threading.RLock": "rlock",
    "threading.Condition": "cond",
    "Lock": "lock", "RLock": "rlock", "Condition": "cond",
}
#: methods on a lock object that are lock protocol, not user callbacks
LOCK_METHODS = {"acquire", "release", "wait", "wait_for", "notify",
                "notify_all", "locked", "__enter__", "__exit__"}

#: Declared lock hierarchy for the package (docs/concurrency.md). Ids are
#: package-root-relative (``mxnet_tpu.`` prefix is stripped before lookup).
#: Acquiring B while holding A requires rank(B) > rank(A); EQUAL ranks
#: declare peer locks that must never nest in either direction; rank 100
#: marks leaf locks (nothing ranked may be acquired under them).
LOCK_HIERARCHY: Dict[str, int] = {
    # engine: the file-write table may create engine vars (engine singleton
    # lock) while holding _file_lock; never the reverse.
    "engine._file_lock": 10,
    "engine._engine_lock": 20,
    "engine.NativeEngine._pending_lock": 100,
    # in-flight gauge table: leaf — the begin/end hooks run inside engine
    # worker callbacks and must never wait on anything ranked.
    "engine._inflight_lock": 100,
    # capture/replay state machine: leaf — state flips only; pushes,
    # callbacks, and logging all happen outside the hold.
    "engine.CapturedSequence._lock": 100,
    # happens-before sanitizer shadow tables: leaf — epoch/guard bookkeeping
    # only; report logging and the telemetry counter inc happen after release.
    "engine._san_lock": 100,
    # serving: former condition and metrics lock are PEERS — the PR 2 ABBA
    # contract: neither side calls into the other under its own lock.
    "serving.batcher.BatchFormer._cond": 50,
    "serving.metrics.ServingMetrics._lock": 50,
    "serving.bucket_cache.BucketCache._lock": 100,
    # staging pool buffer table: leaf — fill()/retain() touch only numpy
    # buffers under it.
    "serving.staging.StagingPool._lock": 100,
    # decode scheduler condition: same stratum as the former — engine
    # pushes/fences (rank 20) NEVER happen under it; stream/kv leaf locks
    # may be taken under it.
    "serving.generate.scheduler.DecodeScheduler._cond": 50,
    # decode leaves: slot bookkeeping and per-stream token delivery only.
    "serving.generate.kv_cache.KVCacheManager._lock": 100,
    # paged block-table lock: leaf — block/refcount/prefix-registry
    # bookkeeping only; engine pushes, device calls, and telemetry all
    # happen outside the hold.
    "serving.generate.paged.PagedKVCacheManager._lock": 100,
    "serving.generate.stream.TokenStream._cond": 100,
    # HTTP admission gate: leaf — in-flight counter + draining flag only;
    # the queue-depth policy reads (former._cond, rank 50) happen strictly
    # outside the hold.
    "serving.frontend.admission.AdmissionController._lock": 100,
    # frontend stop() one-shot guard: leaf — a single flag flip under it.
    "serving.frontend.server.HttpFrontend._stop_once": 100,
    # predictor run path: leaf — forward() holds it across the compiled
    # call but never acquires anything ranked inside.
    "predict.Predictor._run_lock": 100,
    # kvstore PS client: per-address data locks and the control-channel
    # lock are peers — liveness RPCs must work while data RPCs block.
    "kvstore_server.PSClient._locks[*]": 60,
    "kvstore_server.PSClient._ctrl_lock": 60,
    "kvstore.PSKVStore._errs_lock": 100,
    # fault-injection plan table: leaf — match/fire bookkeeping only; the
    # telemetry counter inc happens after release (docs/fault_tolerance.md).
    "resilience.faults._lock": 100,
    # persistent program cache: leaf — guards manifest read-modify-write
    # and the session stat dict only; executable serialization, entry
    # commits, and telemetry increments happen outside holds of it.
    "progcache._lock": 100,
    # compile witness record tables: leaf — dict bookkeeping only; the
    # telemetry counter increments happen after release. May nest under
    # other leaves (BucketCache._lock builds programs under its hold) —
    # safe because nothing is ever acquired under THIS lock.
    "analysis.compile_witness._lock": 100,
    "torch._TH_LOCK": 90,
    "io.DevicePrefetchIter._lock": 100,
    "random._lock": 100,
    "filesystem._MEMORY_LOCK": 100,
}

FuncKey = Tuple[str, Optional[str], str]  # (module, class|None, func)


def _ctor_kind(call: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """'lock'/'rlock'/'cond' if ``call`` constructs a threading lock."""
    if not isinstance(call, ast.Call):
        return None
    d = dotted(call.func)
    if d is None:
        return None
    if d in LOCK_CTORS:
        # bare names must come from threading (import-aware)
        if "." not in d and aliases.get(d, "") != "threading.%s" % d:
            return None
        return LOCK_CTORS[d]
    return None


def _group_kind(value: ast.AST, aliases) -> Optional[str]:
    """Lock kind if ``value`` is a list/comprehension of lock ctors."""
    if isinstance(value, ast.ListComp):
        return _ctor_kind(value.elt, aliases)
    if isinstance(value, (ast.List, ast.Tuple)) and value.elts:
        kinds = {_ctor_kind(e, aliases) for e in value.elts}
        if len(kinds) == 1 and None not in kinds:
            return kinds.pop()
    return None


class _ClassInfo:
    def __init__(self, modname: str, name: str):
        self.modname = modname
        self.name = name
        self.bases: List[str] = []          # dotted base exprs
        self.methods: Dict[str, ast.FunctionDef] = {}
        self.lock_attrs: Dict[str, Tuple[str, str]] = {}  # attr -> (id, kind)
        self.attr_types: Dict[str, Tuple[str, str]] = {}  # attr -> class key


class _Index:
    """Package-wide symbol index built before summarization."""

    def __init__(self, modules: Sequence[SourceModule]):
        self.modules = modules
        self.aliases: Dict[str, Dict[str, str]] = {}
        self.classes: Dict[Tuple[str, str], _ClassInfo] = {}
        self.class_by_name: Dict[str, List[Tuple[str, str]]] = {}
        self.mod_funcs: Dict[Tuple[str, str], ast.FunctionDef] = {}
        self.mod_locks: Dict[str, Dict[str, Tuple[str, str]]] = {}
        self.lock_kinds: Dict[str, str] = {}  # lock id -> kind
        self.relpath: Dict[str, str] = {}     # modname -> relpath
        for m in modules:
            self._index_module(m)
        self._resolve_attr_types()

    def _index_module(self, m: SourceModule):
        al = import_aliases(m.tree)
        self.aliases[m.modname] = al
        self.relpath[m.modname] = m.relpath
        self.mod_locks[m.modname] = {}
        for node in m.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.mod_funcs[(m.modname, node.name)] = node
            elif isinstance(node, ast.ClassDef):
                ci = _ClassInfo(m.modname, node.name)
                ci.bases = [dotted(b) or "" for b in node.bases]
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        ci.methods[sub.name] = sub
                self.classes[(m.modname, node.name)] = ci
                self.class_by_name.setdefault(node.name, []).append(
                    (m.modname, node.name))
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        kind = _ctor_kind(node.value, al)
                        gkind = _group_kind(node.value, al)
                        if kind:
                            lid = "%s.%s" % (m.modname, t.id)
                            self.mod_locks[m.modname][t.id] = (lid, kind)
                            self.lock_kinds[lid] = kind
                        elif gkind:
                            lid = "%s.%s[*]" % (m.modname, t.id)
                            self.mod_locks[m.modname][t.id] = (lid, "group")
                            self.lock_kinds[lid] = "group"
        # second pass: self.<attr> assignments inside methods
        for (mod, cname), ci in list(self.classes.items()):
            if mod != m.modname:
                continue
            for meth in ci.methods.values():
                self._index_self_attrs(m, ci, meth)

    def _index_self_attrs(self, m: SourceModule, ci: _ClassInfo,
                          meth: ast.FunctionDef):
        al = self.aliases[m.modname]
        ann: Dict[str, ast.AST] = {
            a.arg: a.annotation for a in meth.args.args if a.annotation}
        for node in ast.walk(meth):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            t = node.targets[0]
            if not (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                continue
            kind = _ctor_kind(node.value, al)
            gkind = _group_kind(node.value, al)
            if kind:
                lid = "%s.%s.%s" % (ci.modname, ci.name, t.attr)
                ci.lock_attrs[t.attr] = (lid, kind)
                self.lock_kinds[lid] = kind
            elif gkind:
                lid = "%s.%s.%s[*]" % (ci.modname, ci.name, t.attr)
                ci.lock_attrs[t.attr] = (lid, "group")
                self.lock_kinds[lid] = "group"
            else:
                # remember the raw value for attr typing (resolved later,
                # once every class is indexed)
                ci.attr_types.setdefault(
                    t.attr, ("__raw__", (node.value, ann, ci)))  # type: ignore

    # --- class/type resolution -------------------------------------------
    def resolve_class(self, modname: str, ref) -> Optional[Tuple[str, str]]:
        """Resolve a class reference (dotted string or annotation AST) to a
        class key, searching the defining module, import aliases, then a
        package-unique bare name."""
        if ref is None:
            return None
        if isinstance(ref, ast.AST):
            if isinstance(ref, ast.Constant) and isinstance(ref.value, str):
                ref = ref.value
            else:
                ref = dotted(ref)
        if not isinstance(ref, str) or not ref:
            return None
        ref = ref.strip("'\"")
        name = ref.split(".")[-1]
        if (modname, name) in self.classes and ref == name:
            return (modname, name)
        al = self.aliases.get(modname, {})
        target = al.get(ref.split(".")[0])
        if target is not None:
            cands = self.class_by_name.get(name, [])
            for key in cands:
                if key[0].endswith(target.split(".")[0]) or \
                        target.endswith(key[0].split(".")[-1]):
                    return key
        cands = self.class_by_name.get(name, [])
        if len(cands) == 1:
            return cands[0]
        return None

    def _resolve_attr_types(self):
        for ci in self.classes.values():
            resolved: Dict[str, Tuple[str, str]] = {}
            for attr, val in ci.attr_types.items():
                if not (isinstance(val, tuple) and val[0] == "__raw__"):
                    continue
                value, ann, _ = val[1]
                key = None
                if isinstance(value, ast.Call):
                    d = dotted(value.func)
                    if d is not None:
                        # self.x = self._make()  ->  return annotation
                        if d.startswith("self."):
                            meth = self.lookup_method(
                                (ci.modname, ci.name), d.split(".", 1)[1])
                            if meth is not None and meth[1].returns \
                                    is not None:
                                key = self.resolve_class(
                                    meth[0][0], meth[1].returns)
                        else:
                            key = self.resolve_class(ci.modname, d)
                elif isinstance(value, ast.Name):
                    if value.id in ann:  # self.x = param  (annotated)
                        key = self.resolve_class(ci.modname, ann[value.id])
                    else:
                        # self.x = module_alias  (e.g. self._engine = engine)
                        al = self.aliases.get(ci.modname, {})
                        tgt = al.get(value.id)
                        if tgt is not None and tgt in self.relpath:
                            key = (tgt, None)  # module, not class
                if key is not None:
                    resolved[attr] = key
            ci.attr_types = resolved

    def lookup_method(self, cls_key: Tuple[str, str], name: str,
                      _seen=None) -> Optional[Tuple[Tuple[str, str],
                                                    ast.FunctionDef]]:
        """Find ``name`` on the class or its package bases (class key of
        the DEFINING class is returned)."""
        _seen = _seen or set()
        if cls_key in _seen or cls_key not in self.classes:
            return None
        _seen.add(cls_key)
        ci = self.classes[cls_key]
        if name in ci.methods:
            return cls_key, ci.methods[name]
        for b in ci.bases:
            bkey = self.resolve_class(ci.modname, b)
            if bkey is not None:
                hit = self.lookup_method(bkey, name, _seen)
                if hit is not None:
                    return hit
        return None

    def lookup_lock_attr(self, cls_key: Tuple[str, str], attr: str,
                         _seen=None) -> Optional[Tuple[str, str]]:
        _seen = _seen or set()
        if cls_key in _seen or cls_key not in self.classes:
            return None
        _seen.add(cls_key)
        ci = self.classes[cls_key]
        if attr in ci.lock_attrs:
            return ci.lock_attrs[attr]
        for b in ci.bases:
            bkey = self.resolve_class(ci.modname, b)
            if bkey is not None:
                hit = self.lookup_lock_attr(bkey, attr, _seen)
                if hit is not None:
                    return hit
        return None


class _Summary:
    """Per-function facts feeding the interprocedural fixpoint."""

    def __init__(self, key: FuncKey, relpath: str):
        self.key = key
        self.relpath = relpath
        self.direct_acquires: Set[str] = set()
        # (held frozenset, callee key, line)
        self.calls: List[Tuple[frozenset, FuncKey, int]] = []
        # (held frozenset, callback desc, line)
        self.callbacks: List[Tuple[frozenset, str, int]] = []
        # (src, dst, line)
        self.nest_edges: List[Tuple[str, str, int]] = []
        self.reacquires: List[Tuple[str, int]] = []
        self.group_loop_acquires: List[Tuple[str, int]] = []

    @property
    def qualname(self) -> str:
        mod, cls, fn = self.key
        return "%s:%s" % (mod, ("%s.%s" % (cls, fn)) if cls else fn)


class _FnScanner:
    """Linear scan of one function body tracking the held-lock stack."""

    def __init__(self, index: _Index, summary: _Summary,
                 cls_key: Optional[Tuple[str, str]], modname: str):
        self.ix = index
        self.s = summary
        self.cls_key = cls_key
        self.modname = modname
        self.held: List[str] = []
        self.loop_depth = 0
        self.params: Set[str] = set()
        self.local_defs: Dict[str, FuncKey] = {}
        self.local_types: Dict[str, Tuple[str, str]] = {}
        self.assigned: Set[str] = set()

    # --- lock expression resolution --------------------------------------
    def resolve_lock(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Name):
            hit = self.ix.mod_locks.get(self.modname, {}).get(node.id)
            return hit[0] if hit else None
        if isinstance(node, ast.Subscript):
            return self.resolve_lock(node.value)
        if isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name) and node.value.id == "self" \
                    and self.cls_key is not None:
                hit = self.ix.lookup_lock_attr(self.cls_key, node.attr)
                if hit:
                    return hit[0]
                return None
            # obj.attr where obj's class is known
            ckey = self._type_of(node.value)
            if ckey is not None and ckey[1] is not None:
                hit = self.ix.lookup_lock_attr(ckey, node.attr)
                if hit:
                    return hit[0]
        return None

    def _type_of(self, node: ast.AST) -> Optional[Tuple[str, str]]:
        """Class (or (module, None)) of an expression, where inferable."""
        if isinstance(node, ast.Name):
            if node.id in self.local_types:
                return self.local_types[node.id]
            al = self.ix.aliases.get(self.modname, {})
            tgt = al.get(node.id)
            if tgt is not None and tgt in self.ix.relpath:
                return (tgt, None)
            return None
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and node.value.id == "self" \
                and self.cls_key is not None:
            ci = self.ix.classes.get(self.cls_key)
            if ci is not None:
                return ci.attr_types.get(node.attr)
        return None

    # --- held-state events ------------------------------------------------
    def on_acquire(self, lid: str, line: int, via_with: bool):
        kind = self.ix.lock_kinds.get(lid, "lock")
        if lid in self.held:
            if kind == "group":
                self.s.group_loop_acquires.append((lid, line))
            elif kind != "rlock":
                self.s.reacquires.append((lid, line))
        elif kind == "group" and self.loop_depth > 0 and not via_with:
            self.s.group_loop_acquires.append((lid, line))
        for h in self.held:
            if h != lid:
                self.s.nest_edges.append((h, lid, line))
        self.s.direct_acquires.add(lid)
        self.held.append(lid)

    def on_release(self, lid: str):
        if lid in self.held:
            self.held.reverse()
            self.held.remove(lid)
            self.held.reverse()

    # --- statements -------------------------------------------------------
    def scan_function(self, fn: ast.FunctionDef):
        args = fn.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            self.params.add(a.arg)
        if args.vararg:
            self.params.add(args.vararg.arg)
        if args.kwarg:
            self.params.add(args.kwarg.arg)
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.For,
                                 ast.withitem, ast.AnnAssign)):
                tgt = getattr(node, "targets", None) or \
                    [getattr(node, "target", None) or
                     getattr(node, "optional_vars", None)]
                for t in tgt:
                    if isinstance(t, ast.Name):
                        self.assigned.add(t.id)
                    elif isinstance(t, ast.Tuple):
                        for e in t.elts:
                            if isinstance(e, ast.Name):
                                self.assigned.add(e.id)
        # local var types from annotated/ctor assignments
        for node in fn.body:
            self._maybe_local_type(node)
        self.scan_block(fn.body)

    def _maybe_local_type(self, node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Call):
            d = dotted(node.value.func)
            key = self.ix.resolve_class(self.modname, d) if d else None
            if key is not None:
                self.local_types[node.targets[0].id] = key
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name):
            key = self.ix.resolve_class(self.modname, node.annotation)
            if key is not None:
                self.local_types[node.target.id] = key

    def scan_block(self, stmts: Sequence[ast.stmt]):
        for st in stmts:
            self.scan_stmt(st)

    def scan_stmt(self, st: ast.stmt):
        if isinstance(st, (ast.With, ast.AsyncWith)):
            acquired = []
            for item in st.items:
                lid = self.resolve_lock(item.context_expr)
                if lid is not None:
                    self.on_acquire(lid, st.lineno, via_with=True)
                    acquired.append(lid)
                else:
                    self.scan_expr(item.context_expr)
            self.scan_block(st.body)
            for lid in reversed(acquired):
                self.on_release(lid)
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            mod, cls, fn = self.s.key
            self.local_defs[st.name] = (mod, cls, "%s.%s" % (fn, st.name))
        elif isinstance(st, (ast.For, ast.AsyncFor, ast.While)):
            for field in ("iter", "test"):
                val = getattr(st, field, None)
                if val is not None:
                    self.scan_expr(val)
            self.loop_depth += 1
            self.scan_block(st.body)
            self.scan_block(st.orelse)
            self.loop_depth -= 1
        elif isinstance(st, ast.If):
            self.scan_expr(st.test)
            self.scan_block(st.body)
            self.scan_block(st.orelse)
        elif isinstance(st, ast.Try):
            self.scan_block(st.body)
            for h in st.handlers:
                self.scan_block(h.body)
            self.scan_block(st.orelse)
            self.scan_block(st.finalbody)
        elif isinstance(st, ast.ClassDef):
            pass  # nested classes: out of scope
        else:
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.expr):
                    self.scan_expr(child)

    # --- expressions ------------------------------------------------------
    def scan_expr(self, expr: ast.AST):
        """Find calls, skipping lambda/def bodies (they run later, not
        under the current held set)."""
        for node in self._walk_expr(expr):
            if isinstance(node, ast.Call):
                self.handle_call(node)

    def _walk_expr(self, expr):
        stack = [expr]
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.Lambda, ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                continue
            stack.extend(ast.iter_child_nodes(node))

    def handle_call(self, call: ast.Call):
        f = call.func
        held = frozenset(self.held)
        line = call.lineno
        if isinstance(f, ast.Attribute):
            lid = self.resolve_lock(f.value)
            if lid is not None and f.attr in LOCK_METHODS:
                if f.attr == "acquire":
                    self.on_acquire(lid, line, via_with=False)
                elif f.attr == "release":
                    self.on_release(lid)
                return
            if isinstance(f.value, ast.Name) and f.value.id == "self" and \
                    self.cls_key is not None:
                hit = self.ix.lookup_method(self.cls_key, f.attr)
                if hit is not None:
                    dkey, _ = hit
                    self.s.calls.append(
                        (held, (dkey[0], dkey[1], f.attr), line))
                    return
                ci = self.ix.classes.get(self.cls_key)
                tkey = ci.attr_types.get(f.attr) if ci else None
                if tkey is not None and tkey[1] is not None:
                    # callable class instance: route to __call__
                    hit = self.ix.lookup_method(tkey, "__call__")
                    if hit is not None:
                        self.s.calls.append(
                            (held, (tkey[0], tkey[1], "__call__"), line))
                        return
                # unresolvable callable attribute: user-supplied callback —
                # unless the class has an external (unresolvable) base, in
                # which case the attr may be an inherited library method
                # (e.g. BytesIO.getvalue) and flagging it would be noise.
                # Recorded even with nothing held: a CALLER holding a lock
                # inherits this via may_callback (the _fail/_error_hook
                # shape); direct findings are emitted only for held != {}.
                if ci is not None and all(
                        self.ix.resolve_class(self.modname, b) is not None
                        for b in ci.bases if b and b != "object"):
                    self.s.callbacks.append(
                        (held, "self.%s" % f.attr, line))
                return
            tkey = self._type_of(f.value)
            if tkey is not None:
                if tkey[1] is None:  # module reference
                    fn = self.ix.mod_funcs.get((tkey[0], f.attr))
                    if fn is not None:
                        self.s.calls.append(
                            (held, (tkey[0], None, f.attr), line))
                    return
                hit = self.ix.lookup_method(tkey, f.attr)
                if hit is not None:
                    dkey, _ = hit
                    self.s.calls.append(
                        (held, (dkey[0], dkey[1], f.attr), line))
                return
            # module-alias function call: engine.push(...)
            d = dotted(f)
            if d is not None and "." in d:
                head, rest = d.split(".", 1)
                al = self.ix.aliases.get(self.modname, {})
                tgt = al.get(head)
                if tgt is not None and tgt in self.ix.relpath and \
                        "." not in rest:
                    if (tgt, rest) in self.ix.mod_funcs:
                        self.s.calls.append((held, (tgt, None, rest), line))
            return
        if isinstance(f, ast.Name):
            if f.id in self.local_defs:
                self.s.calls.append((held, self.local_defs[f.id], line))
                return
            if (self.modname, f.id) in self.ix.mod_funcs:
                self.s.calls.append((held, (self.modname, None, f.id), line))
                return
            ckey = self.ix.resolve_class(self.modname, f.id)
            al = self.ix.aliases.get(self.modname, {})
            if ckey is not None and (f.id in al or
                                     (self.modname, f.id) in self.ix.classes):
                init = self.ix.lookup_method(ckey, "__init__")
                if init is not None:
                    dkey, _ = init
                    self.s.calls.append(
                        (held, (dkey[0], dkey[1], "__init__"), line))
                return
            if f.id in self.params or (f.id in self.assigned and
                                       f.id not in self.local_defs):
                # calling a parameter / untyped local: user-supplied code
                self.s.callbacks.append((held, f.id, line))


def _collect_summaries(index: _Index) -> Dict[FuncKey, _Summary]:
    summaries: Dict[FuncKey, _Summary] = {}

    def scan(fn: ast.FunctionDef, key: FuncKey,
             cls_key: Optional[Tuple[str, str]], modname: str,
             relpath: str):
        s = _Summary(key, relpath)
        sc = _FnScanner(index, s, cls_key, modname)
        sc.scan_function(fn)
        summaries[key] = s
        # nested defs become their own summaries (executed later — fresh
        # held state), reachable through local_defs call edges
        for st in ast.walk(fn):
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and st is not fn and st.name in sc.local_defs:
                nkey = sc.local_defs[st.name]
                if nkey not in summaries:
                    scan(st, nkey, cls_key, modname, relpath)

    for (mod, name), fn in list(index.mod_funcs.items()):
        scan(fn, (mod, None, name), None, mod, index.relpath[mod])
    for (mod, cname), ci in list(index.classes.items()):
        for mname, fn in ci.methods.items():
            scan(fn, (mod, cname, mname), (mod, cname), mod,
                 index.relpath[mod])
    return summaries


def _fixpoint(summaries: Dict[FuncKey, _Summary]):
    may_acquire: Dict[FuncKey, Set[str]] = {
        k: set(s.direct_acquires) for k, s in summaries.items()}
    may_callback: Dict[FuncKey, Set[str]] = {
        k: {d for _, d, _ in s.callbacks} for k, s in summaries.items()}
    changed = True
    while changed:
        changed = False
        for k, s in summaries.items():
            for _, callee, _ in s.calls:
                if callee not in summaries:
                    continue
                if not may_acquire[callee] <= may_acquire[k]:
                    may_acquire[k] |= may_acquire[callee]
                    changed = True
                if not may_callback[callee] <= may_callback[k]:
                    may_callback[k] |= may_callback[callee]
                    changed = True
    return may_acquire, may_callback


def _norm(lock_id: str) -> str:
    return lock_id[len("mxnet_tpu."):] if lock_id.startswith("mxnet_tpu.") \
        else lock_id


def _find_cycles(edges: Dict[str, Set[str]]) -> List[List[str]]:
    """Strongly connected components with >1 node (Tarjan, iterative)."""
    idx, low, on, order, stack = {}, {}, set(), [], []
    sccs, counter = [], [0]

    def strongconnect(v):
        work = [(v, iter(sorted(edges.get(v, ()))))]
        idx[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in idx:
                    idx[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(sorted(edges.get(w, ())))))
                    advanced = True
                    break
                elif w in on:
                    low[node] = min(low[node], idx[w])
            if advanced:
                continue
            work.pop()
            if work:
                low[work[-1][0]] = min(low[work[-1][0]], low[node])
            if low[node] == idx[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                if len(scc) > 1:
                    sccs.append(sorted(scc))

    nodes = set(edges)
    for ds in edges.values():
        nodes |= ds
    for v in sorted(nodes):
        if v not in idx:
            strongconnect(v)
    return sccs


def check(modules: Sequence[SourceModule],
          hierarchy: Optional[Dict[str, int]] = None) -> List[Finding]:
    hierarchy = LOCK_HIERARCHY if hierarchy is None else hierarchy
    index = _Index(modules)
    summaries = _collect_summaries(index)
    may_acquire, may_callback = _fixpoint(summaries)

    findings: List[Finding] = []
    # (src, dst) -> (relpath, line, qualname) of first witness
    edge_where: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

    for k, s in summaries.items():
        for src, dst, line in s.nest_edges:
            edge_where.setdefault((src, dst), (s.relpath, line, s.qualname))
        for held, callee, line in s.calls:
            if callee not in summaries:
                continue
            for h in sorted(held):
                for a in sorted(may_acquire[callee]):
                    if a == h:
                        kind = index.lock_kinds.get(h, "lock")
                        callee_q = summaries[callee].qualname
                        if kind == "group":
                            findings.append(Finding(
                                "lockorder", "lock-group-multi-acquire",
                                s.relpath, line, s.qualname,
                                "%s via %s" % (_norm(h), callee_q),
                                "lock group %s re-acquired through call to "
                                "%s while a member is already held" %
                                (_norm(h), callee_q)))
                        elif kind != "rlock":
                            findings.append(Finding(
                                "lockorder", "lock-self-deadlock",
                                s.relpath, line, s.qualname,
                                "%s via %s" % (_norm(h), callee_q),
                                "%s (non-reentrant) may be re-acquired "
                                "through call to %s while held — "
                                "self-deadlock" % (_norm(h), callee_q)))
                    else:
                        edge_where.setdefault(
                            (h, a), (s.relpath, line, s.qualname))
            if held and may_callback[callee]:
                callee_q = summaries[callee].qualname
                for h in sorted(held):
                    for desc in sorted(may_callback[callee]):
                        findings.append(Finding(
                            "lockorder", "callback-under-lock",
                            s.relpath, line, s.qualname,
                            "%s->%s->%s" % (_norm(h), callee_q, desc),
                            "callback %s (via %s) runs while %s is held — "
                            "arbitrary user code under a lock is the PR 2 "
                            "deadlock shape" %
                            (desc, callee_q, _norm(h))))
        for held, desc, line in s.callbacks:
            for h in sorted(held):
                findings.append(Finding(
                    "lockorder", "callback-under-lock", s.relpath, line,
                    s.qualname, "%s->%s" % (_norm(h), desc),
                    "callback %s invoked while %s is held — arbitrary "
                    "user code under a lock is the PR 2 deadlock shape" %
                    (desc, _norm(h))))
        for lid, line in s.reacquires:
            findings.append(Finding(
                "lockorder", "lock-self-deadlock", s.relpath, line,
                s.qualname, _norm(lid),
                "%s (non-reentrant) acquired while already held" %
                _norm(lid)))
        for lid, line in s.group_loop_acquires:
            findings.append(Finding(
                "lockorder", "lock-group-multi-acquire", s.relpath, line,
                s.qualname, _norm(lid),
                "multiple members of lock group %s acquired without "
                "releasing — correct only under a total acquisition "
                "order; justify in the baseline" % _norm(lid)))

    # hierarchy violations on the witnessed edge set
    for (src, dst), (relpath, line, qual) in sorted(edge_where.items()):
        rs, rd = hierarchy.get(_norm(src)), hierarchy.get(_norm(dst))
        if rs is None or rd is None:
            continue
        if rd < rs:
            findings.append(Finding(
                "lockorder", "lock-hierarchy", relpath, line, qual,
                "%s->%s" % (_norm(src), _norm(dst)),
                "%s (rank %d) acquired while holding %s (rank %d) — "
                "violates the declared hierarchy (docs/concurrency.md)" %
                (_norm(dst), rd, _norm(src), rs)))
        elif rd == rs:
            findings.append(Finding(
                "lockorder", "lock-hierarchy", relpath, line, qual,
                "%s-><-%s" % (_norm(src), _norm(dst)),
                "%s and %s are declared PEER locks (equal rank %d) — they "
                "must never nest (docs/concurrency.md)" %
                (_norm(src), _norm(dst), rs)))

    # global cycles
    graph: Dict[str, Set[str]] = {}
    for (src, dst) in edge_where:
        graph.setdefault(src, set()).add(dst)
    for scc in _find_cycles(graph):
        witnesses = sorted(
            (edge_where[(a, b)] + (a, b))
            for a in scc for b in graph.get(a, ()) if b in scc)
        relpath, line, qual = witnesses[0][:3]
        detail = "; ".join("%s->%s at %s:%d" % (_norm(a), _norm(b), p, ln)
                           for (p, ln, _q, a, b) in witnesses)
        findings.append(Finding(
            "lockorder", "lock-cycle", relpath, line, qual,
            "->".join(_norm(x) for x in scc),
            "lock acquisition cycle (ABBA deadlock): %s" % detail))
    return findings
