"""Trace-purity lint.

A function traced by ``jax.jit``/``shard_map`` runs ONCE at trace time;
anything impure inside it is baked into the compiled program or silently
races with retraces — the exact bug class behind the torch callback
flake. Rules, applied to every function that is (a) decorated with a
jit-like decorator (incl. ``@partial(jax.jit, ...)``), or (b) passed as a
local def/lambda to a jit-like call:

- ``impure-time``             ``time.time()``/``monotonic``/``perf_counter``
                              inside a traced fn (trace-time constant)
- ``impure-random``           ``np.random.*`` / stdlib ``random.*`` inside
                              a traced fn (use ``jax.random`` keys)
- ``impure-global-mutation``  ``global`` declaration with a store inside a
                              traced fn
- ``impure-closure-mutation`` ``nonlocal`` rebind or subscript/attribute
                              store to a closed-over name inside a traced
                              fn (runs once at trace, not per step)
- ``print-in-trace``          ``print`` in a traced fn (fires at trace
                              time only; use ``jax.debug.print``)
- ``telemetry-in-jit``        ``telemetry.span``/``instant``/registry
                              mutations inside a traced fn — the span
                              brackets trace time (once), not execution;
                              instrument the host call site instead
- ``callback-shared-state``   a ``jax.pure_callback`` callback (or a local
                              helper it calls) mutates closed-over host
                              state with no lock fence around the store —
                              concurrent device-side replays race on it

``pure_callback`` discipline is checked in *every* function, traced or
not, because the callbacks escape into compiled code regardless of where
they are built.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, SourceModule, dotted, import_aliases, unparse

_JIT_TAILS = {"jit", "pjit", "shard_map"}
_TIME_CALLS = {"time.time", "time.monotonic", "time.perf_counter",
               "time.process_time", "datetime.datetime.now"}
_STDLIB_RANDOM = {"random", "randint", "randrange", "choice", "choices",
                  "shuffle", "uniform", "gauss", "normalvariate", "seed"}


def _jit_like(node: ast.AST, aliases: Dict[str, str]) -> bool:
    """True for ``jax.jit`` / ``jit`` / ``collectives.shard_map``-style
    references (import-alias aware: a bare name must come from jax or a
    package module whose name ends with the tail)."""
    d = dotted(node)
    if d is None:
        return False
    tail = d.split(".")[-1]
    if tail not in _JIT_TAILS:
        return False
    if "." in d:
        return True
    src = aliases.get(d, "")
    return src.split(".")[0] in ("jax", "collectives") or \
        src.endswith(".%s" % tail) or src == d


def _decorated_traced(fn: ast.AST, aliases: Dict[str, str]) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        if _jit_like(dec, aliases):
            return True
        if isinstance(dec, ast.Call):
            if _jit_like(dec.func, aliases):
                return True
            d = dotted(dec.func)
            if d is not None and d.split(".")[-1] == "partial" and \
                    dec.args and _jit_like(dec.args[0], aliases):
                return True
    return False


def _fn_params(fn: ast.AST) -> Set[str]:
    args = fn.args
    params = {a.arg for a in args.posonlyargs + args.args + args.kwonlyargs}
    if args.vararg:
        params.add(args.vararg.arg)
    if args.kwarg:
        params.add(args.kwarg.arg)
    return params


def _local_names(fn: ast.AST) -> Set[str]:
    names: Set[str] = set()
    body = [fn.body] if isinstance(fn, ast.Lambda) else fn.body
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Assign, ast.For, ast.AnnAssign)):
                targets = getattr(node, "targets", None) or \
                    [getattr(node, "target")]
                for t in targets:
                    for leaf in ast.walk(t):
                        if isinstance(leaf, ast.Name) and \
                                isinstance(leaf.ctx, ast.Store):
                            names.add(leaf.id)
            elif isinstance(node, ast.withitem) and \
                    isinstance(node.optional_vars, ast.Name):
                names.add(node.optional_vars.id)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                names.add(node.name)
            elif isinstance(node, ast.comprehension):
                for leaf in ast.walk(node.target):
                    if isinstance(leaf, ast.Name):
                        names.add(leaf.id)
    return names


class _TracedFnCheck:
    """Purity scan of one traced function body."""

    def __init__(self, mod: SourceModule, aliases: Dict[str, str],
                 qualname: str, fn: ast.AST, findings: List[Finding]):
        self.mod = mod
        self.aliases = aliases
        self.qualname = qualname
        self.fn = fn
        self.findings = findings

    def _emit(self, rule: str, line: int, subject: str, message: str):
        self.findings.append(Finding(
            "purity", rule, self.mod.relpath, line, self.qualname,
            subject, message))

    def run(self):
        fn = self.fn
        params = _fn_params(fn) if not isinstance(fn, ast.Lambda) \
            else {a.arg for a in fn.args.args}
        local = _local_names(fn)
        nonlocals: Set[str] = set()
        globals_: Set[str] = set()
        body = [fn.body] if isinstance(fn, ast.Lambda) else fn.body
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Global):
                    globals_.update(node.names)
                    self._emit(
                        "impure-global-mutation", node.lineno,
                        ",".join(node.names),
                        "traced fn declares global %s — the mutation "
                        "happens at trace time, not per step"
                        % ",".join(node.names))
                elif isinstance(node, ast.Nonlocal):
                    nonlocals.update(node.names)
                    self._emit(
                        "impure-closure-mutation", node.lineno,
                        ",".join(node.names),
                        "traced fn rebinds nonlocal %s — the mutation "
                        "happens at trace time, not per step"
                        % ",".join(node.names))
                elif isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = getattr(node, "targets", None) or \
                        [node.target]
                    for t in targets:
                        base = _subscript_store_base(t)
                        if base is not None and base not in params and \
                                base not in local and base != "self":
                            self._emit(
                                "impure-closure-mutation", node.lineno,
                                base,
                                "traced fn stores into closed-over '%s' — "
                                "runs once at trace time and races with "
                                "retraces" % base)
                elif isinstance(node, ast.Call):
                    self._check_call(node)

    def _check_call(self, call: ast.Call):
        d = dotted(call.func)
        if d is None:
            return
        if d == "print":
            self._emit("print-in-trace", call.lineno, d,
                       "print() in a traced fn fires at trace time only — "
                       "use jax.debug.print")
            return
        if d in _TIME_CALLS:
            self._emit("impure-time", call.lineno, d,
                       "%s() in a traced fn is a trace-time constant — "
                       "pass time in as an argument" % d)
            return
        parts = d.split(".")
        root = self.aliases.get(parts[0], parts[0])
        if parts[0] != "self" and len(parts) >= 2 and \
                "telemetry" in root.split("."):
            self._emit(
                "telemetry-in-jit", call.lineno, d,
                "%s in a traced fn runs at trace time only — the span/"
                "metric brackets tracing, not execution, and silently "
                "stops firing once the trace is cached; keep "
                "instrumentation outside jit/shard_map" % d)
            return
        if len(parts) == 1 and "telemetry" in root.split("."):
            # bare from-import (`from ..telemetry.context import
            # current_context`): the call reads a THREAD-LOCAL at trace
            # time — the cached trace bakes in whichever request traced
            # first, cross-wiring every later request's ids
            self._emit(
                "telemetry-in-jit", call.lineno, d,
                "%s (from %s) in a traced fn runs at trace time only — "
                "a trace-context read is baked into the cached trace as "
                "a constant; resolve the context outside jit/shard_map "
                "and pass values in" % (d, root))
            return
        if len(parts) >= 3 and parts[-2] == "random" and \
                self.aliases.get(parts[0], parts[0]) == "numpy":
            self._emit("impure-random", call.lineno, d,
                       "%s in a traced fn draws host entropy at trace "
                       "time — use jax.random with an explicit key" % d)
            return
        if len(parts) == 2 and parts[0] == "random" and \
                parts[1] in _STDLIB_RANDOM and \
                self.aliases.get("random", "random") == "random":
            self._emit("impure-random", call.lineno, d,
                       "stdlib %s in a traced fn draws host entropy at "
                       "trace time — use jax.random" % d)


def _subscript_store_base(t: ast.AST) -> Optional[str]:
    seen = False
    while isinstance(t, (ast.Subscript, ast.Attribute, ast.Starred)):
        seen = True
        t = t.value
    if seen and isinstance(t, ast.Name):
        return t.id
    return None


# --- pure_callback shared-state discipline -----------------------------------
def _store_is_fenced(store: ast.AST, enclosing: ast.AST) -> bool:
    """True if ``store`` sits inside a ``with <lock>:`` block within
    ``enclosing`` (any non-call context manager counts as the fence —
    resolution of the actual lock object is the lockorder checker's job)."""
    for node in ast.walk(enclosing):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            fenced = any(not isinstance(i.context_expr, ast.Call) or
                         dotted(i.context_expr.func) is not None
                         for i in node.items)
            if fenced:
                for sub in ast.walk(node):
                    if sub is store:
                        return True
    return False


def _check_pure_callbacks(mod: SourceModule, aliases: Dict[str, str],
                          qualname: str, fn: ast.AST,
                          findings: List[Finding]):
    local_defs: Dict[str, ast.AST] = {}
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                node is not fn:
            local_defs[node.name] = node
    cb_roots: List[ast.AST] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            d = dotted(node.func)
            if d is None or d.split(".")[-1] not in ("pure_callback",
                                                     "io_callback"):
                continue
            if node.args:
                cb = node.args[0]
                if isinstance(cb, ast.Lambda):
                    cb_roots.append(cb)
                elif isinstance(cb, ast.Name) and cb.id in local_defs:
                    cb_roots.append(local_defs[cb.id])
    if not cb_roots:
        return
    # one level of transitive closure over sibling local defs: the callback
    # may delegate its state touch to a helper (get_op-style memoization)
    reach: List[ast.AST] = list(cb_roots)
    for root in cb_roots:
        for node in ast.walk(root):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id in local_defs and \
                    local_defs[node.func.id] not in reach:
                reach.append(local_defs[node.func.id])
    outer_params = _fn_params(fn) if not isinstance(fn, ast.Lambda) else set()
    for cb in reach:
        params = _fn_params(cb) if not isinstance(cb, ast.Lambda) \
            else {a.arg for a in cb.args.args}
        local = _local_names(cb)
        body = [cb.body] if isinstance(cb, ast.Lambda) else cb.body
        for stmt in body:
            for node in ast.walk(stmt):
                if not isinstance(node, (ast.Assign, ast.AugAssign)):
                    continue
                targets = getattr(node, "targets", None) or [node.target]
                for t in targets:
                    base = _subscript_store_base(t)
                    if base is None or base in params or base in local \
                            or base == "self":
                        continue
                    if _store_is_fenced(node, cb):
                        continue
                    cb_name = getattr(cb, "name", "<lambda>")
                    findings.append(Finding(
                        "purity", "callback-shared-state", mod.relpath,
                        node.lineno, qualname,
                        "%s:%s" % (cb_name, base),
                        "pure_callback callback %s mutates shared host "
                        "state '%s' with no lock fence — concurrent "
                        "device-side replays race on it (the torch-flake "
                        "bug class); guard the store with a lock"
                        % (cb_name, base)))
                    _ = outer_params  # kept for future param-aware rules


def check(modules: Sequence[SourceModule]) -> List[Finding]:
    findings: List[Finding] = []
    for m in modules:
        aliases = import_aliases(m.tree)
        # enumerate every def with a qualname; find traced ones. The
        # pure_callback scan walks a whole top-level def's subtree (its
        # callbacks may be declared at any nesting depth), so it runs only
        # for depth-0 defs; the jit-call-arg scan stops at def boundaries,
        # so it runs at every depth without double-reporting.
        def visit(body, prefix, top):
            for node in body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    q = "%s:%s" % (m.modname, prefix + node.name)
                    if _decorated_traced(node, aliases):
                        _TracedFnCheck(m, aliases, q, node, findings).run()
                    if top:
                        _check_pure_callbacks(m, aliases, q, node, findings)
                    _scan_jit_call_args(m, aliases, q, node.body, node.body,
                                        findings)
                    visit(node.body, prefix + node.name + ".", False)
                elif isinstance(node, ast.ClassDef):
                    visit(node.body, prefix + node.name + ".", top)
        visit(m.tree.body, "", True)
        # module scope: defs come from the whole module body, but only
        # top-level statements are searched for jit(f) calls (calls inside
        # defs were handled above under their own qualname)
        top = [s for s in m.tree.body
               if not isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef))]
        _scan_jit_call_args(m, aliases, "%s:" % m.modname, m.tree.body,
                            top, findings)
    return findings


def _walk_stop_at_defs(root: ast.AST):
    """Yield nodes of ``root``'s subtree without descending into nested
    function/class definitions (their bodies are scanned under their own
    qualname)."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        if node is not root and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _scan_jit_call_args(mod: SourceModule, aliases: Dict[str, str],
                        qualname: str, defs_body, search_stmts,
                        findings: List[Finding]):
    """Find ``jit(f)``/``shard_map(f, ...)`` calls in ``search_stmts`` and
    purity-check ``f`` when it resolves to a local def or lambda declared
    in ``defs_body``."""
    local_defs: Dict[str, ast.AST] = {}
    for node in defs_body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            local_defs[node.name] = node
        elif isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Lambda) and \
                len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            local_defs[node.targets[0].id] = node.value
    checked: Set[int] = set()
    for stmt in search_stmts:
        for node in _walk_stop_at_defs(stmt):
            if not (isinstance(node, ast.Call)
                    and _jit_like(node.func, aliases) and node.args):
                continue
            target = node.args[0]
            fn: Optional[ast.AST] = None
            if isinstance(target, ast.Lambda):
                fn = target
            elif isinstance(target, ast.Name) and target.id in local_defs:
                fn = local_defs[target.id]
            if fn is None or id(fn) in checked:
                continue
            checked.add(id(fn))
            name = getattr(fn, "name", "<lambda>")
            q = qualname if qualname.endswith(name) \
                else "%s>%s" % (qualname, name)
            _TracedFnCheck(mod, aliases, q, fn, findings).run()
