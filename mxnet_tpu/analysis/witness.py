"""Runtime lock-order witness.

The static pass proves what *can* happen; the witness records what *does*.
Wrap real locks with :meth:`LockOrderWitness.wrap` and every acquisition
edge (lock B taken while this thread holds lock A) is counted and checked
against the same declared hierarchy the static analyzer uses
(:data:`.lockorder.LOCK_HIERARCHY`).

The surface is the metric.py / ServingMetrics idiom — ``get()`` returns
parallel name/value lists, ``get_name_value()`` zips them — so a serving
dashboard scrapes witness edges and per-bucket latency gauges through one
metrics path::

    witness = LockOrderWitness()
    lock = witness.wrap(threading.Lock(), "serving.metrics.ServingMetrics._lock")
    ...
    names, values = witness.get()        # edge counters + violation count
    assert not witness.violations()

Overhead is one thread-local list append per acquire; intended for tests
and canary deployments (MXNET_ANALYSIS_WITNESS=1), not the hot path.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from .lockorder import LOCK_HIERARCHY


class _WitnessedLock:
    """Context-manager proxy recording acquisition order; delegates the
    full lock protocol (incl. Condition wait/notify) to the real lock."""

    def __init__(self, lock, name: str, witness: "LockOrderWitness"):
        self._lock = lock
        self._name = name
        self._witness = witness

    def acquire(self, *args, **kwargs):
        got = self._lock.acquire(*args, **kwargs)
        if got:
            self._witness._on_acquire(self._name)
        return got

    def release(self):
        self._witness._on_release(self._name)
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __getattr__(self, attr):  # wait/notify/locked/...
        return getattr(self._lock, attr)


class LockOrderWitness:
    """Records observed lock-acquisition edges across all threads."""

    def __init__(self, hierarchy: Optional[Dict[str, int]] = None):
        self._hierarchy = LOCK_HIERARCHY if hierarchy is None else hierarchy
        self._tls = threading.local()
        self._mu = threading.Lock()
        self._edges: Dict[Tuple[str, str], int] = {}

    def wrap(self, lock, name: str) -> _WitnessedLock:
        return _WitnessedLock(lock, name, self)

    def _held(self) -> List[str]:
        if not hasattr(self._tls, "held"):
            self._tls.held = []
        return self._tls.held

    def _on_acquire(self, name: str):
        held = self._held()
        if held:
            edge = (held[-1], name)
            with self._mu:
                self._edges[edge] = self._edges.get(edge, 0) + 1
        held.append(name)

    def _on_release(self, name: str):
        held = self._held()
        if name in held:
            held.reverse()
            held.remove(name)
            held.reverse()

    # --- metric.py-style surface -----------------------------------------
    def edges(self) -> Dict[Tuple[str, str], int]:
        with self._mu:
            return dict(self._edges)

    def violations(self) -> List[str]:
        """Observed edges that contradict the declared hierarchy."""
        out = []
        for (a, b), n in sorted(self.edges().items()):
            ra, rb = self._hierarchy.get(a), self._hierarchy.get(b)
            if ra is None or rb is None:
                continue
            if rb < ra:
                out.append("%s (rank %d) acquired under %s (rank %d), "
                           "%d time(s)" % (b, rb, a, ra, n))
            elif rb == ra and a != b:
                out.append("peer locks nested: %s under %s, %d time(s)"
                           % (b, a, n))
        return out

    def get(self):
        """(names, values) — EvalMetric.get() shape, like ServingMetrics."""
        names, values = [], []
        for (a, b), n in sorted(self.edges().items()):
            names.append("edge:%s->%s" % (a, b))
            values.append(n)
        names.append("violations")
        values.append(len(self.violations()))
        return names, values

    def get_name_value(self):
        names, values = self.get()
        return list(zip(names, values))

    def reset(self):
        with self._mu:
            self._edges.clear()
