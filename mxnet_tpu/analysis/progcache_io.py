"""Persistent-cache commit discipline lint.

The progcache contract (docs/deployment.md "Warm restarts") is that a
crash at ANY instruction can never leave a torn file at a committed name:
every write must stage to a temp file and publish with ``os.replace``,
the same idiom as ``resilience.checkpoint``. A raw
``open(path, "wb")``-and-write at the committed name silently breaks the
contract — a reader in another process sees a half-entry, and while the
CRC check turns that into a fallback-compile rather than a wrong answer,
it costs the warm restart the entry forever. Rules:

- ``raw-binary-commit``   a write-mode ``open()`` call in a progcache
                          module OUTSIDE an ``_atomic_write*`` helper —
                          commits must go through the tmp+``os.replace``
                          helper, not write in place

Scoped to modules whose filename ends with ``progcache.py`` (the cache
implementation, wherever it lives); read-mode opens are untouched.
"""
from __future__ import annotations

import ast
from typing import List, Optional, Sequence

from .core import Finding, SourceModule

#: any of these characters in the mode string means the open can create
#: or destroy content at the target path
_WRITE_MODES = frozenset("wax+")


def _open_mode(call: ast.Call) -> Optional[str]:
    """The literal mode of an ``open()`` call, '' when defaulted, or None
    when the call is not an open / the mode is not a literal (dynamic
    modes are flagged conservatively by returning them as 'w')."""
    f = call.func
    name = f.id if isinstance(f, ast.Name) else (
        f.attr if isinstance(f, ast.Attribute) else None)
    if name != "open":
        return None
    mode_node: Optional[ast.AST] = call.args[1] if len(call.args) > 1 \
        else None
    for kw in call.keywords:
        if kw.arg == "mode":
            mode_node = kw.value
    if mode_node is None:
        return ""
    if isinstance(mode_node, ast.Constant) and \
            isinstance(mode_node.value, str):
        return mode_node.value
    return "w"  # non-literal mode: assume the worst


def check(modules: Sequence[SourceModule]) -> List[Finding]:
    findings: List[Finding] = []
    for m in modules:
        if not m.relpath.endswith("progcache.py"):
            continue
        # stack of (enclosing function name or "") while walking
        def walk(node: ast.AST, fn_stack: List[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    walk(child, fn_stack + [child.name])
                    continue
                if isinstance(child, ast.Call):
                    mode = _open_mode(child)
                    if mode is not None and (set(mode) & _WRITE_MODES):
                        inside_atomic = any(
                            f.startswith("_atomic_write")
                            for f in fn_stack)
                        if not inside_atomic:
                            qual = ".".join(fn_stack)
                            findings.append(Finding(
                                checker="progcache_io",
                                rule="raw-binary-commit",
                                path=m.relpath,
                                line=child.lineno,
                                qualname=("%s:%s" % (m.modname, qual)
                                          if qual else m.modname),
                                subject="open(mode=%r)" % mode,
                                message="write-mode open() outside an "
                                        "_atomic_write* helper — commit "
                                        "via tmp + os.replace so a crash "
                                        "can never tear a cache entry at "
                                        "its committed name"))
                walk(child, fn_stack)
        walk(m.tree, [])
    return findings
