"""CLI for the static-analysis pass.

::

    python -m mxnet_tpu.analysis                      # report everything
    python -m mxnet_tpu.analysis --fail-on-new        # the CI gate
    python -m mxnet_tpu.analysis --update-baseline    # after justifying

Environment defaults (flags win): MXNET_ANALYSIS_MODE (``report`` |
``fail-on-new``), MXNET_ANALYSIS_BASELINE (path or ``none``),
MXNET_ANALYSIS_CHECKS (comma list of
lockorder,engine,purity,progcache_io,racecheck,compilesurface),
MXNET_ANALYSIS_ROOT (scan root). See docs/static_analysis.md.

Exit codes: 0 clean (or no NEW findings in fail-on-new mode), 1 findings
(new findings in fail-on-new mode), 2 usage error.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from . import CHECKERS, run_analysis
from .core import diff_against_baseline, load_baseline, write_baseline

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_DEFAULT_BASELINE = os.path.join(
    os.path.dirname(_PKG_ROOT), "ci", "analysis_baseline.json")


def main(argv=None) -> int:
    env = os.environ.get
    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.analysis",
        description="lock-order / engine-discipline / trace-purity "
                    "static checks")
    ap.add_argument("--root", default=env("MXNET_ANALYSIS_ROOT", _PKG_ROOT),
                    help="directory (or single file) to scan "
                         "[default: the mxnet_tpu package]")
    ap.add_argument("--baseline",
                    default=env("MXNET_ANALYSIS_BASELINE",
                                _DEFAULT_BASELINE),
                    help="baseline json allowlisting justified findings; "
                         "'none' disables [default: ci/analysis_baseline"
                         ".json]")
    ap.add_argument("--checks",
                    default=env("MXNET_ANALYSIS_CHECKS",
                                ",".join(CHECKERS)),
                    help="comma list of checkers to run [default: all]")
    mode = env("MXNET_ANALYSIS_MODE", "report")
    ap.add_argument("--fail-on-new", action="store_true",
                    default=(mode == "fail-on-new"),
                    help="exit non-zero only on findings missing from the "
                         "baseline (the CI mode)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline with the current findings "
                         "(existing justifications are preserved)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    args = ap.parse_args(argv)

    checks = [c.strip() for c in args.checks.split(",") if c.strip()]
    bad = [c for c in checks if c not in CHECKERS]
    if bad:
        print("unknown checker(s): %s (have: %s)"
              % (",".join(bad), ",".join(CHECKERS)), file=sys.stderr)
        return 2
    if not os.path.exists(args.root):
        print("scan root does not exist: %s" % args.root, file=sys.stderr)
        return 2

    findings = run_analysis(args.root, checks)
    baseline = load_baseline(args.baseline)
    new, stale = diff_against_baseline(findings, baseline)

    if args.update_baseline:
        old_just = {fp: e.get("justification", "")
                    for fp, e in baseline.items()}
        write_baseline(args.baseline, findings)
        # preserve justifications already written for surviving findings
        data = json.load(open(args.baseline))
        for e in data["findings"]:
            if old_just.get(e["fingerprint"]):
                e["justification"] = old_just[e["fingerprint"]]
        with open(args.baseline, "w", encoding="utf-8") as f:
            json.dump(data, f, indent=2, sort_keys=True)
            f.write("\n")
        print("baseline updated: %s (%d findings)"
              % (args.baseline, len(findings)))
        return 0

    if args.as_json:
        print(json.dumps({
            "findings": [{"fingerprint": f.fingerprint, "checker": f.checker,
                          "rule": f.rule, "path": f.path, "line": f.line,
                          "qualname": f.qualname, "subject": f.subject,
                          "message": f.message,
                          "new": f.fingerprint not in baseline}
                         for f in findings],
            "stale_baseline": [e["fingerprint"] for e in stale],
        }, indent=2))
    else:
        shown = new if args.fail_on_new else findings
        for f in shown:
            tag = "" if not args.fail_on_new or not baseline else " NEW"
            print("%s%s" % (f.format(), tag))
        for e in stale:
            print("stale baseline entry (finding fixed — remove it): "
                  "%s %s {%s}" % (e.get("rule"), e.get("subject"),
                                  e.get("fingerprint")), file=sys.stderr)
        n_base = sum(1 for f in findings if f.fingerprint in baseline)
        print("%d finding(s): %d new, %d baselined; %d stale baseline "
              "entr(ies)" % (len(findings), len(new), n_base, len(stale)))

    if args.fail_on_new:
        return 1 if new else 0
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
