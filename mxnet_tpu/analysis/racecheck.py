"""Happens-before race checker — the static half of the engine sanitizer.

The engine orders pushed ops ONLY by their declared ``const_vars`` /
``mutable_vars``. Host state a pushed closure touches beyond that
declaration is invisible to the scheduler: two such ops race, and a
host-side read of it is unsynchronized unless a fence intervenes. This
checker tracks state provenance into pushed closures — through lambdas,
local helper defs, module/method helpers one call level deep, and
container aliasing (``alias = results``) — and across host calls via the
same interprocedural fixpoint style as :mod:`.lockorder`, whose
``_Index`` / ``_collect_summaries`` call graph it reuses. Rules:

- ``undeclared-var-access``   two push sites touch the same host state
  (at least one writing it) while sharing no declared var identifier —
  the engine cannot order them: a silent WW/RW race. Both sites are
  named in the finding.
- ``unfenced-host-read``      host code reads (dereferences) state that
  an earlier push in the same function — direct, or through a may-push
  callee — writes, with no ``engine.fence(vars).wait()`` /
  ``wait_to_read`` / may-sync call between push and read.
- ``var-use-after-delete``    an engine var is named in a push/fence/
  wait var list (or deleted again) after ``delete_variable(v)`` with no
  rebinding of ``v`` in between.

Resolution is conservative in the same way as the lock-order pass:
unresolvable receivers create no events and no findings, and any
``.wait()``-shaped call suppresses ``unfenced-host-read`` (an unknown
wait can only hide findings, never invent them). The dynamic complement
is ``MXNET_ENGINE_SANITIZER=1`` (per-var epoch tracking in
``engine.py``); see docs/static_analysis.md and docs/concurrency.md.
"""
from __future__ import annotations

import ast
import builtins
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, SourceModule, dotted, import_aliases
from .engine_lint import (_MUTATORS, _capture_seq_names, _declared_names,
                          _is_engine_push)
from .lockorder import FuncKey, _Index, _collect_summaries

#: call tails that establish a happens-before edge for host reads
_SYNC_TAILS = {"wait", "wait_for_var", "wait_for_all", "wait_to_read",
               "wait_for_file", "join"}

#: builtins whose call dereferences (reads the contents of) an argument
_CONTENT_FNS = {"len", "list", "tuple", "dict", "set", "frozenset", "sum",
                "sorted", "min", "max", "any", "all", "iter", "next",
                "enumerate", "zip", "str", "repr", "bool", "float", "int"}

#: state keys never treated as engine-managed host state
_IGNORED_STATES = {"self"}
_BUILTIN_NAMES = frozenset(dir(builtins))


def _base_key(node: ast.AST) -> Optional[str]:
    """Storage base of a target/receiver chain: bare name ``x`` or
    ``self.attr``; ``None`` when unresolvable."""
    while isinstance(node, (ast.Subscript, ast.Starred)):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        if isinstance(node.value, ast.Name):
            if node.value.id == "self":
                return "self.%s" % node.attr
            return node.value.id
        return _base_key(node.value)
    return None


def _fn_params(fn: ast.AST) -> Set[str]:
    args = fn.args
    params = {a.arg for a in args.posonlyargs + args.args + args.kwonlyargs}
    if args.vararg:
        params.add(args.vararg.arg)
    if args.kwarg:
        params.add(args.kwarg.arg)
    return params


def _closure_touches(fn: ast.AST) -> Dict[str, Tuple[str, int]]:
    """state key -> ("write"|"read", line) for every free piece of host
    state the closure touches (write dominates read)."""
    params = _fn_params(fn)
    body: List[ast.AST] = [fn.body] if isinstance(fn, ast.Lambda) \
        else list(fn.body)
    local: Set[str] = set()
    rebound: Set[str] = set()
    writes: Dict[str, int] = {}
    reads: Dict[str, int] = {}
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Nonlocal, ast.Global)):
                rebound.update(node.names)
            elif isinstance(node, (ast.Assign, ast.AugAssign, ast.For,
                                   ast.AnnAssign)):
                targets = getattr(node, "targets", None) or \
                    [getattr(node, "target")]
                for t in targets:
                    if isinstance(t, ast.Name):
                        if t.id in rebound:
                            writes.setdefault(t.id, node.lineno)
                        else:
                            local.add(t.id)
                    elif isinstance(t, ast.Tuple):
                        for e in t.elts:
                            if isinstance(e, ast.Name):
                                local.add(e.id)
                    else:
                        key = _base_key(t) if t is not None else None
                        if key:
                            writes.setdefault(key, node.lineno)
            elif isinstance(node, ast.withitem) and \
                    isinstance(node.optional_vars, ast.Name):
                local.add(node.optional_vars.id)
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATORS:
                key = _base_key(node.func.value)
                if key:
                    writes.setdefault(key, node.lineno)
            elif isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Load):
                reads.setdefault(node.id, node.lineno)
            elif isinstance(node, ast.Attribute) and \
                    isinstance(node.ctx, ast.Load) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == "self":
                reads.setdefault("self.%s" % node.attr, node.lineno)

    def _free(key: str) -> bool:
        base = key.split(".")[0]
        return base not in params and base not in local and \
            key not in _IGNORED_STATES

    out: Dict[str, Tuple[str, int]] = {}
    for k, ln in writes.items():
        if _free(k):
            out[k] = ("write", ln)
    for k, ln in reads.items():
        if _free(k) and k not in out:
            out[k] = ("read", ln)
    return out


def _var_keys(expr: Optional[ast.AST]) -> Set[str]:
    """Dotted keys of every var reference in a const/mutable-vars (or
    fence/wait argument) expression."""
    keys: Set[str] = set()
    if expr is None:
        return keys
    for node in ast.walk(expr):
        if isinstance(node, (ast.Name, ast.Attribute)):
            d = dotted(node)
            if d and d not in _IGNORED_STATES:
                keys.add(d)
    return keys


def _decl_exprs(call: ast.Call) -> List[ast.AST]:
    exprs: List[ast.AST] = [a for a in call.args[1:3] if a is not None]
    for kw in call.keywords:
        if kw.arg in ("const_vars", "mutable_vars"):
            exprs.append(kw.value)
    return exprs


class _Site:
    """One engine/capture push site and what its closure touches."""

    __slots__ = ("fnkey", "cls", "qualname", "relpath", "line", "name",
                 "declared", "touched")

    def __init__(self, fnkey: FuncKey, cls: Optional[Tuple[str, str]],
                 qualname: str, relpath: str, line: int, name: str,
                 declared: Set[str], touched: Dict[str, Tuple[str, int]]):
        self.fnkey = fnkey
        self.cls = cls
        self.qualname = qualname
        self.relpath = relpath
        self.line = line
        self.name = name
        self.declared = declared
        self.touched = touched


class _Facts:
    """Per-host-function events in source-line order."""

    def __init__(self, key: FuncKey, cls_key: Optional[Tuple[str, str]],
                 qualname: str, relpath: str, nested: bool):
        self.key = key
        self.cls_key = cls_key
        self.qualname = qualname
        self.relpath = relpath
        self.nested = nested
        self.pushes: List[_Site] = []
        self.sync_lines: List[int] = []
        self.reads: List[Tuple[int, str]] = []        # (line, state key)
        self.deletes: List[Tuple[int, str]] = []      # (line, var key)
        self.var_uses: List[Tuple[int, str]] = []     # (line, var key)
        self.assign_lines: Dict[str, List[int]] = {}  # name -> lines
        self.params: Set[str] = set()


def _op_name(call: ast.Call) -> str:
    for kw in call.keywords:
        if kw.arg == "name" and isinstance(kw.value, ast.Constant) and \
                isinstance(kw.value.value, str):
            return kw.value.value
    return "op"


class _HostScanner:
    """Walks one function body WITHOUT descending into nested defs or
    lambdas (those run later, on the engine worker) and records pushes,
    sync points, dereferencing reads, deletes, and var uses."""

    def __init__(self, index: _Index, modname: str, facts: _Facts):
        self.ix = index
        self.modname = modname
        self.facts = facts
        self.aliases = index.aliases.get(modname, {})
        self.local_fns: Dict[str, ast.AST] = {}
        self.alias_map: Dict[str, str] = {}
        self.capture_seqs: Set[str] = set()

    def scan(self, fn: ast.AST):
        self.capture_seqs = _capture_seq_names(fn)
        a = getattr(fn, "args", None)
        if a is not None:
            for grp in (a.posonlyargs, a.args, a.kwonlyargs):
                self.facts.params.update(p.arg for p in grp)
            for va in (a.vararg, a.kwarg):
                if va is not None:
                    self.facts.params.add(va.arg)
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn:
                self.local_fns[node.name] = node
            elif isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Lambda) and \
                    len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                self.local_fns[node.targets[0].id] = node.value
        # pass 1: aliases and assignment lines (the walk below is not in
        # source order, and canonicalization needs the full alias map)
        for node in self._walk_host(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.facts.assign_lines.setdefault(
                            t.id, []).append(node.lineno)
                        if isinstance(node.value, ast.Name):
                            self.alias_map[t.id] = node.value.id
        for node in self._walk_host(fn):
            self._visit(node)

    def _walk_host(self, fn: ast.AST):
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(node))

    def _canon(self, key: str) -> str:
        seen: Set[str] = set()
        while key in self.alias_map and key not in seen:
            seen.add(key)
            key = self.alias_map[key]
        return key

    def _is_noise(self, key: str) -> bool:
        """Names that are never host *state*: builtins, imported modules/
        symbols, module functions/classes, local helper defs."""
        if key in _IGNORED_STATES or key in _BUILTIN_NAMES:
            return True
        base = key.split(".")[0]
        if base in self.aliases or base in self.local_fns:
            return True
        return (self.modname, base) in self.ix.mod_funcs or \
            (self.modname, base) in self.ix.classes

    # --- node dispatch ----------------------------------------------------
    def _visit(self, node: ast.AST):
        if isinstance(node, ast.Subscript) and \
                isinstance(node.ctx, ast.Load):
            self._read(_base_key(node), node.lineno)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            self._read(_base_key(node.iter), node.lineno)
        elif isinstance(node, ast.Call):
            self._visit_call(node)

    def _read(self, key: Optional[str], line: int):
        if key and not self._is_noise(key):
            self.facts.reads.append((line, self._canon(key)))

    def _visit_call(self, call: ast.Call):
        f = call.func
        kind = _is_engine_push(call, self.aliases)
        if kind is None and isinstance(f, ast.Attribute) and \
                f.attr in ("push", "push_async"):
            recv = f.value
            recv_name = recv.id if isinstance(recv, ast.Name) else (
                recv.attr if isinstance(recv, ast.Attribute) else None)
            if recv_name in self.capture_seqs:
                kind = f.attr
        if kind is not None:
            self._record_push(call)
            return
        tail = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        if tail in _SYNC_TAILS:
            self.facts.sync_lines.append(call.lineno)
            if tail in ("wait_for_var", "wait_to_read") and call.args:
                for k in _var_keys(call.args[0]):
                    self.facts.var_uses.append((call.lineno, k))
        elif tail == "fence" and call.args:
            for k in _var_keys(call.args[0]):
                self.facts.var_uses.append((call.lineno, k))
        elif tail == "delete_variable" and call.args:
            key = dotted(call.args[0])
            if key:
                self.facts.deletes.append((call.lineno, key))
        elif tail in _CONTENT_FNS and isinstance(f, ast.Name):
            for a in call.args:
                if isinstance(a, (ast.Name, ast.Attribute, ast.Subscript)):
                    self._read(_base_key(a), call.lineno)
        if isinstance(f, ast.Attribute):
            # method call on state is a dereference of the receiver
            self._read(_base_key(f.value), call.lineno)

    # --- push handling ----------------------------------------------------
    def _record_push(self, call: ast.Call):
        declared = {n for n in _declared_names(call)
                    if n not in _IGNORED_STATES}
        for e in _decl_exprs(call):
            for k in _var_keys(e):
                self.facts.var_uses.append((call.lineno, k))
        touched: Dict[str, Tuple[str, int]] = {}
        closure = self._resolve_closure(call)
        if closure is not None:
            for fn in self._reach(closure):
                for key, (mode, line) in _closure_touches(fn).items():
                    key = self._canon(key)
                    if self._is_noise(key):
                        continue
                    if key in touched and touched[key][0] == "write":
                        continue
                    if key in touched and mode == "read":
                        continue
                    touched[key] = (mode, line)
        self.facts.pushes.append(_Site(
            self.facts.key, self.facts.cls_key, self.facts.qualname,
            self.facts.relpath, call.lineno, _op_name(call), declared,
            touched))

    def _resolve_closure(self, call: ast.Call) -> Optional[ast.AST]:
        if not call.args:
            return None
        fn = call.args[0]
        if isinstance(fn, ast.Lambda):
            return fn
        if isinstance(fn, ast.Name):
            hit = self.local_fns.get(fn.id)
            if hit is not None:
                return hit
            mf = self.ix.mod_funcs.get((self.modname, fn.id))
            if mf is not None:
                return mf
        if isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name) and fn.value.id == "self" \
                and self.facts.cls_key is not None:
            hit = self.ix.lookup_method(self.facts.cls_key, fn.attr)
            if hit is not None:
                return hit[1]
        return None

    def _reach(self, closure: ast.AST) -> List[ast.AST]:
        """The closure plus helpers it calls, one level deep."""
        out = [closure]
        for node in ast.walk(closure):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            target: Optional[ast.AST] = None
            if isinstance(f, ast.Name):
                target = self.local_fns.get(f.id) or \
                    self.ix.mod_funcs.get((self.modname, f.id))
            elif isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name) and f.value.id == "self" \
                    and self.facts.cls_key is not None:
                hit = self.ix.lookup_method(self.facts.cls_key, f.attr)
                if hit is not None:
                    target = hit[1]
            if target is not None and target is not closure and \
                    target not in out:
                out.append(target)
        return out


def _same_object(index: _Index, caller: FuncKey, callee: FuncKey) -> bool:
    """``self.X`` facts flow from callee to caller only when the call is
    a method call on the same instance (``self.m()``): the callee must be
    what ``lookup_method`` finds on the caller's own class."""
    if caller[1] is None or callee[1] is None:
        return False
    hit = index.lookup_method((caller[0], caller[1]), callee[2])
    return hit is not None and hit[0] == (callee[0], callee[1])


def check(modules: Sequence[SourceModule]) -> List[Finding]:
    index = _Index(modules)
    summaries = _collect_summaries(index)
    facts: Dict[FuncKey, _Facts] = {}

    def scan(fn: ast.AST, key: FuncKey, cls_key, modname: str,
             relpath: str, nested: bool):
        qual = "%s:%s" % (key[0], ("%s.%s" % (key[1], key[2]))
                          if key[1] else key[2])
        fx = _Facts(key, cls_key, qual, relpath, nested)
        sc = _HostScanner(index, modname, fx)
        sc.scan(fn)
        facts[key] = fx
        # nested defs contribute sync facts (matching lockorder's nested
        # summary keys) but are never themselves host functions: their
        # reads happen on the engine worker
        for name, sub in sc.local_fns.items():
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nkey = (key[0], key[1], "%s.%s" % (key[2], name))
                if nkey not in facts:
                    scan(sub, nkey, cls_key, modname, relpath, True)

    for (mod, name), fn in sorted(index.mod_funcs.items()):
        scan(fn, (mod, None, name), None, mod, index.relpath[mod], False)
    for (mod, cname), ci in sorted(index.classes.items()):
        for mname, fn in sorted(ci.methods.items()):
            scan(fn, (mod, cname, mname), (mod, cname), mod,
                 index.relpath[mod], False)

    # --- interprocedural fixpoint: may-sync and may-push-writes ----------
    may_sync: Dict[FuncKey, bool] = {
        k: bool(f.sync_lines) for k, f in facts.items()}
    may_push_writes: Dict[FuncKey, Set[str]] = {}
    for k, f in facts.items():
        writes: Set[str] = set()
        if not f.nested:
            for site in f.pushes:
                writes |= {s for s, (m, _) in site.touched.items()
                           if m == "write"}
        may_push_writes[k] = writes
    changed = True
    while changed:
        changed = False
        for k, s in summaries.items():
            for _, callee, _ in s.calls:
                if may_sync.get(callee) and not may_sync.get(k, False):
                    may_sync[k] = True
                    changed = True
                add = may_push_writes.get(callee)
                if add and k in may_push_writes:
                    filt = {st for st in add if st.startswith("self.")
                            and _same_object(index, k, callee)}
                    if not filt <= may_push_writes[k]:
                        may_push_writes[k] |= filt
                        changed = True

    findings: List[Finding] = []

    # --- rule: undeclared-var-access (cross-site, per module) ------------
    sites_by_mod: Dict[str, List[_Site]] = {}
    for f in facts.values():
        if f.nested:
            continue
        for site in f.pushes:
            sites_by_mod.setdefault(site.fnkey[0], []).append(site)
    for mod in sorted(sites_by_mod):
        sites = sorted(sites_by_mod[mod], key=lambda s: s.line)
        for i, s1 in enumerate(sites):
            for s2 in sites[i + 1:]:
                shared = sorted(
                    st for st in s1.touched
                    if st in s2.touched
                    and (s1.touched[st][0] == "write"
                         or s2.touched[st][0] == "write"))
                if not shared or (s1.declared & s2.declared):
                    continue
                states = [st for st in shared
                          if not (st.startswith("self.")
                                  and s1.cls != s2.cls)]
                if s1.fnkey != s2.fnkey:
                    # a bare name that is a local or parameter of either
                    # host function is function-scoped state: the two
                    # sites hold DIFFERENT objects, not a shared race
                    def _fn_scoped(st: str) -> bool:
                        base = st.split(".")[0]
                        if base == "self":
                            return False
                        for fk in (s1.fnkey, s2.fnkey):
                            fx = facts[fk]
                            if base in fx.assign_lines or base in fx.params:
                                return True
                        return False
                    states = [st for st in states if not _fn_scoped(st)]
                if not states:
                    continue
                if s1.fnkey == s2.fnkey:
                    lo, hi = sorted((s1.line, s2.line))
                    fx = facts[s1.fnkey]
                    if any(lo < ls < hi for ls in fx.sync_lines):
                        continue  # fence-ordered pair
                findings.append(Finding(
                    "racecheck", "undeclared-var-access", s2.relpath,
                    s2.line, s2.qualname,
                    "%s~%s" % (",".join(states), s1.qualname),
                    "pushed op '%s' touches %s, also written by op '%s' "
                    "pushed at %s:%d (%s), but the two sites share no "
                    "declared var — the engine cannot order them "
                    "(undeclared WW/RW race)" %
                    (s2.name, ",".join(states), s1.name, s1.relpath,
                     s1.line, s1.qualname)))

    for f in facts.values():
        if f.nested:
            continue
        s = summaries.get(f.key)
        calls = s.calls if s is not None else []

        # --- rule: unfenced-host-read --------------------------------
        push_events: List[Tuple[int, Set[str]]] = []
        for site in f.pushes:
            w = {st for st, (m, _) in site.touched.items() if m == "write"}
            if w:
                push_events.append((site.line, w))
        for _, callee, line in calls:
            w = may_push_writes.get(callee)
            if w:
                filt = {st for st in w if st.startswith("self.")
                        and _same_object(index, f.key, callee)}
                if filt:
                    push_events.append((line, filt))
        sync_events = sorted(set(f.sync_lines) | {
            line for _, callee, line in calls if may_sync.get(callee)})
        flagged: Set[str] = set()
        for lr, state in sorted(f.reads):
            if state in flagged:
                continue
            lps = [lp for lp, ws in push_events if state in ws and lp < lr]
            if not lps:
                continue
            lp = max(lps)
            if any(lp < ls <= lr for ls in sync_events):
                continue
            flagged.add(state)
            findings.append(Finding(
                "racecheck", "unfenced-host-read", f.relpath, lr,
                f.qualname, state,
                "host read of '%s' at line %d races the op pushed at "
                "line %d that writes it — no engine.fence(vars).wait() / "
                "wait_to_read on the path between push and read" %
                (state, lr, lp)))

        # --- rule: var-use-after-delete ------------------------------
        seen_del: Set[str] = set()
        for ld, key in sorted(f.deletes):
            if key in seen_del:
                continue
            base = key.split(".")[0]
            resets = [la for la in f.assign_lines.get(base, []) if la > ld]
            uses = sorted(
                [(lu, k) for lu, k in f.var_uses if k == key and lu > ld] +
                [(lu, k) for lu, k in f.deletes if k == key and lu > ld])
            for lu, _k in uses:
                if any(ld < la <= lu for la in resets):
                    continue
                seen_del.add(key)
                findings.append(Finding(
                    "racecheck", "var-use-after-delete", f.relpath, lu,
                    f.qualname, key,
                    "engine var '%s' used at line %d after "
                    "delete_variable at line %d with no rebinding in "
                    "between — the engine has already dropped its "
                    "dependency record" % (key, lu, ld)))
                break
    return findings
