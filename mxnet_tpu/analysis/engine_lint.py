"""Engine-discipline lint.

The engine orders operations by declared data dependencies
(``const_vars``/``mutable_vars``); host state touched by a pushed closure
but *not* declared is invisible to the scheduler and races with every
other pushed op. Rules:

- ``push-missing-vars``            an engine ``push``/``push_async`` call
                                   site declares neither ``const_vars``
                                   nor ``mutable_vars``
- ``push-async-undeclared-mutable`` the pushed closure mutates host state
                                   it closes over (subscript/attribute
                                   stores, mutating method calls,
                                   ``nonlocal``/``global`` rebinds) whose
                                   names do not appear in the call's
                                   ``mutable_vars``/``const_vars``
- ``waitall-as-fence``             ``waitall()`` after a push in the same
                                   function: ``waitall`` drains the device
                                   queue but is NOT a happens-before edge
                                   for host ``on_complete`` callbacks (the
                                   documented footgun) — use
                                   ``engine.fence(vars).wait()``
- ``drain-as-fence``               a bare loop whose body only calls
                                   ``wait_for_var``/``wait_to_read`` per
                                   element, i.e. a hand-rolled multi-var
                                   fence — ``engine.fence(vars)`` is one
                                   pushed op and also fences callbacks
- ``capture-unstable-push``        a push on a ``CapturedSequence`` whose
                                   var list names a container mutated in
                                   the same function — the mutated list
                                   changes the recorded signature between
                                   iterations, so the capture silently
                                   never stabilizes (or replay-bails
                                   every step); snapshot with
                                   ``tuple(...)`` before pushing
- ``fuse-ineligible-op``           in a module that consumes
                                   ``MXNET_ENGINE_FUSE`` (references
                                   ``fuse_enabled``/the env var), a
                                   capture-region push carries no
                                   ``fuse=`` metadata — one such op marks
                                   the whole sequence fuse-ineligible and
                                   it silently stays on replay; pass
                                   ``fuse=engine.FuseOp(...)`` or an
                                   explicit ``fuse=None`` to opt out

Only *engine* pushes are matched (``push_async`` anywhere; ``push`` only
via an engine module alias / ``self._engine`` / an import from engine) so
``KVStore.push`` and friends are not confused with engine ops.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, SourceModule, dotted, import_aliases, unparse

#: method calls that mutate their receiver in place
_MUTATORS = {"append", "extend", "insert", "add", "update", "setdefault",
             "pop", "popleft", "appendleft", "clear", "remove", "fill",
             "sort", "put"}
_WAIT_CALLS = {"wait_for_var", "wait_to_read"}


def _is_engine_push(call: ast.Call, aliases: Dict[str, str]
                    ) -> Optional[str]:
    d = dotted(call.func)
    if d is None:
        return None
    tail = d.split(".")[-1]
    if tail == "push_async":
        return "push_async"
    if tail == "push":
        head = d.split(".")[0]
        if d == "push" and aliases.get("push", "").endswith("engine.push"):
            return "push"
        if head != "self" and aliases.get(head, "").endswith("engine"):
            return "push"
        if "._engine." in d or d.startswith("_engine."):
            return "push"
    return None


def _declared_names(call: ast.Call) -> Set[str]:
    """Every identifier mentioned in const_vars/mutable_vars expressions
    (positional slots 1/2 or keywords)."""
    exprs: List[ast.AST] = list(call.args[1:3])
    for kw in call.keywords:
        if kw.arg in ("const_vars", "mutable_vars"):
            exprs.append(kw.value)
    names: Set[str] = set()
    for e in exprs:
        if e is None:
            continue
        for node in ast.walk(e):
            if isinstance(node, ast.Name):
                names.add(node.id)
            elif isinstance(node, ast.Attribute):
                names.add(node.attr)
    return names


def _has_var_decl(call: ast.Call) -> bool:
    if len(call.args) >= 2:
        return True
    return any(kw.arg in ("const_vars", "mutable_vars")
               for kw in call.keywords)


def _capture_seq_names(fn: ast.AST) -> Set[str]:
    """Names bound to a ``CapturedSequence(...)`` construction (locals and
    self-attributes) — the receivers that open a capture region."""
    names: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            d = dotted(node.value.func)
            if d is not None and d.split(".")[-1] == "CapturedSequence":
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
                    elif isinstance(t, ast.Attribute):
                        names.add(t.attr)
    return names


def _bare_list_names(call: ast.Call) -> Set[str]:
    """Bare Names passed AS a const_vars/mutable_vars expression or as a
    direct list/tuple element of one — the spellings where a mutated
    container flows straight into the recorded signature. Names nested
    under attributes (``rep.var``) are vars, not containers: skipped."""
    exprs: List[ast.AST] = list(call.args[1:3])
    for kw in call.keywords:
        if kw.arg in ("const_vars", "mutable_vars"):
            exprs.append(kw.value)
    names: Set[str] = set()
    for e in exprs:
        if isinstance(e, ast.Name):
            names.add(e.id)
        elif isinstance(e, (ast.List, ast.Tuple)):
            for el in e.elts:
                if isinstance(el, ast.Name):
                    names.add(el.id)
    return names


def _container_mutations(fn: ast.AST) -> Dict[str, int]:
    """bare name -> first line where it is container-mutated in ``fn``
    (mutator method call, subscript store, or augmented assignment)."""
    muts: Dict[str, int] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATORS and \
                isinstance(node.func.value, ast.Name):
            muts.setdefault(node.func.value.id, node.lineno)
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = getattr(node, "targets", None) or [node.target]
            for t in targets:
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Name):
                    muts.setdefault(t.value.id, node.lineno)
    return muts


def _store_base(node: ast.AST) -> Optional[str]:
    """Innermost Name of a subscript/attribute store target."""
    seen_deref = False
    while isinstance(node, (ast.Subscript, ast.Attribute, ast.Starred)):
        seen_deref = True
        node = node.value
    if seen_deref and isinstance(node, ast.Name):
        return node.id
    return None


def _closure_mutations(fn: ast.AST) -> List[Tuple[str, int]]:
    """(name, line) for every free name the closure mutates."""
    if isinstance(fn, ast.Lambda):
        params = {a.arg for a in fn.args.args}
        body: List[ast.AST] = [fn.body]
    else:
        args = fn.args
        params = {a.arg for a in
                  args.posonlyargs + args.args + args.kwonlyargs}
        if args.vararg:
            params.add(args.vararg.arg)
        if args.kwarg:
            params.add(args.kwarg.arg)
        body = list(fn.body)
    local: Set[str] = set()
    rebound: Set[str] = set()     # nonlocal/global names
    muts: List[Tuple[str, int]] = []
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Nonlocal, ast.Global)):
                rebound.update(node.names)
            elif isinstance(node, (ast.Assign, ast.AugAssign, ast.For,
                                   ast.AnnAssign)):
                targets = getattr(node, "targets", None) or \
                    [getattr(node, "target")]
                for t in targets:
                    if isinstance(t, ast.Name):
                        if t.id in rebound:
                            muts.append((t.id, node.lineno))
                        else:
                            local.add(t.id)
                    elif isinstance(t, ast.Tuple):
                        for e in t.elts:
                            if isinstance(e, ast.Name):
                                local.add(e.id)
                    else:
                        base = _store_base(t)
                        if base is not None:
                            muts.append((base, node.lineno))
            elif isinstance(node, ast.withitem) and \
                    isinstance(node.optional_vars, ast.Name):
                local.add(node.optional_vars.id)
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATORS and \
                    isinstance(node.func.value, ast.Name):
                muts.append((node.func.value.id, node.lineno))
    return [(n, ln) for n, ln in muts
            if n not in params and n not in local and n != "self"]


def _module_consumes_fuse(tree: ast.AST) -> bool:
    """True when the module opts captured sequences into trace-and-fuse:
    it references ``fuse_enabled`` (the engine gate) or spells the
    ``MXNET_ENGINE_FUSE`` env var itself."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and node.id == "fuse_enabled":
            return True
        if isinstance(node, ast.Attribute) and node.attr == "fuse_enabled":
            return True
        if isinstance(node, ast.Constant) and \
                node.value == "MXNET_ENGINE_FUSE":
            return True
    return False


class _FnLint:
    def __init__(self, mod: SourceModule, aliases: Dict[str, str],
                 qualname: str, fn: ast.AST, findings: List[Finding],
                 fuse_consumer: bool = False):
        self.mod = mod
        self.aliases = aliases
        self.qualname = qualname
        self.fn = fn
        self.findings = findings
        self.fuse_consumer = fuse_consumer
        # local defs/lambdas by name, for resolving the pushed closure
        self.local_fns: Dict[str, ast.AST] = {}
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn:
                self.local_fns[node.name] = node
            elif isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Lambda) and \
                    len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                self.local_fns[node.targets[0].id] = node.value

    def run(self):
        calls = [n for n in ast.walk(self.fn) if isinstance(n, ast.Call)]
        push_lines = []
        for node in calls:
            kind = _is_engine_push(node, self.aliases)
            if kind is not None:
                push_lines.append(node.lineno)
                self._check_push(node, kind)
        for node in calls:
            d = dotted(node.func)
            if d is not None and d.split(".")[-1] == "waitall" and \
                    push_lines and node.lineno > min(push_lines):
                self.findings.append(Finding(
                    "engine", "waitall-as-fence", self.mod.relpath,
                    node.lineno, self.qualname, d,
                    "waitall() after an engine push in the same "
                    "function: it drains the queue but is not a "
                    "happens-before edge for host callbacks — use "
                    "engine.fence(vars).wait()"))
        self._check_capture_pushes(calls)
        self._check_drain_loops()

    def _check_capture_pushes(self, calls: List[ast.Call]):
        seqs = _capture_seq_names(self.fn)
        if not seqs:
            return
        muts = None  # lazy: most capture regions have clean var lists
        for node in calls:
            f = node.func
            if not (isinstance(f, ast.Attribute)
                    and f.attr in ("push", "push_async")):
                continue
            recv = f.value
            recv_name = recv.id if isinstance(recv, ast.Name) else (
                recv.attr if isinstance(recv, ast.Attribute) else None)
            if recv_name not in seqs:
                continue
            if self.fuse_consumer and not any(
                    kw.arg == "fuse" for kw in node.keywords):
                self.findings.append(Finding(
                    "engine", "fuse-ineligible-op", self.mod.relpath,
                    node.lineno, self.qualname,
                    "%s.%s" % (recv_name, f.attr),
                    "capture-region push in a MXNET_ENGINE_FUSE consumer "
                    "carries no traceable metadata — one such op marks "
                    "the whole sequence fuse-ineligible and it silently "
                    "stays on replay; pass fuse=engine.FuseOp(...) or an "
                    "explicit fuse=None to opt this op out"))
            if muts is None:
                muts = _container_mutations(self.fn)
            for nm in sorted(_bare_list_names(node)):
                if nm in muts:
                    self.findings.append(Finding(
                        "engine", "capture-unstable-push",
                        self.mod.relpath, node.lineno, self.qualname,
                        "%s:%s" % (recv_name, nm),
                        "capture-region push takes its var list from "
                        "'%s', a container mutated in this function "
                        "(line %d) — the changing list breaks sequence "
                        "stability silently; snapshot it (tuple(%s)) "
                        "before pushing" % (nm, muts[nm], nm)))

    def _check_push(self, call: ast.Call, kind: str):
        if not _has_var_decl(call):
            self.findings.append(Finding(
                "engine", "push-missing-vars", self.mod.relpath,
                call.lineno, self.qualname,
                "%s:%s" % (kind, unparse(call.func)),
                "%s call declares neither const_vars nor mutable_vars — "
                "the engine cannot order this op against anything" % kind))
        has_mutable = len(call.args) >= 3 or any(
            kw.arg == "mutable_vars" for kw in call.keywords)
        if has_mutable:
            # the op owns a write-var; host state it mutates is assumed to
            # be covered by it (name-level matching can't see through var
            # indirection without drowning correct sites in noise)
            return
        closure = self._resolve_closure(call)
        if closure is None:
            return
        # one level transitive: the closure may delegate the mutation to a
        # sibling local helper (lambda: fetch(i, a) style)
        reach = [closure]
        for node in ast.walk(closure):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id in self.local_fns and \
                    self.local_fns[node.func.id] not in reach:
                reach.append(self.local_fns[node.func.id])
        for fn in reach:
            for name, line in _closure_mutations(fn):
                self.findings.append(Finding(
                    "engine", "push-async-undeclared-mutable",
                    self.mod.relpath, line, self.qualname,
                    "%s:%s" % (kind, name),
                    "pushed closure mutates '%s' but the %s declares no "
                    "mutable_vars — the engine cannot serialize this "
                    "against other ops touching it" % (name, kind)))

    def _resolve_closure(self, call: ast.Call) -> Optional[ast.AST]:
        if not call.args:
            return None
        fn = call.args[0]
        if isinstance(fn, ast.Lambda):
            return fn
        if isinstance(fn, ast.Name):
            return self.local_fns.get(fn.id)
        return None

    def _check_drain_loops(self):
        for node in ast.walk(self.fn):
            if not isinstance(node, (ast.For, ast.AsyncFor)):
                continue
            if len(node.body) != 1 or node.orelse:
                continue
            st = node.body[0]
            if not (isinstance(st, ast.Expr)
                    and isinstance(st.value, ast.Call)):
                continue
            func = st.value.func
            tail = func.attr if isinstance(func, ast.Attribute) else (
                func.id if isinstance(func, ast.Name) else None)
            if tail not in _WAIT_CALLS:
                continue
            self.findings.append(Finding(
                "engine", "drain-as-fence", self.mod.relpath, node.lineno,
                self.qualname,
                "%s<-%s" % (tail, unparse(node.iter)),
                "per-element %s loop used as a multi-var fence — "
                "engine.fence(vars) is one pushed op and also fences "
                "host callbacks" % tail))


def check(modules: Sequence[SourceModule]) -> List[Finding]:
    findings: List[Finding] = []
    for m in modules:
        aliases = import_aliases(m.tree)
        fuse_mod = _module_consumes_fuse(m.tree)
        # module-level statements + every def (methods get Class.method)
        _FnLint(m, aliases, "%s:" % m.modname,
                ast.Module(body=[s for s in m.tree.body
                                 if not isinstance(s, (ast.FunctionDef,
                                                       ast.AsyncFunctionDef,
                                                       ast.ClassDef))],
                           type_ignores=[]),
                findings, fuse_mod).run()
        for node in m.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _FnLint(m, aliases, "%s:%s" % (m.modname, node.name),
                        node, findings, fuse_mod).run()
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        _FnLint(m, aliases,
                                "%s:%s.%s" % (m.modname, node.name,
                                              sub.name),
                                sub, findings, fuse_mod).run()
    return findings
