"""Runtime compile witness — the dynamic half of the compile-surface guard.

``MXNET_COMPILE_WITNESS=1`` arms a process-wide recorder that every
sanctioned compile surface (``predict.Predictor._compile``,
``quant.QuantizedPredictor._compile``, ``serving.generate.programs``,
``engine.FusedSequence``, the executor train-step AOT path, and
``progcache.load``) reports into: each fresh XLA compile is recorded with
(kind, key, shapes, stack), each persistent-progcache disk load with
(kind, key). After :func:`steady_state` is called — the phase marker a
server flips once warmup is done — ANY fresh compile is a violation:
the recompile storm the bounded-program invariant forbids, caught with
the stack that caused it instead of a latency cliff in production.

Disabled (the default) every hook is one branch-and-return, mirroring the
telemetry discipline; the bench serving arm gates the overhead at <1%.

Locking: ``_lock`` is a LEAF (rank 100 in
:data:`.lockorder.LOCK_HIERARCHY`) guarding only the record tables —
nothing is acquired under it and the telemetry counter increments happen
after release. It may be taken while a caller holds another leaf lock
(``BucketCache._lock`` builds programs under its hold); that nesting is
deadlock-free because this lock is terminal.

The counters surface on the telemetry registry as
``compiles_total{kind="..."}`` and ``compiles_after_steady_total``
(docs/observability.md). The static half is
:mod:`mxnet_tpu.analysis.compilesurface`.
"""
from __future__ import annotations

import os
import threading
import traceback
from typing import Dict, List, Optional

_TRUTHY = ("1", "true", "yes", "on")

_enabled = os.environ.get("MXNET_COMPILE_WITNESS",
                          "").strip().lower() in _TRUTHY

_lock = threading.Lock()
_tls = threading.local()

#: record/violation lists are bounded; the counts stay exact past the cap
MAX_RECORDS = 512

_records: List[dict] = []
_violations: List[dict] = []
_counts: Dict[str, int] = {}        # kind -> fresh XLA compiles
_disk_counts: Dict[str, int] = {}   # kind -> progcache disk loads
_scope_counts: Dict[tuple, int] = {}  # (scope, "compile"|"disk") -> n
_steady = False
_after_steady = 0
_scope_counter = [0]


def enabled() -> bool:
    """True when the witness records (env ``MXNET_COMPILE_WITNESS=1`` or a
    programmatic :func:`enable`)."""
    return _enabled


def enable(on: bool = True) -> bool:
    """Programmatic arm/disarm (tests and the bench overhead arm — the
    env var is the production switch). Returns the previous state."""
    global _enabled
    prev = _enabled
    _enabled = bool(on)
    return prev


def new_scope() -> int:
    """A fresh scope token: surfaces that want a per-instance compile /
    disk-load split (BucketCache, DecodePrograms) tag their builds with
    one and read it back via :func:`scope_counts`."""
    with _lock:
        _scope_counter[0] += 1
        return _scope_counter[0]


class _NullSurface:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SURFACE = _NullSurface()


class _Surface:
    __slots__ = ("scope",)

    def __init__(self, scope: int):
        self.scope = scope

    def __enter__(self):
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append(self.scope)
        return self

    def __exit__(self, *exc):
        _tls.stack.pop()
        return False


def surface(scope: int):
    """Context manager tagging compiles/disk loads recorded on THIS thread
    with ``scope`` (e.g. BucketCache wraps its ``reshape`` calls so the
    inner Predictor compile lands in the cache's scope counts). Acquires
    no lock — a thread-local push/pop; a no-op singleton when disabled."""
    if not _enabled:
        return _NULL_SURFACE
    return _Surface(scope)


def _current_scope(scope: Optional[int]) -> Optional[int]:
    if scope is not None:
        return scope
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def _capture_stack() -> List[str]:
    frames = traceback.extract_stack(limit=16)[:-2]
    return ["%s:%d %s" % (os.path.basename(f.filename), f.lineno or 0,
                          f.name) for f in frames]


def _export(kind: str, steady: bool):
    # telemetry counters increment OUTSIDE _lock (leaf discipline); the
    # import is lazy so the pure-AST analysis package stays stdlib-only
    # for consumers that never arm the witness
    try:
        from ..telemetry.metrics import registry
    except Exception:
        return
    registry.counter(
        "compiles_total",
        help="fresh XLA compiles recorded by the compile witness",
        labels={"kind": kind}).inc()
    if steady:
        registry.counter(
            "compiles_after_steady_total",
            help="fresh XLA compiles after witness.steady_state() — any "
                 "nonzero value is a recompile-storm violation").inc()
        # a steady-state recompile is exactly the anomaly the flight
        # recorder exists for: snapshot the serving picture around it
        try:
            from ..telemetry import flight

            flight.on_anomaly("compile_after_steady", kind=kind)
        except Exception:
            pass


def record_compile(kind: str, key: str = "", shapes: str = "",
                   scope: Optional[int] = None):
    """One fresh XLA compile on surface ``kind``. After
    :func:`steady_state` this is a violation and keeps the causing stack.
    Disabled: one branch."""
    global _after_steady
    if not _enabled:
        return
    scope = _current_scope(scope)
    rec = {"kind": kind, "key": str(key)[:96], "shapes": str(shapes)[:256],
           "stack": _capture_stack()}
    with _lock:
        steady = _steady
        rec["after_steady"] = steady
        _counts[kind] = _counts.get(kind, 0) + 1
        if scope is not None:
            sk = (scope, "compile")
            _scope_counts[sk] = _scope_counts.get(sk, 0) + 1
        if len(_records) < MAX_RECORDS:
            _records.append(rec)
        if steady:
            _after_steady += 1
            if len(_violations) < MAX_RECORDS:
                _violations.append(rec)
    _export(kind, steady)


def record_disk_load(kind: str, key: str = "",
                     scope: Optional[int] = None):
    """One progcache disk load on surface ``kind`` — never a violation
    (warm restarts disk-load the whole program set by design)."""
    if not _enabled:
        return
    scope = _current_scope(scope)
    with _lock:
        _disk_counts[kind] = _disk_counts.get(kind, 0) + 1
        if scope is not None:
            sk = (scope, "disk")
            _scope_counts[sk] = _scope_counts.get(sk, 0) + 1


def steady_state():
    """Flip the phase marker: warmup is over, the program set is closed.
    Every fresh compile recorded after this call is a violation."""
    global _steady
    if not _enabled:
        return
    with _lock:
        _steady = True
    try:
        # materialize the counter at 0 so scrapers see the gauge before
        # the first (never, ideally) violation
        from ..telemetry.metrics import registry
        registry.counter(
            "compiles_after_steady_total",
            help="fresh XLA compiles after witness.steady_state() — any "
                 "nonzero value is a recompile-storm violation")
    except Exception:
        pass


def in_steady_state() -> bool:
    return _steady


def compiles_total(kind: Optional[str] = None) -> int:
    with _lock:
        if kind is not None:
            return _counts.get(kind, 0)
        return sum(_counts.values())


def disk_loads_total(kind: Optional[str] = None) -> int:
    with _lock:
        if kind is not None:
            return _disk_counts.get(kind, 0)
        return sum(_disk_counts.values())


def compiles_after_steady_total() -> int:
    with _lock:
        return _after_steady


def violations() -> List[dict]:
    """Fresh compiles recorded after :func:`steady_state`, each with the
    host stack that caused it."""
    with _lock:
        return [dict(v) for v in _violations]


def scope_counts(scope: int) -> Dict[str, int]:
    """``{"compiles": n, "disk_hits": n}`` recorded under ``scope``."""
    with _lock:
        return {"compiles": _scope_counts.get((scope, "compile"), 0),
                "disk_hits": _scope_counts.get((scope, "disk"), 0)}


def compile_witness_report() -> dict:
    """The full witness state: per-kind compile/disk-load counts, the
    steady-state flag, and every violation with its stack."""
    with _lock:
        return {
            "enabled": _enabled,
            "steady": _steady,
            "compiles": dict(_counts),
            "disk_loads": dict(_disk_counts),
            "compiles_total": sum(_counts.values()),
            "disk_loads_total": sum(_disk_counts.values()),
            "compiles_after_steady_total": _after_steady,
            "violations": [dict(v) for v in _violations],
            "records": [dict(r) for r in _records],
        }


def reset():
    """Clear records and drop the steady-state marker (tests; dryruns
    that exercise several serving phases in one process)."""
    global _steady, _after_steady
    with _lock:
        _records.clear()
        _violations.clear()
        _counts.clear()
        _disk_counts.clear()
        _scope_counts.clear()
        _steady = False
        _after_steady = 0
