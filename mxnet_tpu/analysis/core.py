"""Shared infrastructure for the static-analysis pass.

The pass is pure ``ast`` — no module under analysis is ever imported, so
the analyzer can be pointed at fixture files reproducing known deadlocks
without executing them. Each checker consumes the parsed module set and
yields :class:`Finding` objects; findings carry a **stable fingerprint**
(checker, rule, file, enclosing def, subject — everything except the line
number) so a finding survives unrelated edits above it, and the checked-in
baseline (``ci/analysis_baseline.json``) can allowlist justified existing
findings while CI fails only on regressions.
"""
from __future__ import annotations

import ast
import hashlib
import json
import os
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Tuple


@dataclass(frozen=True)
class Finding:
    """One analyzer finding.

    ``subject`` is the stable payload of the finding (lock ids, callee
    name, impure call target, ...) — it participates in the fingerprint,
    ``message`` and ``line`` do not.
    """

    checker: str    # "lockorder" | "engine" | "purity"
    rule: str       # e.g. "lock-cycle", "callback-under-lock"
    path: str       # posix path relative to the scan root
    line: int
    qualname: str   # "module:Class.method" of the enclosing def ("" = module)
    subject: str
    message: str

    @property
    def fingerprint(self) -> str:
        blob = "|".join((self.checker, self.rule, self.path,
                         self.qualname, self.subject))
        return hashlib.sha1(blob.encode()).hexdigest()[:16]

    def format(self) -> str:
        return "%s:%d: [%s/%s] %s  {%s}" % (
            self.path, self.line, self.checker, self.rule, self.message,
            self.fingerprint)


class SourceModule:
    """One parsed source file."""

    def __init__(self, root: str, path: str):
        self.path = path
        self.relpath = os.path.relpath(path, root).replace(os.sep, "/")
        name = self.relpath[:-3] if self.relpath.endswith(".py") \
            else self.relpath
        parts = [p for p in name.split("/") if p != "__init__"]
        self.modname = ".".join(parts) or os.path.basename(root)
        with open(path, "r", encoding="utf-8") as f:
            self.source = f.read()
        self.tree = ast.parse(self.source, filename=path)


_SKIP_DIRS = {"__pycache__", ".git", "node_modules", ".ipynb_checkpoints"}


def load_modules(root: str) -> List[SourceModule]:
    """Parse every ``*.py`` under ``root`` (files with syntax errors are
    skipped — they cannot be analyzed and the test suite catches them)."""
    root = os.path.abspath(root)
    if os.path.isfile(root):
        return [SourceModule(os.path.dirname(root), root)]
    mods = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            try:
                mods.append(SourceModule(root, os.path.join(dirpath, fn)))
            except SyntaxError:
                continue
    return mods


# --- small AST helpers shared by the checkers --------------------------------
def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def import_aliases(tree: ast.Module) -> Dict[str, str]:
    """Map of local alias -> imported module/name. ``from . import engine``
    maps ``engine -> engine``; ``import numpy as np`` maps ``np -> numpy``;
    ``from threading import Lock`` maps ``Lock -> threading.Lock``."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            for a in node.names:
                if a.name == "*":
                    continue
                target = ("%s.%s" % (base, a.name)) if base else a.name
                out[a.asname or a.name] = target
    return out


def unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return "<expr>"


# --- baseline ----------------------------------------------------------------
def load_baseline(path: Optional[str]) -> Dict[str, dict]:
    """fingerprint -> baseline entry. ``None``/``"none"``/missing file
    mean an empty baseline (every finding is new)."""
    if not path or path == "none" or not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return {e["fingerprint"]: e for e in data.get("findings", [])}


def write_baseline(path: str, findings: Sequence[Finding],
                   justification: str = "TODO: justify") -> None:
    entries = [{"fingerprint": f.fingerprint, "rule": f.rule, "path": f.path,
                "qualname": f.qualname, "subject": f.subject,
                "justification": justification}
               for f in findings]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"findings": entries}, f, indent=2, sort_keys=True)
        f.write("\n")


def diff_against_baseline(findings: Sequence[Finding],
                          baseline: Dict[str, dict]
                          ) -> Tuple[List[Finding], List[dict]]:
    """(new findings, stale baseline entries). Stale entries are reported
    as warnings so the baseline shrinks as findings get fixed."""
    fps = {f.fingerprint for f in findings}
    new = [f for f in findings if f.fingerprint not in baseline]
    stale = [e for fp, e in sorted(baseline.items()) if fp not in fps]
    return new, stale


def dedupe(findings: Iterable[Finding]) -> List[Finding]:
    """Drop duplicate fingerprints (first occurrence wins) and order the
    report by location."""
    seen, out = set(), []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        if f.fingerprint in seen:
            continue
        seen.add(f.fingerprint)
        out.append(f)
    return out
