"""Compile-surface analyzer — the static half of the bounded-program guard.

The framework's production claim is a *bounded program set*: weights are
program arguments (weight-independent progcache keys), every compile
surface has a declared ladder+k bound, donated buffers are never touched
after the call, and steady state compiles nothing. This checker enforces
the shape of that invariant over the whole tree, pure-``ast`` (nothing is
imported), reusing :mod:`.lockorder`'s package index + per-function call
summaries for the interprocedural caller map. Rules:

- ``weight-as-closure-constant``  a fn traced by ``jax.jit``/``pjit``
  closes over param/weight/aux state instead of taking it as an argument
  — the weights get baked into the executable, so the progcache key must
  hash param BYTES and a warm restart or weight swap recompiles (the
  invariant quant/PR 14 states explicitly: weights ride as arguments).
- ``stray-jit``  a jit call site outside the sanctioned surfaces
  (:data:`SANCTIONED_SURFACES`), interprocedural one helper level deep: a
  helper whose resolvable callers are ALL sanctioned inherits their
  sanction. New surfaces are allowlisted in ``ci/analysis_baseline.json``
  with a written justification — or properly sanctioned + budgeted.
- ``donated-arg-reuse``  a host reference passed at a ``donate_argnums``
  position of a jit-compiled callable and dereferenced later in the same
  block — XLA invalidated that buffer at the call.
- ``undeclared-program-budget``  every sanctioned surface that owns a
  jit site must declare its ladder+k bound in :data:`PROGRAM_BUDGETS`,
  so a new compile surface fails the gate until its bound is written
  down.

The dynamic half is :mod:`.compile_witness`
(``MXNET_COMPILE_WITNESS=1``).
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, SourceModule, dotted, import_aliases, unparse
from .lockorder import FuncKey, _Index, _collect_summaries
from .trace_purity import _fn_params, _local_names, _walk_stop_at_defs

#: call tails that trigger an XLA compile surface (shard_map alone does
#: not compile — it surfaces through the jit that wraps it)
_COMPILE_TAILS = {"jit", "pjit"}

#: Sanctioned compile surfaces, matched on dotted-segment boundaries
#: against ``module.Class.func`` ids (so ``DecodePrograms`` covers every
#: method, and ``Executor.make_train_step`` covers the nested
#: ``_run_impl``). A jit site inside one of these — or inside a helper
#: whose resolvable callers are all sanctioned — is legal IF the matched
#: surface declares its bound in :data:`PROGRAM_BUDGETS`.
SANCTIONED_SURFACES: Tuple[str, ...] = (
    "Predictor._compile",
    "QuantizedPredictor._compile",
    "BucketCache",
    "DecodePrograms",
    "PagedDecodePrograms",
    "Executor._get_fwd",
    "Executor._get_fwd_bwd",
    "Executor.make_train_step",
    "FusedSequence",
)

#: Declared program budgets: sanctioned surface id -> the ladder+k bound
#: CI gates (docs/static_analysis.md has the rendered table). A
#: sanctioned surface owning a jit site but missing here fails the
#: ``undeclared-program-budget`` rule.
PROGRAM_BUDGETS: Dict[str, str] = {
    "predict.Predictor._compile":
        "1 per bound input signature; serving bounds signatures via the "
        "BucketCache ladder. The traced fn closes over weights BY DESIGN "
        "(baselined) — compensated by a weight-DEPENDENT progcache key "
        "(model_fingerprint hashes param bytes).",
    "quant.QuantizedPredictor._compile":
        "1 per bound input signature — weights/scales are program "
        "arguments, key is weight-independent lowered text.",
    "serving.bucket_cache.BucketCache":
        "len(buckets) programs, ever — one per ladder rung; set_ladder "
        "enforces the program budget on swaps. (Owns no jit site itself; "
        "compiles route through Predictor._compile under its witness "
        "scope.)",
    "serving.generate.programs.DecodePrograms":
        "ladder + 3: one prefill per rung + ONE decode step + ONE admit "
        "(+ ONE spec verify when enabled; the draft step replaces the "
        "vanilla step, keeping spec at ladder + 2 extra).",
    "serving.generate.programs.PagedDecodePrograms":
        "ladder + 2: one paged-prefill per rung (admit folded in) + ONE "
        "paged decode step (+ ONE spec verify when enabled).",
    "executor.Executor._get_fwd":
        "<= 2 (is_train in {False, True}) per executor bind.",
    "executor.Executor._get_fwd_bwd":
        "1 per executor bind.",
    "executor.Executor.make_train_step":
        "1 per (update_fn, chain, avals) — the fused train step; "
        "chain-K folds K sub-steps into the one program.",
    "engine.FusedSequence":
        "1 per stabilized capture signature, progcache-keyed by the "
        "fused lowered text; carry/feed avals fold in the committed "
        "sharding signature, so a ZeRO stage or mesh change is a new "
        "signature (re-stage), never a silent respecialization.",
}

#: names whose presence as a traced-fn FREE variable means weights are
#: closure constants; attribute loads of these on free receivers too
_WEIGHT_NAME_RE = re.compile(r"(^|_)(param|params|weight|weights|qval|"
                             r"qvals)($|_|s$)")
_WEIGHT_ATTRS = {"params", "_arg_params", "_aux_params", "arg_params",
                 "aux_params", "weights", "_qvals"}


def _weighty_name(name: str) -> bool:
    return bool(_WEIGHT_NAME_RE.search(name)) or name.startswith("aux_")


def _compile_like(node: ast.AST, aliases: Dict[str, str]) -> bool:
    """True for a reference to ``jax.jit``/``pjit`` (import-alias aware)."""
    d = dotted(node)
    if d is None:
        return False
    tail = d.split(".")[-1]
    if tail not in _COMPILE_TAILS:
        return False
    head = d.split(".")[0]
    if "." in d:
        src = aliases.get(head, head)
        return src.split(".")[0] == "jax"
    src = aliases.get(d, "")
    return src.split(".")[0] == "jax" or src.endswith(".%s" % tail)


def _match_surface(cand: str, pattern: str) -> Optional[str]:
    """The surface id (prefix of ``cand`` through ``pattern``) when
    ``pattern`` matches ``cand`` on dotted-segment boundaries."""
    wrapped = "." + cand + "."
    pos = wrapped.find("." + pattern + ".")
    if pos < 0:
        return None
    return cand[:pos + len(pattern)]


def _key_candidate(key: FuncKey) -> str:
    mod, cls, fn = key
    return ".".join(p for p in (mod, cls, fn) if p)


def _surface_of(key: FuncKey) -> Optional[str]:
    cand = _key_candidate(key)
    for p in SANCTIONED_SURFACES:
        s = _match_surface(cand, p)
        if s is not None:
            return s
    return None


def _qualname(key: FuncKey) -> str:
    mod, cls, fn = key
    if not fn:
        return "%s:" % mod
    return "%s:%s" % (mod, ("%s.%s" % (cls, fn)) if cls else fn)


def _functions(tree: ast.Module):
    """Every def in the module as ``(cls_name, dotted_fn_name, node)``,
    nested defs dotted like lockorder's summary keys
    (``make_train_step._run_impl``)."""
    out: List[Tuple[Optional[str], str, ast.AST]] = []

    def rec(node, cls, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                name = prefix + child.name
                out.append((cls, name, child))
                rec(child, cls, name + ".")
            elif isinstance(child, ast.ClassDef):
                rec(child, child.name, "")
            else:
                rec(child, cls, prefix)

    rec(tree, None, "")
    return out


# --- weight-as-closure-constant ----------------------------------------------
def _traced_target(call: ast.Call, local_defs: Dict[str, ast.AST]
                   ) -> Tuple[Optional[ast.AST], str]:
    """(fn ast, display name) for the traced callable of a jit call, when
    it resolves to an inline lambda or a local def."""
    if not call.args:
        return None, ""
    target = call.args[0]
    if isinstance(target, ast.Lambda):
        return target, "<lambda>"
    if isinstance(target, ast.Name) and target.id in local_defs:
        return local_defs[target.id], target.id
    return None, unparse(target)


def _check_weight_closure(mod: SourceModule, qual: str, fn: ast.AST,
                          fn_name: str, line: int,
                          findings: List[Finding]):
    params = _fn_params(fn) if not isinstance(fn, ast.Lambda) \
        else {a.arg for a in fn.args.args}
    local = _local_names(fn)
    body = [fn.body] if isinstance(fn, ast.Lambda) else fn.body
    # a free name used only as a call TARGET is a helper function, not
    # weight state (dequantize_weight(...) is fine; weights(...) is not a
    # shape that occurs)
    call_funcs: Set[int] = set()
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name):
                call_funcs.add(id(node.func))
    flagged: Set[str] = set()
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                n = node.id
                if n in params or n in local or n in flagged or \
                        id(node) in call_funcs:
                    continue
                if _weighty_name(n):
                    flagged.add(n)
                    findings.append(Finding(
                        "compilesurface", "weight-as-closure-constant",
                        mod.relpath, getattr(node, "lineno", line), qual,
                        "%s:%s" % (fn_name, n),
                        "traced fn %s closes over weight-like state %r — "
                        "weights baked into the executable break "
                        "weight-independent progcache keys; pass them as "
                        "program arguments (the quant/PR 14 invariant)"
                        % (fn_name, n)))
            elif isinstance(node, ast.Attribute) and \
                    isinstance(node.ctx, ast.Load) and \
                    node.attr in _WEIGHT_ATTRS:
                base = node.value
                while isinstance(base, ast.Attribute):
                    base = base.value
                if not isinstance(base, ast.Name):
                    continue
                if base.id in params or base.id in local:
                    continue
                subj = "%s:%s.%s" % (fn_name, base.id, node.attr)
                if subj in flagged:
                    continue
                flagged.add(subj)
                findings.append(Finding(
                    "compilesurface", "weight-as-closure-constant",
                    mod.relpath, getattr(node, "lineno", line), qual,
                    subj,
                    "traced fn %s reads %s.%s through its closure — "
                    "weights baked into the executable break "
                    "weight-independent progcache keys; pass them as "
                    "program arguments" % (fn_name, base.id, node.attr)))


# --- donated-arg-reuse -------------------------------------------------------
def _donate_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for e in v.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    out.append(e.value)
            return tuple(out)
    return None


def _jit_call_in(value: ast.AST, aliases) -> Optional[ast.Call]:
    """The jit ctor call inside an assignment value (unwraps IfExp)."""
    if isinstance(value, ast.IfExp):
        return _jit_call_in(value.body, aliases) or \
            _jit_call_in(value.orelse, aliases)
    if isinstance(value, ast.Call) and _compile_like(value.func, aliases):
        return value
    return None


def _check_donated_reuse(mod: SourceModule, qual_for, top_fn: ast.AST,
                         aliases, findings: List[Finding]):
    """Linear same-block scan over a top-level def's subtree: names
    assigned from ``jax.jit(..., donate_argnums=...)``, then called with
    Name args at donated positions, kill those names; a later load in the
    same statement block (no rebind between) is a dangling-buffer read."""
    donated_fns: Dict[str, Tuple[int, ...]] = {}
    for node in ast.walk(top_fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            call = _jit_call_in(node.value, aliases)
            if call is not None:
                pos = _donate_positions(call)
                if pos:
                    donated_fns[node.targets[0].id] = pos
    if not donated_fns:
        return

    def scan_block(stmts: Sequence[ast.stmt]):
        dead: Dict[str, int] = {}  # name -> line it was donated at
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            loads, dons, stores = [], [], []
            for node in _walk_stop_at_defs(st):
                if isinstance(node, ast.Name):
                    if isinstance(node.ctx, ast.Load):
                        loads.append(node)
                    elif isinstance(node.ctx, ast.Store):
                        stores.append(node.id)
                elif isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Name) and \
                        node.func.id in donated_fns:
                    for p in donated_fns[node.func.id]:
                        if p < len(node.args) and \
                                isinstance(node.args[p], ast.Name):
                            dons.append((node.args[p].id, node.lineno))
            for nd in loads:
                if nd.id in dead:
                    findings.append(Finding(
                        "compilesurface", "donated-arg-reuse",
                        mod.relpath, nd.lineno, qual_for,
                        nd.id,
                        "%r was passed at a donate_argnums position "
                        "(line %d) and is dereferenced after the call — "
                        "XLA invalidated that buffer; rebind the name to "
                        "the program's output or drop the donation"
                        % (nd.id, dead[nd.id])))
                    dead.pop(nd.id, None)  # one finding per donation
            for name, line in dons:
                dead[name] = line
            for name in stores:
                dead.pop(name, None)

    for node in ast.walk(top_fn):
        for field in ("body", "orelse", "finalbody"):
            blk = getattr(node, field, None)
            if isinstance(blk, list) and blk and \
                    isinstance(blk[0], ast.stmt):
                scan_block(blk)


# --- the checker -------------------------------------------------------------
def check(modules: Sequence[SourceModule]) -> List[Finding]:
    index = _Index(modules)
    summaries = _collect_summaries(index)
    callers: Dict[FuncKey, Set[FuncKey]] = {}
    for k, s in summaries.items():
        for _held, callee, _line in s.calls:
            callers.setdefault(callee, set()).add(k)

    findings: List[Finding] = []
    budget_flagged: Set[str] = set()

    def check_budget(surface: str, mod: SourceModule, line: int,
                     qual: str):
        if surface in PROGRAM_BUDGETS or surface in budget_flagged:
            return
        budget_flagged.add(surface)
        findings.append(Finding(
            "compilesurface", "undeclared-program-budget", mod.relpath,
            line, qual, surface,
            "sanctioned compile surface %s owns a jit site but declares "
            "no bound in analysis.PROGRAM_BUDGETS — register its "
            "ladder+k program budget (docs/static_analysis.md)"
            % surface))

    for m in modules:
        aliases = index.aliases.get(m.modname) or import_aliases(m.tree)
        fns = _functions(m.tree)
        # local defs per top-level def subtree, for traced-fn resolution
        for cls, fname, fn in fns:
            key: FuncKey = (m.modname, cls, fname)
            qual = _qualname(key)
            local_defs: Dict[str, ast.AST] = {}
            for node in ast.walk(fn):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) and \
                        node is not fn:
                    local_defs[node.name] = node
                elif isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Lambda) and \
                        len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Name):
                    local_defs[node.targets[0].id] = node.value
            for node in _walk_stop_at_defs(fn):
                if not (isinstance(node, ast.Call) and
                        _compile_like(node.func, aliases)):
                    continue
                traced, tname = _traced_target(node, local_defs)
                # rule: stray-jit / undeclared-program-budget
                surface = _surface_of(key)
                if surface is not None:
                    check_budget(surface, m, node.lineno, qual)
                else:
                    csurf = [_surface_of(c)
                             for c in sorted(callers.get(key, ()))]
                    if csurf and all(csurf):
                        for s in sorted(set(csurf)):
                            check_budget(s, m, node.lineno, qual)
                    else:
                        findings.append(Finding(
                            "compilesurface", "stray-jit", m.relpath,
                            node.lineno, qual,
                            "jit(%s)" % (tname or "<expr>"),
                            "jit call site outside the sanctioned compile "
                            "surfaces (%s is not sanctioned and neither "
                            "are all its callers) — route it through a "
                            "budgeted surface or baseline it with a "
                            "justification" % (qual,)))
                # rule: weight-as-closure-constant
                if traced is not None:
                    _check_weight_closure(m, qual, traced, tname,
                                          node.lineno, findings)
            # rule: donated-arg-reuse (whole top-level subtree once)
            if "." not in fname:
                _check_donated_reuse(m, qual, fn, aliases, findings)
        # module-scope jit sites (outside any def) are always stray
        for st in m.tree.body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            for node in _walk_stop_at_defs(st):
                if isinstance(node, ast.Call) and \
                        _compile_like(node.func, aliases):
                    findings.append(Finding(
                        "compilesurface", "stray-jit", m.relpath,
                        node.lineno, "%s:" % m.modname,
                        "jit(%s)" % (unparse(node.args[0])
                                     if node.args else "<expr>"),
                        "module-scope jit call site — compile surfaces "
                        "must live inside a sanctioned, budgeted "
                        "surface"))
    return findings
