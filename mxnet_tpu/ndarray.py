"""Imperative NDArray API.

TPU-native analogue of the reference NDArray
(src/ndarray/ndarray.cc, include/mxnet/ndarray.h:58-421, python wrapper
python/mxnet/ndarray.py). An NDArray is a mutable *handle* over an immutable
``jax.Array``: in-place ops rebind the handle, which is exactly the
reference's chunk-with-engine-var semantics mapped onto XLA's async runtime
— dispatch is async (jax ops return futures over device buffers),
``wait_to_read`` ≡ ``block_until_ready`` (ndarray.h:153-168).

Every registered operator becomes a module-level function here, generated
from the op registry at import — the same mechanism as the reference's
ctypes-generated functions (python/mxnet/ndarray.py:28-39).
"""
from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import autograd as _autograd
from . import random as _random
from .base import MXNetError, attrs_key, dtype_mx_to_np, dtype_np_to_mx
from .context import Context, default_context
from .ops import OP_REGISTRY, OpContext, OpDef, get_op


# generated op functions below shadow some builtins in this namespace
# (slice, sum, max, min, abs); keep aliases for internal use
_py_slice = slice
_py_sum = sum
_py_max = max
_py_min = min
_py_abs = abs


def _as_jax_dtype(dtype):
    if dtype is None:
        return jnp.float32
    if dtype == "bfloat16":
        return jnp.bfloat16
    return jnp.dtype(np.dtype(dtype))


class NDArray:
    """Mutable handle over an immutable jax.Array."""

    __slots__ = ("_data", "_ctx", "_grad", "__weakref__")

    def __init__(self, data, ctx: Optional[Context] = None):
        if isinstance(data, NDArray):
            data = data._data
        self._data = data
        self._ctx = ctx
        self._grad = None

    # --- metadata --------------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return np.dtype(str(self._data.dtype)) if self._data.dtype != jnp.bfloat16 else self._data.dtype

    @property
    def size(self):
        return int(np.prod(self.shape)) if self.shape else 1

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def context(self) -> Context:
        if self._ctx is not None:
            return self._ctx
        try:
            dev = list(self._data.devices())[0]
        except Exception:
            return default_context()
        if dev.platform == "cpu":
            return Context("cpu", dev.id)
        return Context("tpu", dev.id)

    ctx = context

    @property
    def grad(self):
        return self._grad

    # --- sync / transfer --------------------------------------------------
    def wait_to_read(self):
        """Block until the value is computed (reference WaitToRead,
        ndarray.h:153-160)."""
        jax.block_until_ready(self._data)
        return self

    wait_to_write = wait_to_read

    def asnumpy(self) -> np.ndarray:
        return np.asarray(self._data)

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def astype(self, dtype):
        return NDArray(self._data.astype(_as_jax_dtype(dtype)))

    def copy(self) -> "NDArray":
        return NDArray(self._data + 0 if self._data.dtype != jnp.bool_ else self._data)

    def copyto(self, other):
        """Copy into another NDArray handle or to a context (reference
        CopyFromTo, ndarray.cc:294-347)."""
        if isinstance(other, NDArray):
            if other.shape != self.shape:
                raise MXNetError("copyto shape mismatch %s vs %s" % (other.shape, self.shape))
            other._data = jax.device_put(self._data, _ctx_device(other.context)).astype(other._data.dtype)
            return other
        if isinstance(other, Context):
            return NDArray(jax.device_put(self._data, _ctx_device(other)), ctx=other)
        raise MXNetError("copyto: unsupported target %r" % (other,))

    def as_in_context(self, ctx: Context) -> "NDArray":
        if ctx == self.context:
            return self
        return self.copyto(ctx)

    # --- shape ops (zero-copy views in the reference; functional here) ----
    def reshape(self, shape):
        if isinstance(shape, int):
            shape = (shape,)
        return NDArray(jnp.reshape(self._data, tuple(shape)))

    T = property(lambda self: NDArray(self._data.T))

    def slice(self, start, stop):
        return NDArray(self._data[start:stop])

    def flatten(self):
        return NDArray(self._data.reshape(self.shape[0], -1))

    def expand_dims(self, axis):
        return NDArray(jnp.expand_dims(self._data, axis))

    # --- indexing ---------------------------------------------------------
    def __getitem__(self, key):
        if isinstance(key, NDArray):
            key = key._data.astype(jnp.int32)
        return NDArray(self._data[key])

    def __setitem__(self, key, value):
        if isinstance(value, NDArray):
            value = value._data
        if isinstance(key, _py_slice) and key == _py_slice(None):
            if np.isscalar(value):
                self._data = jnp.full_like(self._data, value)
            else:
                value = jnp.asarray(value, self._data.dtype)
                self._data = jnp.broadcast_to(value, self.shape).astype(self._data.dtype)
        else:
            if isinstance(key, NDArray):
                key = key._data.astype(jnp.int32)
            self._data = self._data.at[key].set(value)

    def __len__(self):
        return self.shape[0]

    def __iter__(self):
        for i in range(self.shape[0]):
            yield self[i]

    def __bool__(self):
        return bool(self.asscalar())

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __repr__(self):
        return "<NDArray %s @%s>\n%s" % (
            "x".join(str(s) for s in self.shape),
            self.context,
            self.asnumpy(),
        )

    # --- arithmetic -------------------------------------------------------
    def _binop(self, other, op, scalar_op, reverse=False):
        if isinstance(other, NDArray):
            a, b = (other, self) if reverse else (self, other)
            return invoke(get_op(op), [a, b], {})[0]
        return invoke(
            get_op(scalar_op if not reverse else scalar_op.replace("_", "_r", 1)),
            [self],
            {"scalar": float(other)},
        )[0]

    def __add__(self, other):
        return self._binop(other, "broadcast_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binop(other, "broadcast_sub", "_minus_scalar")

    def __rsub__(self, other):
        return self._binop(other, "broadcast_sub", "_minus_scalar", reverse=True)

    def __mul__(self, other):
        return self._binop(other, "broadcast_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binop(other, "broadcast_div", "_div_scalar")

    def __rtruediv__(self, other):
        return self._binop(other, "broadcast_div", "_div_scalar", reverse=True)

    def __mod__(self, other):
        return self._binop(other, "broadcast_mod", "_mod_scalar")

    def __rmod__(self, other):
        return self._binop(other, "broadcast_mod", "_mod_scalar", reverse=True)

    def __pow__(self, other):
        return self._binop(other, "broadcast_power", "_power_scalar")

    def __rpow__(self, other):
        return self._binop(other, "broadcast_power", "_power_scalar", reverse=True)

    def __neg__(self):
        return invoke(get_op("negative"), [self], {})[0]

    def __abs__(self):
        return invoke(get_op("abs"), [self], {})[0]

    def __iadd__(self, other):
        out = self.__add__(other)
        self._data = out._data
        return self

    def __isub__(self, other):
        out = self.__sub__(other)
        self._data = out._data
        return self

    def __imul__(self, other):
        out = self.__mul__(other)
        self._data = out._data
        return self

    def __itruediv__(self, other):
        out = self.__truediv__(other)
        self._data = out._data
        return self

    def __eq__(self, other):
        if isinstance(other, (NDArray, int, float)):
            return self._binop(other, "broadcast_equal", "_equal_scalar")
        return NotImplemented

    def __ne__(self, other):
        if isinstance(other, (NDArray, int, float)):
            return self._binop(other, "broadcast_not_equal", "_not_equal_scalar")
        return NotImplemented

    def __gt__(self, other):
        return self._binop(other, "broadcast_greater", "_greater_scalar")

    def __ge__(self, other):
        return self._binop(other, "broadcast_greater_equal", "_greater_equal_scalar")

    def __lt__(self, other):
        return self._binop(other, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, other):
        return self._binop(other, "broadcast_lesser_equal", "_lesser_equal_scalar")

    __hash__ = object.__hash__

    # --- autograd ---------------------------------------------------------
    def attach_grad(self, grad_req="write"):
        grad = NDArray(jnp.zeros_like(self._data))
        _autograd.mark_variables([self], [grad], grad_req)

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        _autograd.backward(
            [self],
            None if out_grad is None else [out_grad],
            retain_graph=retain_graph,
            train_mode=train_mode,
        )

    # reductions / conveniences mirroring reference methods
    def sum(self, axis=None, keepdims=False):
        return invoke(get_op("sum"), [self], {"axis": axis, "keepdims": keepdims})[0]

    def mean(self, axis=None, keepdims=False):
        return invoke(get_op("mean"), [self], {"axis": axis, "keepdims": keepdims})[0]

    def max(self, axis=None, keepdims=False):
        return invoke(get_op("max"), [self], {"axis": axis, "keepdims": keepdims})[0]

    def min(self, axis=None, keepdims=False):
        return invoke(get_op("min"), [self], {"axis": axis, "keepdims": keepdims})[0]


def _ctx_device(ctx: Context):
    return ctx.jax_device()


# --- imperative invoke ------------------------------------------------------
@functools.lru_cache(maxsize=8192)
def _jitted(op_name: str, akey, is_train: bool, n_inputs: int, n_aux: int, with_rng: bool):
    op = get_op(op_name)
    attrs = {k: _unfreeze(v) for k, v in akey}

    def run(rng, *arrs):
        inputs = arrs[:n_inputs]
        aux = arrs[n_inputs:]
        return op.impl(attrs, inputs, aux, OpContext(is_train, rng))

    return jax.jit(run)


def _unfreeze(v):
    return v


def invoke(op: OpDef, inputs: Sequence[NDArray], attrs: Dict[str, Any], out=None):
    """Execute one operator imperatively — the analogue of MXImperativeInvoke
    (src/c_api/c_api_ndarray.cc:324): resolve attrs, dispatch the jitted
    kernel, record on the autograd tape when recording.

    ``inputs`` is ordered arg_names + aux_names. Returns list of NDArrays
    (outputs only); aux handles are mutated in place like the reference's
    mutable inputs.
    """
    attrs = op.parse_attrs(attrs)
    arg_names = op.get_arg_names(attrs)
    aux_names = op.get_aux_names(attrs)
    if op.variadic:
        n_in = len(inputs)
        n_aux = 0
    else:
        n_aux = len(aux_names)
        n_in = len(inputs) - n_aux
    in_arrays = tuple(x._data for x in inputs[:n_in])
    aux_arrays = tuple(x._data for x in inputs[n_in:])
    rng = _random.next_key() if op.needs_rng else None
    is_train = _autograd.is_training()

    fn = _jitted(op.name, attrs_key(attrs), is_train, n_in, n_aux, rng is not None)
    outs, aux_out = fn(rng, *(in_arrays + aux_arrays))

    if _autograd.is_recording():
        _autograd.record_op(op, attrs, in_arrays, aux_arrays, rng, is_train, outs, aux_out)

    # mutate aux handles (reference: mutable inputs updated by engine op)
    for handle, new in zip(inputs[n_in:], aux_out):
        handle._data = new

    results = [NDArray(o) for o in outs]
    if out is not None:
        if isinstance(out, NDArray):
            out = [out]
        for tgt, res in zip(out, results):
            tgt._data = res._data
        results = list(out)
    return results


def _split_args(op: OpDef, args, kwargs):
    """Split user args/kwargs into (ordered inputs, attr dict)."""
    tensor_kwargs = {}
    attrs = {}
    for k, v in kwargs.items():
        if isinstance(v, NDArray):
            tensor_kwargs[k] = v
        else:
            attrs[k] = v
    attrs.pop("name", None)
    parsed = op.parse_attrs(attrs)
    names = list(op.get_arg_names(parsed)) + list(op.get_aux_names(parsed))
    if op.variadic:
        inputs = list(args) + [tensor_kwargs[k] for k in sorted(tensor_kwargs)]
        return inputs, attrs
    inputs: List[Optional[NDArray]] = [None] * len(names)
    for i, a in enumerate(args):
        inputs[i] = a
    for k, v in tensor_kwargs.items():
        if k not in names:
            raise MXNetError("%s: unexpected tensor argument %r" % (op.name, k))
        inputs[names.index(k)] = v
    filled = [x for x in inputs if x is not None]
    if len(filled) != len(names):
        missing = [n for n, x in zip(names, inputs) if x is None]
        raise MXNetError("%s missing inputs %s" % (op.name, missing))
    return filled, attrs


def _make_nd_function(op: OpDef):
    def fn(*args, **kwargs):
        out = kwargs.pop("out", None)
        inputs, attrs = _split_args(op, args, kwargs)
        results = invoke(op, inputs, attrs, out=out)
        if op.get_num_outputs(op.parse_attrs(attrs)) == 1:
            return results[0]
        return results

    fn.__name__ = op.py_name or op.name
    fn.__doc__ = op.build_doc()
    return fn


def _populate_namespace():
    g = globals()
    seen = {}
    for name, op in OP_REGISTRY.items():
        if id(op) in seen:
            target = seen[id(op)]
        else:
            target = _make_nd_function(op)
            seen[id(op)] = target
        if name not in g:
            g[name] = target
        pub = op.py_name or name
        if pub not in g:
            g[pub] = target


# --- creation / utility -----------------------------------------------------
def array(source, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    if isinstance(source, NDArray):
        source = source.asnumpy()
    was_ndarray = isinstance(source, np.ndarray)
    arr = np.asarray(source, dtype=np.dtype(dtype) if dtype and dtype != "bfloat16" else None)
    if dtype is None and (not was_ndarray or arr.dtype == np.float64):
        # reference semantics: python lists default to float32
        # (python/mxnet/ndarray.py array); np arrays keep their dtype
        arr = arr.astype(np.float32)
    ctx = ctx or default_context()
    data = jax.device_put(arr, _ctx_device(ctx))
    if dtype == "bfloat16":
        data = data.astype(jnp.bfloat16)
    return NDArray(data, ctx=ctx)


def empty(shape, ctx=None, dtype=None) -> NDArray:
    return zeros(shape, ctx, dtype)


def zeros(shape, ctx=None, dtype=None) -> NDArray:
    if isinstance(shape, int):
        shape = (shape,)
    ctx = ctx or default_context()
    with jax.default_device(_ctx_device(ctx)):
        return NDArray(jnp.zeros(tuple(shape), _as_jax_dtype(dtype)), ctx=ctx)


def ones(shape, ctx=None, dtype=None) -> NDArray:
    if isinstance(shape, int):
        shape = (shape,)
    ctx = ctx or default_context()
    with jax.default_device(_ctx_device(ctx)):
        return NDArray(jnp.ones(tuple(shape), _as_jax_dtype(dtype)), ctx=ctx)


def full(shape, val, ctx=None, dtype=None) -> NDArray:
    if isinstance(shape, int):
        shape = (shape,)
    ctx = ctx or default_context()
    with jax.default_device(_ctx_device(ctx)):
        return NDArray(jnp.full(tuple(shape), val, _as_jax_dtype(dtype)), ctx=ctx)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype="float32") -> NDArray:
    out = np.arange(start, stop, step, dtype=np.dtype(dtype))
    if repeat != 1:
        out = np.repeat(out, repeat)
    return array(out, ctx=ctx, dtype=dtype)


def onehot_encode(indices: NDArray, out: NDArray) -> NDArray:
    """Reference _onehot_encode (ndarray.cc): one-hot into out's shape."""
    depth = out.shape[1]
    res = invoke(get_op("one_hot"), [indices], {"depth": depth})[0]
    out._data = res._data.astype(out._data.dtype)
    return out


def concatenate(arrays: Sequence[NDArray], axis=0, always_copy=True) -> NDArray:
    return NDArray(jnp.concatenate([a._data for a in arrays], axis=axis))


def moveaxis(tensor: NDArray, source, destination) -> NDArray:
    return NDArray(jnp.moveaxis(tensor._data, source, destination))


def waitall():
    """Block on all outstanding async work (reference Engine WaitForAll /
    MXNDArrayWaitAll)."""
    (jax.device_put(0.0) + 0).block_until_ready()


def imdecode(buf, **kwargs):  # placed in mx.image in the full pipeline
    raise NotImplementedError("use mxnet_tpu.image.imdecode")


# --- save / load (checkpoint format, reference ndarray.h:334-343) -----------
_NDLIST_MAGIC = 0x112


def save(fname: str, data, format: str = "npz") -> None:
    """Save dict/list of NDArrays (npz container with the reference's
    arg:/aux: naming preserved by callers). ``format="reference"``
    writes the reference ecosystem's dmlc .params blob instead
    (interop.save_params), so artifacts round-trip back into reference
    tooling; nd.load auto-detects either on read."""
    if format not in ("npz", "reference"):
        raise ValueError("nd.save format must be 'npz' or 'reference', "
                         "got %r" % (format,))
    if isinstance(data, NDArray):
        data = [data]
    if format == "reference":
        from . import interop

        interop.save_params(fname, data)
        return
    if isinstance(data, dict):
        payload = {k: np.asarray(v._data) for k, v in data.items()}
        np.savez(fname, __format__="dict", **payload)
    else:
        payload = {("arr_%d" % i): np.asarray(v._data) for i, v in enumerate(data)}
        np.savez(fname, __format__="list", **payload)
    import os

    if not fname.endswith(".npz") and os.path.exists(fname + ".npz"):
        os.replace(fname + ".npz", fname)


def load(fname: str):
    # reference-ecosystem .params (dmlc blob, magic 0x112) loads through
    # interop.py; our own container is npz
    with open(fname, "rb") as fh:
        head = fh.read(8)
    from . import interop

    if interop.is_reference_params(head):
        return interop.load_params(fname)
    with np.load(fname, allow_pickle=False) as f:
        fmt = str(f["__format__"]) if "__format__" in f else "dict"
        if fmt == "list":
            keys = sorted(
                (k for k in f.files if k.startswith("arr_")),
                key=lambda s: int(s.split("_")[1]),
            )
            return [array(f[k]) for k in keys]
        return {k: array(f[k]) for k in f.files if k != "__format__"}


_populate_namespace()
