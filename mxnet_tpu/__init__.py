"""mxnet_tpu — a TPU-native deep learning framework with the capabilities of
MXNet 0.9.x (NNVM era), built on JAX/XLA idioms rather than ported from the
reference's CUDA/C++ engine. See SURVEY.md for the architectural map.
"""
import os as _os

from . import base
from .base import MXNetError, __version__
from .context import Context, cpu, cpu_pinned, gpu, tpu, current_context, num_devices
from . import ndarray
from . import ndarray as nd
from . import symbol
from . import symbol as sym
from .symbol import Variable, Group

# Predict-only builds (reference amalgamation MXNET_PREDICT_ONLY,
# include/mxnet/base.h:72-74): bind only the deployment surface — arrays,
# symbols, executor, predictor (plus their transitive deps like random/
# autograd) — and leave the training-stack names unbound. Direct
# `import mxnet_tpu.module` still works, as reference amalgamation users
# could still link the full library; the flag shapes the default surface.
_PREDICT_ONLY = _os.environ.get("MXNET_PREDICT_ONLY", "") not in ("", "0")

from . import executor
from .executor import Executor
from . import progcache
from . import predict
from . import quant
from . import serving
from . import telemetry
from . import autograd   # transitive deps of the executor surface:
from . import random     # bound unconditionally for consistency
from .random import seed

_TRAINING_SURFACE = frozenset((
    "AttrScope", "NameManager", "Prefix", "initializer", "init_registry",
    "optimizer", "metric", "lr_scheduler", "callback", "io", "kvstore",
    "mod", "module", "monitor", "Monitor", "visualization", "viz",
    "test_utils", "model", "FeedForward", "executor_manager",
    "kvstore_server", "operator", "models", "recordio", "rtc", "engine",
    "rnn", "profiler", "image", "registry", "log", "libinfo", "contrib",
    "notebook", "plugins", "misc", "torch", "th", "filesystem",
    "resilience",
))

if not _PREDICT_ONLY:
    from .attribute import AttrScope
    from .name import NameManager, Prefix
    from . import initializer
    from .initializer import init_registry  # noqa: F401
    from . import optimizer
    from . import metric
    from . import lr_scheduler
    from . import callback
    from . import io
    from . import kvstore
    from . import module as mod
    from . import module
    from . import monitor
    from .monitor import Monitor
    from . import visualization
    from . import visualization as viz
    from . import test_utils
    from . import model
    from .model import FeedForward
    from . import executor_manager
    from . import kvstore_server
    from . import operator
    from . import models
    from . import recordio
    from . import rtc
    from . import engine
    from . import rnn
    from . import profiler
    from . import image
    from . import registry
    from . import log
    from . import libinfo
    from . import contrib
    from . import notebook
    from . import plugins
    from . import misc
    from . import filesystem


def __getattr__(name):
    if _PREDICT_ONLY and name in _TRAINING_SURFACE:
        raise AttributeError(
            "mxnet_tpu was imported with MXNET_PREDICT_ONLY=1; %r is "
            "outside the predict-only surface (unset the env var, or "
            "import the submodule explicitly)" % name)
    # Lazy heavy/optional plugins: mx.torch (PyTorch foreign-kernel seam,
    # torch.py) is only imported on first touch, like the reference's
    # opt-in Torch plugin (plugin/torch, make/config.mk TORCH_PATH).
    if name in ("torch", "th"):
        import importlib

        m = importlib.import_module(".torch", __name__)
        globals()["torch"] = globals()["th"] = m
        return m
    # mx.analysis (static checkers + lock-order witness, docs/
    # static_analysis.md): dev/CI tooling, lazy so `import mxnet_tpu`
    # never pays for it.
    if name == "analysis":
        import importlib

        m = importlib.import_module(".analysis", __name__)
        globals()["analysis"] = m
        return m
    # mx.resilience (sharded checkpoints, fault injection, supervisor):
    # training-surface depth, lazy so plain imports never pay for it.
    if name == "resilience":
        import importlib

        m = importlib.import_module(".resilience", __name__)
        globals()["resilience"] = m
        return m
    raise AttributeError("module %r has no attribute %r" % (__name__, name))
