"""mxnet_tpu — a TPU-native deep learning framework with the capabilities of
MXNet 0.9.x (NNVM era), built on JAX/XLA idioms rather than ported from the
reference's CUDA/C++ engine. See SURVEY.md for the architectural map.
"""
from . import base
from .base import MXNetError, __version__
from .context import Context, cpu, cpu_pinned, gpu, tpu, current_context, num_devices
from . import ndarray
from . import ndarray as nd
from . import symbol
from . import symbol as sym
from .symbol import Variable, Group
from . import autograd
from . import random
from .random import seed
from . import executor
from .executor import Executor
from .attribute import AttrScope
from .name import NameManager, Prefix
from . import initializer
from .initializer import init_registry  # noqa: F401
from . import optimizer
from . import metric
from . import lr_scheduler
from . import callback
from . import io
from . import kvstore
from . import module as mod
from . import module
from . import monitor
from .monitor import Monitor
from . import visualization
from . import visualization as viz
from . import test_utils
from . import model
from .model import FeedForward
from . import operator
from . import models
from . import recordio
from . import rtc
from . import predict
from . import engine
from . import rnn
from . import profiler
