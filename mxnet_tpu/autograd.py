"""Imperative autograd.

TPU-native analogue of the reference autograd runtime
(src/ndarray/autograd.{h,cc}): a thread-local tape records every imperative
op call (RecordImperativeFCompute, autograd.cc:70-135); ``backward`` replays
the recorded graph through ``jax.vjp`` — the counterpart of the reference's
"build a GraphExecutor over the recorded symbol and run Backward"
(autograd.cc:138-205).

Design notes:
- jax arrays are immutable, so a tape node can safely hold the exact input
  values seen at record time; NDArray mutation after recording cannot
  corrupt the tape (the reference needs engine versioning for this).
- Replays are compiled: the whole replay+vjp is jitted once per tape
  *structure* (op sequence + shapes), so steady-state imperative training
  pays one XLA executable launch per backward — the analogue of the
  reference's cached-op path (graph_executor.cc:567-679).
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .base import MXNetError, attrs_key
from .ops.registry import OpContext, OpDef

_GRAD_REQ = {"null": 0, "write": 1, "add": 3}


class _TapeNode:
    __slots__ = ("op", "attrs", "inputs", "aux", "rng", "is_train", "outputs", "aux_outputs")

    def __init__(self, op, attrs, inputs, aux, rng, is_train, outputs, aux_outputs):
        self.op = op
        self.attrs = attrs
        self.inputs = tuple(inputs)
        self.aux = tuple(aux)
        self.rng = rng
        self.is_train = is_train
        self.outputs = tuple(outputs)
        self.aux_outputs = tuple(aux_outputs)


class _State(threading.local):
    def __init__(self):
        self.recording = False
        self.training = False
        self.tape: List[_TapeNode] = []
        # keyed by id(NDArray handle) so rebinds of ._data (optimizer steps,
        # x[:]=) keep the variable attached; values (handle, grad, req)
        self.marked: Dict[int, Tuple[Any, Any, str]] = {}
        self.marked_order: List[int] = []


_state = _State()
_bwd_cache: Dict[Any, Any] = {}


def is_recording() -> bool:
    return _state.recording


def is_training() -> bool:
    return _state.training


def set_recording(flag: bool) -> bool:
    old = _state.recording
    _state.recording = flag
    return old


def set_training(flag: bool) -> bool:
    old = _state.training
    _state.training = flag
    return old


class _RecordScope:
    def __init__(self, recording, train_mode):
        self._recording = recording
        self._train = train_mode

    def __enter__(self):
        self._old_rec = set_recording(self._recording)
        self._old_train = set_training(self._train)
        return self

    def __exit__(self, *args):
        set_recording(self._old_rec)
        set_training(self._old_train)


def record(train_mode: bool = True):
    """Context manager entering record+train mode (reference
    python/mxnet/autograd-style API)."""
    return _RecordScope(True, train_mode)


def pause(train_mode: bool = False):
    return _RecordScope(False, train_mode)


def train_mode():
    return _RecordScope(_state.recording, True)


def predict_mode():
    return _RecordScope(_state.recording, False)


def mark_variables(variables, gradients, grad_reqs="write"):
    """Attach gradient buffers to arrays (reference autograd.cc:54-68
    MarkVariables / MXAutogradMarkVariables)."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for var, grad, req in zip(variables, gradients, grad_reqs):
        var._grad = grad
        key = id(var)
        if key not in _state.marked:
            _state.marked_order.append(key)
        _state.marked[key] = (var, grad, req)


def record_op(op: OpDef, attrs: dict, inputs, aux, rng, is_train, outputs, aux_outputs):
    """Append one imperative call to the tape (reference
    RecordImperativeFCompute, autograd.cc:70-82)."""
    _state.tape.append(
        _TapeNode(op, attrs, inputs, aux, rng, is_train, outputs, aux_outputs)
    )


def _clear_tape():
    _state.tape = []
    _state.marked = {}
    _state.marked_order = []


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Compute gradients of heads w.r.t. marked variables and write them into
    the attached grad buffers honouring grad_req write/add/null.

    Mirrors MXAutogradComputeGradient → AutogradRuntime::ComputeGradient
    (autograd.cc:138-205), except the "executor" is a jitted jax.vjp replay.
    """
    if not isinstance(heads, (list, tuple)):
        heads = [heads]
    if head_grads is not None and not isinstance(head_grads, (list, tuple)):
        head_grads = [head_grads]

    tape = _state.tape
    marked = dict(_state.marked)
    order = list(_state.marked_order)
    if not marked:
        raise MXNetError("no variables marked for gradient (call mark_variables)")

    # --- classify every array slot: marked var / produced by node / constant
    produced: Dict[int, Tuple[int, int, bool]] = {}  # id -> (node_idx, out_idx, is_aux)
    const_ids: Dict[int, Any] = {}
    # resolve each marked handle to its CURRENT array (rebinds since mark
    # time — optimizer steps, x[:]= — must keep the variable attached)
    var_entries = [marked[k] for k in order]
    var_index = {id(v._data): i for i, (v, _, _) in enumerate(var_entries)}

    const_index: Dict[int, int] = {}

    def slot(arr):
        k = id(arr)
        if k in var_index:
            return ("v", var_index[k])
        if k in produced:
            n, o, a = produced[k]
            return ("n", n, o, a)
        if k not in const_index:
            const_index[k] = len(const_index)
            const_ids[k] = arr
        return ("c", const_index[k])

    node_sigs = []
    node_slots = []
    for ni, node in enumerate(tape):
        in_slots = [slot(a) for a in node.inputs]
        aux_slots = [slot(a) for a in node.aux]
        rng_slot = None
        if node.rng is not None:
            rng_slot = slot(node.rng)
        for oi, oa in enumerate(node.outputs):
            produced[id(oa)] = (ni, oi, False)
        for oi, oa in enumerate(node.aux_outputs):
            produced[id(oa)] = (ni, oi, True)
        node_slots.append((in_slots, aux_slots, rng_slot))
        node_sigs.append(
            (node.op.name, attrs_key(node.attrs), node.is_train,
             tuple(in_slots), tuple(aux_slots), rng_slot)
        )

    head_slots = []
    for h in heads:
        k = id(h._data)
        if k in var_index:
            head_slots.append(("v", var_index[k]))
        elif k in produced:
            n, o, a = produced[k]
            head_slots.append(("n", n, o, a))
        else:
            raise MXNetError("backward head was not computed under record()")

    var_vals = [v._data for v, _, _ in var_entries]
    const_vals = list(const_ids.values())
    reqs = tuple(req for _, _, req in var_entries)

    sig = (
        tuple(node_sigs),
        tuple(head_slots),
        reqs,
        tuple((v.shape, str(v.dtype)) for v in var_vals),
        tuple((getattr(c, "shape", ()), str(getattr(c, "dtype", ""))) for c in const_vals),
        head_grads is None,
    )

    fn = _bwd_cache.get(sig)
    if fn is None:
        ops = [(node.op, dict(node.attrs), node.is_train) for node in tape]
        slots_c = list(node_slots)
        heads_c = list(head_slots)

        def resolve(env_nodes, vvals, cvals, s):
            if s[0] == "v":
                return vvals[s[1]]
            if s[0] == "c":
                return cvals[s[1]]
            _, n, o, a = s
            return env_nodes[n][1][o] if a else env_nodes[n][0][o]

        def replay(vvals, cvals):
            env_nodes = []
            for (op, attrs, is_train), (in_s, aux_s, rng_s) in zip(ops, slots_c):
                ins = [resolve(env_nodes, vvals, cvals, s) for s in in_s]
                auxs = [resolve(env_nodes, vvals, cvals, s) for s in aux_s]
                rng = resolve(env_nodes, vvals, cvals, rng_s) if rng_s else None
                outs, aux_out = op.impl(attrs, tuple(ins), tuple(auxs), OpContext(is_train, rng))
                env_nodes.append((tuple(outs), tuple(aux_out)))
            return [resolve(env_nodes, vvals, cvals, s) for s in heads_c]

        def grad_fn(vvals, cvals, hgrads, old_grads):
            outs, vjp = jax.vjp(lambda *vs: replay(list(vs), cvals), *vvals)
            if hgrads is None:
                hgrads = [jnp.ones_like(o) for o in outs]
            grads = vjp(list(hgrads))
            results = []
            for g, req, old in zip(grads, reqs, old_grads):
                if req == "null":
                    results.append(old)
                elif req == "add":
                    results.append(old + g)
                else:
                    results.append(g)
            return results

        fn = jax.jit(grad_fn, static_argnames=())
        _bwd_cache[sig] = fn

    hg_vals = None if head_grads is None else [g._data for g in head_grads]
    old_grads = [
        (grad._data if grad is not None else jnp.zeros_like(v))
        for (_, grad, _), v in zip(var_entries, var_vals)
    ]
    new_grads = fn(var_vals, const_vals, hg_vals, old_grads)
    for (_, grad_nd, req), g in zip(var_entries, new_grads):
        if req != "null" and grad_nd is not None:
            grad_nd._data = g
    if not retain_graph:
        _state.tape = []


def grad(heads, variables, head_grads=None, retain_graph=False, create_graph=False,
         train_mode=True):
    """Functional gradient of heads w.r.t. variables (returns new arrays)."""
    from .ndarray import NDArray, zeros

    grads = [zeros(v.shape, dtype=v.dtype) for v in variables]
    mark_variables(variables, grads, "write")
    backward(heads, head_grads, retain_graph=retain_graph, train_mode=train_mode)
    return grads
