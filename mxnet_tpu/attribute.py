"""Attribute scoping (reference python/mxnet/attribute.py AttrScope).

``with mx.AttrScope(ctx_group='dev1'):`` tags symbols created inside the
scope — the reference's model-parallel placement mechanism
(example/model-parallel-lstm/lstm.py:48-99, SURVEY §2.2). In the TPU build
ctx_group maps to sharding groups consumed by the parallel layer.
"""
from __future__ import annotations

import threading


class AttrScope:
    _current = threading.local()

    def __init__(self, **kwargs):
        self._old_scope = None
        for value in kwargs.values():
            if not isinstance(value, str):
                raise ValueError("Attributes need to be strings")
        self._attr = kwargs

    def get(self, attr):
        if self._attr:
            ret = self._attr.copy()
            if attr:
                ret.update(attr)
            return ret
        return attr if attr else {}

    def __enter__(self):
        if not hasattr(AttrScope._current, "value"):
            AttrScope._current.value = AttrScope()
        self._old_scope = AttrScope._current.value
        attr = AttrScope._current.value._attr.copy()
        attr.update(self._attr)
        self._attr = attr
        AttrScope._current.value = self
        return self

    def __exit__(self, ptype, value, trace):
        AttrScope._current.value = self._old_scope


def current() -> AttrScope:
    if not hasattr(AttrScope._current, "value"):
        AttrScope._current.value = AttrScope()
    return AttrScope._current.value
