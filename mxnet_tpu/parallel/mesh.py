"""Device mesh construction.

Replaces the reference's Context/group2ctx device-placement machinery
(include/mxnet/base.h:116-207, graph_executor.cc AssignContext :245-334)
with jax.sharding.Mesh axes. A Context named a single device; a MeshConfig
names how the whole job's devices factor into parallelism axes.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("data", "expert", "seq", "pipe", "model")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Axis sizes for the canonical 5-axis mesh. Any axis may be 1.

    "expert" is a dedicated expert-parallel axis (parallel/moe.py); MoE
    experts are sharded over the combined (data, expert, seq) group, so EP
    is exercised even when the expert axis itself is size 1."""

    data: int = 1
    expert: int = 1
    seq: int = 1
    pipe: int = 1
    model: int = 1

    @property
    def size(self) -> int:
        return self.data * self.expert * self.seq * self.pipe * self.model

    def axis_sizes(self):
        return (self.data, self.expert, self.seq, self.pipe, self.model)


def make_mesh(config: MeshConfig, devices: Optional[Sequence] = None) -> Mesh:
    """Build the Mesh. Axis order puts "model" innermost so tensor-parallel
    collectives ride nearest-neighbor ICI links, and "data" outermost so
    gradient all-reduce spans the slowest links (DCN on multi-host) —
    the standard ICI-vs-DCN layout recipe."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if config.size != n:
        raise ValueError(
            "mesh config %s needs %d devices, have %d" % (config, config.size, n))
    arr = np.asarray(devices).reshape(config.axis_sizes())
    return Mesh(arr, AXES)


def auto_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    """Factor n devices into (data, expert, seq, pipe, model) greedily:
    split off 2s into model, then pipe, then seq, then expert, rest to
    data. Guarantees tp/pp/sp are exercised on n>=8 (the virtual-CPU test
    mesh) and the dedicated expert axis on n>=16; EP itself is exercised
    for any n>=2 because experts shard over (data, expert, seq)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    sizes = {"data": 1, "expert": 1, "seq": 1, "pipe": 1, "model": 1}
    for axis in ("model", "pipe", "seq", "expert"):
        if n % 2 == 0 and n > 1:
            sizes[axis] *= 2
            n //= 2
    sizes["data"] = n
    cfg = MeshConfig(**sizes)
    return make_mesh(cfg, devices)


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
