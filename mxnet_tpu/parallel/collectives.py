"""Collective primitives + bandwidth harness.

The reference reduces gradients with hand-written tree-sums and P2P copies
(CommCPU/CommDevice, src/kvstore/comm.h:62-373) and ships a bus-bandwidth
measurement tool (tools/bandwidth/, cited by docs/how_to/perf.md). Here the
primitives are XLA collectives (psum/all_gather/ppermute/reduce_scatter)
addressed by mesh axis name — usable both inside shard_map'd code and, via
the jitted wrappers below, on full arrays from host-level code (the
imperative kvstore path).
"""
from __future__ import annotations

import functools
import time
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map


# --- in-shard_map primitives (use inside manually-sharded code) -----------
def all_reduce(x, axis_name):
    """Sum across a mesh axis (reference Comm::Reduce, comm.h:18-56)."""
    return jax.lax.psum(x, axis_name)


def all_gather(x, axis_name, axis=0, tiled=True):
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, axis=0):
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=axis,
                                tiled=True)


def ring_shift(x, axis_name, shift=1):
    """Send shard to the next device along a ring (ppermute) — the
    building block of ring attention and the SPMD pipeline."""
    n = jax.lax.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name, perm)


# --- host-level collectives over a mesh (imperative kvstore path) ---------
def mesh_all_reduce(x, mesh: Mesh, axis: str = "data"):
    """All-reduce stacked per-device contributions: x has a leading axis of
    size mesh.shape[axis] (one slot per device — the kvstore Push value
    list, kvstore_local.h:50-73); returns the replicated sum without the
    leading axis."""
    n = mesh.shape[axis]
    assert x.shape[0] == n, (x.shape, n)

    def f(s):
        return jax.lax.psum(s[0], axis)

    fn = shard_map(f, mesh=mesh, in_specs=(P(axis),), out_specs=P())
    return fn(x)


def barrier(mesh: Mesh):
    """Cross-device barrier: a tiny all-reduce forced to completion
    (reference ps::Postoffice::Barrier semantics)."""
    x = jnp.zeros((mesh.shape["data"], 1), jnp.float32)
    mesh_all_reduce(x, mesh, "data").block_until_ready()


def bus_bandwidth(mesh: Mesh, axis: str = "data", size_mb: float = 64.0,
                  iters: int = 10, dtype=jnp.float32):
    """Measure all-reduce bus bandwidth over a mesh axis — the analogue of
    the reference's tools/bandwidth harness. Returns GB/s of bus bandwidth
    using the standard ring-allreduce accounting 2*(n-1)/n * bytes."""
    n = int(np.prod([mesh.shape[a] for a in (axis,)]))
    itemsize = jnp.dtype(dtype).itemsize
    num = int(size_mb * 1024 * 1024 / itemsize) // n * n
    x = jnp.ones((num,), dtype)
    x = jax.device_put(x, NamedSharding(mesh, P(axis)))

    def f(s):
        return jax.lax.psum(s, axis)

    fn = jax.jit(shard_map(f, mesh=mesh, in_specs=(P(axis),), out_specs=P()))
    fn(x).block_until_ready()  # compile
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(x)
    out.block_until_ready()
    dt = (time.perf_counter() - t0) / iters
    bus_bytes = 2 * (n - 1) / max(n, 1) * num * itemsize
    return bus_bytes / dt / 1e9
