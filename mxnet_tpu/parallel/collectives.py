"""Collective primitives + bandwidth harness.

The reference reduces gradients with hand-written tree-sums and P2P copies
(CommCPU/CommDevice, src/kvstore/comm.h:62-373) and ships a bus-bandwidth
measurement tool (tools/bandwidth/, cited by docs/how_to/perf.md). Here the
primitives are XLA collectives (psum/all_gather/ppermute/reduce_scatter)
addressed by mesh axis name — usable both inside shard_map'd code and, via
the jitted wrappers below, on full arrays from host-level code (the
imperative kvstore path).
"""
from __future__ import annotations

import functools
import os
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # moved to the jax namespace after 0.4.x
    from jax import shard_map as _raw_shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map as _raw_shard_map

import inspect as _inspect

_SHARD_MAP_KW = set(_inspect.signature(_raw_shard_map).parameters)


def shard_map(f, **kw):
    """Version-tolerant shard_map: newer jax renamed check_rep ->
    check_vma (and moved the function out of jax.experimental). Translate
    whichever spelling the caller used into the one this jax accepts, so
    the parallel modules run on both."""
    if "check_vma" in kw and "check_vma" not in _SHARD_MAP_KW:
        kw["check_rep"] = kw.pop("check_vma")
    elif "check_rep" in kw and "check_rep" not in _SHARD_MAP_KW:
        kw["check_vma"] = kw.pop("check_rep")
    return _raw_shard_map(f, **kw)


def axis_size(axis_name):
    """Static size of a mapped mesh axis (or tuple of axes) from inside
    shard_map'd code. jax.lax.axis_size only exists on newer jax; older
    versions expose the bound frame via jax.core.axis_frame."""
    if hasattr(jax.lax, "axis_size"):
        return int(jax.lax.axis_size(axis_name))
    from jax.core import axis_frame

    if isinstance(axis_name, (tuple, list)):
        out = 1
        for a in axis_name:
            out *= int(axis_frame(a))
        return out
    return int(axis_frame(axis_name))


# --- in-shard_map primitives (use inside manually-sharded code) -----------
def all_reduce(x, axis_name):
    """Sum across a mesh axis (reference Comm::Reduce, comm.h:18-56)."""
    return jax.lax.psum(x, axis_name)


def all_gather(x, axis_name, axis=0, tiled=True):
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, axis=0):
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=axis,
                                tiled=True)


def ring_shift(x, axis_name, shift=1):
    """Send shard to the next device along a ring (ppermute) — the
    building block of ring attention and the SPMD pipeline."""
    n = axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name, perm)


# --- ZeRO-1 sharded weight update (Xu et al., "Automatic Cross-Replica
# --- Sharding of Weight Update in Data-Parallel Training") ----------------
#
# The weight-update phase of data-parallel training is redundant: every
# replica applies the same optimizer math to the same (all-reduced)
# gradients. Sharding it means each replica reduce_scatters the gradients,
# updates only its 1/N shard of the f32 master weights and optimizer state,
# and all_gathers the updated weights for the next forward. Per-replica
# optimizer-state memory drops ~N x.
#
# Two realizations live here:
# - spec/placement helpers for the AUTOMATIC (GSPMD) path used by
#   Executor.make_train_step: master weights/optimizer state are committed
#   with zero1_sharding and in-jit sharding constraints let XLA's SPMD
#   partitioner place the collectives (on TPU it fuses the gradient
#   all-reduce + shard into reduce-scatter — the paper's pass).
# - zero1_update_local for MANUAL shard_map code (parallel/transformer.py),
#   where the reduce_scatter/all_gather pair is written out explicitly.

def zero1_enabled(mesh: Optional[Mesh], axis_name: str = "data") -> bool:
    """True when a ZeRO sharded update (any stage >= 1) should be used:
    a mesh with a >1-sized axis_name and no MXNET_SHARDED_UPDATE=0
    opt-out. Callers fall back to the replicated update otherwise."""
    return sharded_stage(mesh, axis_name) >= 1


def sharded_stage(mesh: Optional[Mesh], axis_name: str = "data") -> int:
    """ZeRO stage selected by MXNET_SHARDED_UPDATE (Xu et al. + the
    DeepSpeed/ZeRO staging taxonomy):

      0  replicated update (opt-out)
      1  optimizer state + master weights 1/N at rest; whole-tree weight
         gather per step; gradients reduce-scattered at the end of backward
      2  stage 1 + gradients reduce-scattered AS backward emits them
         (bucketed, overlapping the remaining backward compute) — full
         gradient-tree residency is never required
      3  stage 2 + parameters stay 1/N at rest THROUGH the step: each leaf
         is all-gathered on demand and re-gathered in backward (remat)
         instead of held as a residual — param bytes/chip scale 1/N too

    Default is stage 1 (the shipped ZeRO-1 behavior). 0 when there is no
    mesh or the axis is trivial. Values clamp into [0, 3]."""
    if mesh is None:
        return 0
    if int(dict(mesh.shape).get(axis_name, 0)) <= 1:
        return 0
    raw = os.environ.get("MXNET_SHARDED_UPDATE", "1")
    try:
        stage = int(raw)
    except ValueError:
        stage = 1
    return max(0, min(3, stage))


def zero1_partition_spec(shape, n_shards: int, axis_name: str = "data") -> P:
    """PartitionSpec sharding the FIRST dim divisible by n_shards over
    axis_name. Leaves with no divisible dim stay replicated (per-leaf
    assignment rather than padding: uneven trees round-trip exactly, at
    the cost of keeping those — typically tiny bias/gamma — leaves
    unsharded)."""
    for i, d in enumerate(shape):
        if d >= n_shards and d % n_shards == 0:
            return P(*((None,) * i + (axis_name,)))
    return P()


def zero1_sharding(mesh: Mesh, shape, axis_name: str = "data") -> NamedSharding:
    """NamedSharding for one weight/state leaf under the ZeRO-1 layout."""
    n = int(dict(mesh.shape)[axis_name])
    return NamedSharding(mesh, zero1_partition_spec(shape, n, axis_name))


def zero1_place(tree, mesh: Mesh, axis_name: str = "data"):
    """Materialize every leaf of a weight/optimizer-state tree with its
    sharded NamedSharding — used at FIRST BIND so state is born sharded,
    never replicated-then-sliced. Always returns fresh buffers (safe to
    donate even when a leaf already had the target sharding)."""
    def place(a):
        out = jax.device_put(a, zero1_sharding(mesh, a.shape, axis_name))
        if out is a:
            # device_put with a matching sharding aliases; the caller will
            # donate this buffer, so force a real copy
            out = jnp.array(a, copy=True)
        return out

    return jax.tree_util.tree_map(place, tree)


def zero1_constrain(tree, mesh: Mesh, axis_name: str = "data"):
    """In-jit: pin every leaf to its ZeRO-1 sharding. Applied to the
    gradient tree this turns the data-parallel all-reduce into a
    reduce_scatter (each replica keeps only its shard); applied to the
    update's outputs it keeps new weights/state sharded for donation."""
    return jax.tree_util.tree_map(
        lambda a: jax.lax.with_sharding_constraint(
            a, zero1_sharding(mesh, a.shape, axis_name)), tree)


def replicate_constrain(tree, mesh: Mesh):
    """In-jit: gather every leaf to full (replicated) form — the weight
    all_gather ahead of the forward pass."""
    repl = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(
        lambda a: jax.lax.with_sharding_constraint(a, repl), tree)


def replicate_place(tree, mesh: Mesh):
    """Host-level: all-gather a (possibly ZeRO-sharded) tree into fully
    replicated buffers on the mesh — used when sharded master values are
    synced back into replicated executor/updater/kvstore storage."""
    return jax.device_put(tree, NamedSharding(mesh, P()))


def per_device_bytes(tree) -> int:
    """Max over devices of resident bytes for a pytree of jax arrays —
    the per-replica memory the ZeRO-1 layout is shrinking. Replicated
    leaves count fully on every device; sharded leaves 1/N."""
    per: dict = {}
    for leaf in jax.tree_util.tree_leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards is None:
            per[None] = per.get(None, 0) + int(getattr(leaf, "nbytes", 0))
            continue
        for s in shards:
            key = getattr(s.device, "id", s.device)
            per[key] = per.get(key, 0) + int(s.data.nbytes)
    return max(per.values()) if per else 0


def zero1_update_local(w, g, update_fn, axis_name: str = "data",
                       mean_grad: bool = True):
    """ZeRO-1 weight update INSIDE shard_map code: reduce_scatter the
    (flattened, padded) local gradient contribution over axis_name, apply
    `update_fn(w_shard, g_shard)` to this replica's 1/N shard, all_gather
    the updated weights back. The cross-replica gradient mean is folded
    into the reduce_scatter (mean_grad=True); padding makes any leaf shape
    round-trip exactly. w must be replicated over axis_name."""
    n = axis_size(axis_name)
    if n == 1:
        return update_fn(w, g)
    idx = jax.lax.axis_index(axis_name)
    size = w.size
    pad = (-size) % n
    gf = jnp.ravel(g)
    wf = jnp.ravel(w)
    if pad:
        gf = jnp.pad(gf, (0, pad))
        wf = jnp.pad(wf, (0, pad))
    chunk = (size + pad) // n
    g_sh = jax.lax.psum_scatter(gf, axis_name, scatter_dimension=0,
                                tiled=True)
    if mean_grad:
        g_sh = g_sh / n
    w_sh = jax.lax.dynamic_slice(wf, (idx * chunk,), (chunk,))
    new_sh = update_fn(w_sh, g_sh)
    nf = jax.lax.all_gather(new_sh, axis_name, axis=0, tiled=True)
    if pad:
        nf = nf[:size]
    return nf.reshape(w.shape).astype(w.dtype)


# --- ZeRO-2: gradients sharded end-to-end -----------------------------------
#
# Stage 1 lets the full gradient tree materialize out of backward and only
# then pins it to the 1/N layout (one constraint group after jax.vjp
# returns). Stage 2 moves the reduce-scatter INTO backward: each parameter
# leaf is wrapped in an identity whose custom cotangent rule constrains the
# incoming gradient to the sharded layout, so the scatter for leaf L is
# emitted adjacent to L's gradient producer and XLA's latency-hiding
# scheduler overlaps it with the remaining backward compute. Small leaves
# are grouped into flat buckets (MXNET_ZERO2_BUCKET_MB, default 4) so the
# wire carries a few large collectives instead of many tiny ones — the
# classic bucketed reduce-scatter. Values are untouched (layout only).

ZERO2_BUCKET_MB_DEFAULT = 4.0


def zero2_bucket_bytes() -> int:
    try:
        mb = float(os.environ.get("MXNET_ZERO2_BUCKET_MB",
                                  str(ZERO2_BUCKET_MB_DEFAULT)))
    except ValueError:
        mb = ZERO2_BUCKET_MB_DEFAULT
    return max(1, int(mb * 1024 * 1024))


def _grad_ct_constrain(x, sharding):
    """Identity whose COTANGENT is pinned to `sharding` — places the
    gradient reduce-scatter at the leaf's grad-producer site in backward."""

    @jax.custom_vjp
    def ident(v):
        return v

    def fwd(v):
        return v, None

    def bwd(_, ct):
        return (jax.lax.with_sharding_constraint(ct, sharding),)

    ident.defvjp(fwd, bwd)
    return ident(x)


def _grad_ct_bucket(leaves, shardings, flat_sharding):
    """Identity on a tuple of (same-dtype) leaves whose cotangents are
    flattened, concatenated and constrained as ONE flat sharded bucket —
    one collective for the whole group — then split back per leaf."""

    @jax.custom_vjp
    def ident(*vs):
        return tuple(vs)

    def fwd(*vs):
        return tuple(vs), None

    def bwd(_, cts):
        flat = jnp.concatenate([jnp.ravel(c) for c in cts])
        flat = jax.lax.with_sharding_constraint(flat, flat_sharding)
        out, off = [], 0
        for c, sh in zip(cts, shardings):
            piece = jax.lax.dynamic_slice(flat, (off,), (c.size,))
            off += c.size
            out.append(jax.lax.with_sharding_constraint(
                piece.reshape(c.shape), sh))
        return tuple(out)

    ident.defvjp(fwd, bwd)
    return ident(*leaves)


def zero2_grad_scatter(full, mesh: Mesh, axis_name: str = "data",
                       bucket_bytes: Optional[int] = None):
    """Wrap a dict of FULL (gathered) param leaves so backward emits
    reduce-scattered gradient shards bucket-by-bucket as it runs. Returns
    a dict with identical values; only the cotangent layout differs.
    Bucket plan: reverse insertion order (~ backward emission order); a
    leaf >= bucket_bytes scatters on its own, smaller leaves group into
    flat same-dtype buckets up to bucket_bytes."""
    if bucket_bytes is None:
        bucket_bytes = zero2_bucket_bytes()
    n = int(dict(mesh.shape)[axis_name])
    flat_sh = NamedSharding(mesh, P(axis_name))
    out = dict(full)
    group: list = []
    group_dtype = None
    group_bytes = 0

    def flush():
        nonlocal group, group_dtype, group_bytes
        if not group:
            return
        names = [nm for nm, _ in group]
        leaves = [lv for _, lv in group]
        shardings = [zero1_sharding(mesh, lv.shape, axis_name)
                     for lv in leaves]
        wrapped = _grad_ct_bucket(leaves, shardings, flat_sh)
        for nm, w in zip(names, wrapped):
            out[nm] = w
        group, group_dtype, group_bytes = [], None, 0

    for name in reversed(list(full)):
        leaf = full[name]
        nbytes = leaf.size * jnp.dtype(leaf.dtype).itemsize
        if nbytes >= bucket_bytes:
            out[name] = _grad_ct_constrain(
                leaf, zero1_sharding(mesh, leaf.shape, axis_name))
            continue
        if group and (jnp.dtype(leaf.dtype) != group_dtype
                      or group_bytes + nbytes > bucket_bytes):
            flush()
        group.append((name, leaf))
        group_dtype = jnp.dtype(leaf.dtype)
        group_bytes += nbytes
    flush()
    return out


# --- ZeRO-3: parameters sharded at rest, gathered on demand -----------------
#
# The gather for each leaf runs INSIDE the differentiated function and is
# tagged with checkpoint_name; the surrounding jax.checkpoint policy saves
# every residual EXCEPT those tags, so backward re-gathers weights from the
# 1/N shards instead of holding full-weight residuals across the step. The
# gathered copy is therefore transient in both passes (freed after its
# consumers), at the cost of a second gather in backward; XLA's
# latency-hiding scheduler starts gather L+1 while layer L computes — the
# one-layer prefetch.

ZERO3_GATHER_NAME = "zero3_allgather"

try:
    from jax.ad_checkpoint import checkpoint_name as _checkpoint_name
except ImportError:  # very old jax: lose the tag, keep the math
    def _checkpoint_name(x, name):
        return x


def _zero3_gather_leaf(x, repl, grad_sharding):
    """Per-leaf gather with an explicit cotangent rule: fwd gathers the
    shard to full (tagged so the remat policy drops it from residuals);
    bwd pins the incoming gradient straight to the 1/N layout — the
    reduce-scatter happens AT the leaf's grad-producer site, never a full
    replicated gradient (jax's default transpose of a sharding constraint
    would re-replicate the cotangent)."""

    @jax.custom_vjp
    def gather(v):
        return jax.lax.with_sharding_constraint(v, repl)

    def fwd(v):
        return jax.lax.with_sharding_constraint(v, repl), None

    def bwd(_, ct):
        return (jax.lax.with_sharding_constraint(ct, grad_sharding),)

    gather.defvjp(fwd, bwd)
    return _checkpoint_name(gather(x), ZERO3_GATHER_NAME)


def zero3_gather(tree, mesh: Mesh, axis_name: str = "data"):
    """In-jit per-leaf gather-on-demand (use INSIDE the function handed to
    zero3_remat so the re-gather in backward and the remat policy both see
    it). Gradients come back already in the 1/N layout."""
    repl = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(
        lambda a: _zero3_gather_leaf(
            a, repl, zero1_sharding(mesh, a.shape, axis_name)), tree)


def zero3_remat(f):
    """Wrap the fwd function so gathered weights are NOT saved as
    residuals: policy saves anything except ZERO3_GATHER_NAME tags, so
    the only backward recompute is the (re-)gathers themselves."""
    try:
        policy = jax.checkpoint_policies.save_any_names_but_these(
            ZERO3_GATHER_NAME)
    except AttributeError:  # old jax: fall back to saving residuals
        return f
    return jax.checkpoint(f, policy=policy)


def stage_train_bytes(params, stage: int, n_shards: int,
                      axis_name: str = "data",
                      bucket_bytes: Optional[int] = None):
    """(param_bytes, grad_bytes) per chip implied by the stage's LAYOUT
    CONTRACT for one train step over `params` (dict name -> array-like).

    This is the model behind the train_param_bytes / train_grad_bytes
    gauges: what the program's sharding constraints bound, not a live
    allocator reading (gradients are in-program transients).

      params: stage <= 2 holds the whole gathered tree through fwd+bwd
              (residuals); stage 3 holds the 1/N shards plus one transient
              gathered leaf (remat frees each copy after use).
      grads:  stage <= 1 lets the full tree materialize before the end-of-
              backward scatter; stage >= 2 bounds residency by the shard
              tree plus one in-flight bucket.

    Leaves with no n-divisible dim stay replicated in every stage (the
    zero1_partition_spec contract)."""
    if bucket_bytes is None:
        bucket_bytes = zero2_bucket_bytes()
    full = 0
    shard = 0
    max_leaf = 0
    for leaf in params.values():
        nbytes = int(leaf.size * jnp.dtype(leaf.dtype).itemsize)
        full += nbytes
        max_leaf = max(max_leaf, nbytes)
        if zero1_partition_spec(leaf.shape, n_shards, axis_name) == P():
            shard += nbytes
        else:
            shard += nbytes // n_shards
    if stage >= 3:
        param_bytes = shard + max_leaf
    elif stage >= 1:
        param_bytes = full + shard
    else:
        param_bytes = full
    if stage >= 2:
        # in-flight transient: one bucket, or one big leaf scattering
        # alone; never worse than the unsharded footprint (a bucket
        # larger than the whole tree degenerates to stage-1 residency)
        grad_bytes = min(full, shard + max(bucket_bytes, max_leaf))
    else:
        grad_bytes = full
    return param_bytes, grad_bytes


# --- host-level collectives over a mesh (imperative kvstore path) ---------
def mesh_all_reduce(x, mesh: Mesh, axis: str = "data"):
    """All-reduce stacked per-device contributions: x has a leading axis of
    size mesh.shape[axis] (one slot per device — the kvstore Push value
    list, kvstore_local.h:50-73); returns the replicated sum without the
    leading axis."""
    n = mesh.shape[axis]
    assert x.shape[0] == n, (x.shape, n)

    def f(s):
        return jax.lax.psum(s[0], axis)

    fn = shard_map(f, mesh=mesh, in_specs=(P(axis),), out_specs=P())
    return fn(x)


def barrier(mesh: Mesh):
    """Cross-device barrier: a tiny all-reduce forced to completion
    (reference ps::Postoffice::Barrier semantics)."""
    x = jnp.zeros((mesh.shape["data"], 1), jnp.float32)
    mesh_all_reduce(x, mesh, "data").block_until_ready()


def bus_bandwidth(mesh: Mesh, axis: str = "data", size_mb: float = 64.0,
                  iters: int = 10, dtype=jnp.float32):
    """Measure all-reduce bus bandwidth over a mesh axis — the analogue of
    the reference's tools/bandwidth harness. Returns GB/s of bus bandwidth
    using the standard ring-allreduce accounting 2*(n-1)/n * bytes."""
    n = int(np.prod([mesh.shape[a] for a in (axis,)]))
    itemsize = jnp.dtype(dtype).itemsize
    num = int(size_mb * 1024 * 1024 / itemsize) // n * n
    x = jnp.ones((num,), dtype)
    x = jax.device_put(x, NamedSharding(mesh, P(axis)))

    def f(s):
        return jax.lax.psum(s, axis)

    fn = jax.jit(shard_map(f, mesh=mesh, in_specs=(P(axis),), out_specs=P()))
    fn(x).block_until_ready()  # compile
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(x)
    out.block_until_ready()
    dt = (time.perf_counter() - t0) / iters
    bus_bytes = 2 * (n - 1) / max(n, 1) * num * itemsize
    return bus_bytes / dt / 1e9
