"""Collective primitives + bandwidth harness.

The reference reduces gradients with hand-written tree-sums and P2P copies
(CommCPU/CommDevice, src/kvstore/comm.h:62-373) and ships a bus-bandwidth
measurement tool (tools/bandwidth/, cited by docs/how_to/perf.md). Here the
primitives are XLA collectives (psum/all_gather/ppermute/reduce_scatter)
addressed by mesh axis name — usable both inside shard_map'd code and, via
the jitted wrappers below, on full arrays from host-level code (the
imperative kvstore path).
"""
from __future__ import annotations

import functools
import os
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # moved to the jax namespace after 0.4.x
    from jax import shard_map as _raw_shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map as _raw_shard_map

import inspect as _inspect

_SHARD_MAP_KW = set(_inspect.signature(_raw_shard_map).parameters)


def shard_map(f, **kw):
    """Version-tolerant shard_map: newer jax renamed check_rep ->
    check_vma (and moved the function out of jax.experimental). Translate
    whichever spelling the caller used into the one this jax accepts, so
    the parallel modules run on both."""
    if "check_vma" in kw and "check_vma" not in _SHARD_MAP_KW:
        kw["check_rep"] = kw.pop("check_vma")
    elif "check_rep" in kw and "check_rep" not in _SHARD_MAP_KW:
        kw["check_vma"] = kw.pop("check_rep")
    return _raw_shard_map(f, **kw)


def axis_size(axis_name):
    """Static size of a mapped mesh axis (or tuple of axes) from inside
    shard_map'd code. jax.lax.axis_size only exists on newer jax; older
    versions expose the bound frame via jax.core.axis_frame."""
    if hasattr(jax.lax, "axis_size"):
        return int(jax.lax.axis_size(axis_name))
    from jax.core import axis_frame

    if isinstance(axis_name, (tuple, list)):
        out = 1
        for a in axis_name:
            out *= int(axis_frame(a))
        return out
    return int(axis_frame(axis_name))


# --- in-shard_map primitives (use inside manually-sharded code) -----------
def all_reduce(x, axis_name):
    """Sum across a mesh axis (reference Comm::Reduce, comm.h:18-56)."""
    return jax.lax.psum(x, axis_name)


def all_gather(x, axis_name, axis=0, tiled=True):
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, axis=0):
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=axis,
                                tiled=True)


def ring_shift(x, axis_name, shift=1):
    """Send shard to the next device along a ring (ppermute) — the
    building block of ring attention and the SPMD pipeline."""
    n = axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name, perm)


# --- ZeRO-1 sharded weight update (Xu et al., "Automatic Cross-Replica
# --- Sharding of Weight Update in Data-Parallel Training") ----------------
#
# The weight-update phase of data-parallel training is redundant: every
# replica applies the same optimizer math to the same (all-reduced)
# gradients. Sharding it means each replica reduce_scatters the gradients,
# updates only its 1/N shard of the f32 master weights and optimizer state,
# and all_gathers the updated weights for the next forward. Per-replica
# optimizer-state memory drops ~N x.
#
# Two realizations live here:
# - spec/placement helpers for the AUTOMATIC (GSPMD) path used by
#   Executor.make_train_step: master weights/optimizer state are committed
#   with zero1_sharding and in-jit sharding constraints let XLA's SPMD
#   partitioner place the collectives (on TPU it fuses the gradient
#   all-reduce + shard into reduce-scatter — the paper's pass).
# - zero1_update_local for MANUAL shard_map code (parallel/transformer.py),
#   where the reduce_scatter/all_gather pair is written out explicitly.

def zero1_enabled(mesh: Optional[Mesh], axis_name: str = "data") -> bool:
    """True when the ZeRO-1 sharded update should be used: a mesh with a
    >1-sized axis_name and no MXNET_SHARDED_UPDATE=0 opt-out. Callers fall
    back to the replicated update otherwise."""
    if mesh is None:
        return False
    if os.environ.get("MXNET_SHARDED_UPDATE", "1") == "0":
        return False
    return int(dict(mesh.shape).get(axis_name, 0)) > 1


def zero1_partition_spec(shape, n_shards: int, axis_name: str = "data") -> P:
    """PartitionSpec sharding the FIRST dim divisible by n_shards over
    axis_name. Leaves with no divisible dim stay replicated (per-leaf
    assignment rather than padding: uneven trees round-trip exactly, at
    the cost of keeping those — typically tiny bias/gamma — leaves
    unsharded)."""
    for i, d in enumerate(shape):
        if d >= n_shards and d % n_shards == 0:
            return P(*((None,) * i + (axis_name,)))
    return P()


def zero1_sharding(mesh: Mesh, shape, axis_name: str = "data") -> NamedSharding:
    """NamedSharding for one weight/state leaf under the ZeRO-1 layout."""
    n = int(dict(mesh.shape)[axis_name])
    return NamedSharding(mesh, zero1_partition_spec(shape, n, axis_name))


def zero1_place(tree, mesh: Mesh, axis_name: str = "data"):
    """Materialize every leaf of a weight/optimizer-state tree with its
    sharded NamedSharding — used at FIRST BIND so state is born sharded,
    never replicated-then-sliced. Always returns fresh buffers (safe to
    donate even when a leaf already had the target sharding)."""
    def place(a):
        out = jax.device_put(a, zero1_sharding(mesh, a.shape, axis_name))
        if out is a:
            # device_put with a matching sharding aliases; the caller will
            # donate this buffer, so force a real copy
            out = jnp.array(a, copy=True)
        return out

    return jax.tree_util.tree_map(place, tree)


def zero1_constrain(tree, mesh: Mesh, axis_name: str = "data"):
    """In-jit: pin every leaf to its ZeRO-1 sharding. Applied to the
    gradient tree this turns the data-parallel all-reduce into a
    reduce_scatter (each replica keeps only its shard); applied to the
    update's outputs it keeps new weights/state sharded for donation."""
    return jax.tree_util.tree_map(
        lambda a: jax.lax.with_sharding_constraint(
            a, zero1_sharding(mesh, a.shape, axis_name)), tree)


def replicate_constrain(tree, mesh: Mesh):
    """In-jit: gather every leaf to full (replicated) form — the weight
    all_gather ahead of the forward pass."""
    repl = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(
        lambda a: jax.lax.with_sharding_constraint(a, repl), tree)


def replicate_place(tree, mesh: Mesh):
    """Host-level: all-gather a (possibly ZeRO-sharded) tree into fully
    replicated buffers on the mesh — used when sharded master values are
    synced back into replicated executor/updater/kvstore storage."""
    return jax.device_put(tree, NamedSharding(mesh, P()))


def per_device_bytes(tree) -> int:
    """Max over devices of resident bytes for a pytree of jax arrays —
    the per-replica memory the ZeRO-1 layout is shrinking. Replicated
    leaves count fully on every device; sharded leaves 1/N."""
    per: dict = {}
    for leaf in jax.tree_util.tree_leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards is None:
            per[None] = per.get(None, 0) + int(getattr(leaf, "nbytes", 0))
            continue
        for s in shards:
            key = getattr(s.device, "id", s.device)
            per[key] = per.get(key, 0) + int(s.data.nbytes)
    return max(per.values()) if per else 0


def zero1_update_local(w, g, update_fn, axis_name: str = "data",
                       mean_grad: bool = True):
    """ZeRO-1 weight update INSIDE shard_map code: reduce_scatter the
    (flattened, padded) local gradient contribution over axis_name, apply
    `update_fn(w_shard, g_shard)` to this replica's 1/N shard, all_gather
    the updated weights back. The cross-replica gradient mean is folded
    into the reduce_scatter (mean_grad=True); padding makes any leaf shape
    round-trip exactly. w must be replicated over axis_name."""
    n = axis_size(axis_name)
    if n == 1:
        return update_fn(w, g)
    idx = jax.lax.axis_index(axis_name)
    size = w.size
    pad = (-size) % n
    gf = jnp.ravel(g)
    wf = jnp.ravel(w)
    if pad:
        gf = jnp.pad(gf, (0, pad))
        wf = jnp.pad(wf, (0, pad))
    chunk = (size + pad) // n
    g_sh = jax.lax.psum_scatter(gf, axis_name, scatter_dimension=0,
                                tiled=True)
    if mean_grad:
        g_sh = g_sh / n
    w_sh = jax.lax.dynamic_slice(wf, (idx * chunk,), (chunk,))
    new_sh = update_fn(w_sh, g_sh)
    nf = jax.lax.all_gather(new_sh, axis_name, axis=0, tiled=True)
    if pad:
        nf = nf[:size]
    return nf.reshape(w.shape).astype(w.dtype)


# --- host-level collectives over a mesh (imperative kvstore path) ---------
def mesh_all_reduce(x, mesh: Mesh, axis: str = "data"):
    """All-reduce stacked per-device contributions: x has a leading axis of
    size mesh.shape[axis] (one slot per device — the kvstore Push value
    list, kvstore_local.h:50-73); returns the replicated sum without the
    leading axis."""
    n = mesh.shape[axis]
    assert x.shape[0] == n, (x.shape, n)

    def f(s):
        return jax.lax.psum(s[0], axis)

    fn = shard_map(f, mesh=mesh, in_specs=(P(axis),), out_specs=P())
    return fn(x)


def barrier(mesh: Mesh):
    """Cross-device barrier: a tiny all-reduce forced to completion
    (reference ps::Postoffice::Barrier semantics)."""
    x = jnp.zeros((mesh.shape["data"], 1), jnp.float32)
    mesh_all_reduce(x, mesh, "data").block_until_ready()


def bus_bandwidth(mesh: Mesh, axis: str = "data", size_mb: float = 64.0,
                  iters: int = 10, dtype=jnp.float32):
    """Measure all-reduce bus bandwidth over a mesh axis — the analogue of
    the reference's tools/bandwidth harness. Returns GB/s of bus bandwidth
    using the standard ring-allreduce accounting 2*(n-1)/n * bytes."""
    n = int(np.prod([mesh.shape[a] for a in (axis,)]))
    itemsize = jnp.dtype(dtype).itemsize
    num = int(size_mb * 1024 * 1024 / itemsize) // n * n
    x = jnp.ones((num,), dtype)
    x = jax.device_put(x, NamedSharding(mesh, P(axis)))

    def f(s):
        return jax.lax.psum(s, axis)

    fn = jax.jit(shard_map(f, mesh=mesh, in_specs=(P(axis),), out_specs=P()))
    fn(x).block_until_ready()  # compile
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(x)
    out.block_until_ready()
    dt = (time.perf_counter() - t0) / iters
    bus_bytes = 2 * (n - 1) / max(n, 1) * num * itemsize
    return bus_bytes / dt / 1e9
