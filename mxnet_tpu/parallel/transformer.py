"""Fully-sharded transformer training step: DP x SP x PP x TP in one
shard_map program.

This is the framework's flagship distributed training path — the composed
demonstration that the mesh axes from mesh.py all work together:

- "data":  batch sharding; gradient psum (the kvstore-'device' analogue,
           SURVEY §5.8).
- "seq":   ring attention over sequence chunks (ring_attention.py).
- "pipe":  1F1B (default; O(n_stages) live activations) or GPipe
           shift-register over layer stages (pipeline.py,
           cfg.pipeline_schedule).
- "model": Megatron-style tensor parallelism — QKV/FFN-in weights
           column-sharded, out-proj/FFN-out row-sharded, one psum per
           block half.

Everything is manual-collective SPMD inside ONE shard_map, so XLA sees the
exact communication schedule; jax.grad differentiates through it, giving
the reversed pipeline/ring schedules for backward automatically.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from .collectives import shard_map, zero1_update_local
from .moe import EXPERT_GROUP, scale_expert_grads, switch_moe_local
from .pipeline import spmd_pipeline_local, spmd_pipeline_local_1f1b
from .ring_attention import _ring_attn_local


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 256
    dm: int = 64
    heads: int = 4
    dff: int = 128
    layers_per_stage: int = 1
    seq_len: int = 32
    dtype: Any = jnp.float32
    # MoE / expert parallelism (parallel/moe.py). When moe=True the dense
    # FFN of every layer becomes a Switch-routed expert FFN whose experts
    # are sharded over the (data, expert, seq) group — "ep" in the dryrun.
    moe: bool = False
    n_experts_local: int = 2
    capacity_factor: float = 2.0
    # Switch load-balancing loss coefficient (Switch Transformer's 1e-2).
    # Capacity bounds DROP overflow tokens when routing collapses; the aux
    # loss is what keeps routing balanced so they rarely drop
    # (tests/test_parallel.py::test_moe_aux_loss_keeps_routing_balanced).
    moe_aux_coef: float = 1e-2
    # "1f1b" (default: live activations O(n_stages), pipeline.py) or
    # "gpipe" (scan-through-backward baseline).
    pipeline_schedule: str = "1f1b"


# Parameters carrying a leading pipeline-stage axis (sharded over "pipe").
_STAGE_KEYS = ("wqkv", "wo", "w1", "w2", "ln1", "ln2", "wg", "w1e", "w2e")
# Expert-sharded parameters: grads are 1/G-scaled, not pmean'd (moe.py).
EXPERT_KEYS = ("w1e", "w2e")


def init_params(cfg: TransformerConfig, n_stages: int, key=None,
                expert_group: int = 1):
    """Stacked parameters: layer weights carry leading axes
    (n_stages, layers_per_stage, ...) — "pipe" shards axis 0. For MoE,
    expert_group = data*expert*seq mesh size; the global expert count is
    expert_group * cfg.n_experts_local."""
    if key is None:
        key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 10)
    d, f, v = cfg.dm, cfg.dff, cfg.vocab
    L = (n_stages, cfg.layers_per_stage)

    def nrm(k, shape, scale):
        return (jax.random.normal(k, shape) * scale).astype(cfg.dtype)

    params = {
        "embed": nrm(ks[0], (v, d), 0.02),
        "wqkv": nrm(ks[1], L + (d, 3 * d), d ** -0.5),
        "wo": nrm(ks[2], L + (d, d), d ** -0.5),
        "ln1": jnp.ones(L + (d,), cfg.dtype),
        "ln2": jnp.ones(L + (d,), cfg.dtype),
        "lnf": jnp.ones((d,), cfg.dtype),
        "unembed": nrm(ks[5], (d, v), d ** -0.5),
    }
    if cfg.moe:
        n_exp = expert_group * cfg.n_experts_local
        params["wg"] = nrm(ks[6], L + (d, n_exp), d ** -0.5)
        params["w1e"] = nrm(ks[7], L + (n_exp, d, f), d ** -0.5)
        params["w2e"] = nrm(ks[8], L + (n_exp, f, d), f ** -0.5)
    else:
        params["w1"] = nrm(ks[3], L + (d, f), d ** -0.5)
        params["w2"] = nrm(ks[4], L + (f, d), f ** -0.5)
    return params


def param_specs(cfg: TransformerConfig) -> Dict[str, P]:
    """Mesh shardings: "pipe" on the stage axis, "model" on the TP dim,
    the (data, expert, seq) group on the MoE expert axis."""
    specs = {
        "embed": P(None, "model"),
        "wqkv": P("pipe", None, None, "model"),
        "wo": P("pipe", None, "model", None),
        "ln1": P("pipe", None, None),
        "ln2": P("pipe", None, None),
        "lnf": P(None),
        "unembed": P(None, "model"),
    }
    if cfg.moe:
        specs["wg"] = P("pipe", None, None, None)
        specs["w1e"] = P("pipe", None, EXPERT_GROUP, None, "model")
        specs["w2e"] = P("pipe", None, EXPERT_GROUP, "model", None)
    else:
        specs["w1"] = P("pipe", None, None, "model")
        specs["w2"] = P("pipe", None, "model", None)
    return specs


def _ln(x, g):
    mu = jnp.mean(x, -1, keepdims=True)
    var = jnp.var(x, -1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + 1e-5) * g


def _layer(p, x, cfg: TransformerConfig, li):
    """One transformer layer with TP (model axis) + SP (seq axis ring
    attention). x: local (b, t_local, d); weights: local TP shards."""
    dh = cfg.dm // cfg.heads
    # fused QKV layout is HEADS-MAJOR (d, heads*3*dh) so the "model"-axis
    # shard boundary falls between whole heads, never inside one
    heads_local = p["wqkv"].shape[-1] // (3 * dh)
    h = _ln(x, p["ln1"][li])
    qkv = h @ p["wqkv"][li]                      # (b, t, h_loc*3*dh)
    b, t, _ = qkv.shape
    qkv = qkv.reshape(b, t, heads_local, 3, dh).transpose(3, 0, 2, 1, 4)
    q, k, v = qkv[0], qkv[1], qkv[2]             # (b, h_loc, t, dh)
    att = _ring_attn_local(q, k, v, axis_name="seq", causal=True,
                           chunk=t)
    att = att.transpose(0, 2, 1, 3).reshape(b, t, heads_local * dh)
    o = att @ p["wo"][li]                        # partial over TP shards
    o = jax.lax.psum(o, "model")
    x = x + o
    h = _ln(x, p["ln2"][li])
    aux = jnp.zeros((), jnp.float32)
    if cfg.moe:
        bb, tt, dd = h.shape
        # Switch-MoE over the (data, expert, seq) expert group; the
        # router's load-balancing aux loss rides the pipeline's aux
        # channel into the training loss (cfg.moe_aux_coef)
        y, aux = switch_moe_local(
            h.reshape(bb * tt, dd), p["wg"][li], p["w1e"][li], p["w2e"][li],
            capacity_factor=cfg.capacity_factor)
        h = y.reshape(bb, tt, dd)
        aux = aux.astype(jnp.float32)
    else:
        h = jax.nn.gelu(h @ p["w1"][li])
        h = h @ p["w2"][li]
        h = jax.lax.psum(h, "model")
    return x + h, aux


def _stage_fn(stage_params, h, cfg: TransformerConfig):
    aux = jnp.zeros((), jnp.float32)
    for li in range(cfg.layers_per_stage):
        h, a = _layer(stage_params, h, cfg, li)
        aux = aux + a
    return h, aux


def make_train_step(mesh: Mesh, cfg: TransformerConfig, n_micro: int = None,
                    lr: float = 1e-2, sharded_update: bool = None):
    """Returns (train_step, sharded_init) where
    train_step(params, tokens, targets) -> (loss, new_params) is jitted
    over the full 4-axis mesh with SGD applied in-graph — the
    'update_on_kvstore inside the step' design (SURVEY §7 table).

    sharded_update: manual ZeRO-1 weight update over the "data" axis
    (collectives.zero1_update_local): dense grads are reduce-scattered
    instead of pmean'd, each data replica updates its 1/N weight shard,
    and the new weights are all-gathered — the explicit-collective twin
    of Executor.make_train_step's GSPMD path. Default: on when the data
    axis is >1 and MXNET_SHARDED_UPDATE != 0. Expert-sharded weights
    already hold distinct experts per rank and keep their local update."""
    n_pipe = mesh.shape["pipe"]
    if n_micro is None:
        n_micro = max(2, n_pipe)
    if sharded_update is None:
        import os
        sharded_update = (int(mesh.shape["data"]) > 1
                          and os.environ.get("MXNET_SHARDED_UPDATE",
                                             "1") != "0")
    specs = param_specs(cfg)

    def local_fwd(params, tokens, targets):
        """Per-device program. tokens: (b_loc, t_loc) ints;
        params: local shards per param_specs."""
        x = jnp.take(params["embed"], tokens, axis=0)  # (b, t, d/1) emb TP?
        # embed is column(model)-sharded: gather the full d via all_gather
        x = jax.lax.all_gather(x, "model", axis=-1, tiled=True)
        b = x.shape[0]
        assert b % n_micro == 0, "local batch %d vs n_micro %d" % (b, n_micro)
        x_mb = x.reshape((n_micro, b // n_micro) + x.shape[1:])

        def stage(sp_params, h):
            # strip the local stage axis (pipe shards it fully: size 1)
            sp = jax.tree_util.tree_map(lambda a: a[0], sp_params)
            return _stage_fn(sp, h, cfg)

        stage_params = {k2: params[k2] for k2 in _STAGE_KEYS
                        if k2 in params}
        if cfg.pipeline_schedule == "1f1b":
            out, aux = spmd_pipeline_local_1f1b(
                stage, stage_params, x_mb, "pipe", True)
        else:
            out, aux = spmd_pipeline_local(
                stage, stage_params, x_mb, axis="pipe", with_aux=True,
                broadcast_out=False)
        # `out` is valid ONLY on the last pipe rank (no activation-buffer
        # broadcast): the head + loss run there and a SCALAR psum
        # replaces the old (n_micro, mb, t, d) psum
        out = out.reshape((b,) + out.shape[2:])
        out = _ln(out, params["lnf"])
        logits = out @ params["unembed"]             # (b, t, v/tp) TP-sharded
        # stable softmax-CE with the vocab axis sharded over "model"
        mx_loc = jnp.max(logits, axis=-1)
        # max shift is gradient-free for softmax-CE (cancels exactly);
        # pmax also has no differentiation rule
        mx_all = jax.lax.pmax(jax.lax.stop_gradient(mx_loc), "model")
        z = jnp.exp(logits - mx_all[..., None])
        denom = jax.lax.psum(jnp.sum(z, -1), "model")
        # local one-hot of targets that fall in this shard's vocab slice
        vloc = logits.shape[-1]
        voff = jax.lax.axis_index("model") * vloc
        tloc = targets - voff
        in_shard = (tloc >= 0) & (tloc < vloc)
        tgt_logit = jnp.take_along_axis(
            logits, jnp.clip(tloc, 0, vloc - 1)[..., None], axis=-1)[..., 0]
        tgt_logit = jax.lax.psum(jnp.where(in_shard, tgt_logit, 0.0), "model")
        nll = jnp.log(denom) + mx_all - tgt_logit
        pipe_idx = jax.lax.axis_index("pipe")
        ce = jax.lax.psum(
            jnp.where(pipe_idx == n_pipe - 1, jnp.mean(nll), 0.0), "pipe")
        # Switch aux: mean over (microbatch, stage, layer) contributions,
        # weighted into the trained objective (Switch Transformer's ~1e-2)
        aux_mean = aux / (n_micro * n_pipe * cfg.layers_per_stage)
        # LOCAL losses; the cross-(data,seq) mean happens on the gradients.
        # The CE is returned separately so callers still see the model
        # loss; the OPTIMIZED objective is ce + coef*aux.
        return ce + cfg.moe_aux_coef * aux_mean, ce

    batch_spec = P(("data", "expert"), "seq")
    in_specs = (specs, batch_spec, batch_spec)
    dp_axes = ("data", "expert", "seq")

    def step(params, tokens, targets):
        (_, loss), grads = jax.value_and_grad(
            lambda p: local_fwd(p, tokens, targets), has_aux=True)(params)
        # DP/SP gradient all-reduce — the in-graph kvstore push/pull
        # (SURVEY §5.8: CommDevice reduce ≡ psum over ICI). Expert-sharded
        # weights hold DIFFERENT experts per rank: AD already summed the
        # cross-rank contributions through the all_to_all transpose, so
        # they take a 1/G scale instead of a pmean (moe.scale_expert_grads).
        # Under the sharded update the "data" leg of the dense pmean is
        # DEFERRED: zero1_update_local folds it into its reduce_scatter.
        dense_axes = ("expert", "seq") if sharded_update else None
        grads = scale_expert_grads(grads, EXPERT_KEYS, group=dp_axes,
                                   dense_axes=dense_axes)
        # embed's cotangent only reaches pipe rank 0 (the pipeline ingests
        # x there); unembed/lnf cotangents only reach the LAST pipe rank
        # (the head + loss are rank-masked there — no activation-buffer
        # broadcast). psum over "pipe" makes each whole/replicated.
        for k in ("embed", "unembed", "lnf"):
            grads[k] = jax.lax.psum(grads[k], "pipe")

        def sgd(w, g):
            return (w - lr * g).astype(w.dtype)

        if sharded_update:
            new_params = {}
            for k in params:
                if k in EXPERT_KEYS:
                    # distinct experts per rank: grads are already summed
                    # + 1/G scaled; the update stays local
                    new_params[k] = sgd(params[k], grads[k])
                else:
                    new_params[k] = zero1_update_local(
                        params[k], grads[k], sgd, axis_name="data")
        else:
            new_params = jax.tree_util.tree_map(sgd, params, grads)
        loss = jax.lax.pmean(loss, dp_axes)
        return loss, new_params

    smapped = shard_map(
        step, mesh=mesh,
        in_specs=in_specs,
        out_specs=(P(), specs),
        check_vma=False)
    return jax.jit(smapped)


def shard_params(params, mesh: Mesh, cfg: TransformerConfig):
    specs = param_specs(cfg)
    return {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
            for k, v in params.items()}
