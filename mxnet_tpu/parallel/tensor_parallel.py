"""Tensor-parallel sharding rules for Symbol-executor parameters.

The reference has NO intra-op sharding (SURVEY §2.2 "Tensor parallelism:
absent"); its closest mechanism is ctx_group placement (AttrScope,
python/mxnet/attribute.py). This module is the idiomatic TPU upgrade: a
pattern → PartitionSpec rule table applied to an executor's argument dict,
after which XLA's sharding propagation (GSPMD) partitions the matmuls and
inserts the collectives — no manual comm code for the annotated path.

The ctx_group attribute from AttrScope survives: rules may target it via
``group:<name>`` patterns, so reference-style ``with mx.AttrScope
(ctx_group='dev1')`` models map onto mesh axes instead of gpu ids
(SURVEY §2.2 model-parallel row, example/model-parallel-lstm/lstm.py).
"""
from __future__ import annotations

import re
from typing import Dict, List, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


# (regex over param name, spec builder given ndim) — first match wins.
# Megatron convention for transformer params on FC weights of shape
# (out_features, in_features) [reference FC layout, fully_connected-inl.h]:
# column-parallel = shard out axis; row-parallel = shard in axis.
DEFAULT_RULES: List[Tuple[str, P]] = [
    (r".*(_q|_k|_v|_qkv)_weight$", P("model", None)),
    (r".*(_o|_proj)_weight$", P(None, "model")),
    (r".*_ffn1_weight$", P("model", None)),
    (r".*_ffn2_weight$", P(None, "model")),
    (r".*embed_weight$", P(None, "model")),
    (r"pred_weight$", P("model", None)),
    (r".*(_q|_k|_v|_qkv|_ffn1)_bias$", P("model")),
]


def spec_for(name: str, shape, rules=None, attrs: Dict[str, str] = None) -> P:
    """Resolve the PartitionSpec for one parameter."""
    rules = DEFAULT_RULES if rules is None else rules
    group = (attrs or {}).get("__ctx_group__")
    for pat, spec in rules:
        if pat.startswith("group:"):
            if group == pat[len("group:"):]:
                return spec
            continue
        if re.match(pat, name):
            if len(spec) <= len(shape):
                return spec
    return P()


def shard_arg_dict(arg_dict, mesh: Mesh, rules=None, attrs_by_name=None):
    """device_put every NDArray in an executor arg dict per the rules.
    Subsequent jit executions respect the input shardings and GSPMD
    propagates them through the graph (the PlaceDevice-pass analogue)."""
    from ..ndarray import NDArray

    for name, arr in arg_dict.items():
        spec = spec_for(name, arr.shape, rules,
                        (attrs_by_name or {}).get(name))
        sh = NamedSharding(mesh, spec)
        if isinstance(arr, NDArray):
            arr._data = jax.device_put(arr._data, sh)
        else:
            arg_dict[name] = jax.device_put(arr, sh)
    return arg_dict


def data_parallel_sharding(mesh: Mesh, ndim: int, batch_axis: int = 0):
    """Sharding for a data tensor: batch over "data" (× "seq" if the
    tensor has a sequence axis handled elsewhere)."""
    spec = [None] * ndim
    spec[batch_axis] = "data"
    return NamedSharding(mesh, P(*spec))
