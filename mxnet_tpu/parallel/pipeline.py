"""Micro-batched pipeline parallelism over the "pipe" axis.

The reference only has layer-placement model parallelism with no
micro-batching (SURVEY §2.2: group2ctx + PlaceDevice inserting
_CrossDeviceCopy, example/model-parallel-lstm) — its pipeline overlap falls
out of engine dataflow. Here the same overlap is expressed as an SPMD
shift-register: every device runs the identical program, holds one stage's
parameters (sharded over "pipe"), and at each tick applies its stage and
ppermutes the activation to its neighbor.

Two schedules:

- ``spmd_pipeline_local`` — GPipe: n_micro microbatches drain forward in
  n_micro + n_stages - 1 ticks; jax.grad differentiates through the scan,
  so backward SAVES every tick's internal activations (memory grows with
  n_micro × per-tick activations). Fine at small depth; the baseline the
  1F1B schedule is equivalence-tested against.
- ``spmd_pipeline_local_1f1b`` — one-forward-one-backward with per-stage
  recompute, as a custom_vjp: the primal runs the cheap forward-only scan
  (nothing retained but the pipeline INPUTS), and the backward runs an
  interleaved scan where each tick does one forward sub-step and one
  backward sub-step. Stage inputs of in-flight microbatches live in a
  ring buffer of depth 2·n_stages - 1 — at most 2(n-1-s)+1 microbatches
  are in flight between stage s's forward of microbatch i and its
  backward (fwd at tick s+i, bwd at tick 2(n-1)-s+i), so LIVE ACTIVATION
  memory is O(n_stages), independent of n_micro. The stage forward is
  recomputed inside each backward sub-step (jax.vjp), trading ~1 extra
  forward per microbatch-stage for the memory bound — the standard
  1F1B + activation-recompute design.

Neither schedule broadcasts the output across the pipe axis when
``broadcast_out=False``: the (n_micro, mb, ...) output is valid ONLY on
the last pipe rank (zeros elsewhere), and callers reduce to a scalar
loss there and psum THAT (parallel/transformer.py) — replacing the old
full-activation-buffer psum with a scalar collective.

MoE support: with ``with_aux=True`` the stage function returns
(h, aux_scalar) and the pipeline returns (out, aux_sum) where aux_sum is
the psum over pipe ranks of every VALID (stage, microbatch) aux
contribution (bubble ticks are masked out — they run the stage on
garbage). The Switch load-balancing loss rides this channel
(parallel/moe.py switch_moe_local).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from .collectives import axis_size, shard_map  # version-tolerant wrappers


def _fwd_scan(stage_fn, stage_params, x_mb, axis, with_aux):
    """Forward-only GPipe scan. Returns (out, aux_sum_local) where `out`
    is populated ONLY on the last pipe rank (zeros elsewhere) and
    aux_sum_local is this rank's masked aux total (0.0 when not
    with_aux)."""
    n = axis_size(axis)
    idx = jax.lax.axis_index(axis)
    n_micro = x_mb.shape[0]
    steps = n_micro + n - 1
    perm = [(j, (j + 1) % n) for j in range(n)]

    def tick(carry, t):
        h_recv, out, aux_sum = carry
        i = t - idx                     # microbatch this stage works on
        valid = (i >= 0) & (i < n_micro)
        h_in = jnp.where(idx == 0,
                         x_mb[jnp.clip(t, 0, n_micro - 1)], h_recv)
        res = stage_fn(stage_params, h_in)
        if with_aux:
            h_out, aux = res
            aux_sum = aux_sum + jnp.where(valid, aux, 0.0)
        else:
            h_out = res
        h_next = jax.lax.ppermute(h_out, axis, perm)
        slot = t - (n - 1)
        emit = (idx == n - 1) & (slot >= 0)
        out = jnp.where(
            emit,
            jax.lax.dynamic_update_index_in_dim(
                out, h_out, jnp.maximum(slot, 0), 0),
            out)
        return (h_next, out, aux_sum), None

    h0 = jnp.zeros_like(x_mb[0])
    out0 = jnp.zeros_like(x_mb)
    # (1,)-shaped aux carry, NOT a scalar: this jax's shard_map autodiff
    # can't emit rank-0 device-varying residuals (its own error text says
    # to "add at least one (singleton) axis"), and a scalar carry here
    # surfaces as exactly such a residual under jax.grad
    (_, out, aux_sum), _ = jax.lax.scan(
        tick, (h0, out0, jnp.zeros((1,), jnp.float32)), jnp.arange(steps))
    return out, aux_sum


def broadcast_from_last(out, axis):
    """Replicate the last pipe rank's buffer to every rank (the legacy
    output convention; callers that reduce to a scalar on the last rank
    skip this and psum the scalar instead)."""
    idx = jax.lax.axis_index(axis)
    n = axis_size(axis)
    return jax.lax.psum(
        jnp.where(idx == n - 1, out, jnp.zeros_like(out)), axis)


def spmd_pipeline_local(stage_fn, stage_params, x_mb, *, axis="pipe",
                        with_aux=False, broadcast_out=True):
    """Per-device GPipe pipeline body (call inside shard_map).

    stage_fn(stage_params, h) -> h — or (h, aux_scalar) with
    ``with_aux=True``.
    stage_params: this device's stage parameters (leading stage axis
    already consumed by the shard_map in_spec).
    x_mb: (n_micro, mb, ...) all microbatches (replicated).
    Returns (n_micro, mb, ...) outputs of the LAST stage — replicated via
    a psum-broadcast when ``broadcast_out`` (legacy), else valid only on
    the last pipe rank. With ``with_aux`` returns (out, aux_sum) where
    aux_sum is replicated over the pipe axis."""
    out, aux_sum = _fwd_scan(stage_fn, stage_params, x_mb, axis, with_aux)
    if broadcast_out:
        out = broadcast_from_last(out, axis)
    if with_aux:
        return out, jax.lax.psum(aux_sum, axis)[0]
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 3, 4))
def spmd_pipeline_local_1f1b(stage_fn, stage_params, x_mb, axis="pipe",
                             with_aux=False):
    """1F1B pipeline body (call inside shard_map): same contract as
    spmd_pipeline_local(..., broadcast_out=False), but backward memory is
    O(n_stages) instead of O(n_micro) — see the module docstring.
    Always returns (out, aux_sum); aux_sum is 0.0 when not with_aux."""
    out, aux = _fwd_scan(stage_fn, stage_params, x_mb, axis, with_aux)
    return out, jax.lax.psum(aux, axis)[0]


def _1f1b_fwd(stage_fn, stage_params, x_mb, axis, with_aux):
    out, aux = _fwd_scan(stage_fn, stage_params, x_mb, axis, with_aux)
    # residuals: pipeline INPUTS only — every stage activation is
    # recomputed in the backward's fwd sub-steps
    return ((out, jax.lax.psum(aux, axis)[0]), (stage_params, x_mb))


def _1f1b_bwd(stage_fn, axis, with_aux, res, cots):
    stage_params, x_mb = res
    dout, daux = cots
    # mirror the transpose of the primal's `psum(aux)`: the cotangent of
    # each rank's LOCAL aux contribution is the SUM of all ranks' output
    # cotangents (shard_map delivers a replicated output's cotangent
    # split across ranks)
    daux = jax.lax.psum(daux, axis)
    n = axis_size(axis)
    idx = jax.lax.axis_index(axis)
    m = x_mb.shape[0]
    ring_depth = 2 * n - 1           # max in-flight microbatches per stage
    steps = 2 * (n - 1) + m          # last bwd: stage 0, mb m-1
    perm_fwd = [(j, (j + 1) % n) for j in range(n)]
    perm_bwd = [(j, (j - 1) % n) for j in range(n)]

    def stage_h(p, h):
        r = stage_fn(p, h)
        return r if with_aux else (r, jnp.zeros((), jnp.float32))

    def tick(carry, u):
        h_recv, g_recv, ring, dparams, dx = carry

        # ---- forward sub-step (GPipe timing: stage s runs mb u - s) ----
        i = u - idx
        fwd_valid = (i >= 0) & (i < m)
        h_in = jnp.where(idx == 0, x_mb[jnp.clip(u, 0, m - 1)], h_recv)
        ring = jnp.where(
            fwd_valid,
            jax.lax.dynamic_update_index_in_dim(
                ring, h_in, jnp.clip(i, 0, m - 1) % ring_depth, 0),
            ring)
        h_out, _ = stage_h(stage_params, h_in)
        h_next = jax.lax.ppermute(h_out, axis, perm_fwd)

        # ---- backward sub-step (stage s runs bwd of mb u - 2(n-1) + s;
        # the cotangent it needs left stage s+1 on the previous tick) ----
        j = u - 2 * (n - 1) + idx
        bwd_valid = (j >= 0) & (j < m)
        jc = jnp.clip(j, 0, m - 1)
        g_in = jnp.where(idx == n - 1, dout[jc], g_recv)
        h_saved = ring[jc % ring_depth]
        _, vjp_fn = jax.vjp(lambda p, hh: stage_h(p, hh), stage_params,
                            h_saved)
        g_aux = jnp.where(bwd_valid, daux, 0.0)
        dp, dh = vjp_fn((jnp.where(bwd_valid, g_in, jnp.zeros_like(g_in)),
                         g_aux))
        dparams = jax.tree_util.tree_map(
            lambda a, b: a + jnp.where(bwd_valid, b, 0.0), dparams, dp)
        # stage 0's input cotangent belongs to x_mb[j]
        dx = jnp.where(
            bwd_valid & (idx == 0),
            jax.lax.dynamic_update_index_in_dim(dx, dh, jc, 0),
            dx)
        g_next = jax.lax.ppermute(
            jnp.where(bwd_valid, dh, jnp.zeros_like(dh)), axis, perm_bwd)
        return (h_next, g_next, ring, dparams, dx), None

    h0 = jnp.zeros_like(x_mb[0])
    g0 = jnp.zeros_like(x_mb[0])
    ring0 = jnp.zeros((ring_depth,) + x_mb.shape[1:], x_mb.dtype)
    dparams0 = jax.tree_util.tree_map(
        lambda a: jnp.zeros(a.shape, a.dtype), stage_params)
    dx0 = jnp.zeros_like(x_mb)
    (_, _, _, dparams, dx), _ = jax.lax.scan(
        tick, (h0, g0, ring0, dparams0, dx0), jnp.arange(steps))
    return dparams, dx


spmd_pipeline_local_1f1b.defvjp(_1f1b_fwd, _1f1b_bwd)


def spmd_pipeline(stage_fn, params, x, mesh: Mesh, n_micro: int,
                  axis: str = "pipe", schedule: str = "gpipe"):
    """Full-array entry. params: pytree with leading axis n_stages
    (sharded over `axis`); x: (batch, ...) split into n_micro microbatches.
    Mainly for tests — real models embed the *_local bodies inside their
    own shard_map (parallel/transformer.py)."""
    n = mesh.shape[axis]
    b = x.shape[0]
    assert b % n_micro == 0
    x_mb = x.reshape((n_micro, b // n_micro) + x.shape[1:])

    def body(p, xm):
        sp = jax.tree_util.tree_map(lambda a: a[0], p)  # squeeze stage axis
        if schedule == "1f1b":
            out, _ = spmd_pipeline_local_1f1b(stage_fn, sp, xm, axis, False)
            return broadcast_from_last(out, axis)
        return spmd_pipeline_local(stage_fn, sp, xm, axis=axis)

    pspec = jax.tree_util.tree_map(lambda _: P(axis), params)
    fn = shard_map(body, mesh=mesh, in_specs=(pspec, P()), out_specs=P(),
                   check_vma=False)
    out = fn(params, x_mb)
    return out.reshape((b,) + out.shape[2:])
