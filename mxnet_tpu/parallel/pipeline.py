"""Micro-batched pipeline parallelism (GPipe schedule) over the "pipe" axis.

The reference only has layer-placement model parallelism with no
micro-batching (SURVEY §2.2: group2ctx + PlaceDevice inserting
_CrossDeviceCopy, example/model-parallel-lstm) — its pipeline overlap falls
out of engine dataflow. Here the same overlap is expressed as an SPMD
shift-register: every device runs the identical program, holds one stage's
parameters (sharded over "pipe"), and at each tick applies its stage and
ppermutes the activation to its neighbor. n_micro microbatches drain in
n_micro + n_stages - 1 ticks; forward and backward of in-flight
microbatches overlap across devices exactly as the engine overlapped
per-device segments.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map


def spmd_pipeline_local(stage_fn, stage_params, x_mb, *, axis="pipe"):
    """Per-device pipeline body (call inside shard_map).

    stage_fn(stage_params, h) -> h (shape-preserving).
    stage_params: this device's stage parameters (leading stage axis
    already consumed by the shard_map in_spec).
    x_mb: (n_micro, mb, ...) all microbatches (replicated).
    Returns (n_micro, mb, ...) outputs of the LAST stage (replicated via a
    final psum-broadcast)."""
    n = jax.lax.axis_size(axis)
    idx = jax.lax.axis_index(axis)
    n_micro = x_mb.shape[0]
    steps = n_micro + n - 1
    perm = [(j, (j + 1) % n) for j in range(n)]

    def tick(carry, t):
        h_recv, out = carry
        h_in = jnp.where(idx == 0,
                         x_mb[jnp.minimum(t, n_micro - 1)], h_recv)
        h_out = stage_fn(stage_params, h_in)
        h_next = jax.lax.ppermute(h_out, axis, perm)
        slot = t - (n - 1)
        emit = (idx == n - 1) & (slot >= 0)
        out = jnp.where(
            emit,
            jax.lax.dynamic_update_index_in_dim(
                out, h_out, jnp.maximum(slot, 0), 0),
            out)
        return (h_next, out), None

    h0 = jnp.zeros_like(x_mb[0])
    out0 = jnp.zeros_like(x_mb)
    (_, out), _ = jax.lax.scan(tick, (h0, out0), jnp.arange(steps))
    # broadcast the last stage's buffer to every pipe rank
    out = jax.lax.psum(jnp.where(idx == n - 1, out, jnp.zeros_like(out)),
                       axis)
    return out


def spmd_pipeline(stage_fn, params, x, mesh: Mesh, n_micro: int,
                  axis: str = "pipe"):
    """Full-array entry. params: pytree with leading axis n_stages
    (sharded over `axis`); x: (batch, ...) split into n_micro microbatches.
    Mainly for tests — real models embed spmd_pipeline_local inside their
    own shard_map (parallel/transformer.py)."""
    n = mesh.shape[axis]
    b = x.shape[0]
    assert b % n_micro == 0
    x_mb = x.reshape((n_micro, b // n_micro) + x.shape[1:])

    def body(p, xm):
        sp = jax.tree_util.tree_map(lambda a: a[0], p)  # squeeze stage axis
        return spmd_pipeline_local(stage_fn, sp, xm, axis=axis)

    pspec = jax.tree_util.tree_map(lambda _: P(axis), params)
    fn = shard_map(body, mesh=mesh, in_specs=(pspec, P()), out_specs=P(),
                   check_vma=False)
    out = fn(params, x_mb)
    return out.reshape((b,) + out.shape[2:])
