"""Multi-host distributed runtime.

Replaces the reference's ps-lite process fabric (SURVEY §2.1 #37, §3.4):
scheduler → jax.distributed coordinator; DMLC_ROLE/DMLC_PS_ROOT_URI env →
coordinator_address/process_id env; worker barrier →
multihost_utils.sync_global_devices; dead-node query
(kvstore_dist.h:159-168) → coordinator client health; tools/launch.py →
launch() helper spawning one process per host.

There are no separate 'server' processes: the optimizer state lives
sharded across the same mesh that computes (SURVEY §5.8 translation), so
every process is a worker.
"""
from __future__ import annotations

import os
import time
from typing import Optional

import jax


_initialized = False


def init(coordinator_address: Optional[str] = None,
         num_processes: Optional[int] = None,
         process_id: Optional[int] = None):
    """Initialize the multi-host runtime (idempotent).

    Resolution order: explicit args → MXNET_TPU_* env vars → JAX
    auto-detection (TPU pod metadata). Single-process when nothing is
    configured — the same degradation as kvstore 'local' vs 'dist'."""
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get(
        "MXNET_TPU_COORDINATOR")
    if num_processes is None and "MXNET_TPU_NUM_PROCS" in os.environ:
        num_processes = int(os.environ["MXNET_TPU_NUM_PROCS"])
    if process_id is None and "MXNET_TPU_PROC_ID" in os.environ:
        process_id = int(os.environ["MXNET_TPU_PROC_ID"])
    if coordinator_address is None and num_processes in (None, 1):
        _initialized = True  # single-process mode
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)
    _initialized = True


def rank() -> int:
    """This process's rank (reference KVStore::get_rank, kvstore.h:227)."""
    return jax.process_index()


def size() -> int:
    """World size (reference KVStore::get_group_size, kvstore.h:232)."""
    return jax.process_count()


def barrier(name: str = "barrier"):
    """Global process barrier (reference Barrier → ps::Postoffice)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def num_dead_nodes(timeout_s: float = 0.0) -> int:
    """Dead-node surface (reference MXKVStoreGetNumDeadNode,
    kvstore_dist.h:159-168). Under jax.distributed a failed host aborts
    the job rather than running degraded, so a live call always sees 0;
    the API exists so reference callers port cleanly, and the timeout is
    honored as a liveness probe window."""
    if timeout_s > 0:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            time.sleep(min(0.1, deadline - time.time()))
    return 0


def is_recovery() -> bool:
    """Recovery flag (reference ps::Postoffice::is_recovery). Restarted
    jobs resume from checkpoints (orbax/save_checkpoint) instead of
    rejoining live — always False."""
    return False
