"""Multi-host distributed runtime.

Replaces the reference's ps-lite process fabric (SURVEY §2.1 #37, §3.4):
scheduler → jax.distributed coordinator; DMLC_ROLE/DMLC_PS_ROOT_URI env →
coordinator_address/process_id env; worker barrier →
multihost_utils.sync_global_devices; dead-node query
(kvstore_dist.h:159-168) → coordinator client health; tools/launch.py →
launch() helper spawning one process per host.

There are no separate 'server' processes: the optimizer state lives
sharded across the same mesh that computes (SURVEY §5.8 translation), so
every process is a worker.
"""
from __future__ import annotations

import os
import time
from typing import Optional

import jax


_initialized = False


def init(coordinator_address: Optional[str] = None,
         num_processes: Optional[int] = None,
         process_id: Optional[int] = None):
    """Initialize the multi-host runtime (idempotent).

    Resolution order: explicit args → MXNET_TPU_* env vars → resource-
    manager env (OpenMPI/MPICH `mpirun`, SLURM, SGE array tasks — the
    trackers the reference's dmlc launcher fed through DMLC_* env,
    reference tools/launch.py:33-60) → JAX auto-detection (TPU pod
    metadata). Single-process when nothing is configured — the same
    degradation as kvstore 'local' vs 'dist'."""
    global _initialized
    if _initialized:
        return
    coordinator_address = (coordinator_address
                           or os.environ.get("MXNET_TPU_COORDINATOR")
                           or None)  # empty string counts as unset
    if num_processes is None and os.environ.get("MXNET_TPU_NUM_PROCS"):
        num_processes = int(os.environ["MXNET_TPU_NUM_PROCS"])
    if process_id is None and os.environ.get("MXNET_TPU_PROC_ID"):
        process_id = int(os.environ["MXNET_TPU_PROC_ID"])
    if (coordinator_address is not None
            and (process_id is None or num_processes is None)):
        # resource-manager env only FILLS IN rank/world once a
        # coordinator is explicitly configured (launcher env or arg) —
        # RM variables alone must not promote a bare single-process run
        # to a distributed init that would block waiting for peers the
        # user never started (e.g. `python train.py` inside an sbatch
        # allocation without srun)
        rank_id, world = _resource_manager_rank()
        if process_id is None:
            process_id = rank_id
        if num_processes is None:
            num_processes = world
    if coordinator_address is None and num_processes in (None, 1):
        _initialized = True  # single-process mode
        return
    plats = (jax.config.jax_platforms
             or os.environ.get("JAX_PLATFORMS") or "")
    first = plats.split(",")[0].strip().lower()
    if first in ("", "cpu"):
        # multi-process CPU (the reference's multi-device-without-
        # hardware emulation, SURVEY §4.3, across OS processes): without
        # a CPU collectives backend each process builds a LOCAL-only
        # client and process_count() stays 1 — gloo makes the processes
        # form one global backend. Applied also when no platform is
        # configured (a CPU-only host resolves to cpu; on accelerator
        # hosts the option only affects the secondary CPU client). TPU
        # backends form the global view natively.
        try:
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except Exception:  # older jaxlib without the option
            pass
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)
    _initialized = True


def _resource_manager_rank():
    """(rank, world) from whatever resource manager launched this process:
    OpenMPI (OMPI_COMM_WORLD_*), MPICH/hydra (PMI_*), SLURM
    (SLURM_PROCID/SLURM_NTASKS), SGE array jobs (SGE_TASK_ID, 1-based).
    Returns (None, None) when none apply."""
    env = os.environ
    if "OMPI_COMM_WORLD_RANK" in env:
        return (int(env["OMPI_COMM_WORLD_RANK"]),
                int(env.get("OMPI_COMM_WORLD_SIZE", "1")))
    if "PMI_RANK" in env:
        return int(env["PMI_RANK"]), int(env.get("PMI_SIZE", "1"))
    if "SLURM_PROCID" in env:
        return (int(env["SLURM_PROCID"]),
                int(env.get("SLURM_NTASKS", "1")))
    if "SGE_TASK_ID" in env and env["SGE_TASK_ID"].isdigit():
        # array jobs may start anywhere and stride (qsub -t f-l:s):
        # rank = (id - first) / step, world = (last - first) / step + 1
        first = int(env.get("SGE_TASK_FIRST", "1"))
        step = int(env.get("SGE_TASK_STEPSIZE", "1") or "1")
        last = int(env.get("SGE_TASK_LAST", env["SGE_TASK_ID"]))
        return ((int(env["SGE_TASK_ID"]) - first) // step,
                (last - first) // step + 1)
    return None, None


def rank() -> int:
    """This process's rank (reference KVStore::get_rank, kvstore.h:227)."""
    return jax.process_index()


def size() -> int:
    """World size (reference KVStore::get_group_size, kvstore.h:232)."""
    return jax.process_count()


def barrier(name: str = "barrier"):
    """Global process barrier (reference Barrier → ps::Postoffice)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def dead_nodes(step: Optional[int] = None) -> set:
    """Ranks currently considered dead — the poll surface the
    resilience.TrainingSupervisor consults between steps.

    Under jax.distributed a really-failed host aborts the job rather
    than running degraded, so live detection comes from the PS kvstore
    (``kv.num_dead_node`` / ``PSClient.dead_nodes``); what THIS function
    contributes is the simulated layer: ``kill_rank`` entries of the
    active ``MXNET_FAULT_PLAN`` (mxnet_tpu.resilience.faults) read as
    dead from their planned step on, through the same surface real
    deaths would use."""
    from ..resilience import faults  # lazy: resilience is optional depth

    return set(faults.killed_ranks(step))


def num_dead_nodes(timeout_s: float = 0.0) -> int:
    """Dead-node surface (reference MXKVStoreGetNumDeadNode,
    kvstore_dist.h:159-168). Under jax.distributed a failed host aborts
    the job rather than running degraded, so a live call sees only
    simulated deaths (:func:`dead_nodes`); the timeout is honored as a
    liveness probe window."""
    if timeout_s > 0 and not dead_nodes():
        deadline = time.time() + timeout_s
        while time.time() < deadline and not dead_nodes():
            time.sleep(min(0.1, deadline - time.time()))
    return len(dead_nodes())


def is_recovery() -> bool:
    """Recovery flag (reference ps::Postoffice::is_recovery). Restarted
    jobs resume from checkpoints (resilience.load_sharded /
    save_checkpoint) instead of rejoining live — always False."""
    return False
