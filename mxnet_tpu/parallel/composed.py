"""Composed dp×pp training module: ZeRO-sharded data parallelism across
the "data" axis with 1f1b pipeline stages, behind the three-method
surface ``TrainingSupervisor`` drives (``fit_step`` /
``get_checkpoint_state`` / ``restore_checkpoint_state``).

``transformer.make_train_step`` already composes the pieces — the manual
ZeRO update (``collectives.zero1_update_local``) over "data" with the
1f1b pipeline over "pipe" in ONE shard_map program. This wrapper gives
that program a Module-shaped face so elastic training (checkpoint
cadence, dead-rank poll, restore + deterministic replay under an
``MXNET_FAULT_PLAN``) applies to the composed run unchanged: the in-graph
SGD carries no host RNG, so replaying ``batch_fn(step)`` from a restored
checkpoint is bit-identical — the property the composed fault dryrun
(CI stage 8) asserts.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from . import transformer as _tf

__all__ = ["ComposedTrainModule"]


class ComposedTrainModule:
    """dp×pp (optionally ×tp×sp) transformer training under supervision.

    The mesh's "data" axis carries the ZeRO-sharded update (stage per
    MXNET_SHARDED_UPDATE, 0 opts out), "pipe" the 1f1b schedule; any
    "model"/"seq" extent rides along. Checkpoint state is the full host
    param tree ("param:<name>") + the completed-step count, so a restore
    onto any shard fan-out (dp=4→2→4 via ``checkpoint.reshard``)
    reproduces the exact device values.
    """

    def __init__(self, mesh: Mesh, cfg: _tf.TransformerConfig, *,
                 lr: float = 1e-2, seed: int = 0,
                 n_micro: Optional[int] = None,
                 sharded_update: Optional[bool] = None):
        self._mesh = mesh
        self._cfg = cfg
        expert_group = int(mesh.shape["data"] * mesh.shape["expert"]
                           * mesh.shape["seq"])
        host = _tf.init_params(cfg, int(mesh.shape["pipe"]),
                               key=jax.random.PRNGKey(seed),
                               expert_group=expert_group)
        self._params = _tf.shard_params(host, mesh, cfg)
        self._step = _tf.make_train_step(mesh, cfg, n_micro=n_micro,
                                         lr=lr, sharded_update=sharded_update)
        # supervisor's default num_shards = len(module._context)
        self._context = list(np.asarray(mesh.devices).flat)
        self.steps_done = 0
        self.last_loss = None

    # --- the TrainingSupervisor surface ----------------------------------
    def fit_step(self, batch: Tuple):
        """One composed dp×pp step. ``batch`` is ``(tokens, targets)``
        int arrays of shape (global_batch, seq_len) — or a DataBatch
        whose data[0]/label[0] hold them."""
        if hasattr(batch, "data"):
            tokens, targets = batch.data[0], batch.label[0]
            tokens = tokens.asnumpy() if hasattr(tokens, "asnumpy") else tokens
            targets = (targets.asnumpy()
                       if hasattr(targets, "asnumpy") else targets)
        else:
            tokens, targets = batch
        loss, self._params = self._step(self._params,
                                        jnp.asarray(tokens, jnp.int32),
                                        jnp.asarray(targets, jnp.int32))
        self.steps_done += 1
        self.last_loss = loss
        return loss

    def get_checkpoint_state(self):
        """Host snapshot of the sharded param tree (per-shard device→host
        reads; nothing is re-replicated on device) + the step count."""
        arrays = {"param:%s" % k: np.asarray(v)
                  for k, v in self._params.items()}
        return arrays, {"num_update": int(self.steps_done)}

    def restore_checkpoint_state(self, arrays, opt_meta=None):
        host = {}
        for key, a in arrays.items():
            kind, _, name = key.partition(":")
            if kind != "param":
                raise ValueError("unknown composed checkpoint key %r" % key)
            host[name] = jnp.asarray(a)
        self._params = _tf.shard_params(host, self._mesh, self._cfg)
        if opt_meta:
            self.steps_done = int(opt_meta.get("num_update",
                                               self.steps_done))
        self.last_loss = None
