"""Parallelism subsystem — the TPU-native replacement for the reference's
entire communication plane (SURVEY §2.2, §5.8):

==========================  =================================================
reference mechanism          TPU-native realization (this package)
==========================  =================================================
kvstore 'device' reduce      in-graph psum over the "data" mesh axis
kvstore dist_sync / ps-lite  global all-reduce over ICI+DCN (jax.distributed)
group2ctx model parallel     NamedSharding / shard_map placement (mesh.py)
(absent in reference) TP     tensor_parallel.py sharding rules
(absent) SP / long context   ring_attention.py (ppermute ring over "seq")
(absent) PP micro-batching   pipeline.py (SPMD shift-register pipeline)
(absent) EP / MoE            moe.py (Switch routing + all_to_all dispatch)
tools/bandwidth harness      collectives.bus_bandwidth
==========================  =================================================

Mesh axes are canonically named ("data", "expert", "seq", "pipe", "model").
"""
from .mesh import MeshConfig, auto_mesh, make_mesh, AXES
from . import collectives
from .collectives import (all_reduce, all_gather, reduce_scatter, ring_shift,
                          barrier, bus_bandwidth)
from . import tensor_parallel
from . import ring_attention
from . import pipeline
from . import moe
from . import transformer
from . import dist
