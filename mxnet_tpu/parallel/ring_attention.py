"""Ring attention — sequence/context parallelism over the mesh "seq" axis.

The reference has NO sequence parallelism (SURVEY §5.7: long sequences are
handled by bucketing + unrolling); this is the modern TPU-idiomatic
mechanism that replaces it. Q, K, V are sharded along the sequence axis;
each device computes attention of its local query block against the K/V
block it currently holds, then passes K/V to its ring neighbor (ppermute
over ICI) while accumulating the online-softmax statistics — compute and
ICI transfer overlap, and no device ever materializes the full sequence.

Causal masking per ring step: a chunk pair is fully visible (kv earlier
than q), fully masked (kv later — skipped as a zero contribution), or
diagonal (local causal mask), indexed by the source chunk position.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from .collectives import axis_size, shard_map  # version-tolerant wrappers

_NEG = float(jnp.finfo(jnp.float32).min)


def _block_attn(q, k, v, mode, q_off, k_off):
    """Un-normalized blockwise attention with stats.

    q: (B,H,Tq,D), k/v: (B,H,Tk,D). mode: 0=full, 1=causal-diagonal,
    2=skip. Returns (acc f32 (B,H,Tq,D), m (B,H,Tq), l (B,H,Tq))."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    tq, tk = q.shape[-2], k.shape[-2]
    q_pos = q_off + jnp.arange(tq)[:, None]
    k_pos = k_off + jnp.arange(tk)[None, :]
    causal_mask = q_pos >= k_pos
    mask = jnp.where(mode == 1, causal_mask, mode == 0)
    s = jnp.where(mask, s, _NEG)
    m = jnp.max(s, axis=-1)
    m_safe = jnp.maximum(m, _NEG / 2)  # avoid -inf - -inf
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return acc, m_safe, l


def _merge(acc1, m1, l1, acc2, m2, l2):
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    return (acc1 * a1[..., None] + acc2 * a2[..., None],
            m, l1 * a1 + l2 * a2)


def _block_attn_flash(q, k, v, mode, interpret=False):
    """Per-shard compute through the Pallas flash kernel (docs/perf.md:
    2-15.7x over einsum attention at long chunks, blocked fwd AND bwd).

    Returns the same mergeable (acc, m, l) triple as _block_attn via the
    normalized-representation trick: for flash output O and logsumexp L,
    (O, L, 1) merges identically to (sum exp(s-m) v, m, sum exp(s-m)) —
    exp(L - m') * O = exp(m - m') * acc and exp(L - m') * 1 = the scaled
    l. The lse cotangent flows through the custom vjp (folded into the
    backward's D-vector). ``mode`` selects full/diagonal-causal/skip via
    lax.switch (it is data-dependent on the ring position)."""
    from ..ops.pallas.flash_attention import _flash_with_lse

    b, h, t, d = q.shape
    scale = 1.0 / (d ** 0.5)

    def run(is_causal):
        def f():
            # grouped-kernel layout with group size 1 (q: (bh, 1, t, d))
            out, lse = _flash_with_lse(
                q.reshape(b * h, 1, t, d), k.reshape(b * h, t, d),
                v.reshape(b * h, t, d), is_causal, scale, interpret)
            return (out.reshape(b, h, t, d).astype(jnp.float32),
                    lse.reshape(b, h, t),
                    jnp.ones((b, h, t), jnp.float32))
        return f

    def skip():
        return (jnp.zeros((b, h, t, d), jnp.float32),
                jnp.full((b, h, t), _NEG / 2, jnp.float32),
                jnp.zeros((b, h, t), jnp.float32))

    return jax.lax.switch(mode, [run(False), run(True), skip])


def _ring_attn_local(q, k, v, *, axis_name, causal, chunk, use_flash=False):
    """Body run per-device inside shard_map. q/k/v: local (B,H,T/n,D)."""
    n = axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    b, h, t, d = q.shape

    acc = jnp.zeros((b, h, t, d), jnp.float32)
    m = jnp.full((b, h, t), _NEG / 2, jnp.float32)
    l = jnp.zeros((b, h, t), jnp.float32)

    def step(i, carry):
        acc, m, l, kv = carry
        k_cur, v_cur = kv
        src = (my - i) % n  # which chunk we currently hold
        if causal:
            mode = jnp.where(src == my, 1, jnp.where(src < my, 0, 2))
        else:
            mode = jnp.zeros((), jnp.int32)
        if use_flash:
            a2, m2, l2 = _block_attn_flash(
                q, k_cur, v_cur, mode,
                interpret=(use_flash == "interpret"))
        else:
            a2, m2, l2 = _block_attn(q, k_cur, v_cur, mode,
                                     my * chunk, src * chunk)
        acc2, mm, ll = _merge(acc, m, l, a2, m2, l2)
        # overlap-friendly: shift kv for the next step
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (acc2, mm, ll, (k_nxt, v_nxt))

    acc, m, l, _ = jax.lax.fori_loop(0, n, step, (acc, m, l, (k, v)))
    return (acc / jnp.maximum(l, 1e-20)[..., None]).astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, causal=True, seq_axis="seq",
                   use_flash=None):
    """Full-array entry: q/k/v (B, H, T, D) sharded (or shardable) on T
    over `seq_axis`. Composable inside an outer pjit — shard_map nests.

    use_flash: None = auto (Pallas flash kernel per shard when on TPU
    with qualifying chunk shapes — the same selection contract as
    flash_attention); True/False forces; "interpret" runs the kernel in
    interpreter mode (tests)."""
    from ..ops.pallas import flash_attention as _fa
    from ..ops.pallas import on_tpu

    n = mesh.shape[seq_axis]
    t = q.shape[2]
    assert t % n == 0, "sequence length %d not divisible by seq axis %d" % (t, n)
    chunk = t // n
    if use_flash is None:
        use_flash = (on_tpu()
                     and _fa.kernel_qualifies(chunk, chunk, q.shape[-1])
                     and chunk >= _fa.MIN_SEQ)
    elif use_flash and not _fa.kernel_qualifies(
            chunk, chunk, q.shape[-1],
            compiled=(use_flash != "interpret")):
        # forcing the kernel past its block contract would read padding
        # into the softmax — refuse loudly instead of computing garbage
        raise ValueError(
            "ring_attention(use_flash=%r): chunk %d / head_dim %d do not "
            "satisfy the flash kernel's block contract"
            % (use_flash, chunk, q.shape[-1]))
    body = functools.partial(_ring_attn_local, axis_name=seq_axis,
                             causal=causal, chunk=chunk,
                             use_flash=use_flash)
    spec = P(None, None, seq_axis, None)
    fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec, check_vma=False)
    return fn(q, k, v)
