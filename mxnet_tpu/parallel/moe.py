"""Mixture-of-Experts with expert parallelism (EP) over the device mesh.

The reference has no MoE (SURVEY §2.2: "Expert parallelism (EP/MoE) —
absent"); this is one of the beyond-parity axes the TPU build supplies
natively, because mesh axes make it cheap to express. Design follows the
GShard/Switch recipe mapped to shard_map manual SPMD:

- Experts are sharded over the *expert group* — the combined
  ("data", "expert", "seq") mesh axes — so EP rides the same devices that
  hold data/sequence shards (the standard ep ⊆ dp overlay), plus a
  dedicated "expert" axis when the mesh has one.
- Routing is Switch-style top-1 with a static per-shard capacity
  (XLA-friendly: the dispatch/combine tensors are dense one-hot matmuls
  that lower onto the MXU; no dynamic shapes).
- Token exchange is a single tiled `all_to_all` over the expert group in
  each direction — the ICI-native equivalent of the reference's
  cross-device sends (comm.h P2P copies), but as one fused collective.
- Expert FFN weights compose with tensor parallelism: the hidden dim f is
  still sharded over "model" (Megatron column/row split), with one psum
  after the second matmul.

Gradient semantics (used by transformer.make_train_step): jax.grad through
the all_to_all accumulates every group member's contribution into the
local expert's weight gradient, so expert-weight grads must be scaled by
1/group_size rather than pmean'd — see `scale_expert_grads`.
"""
from __future__ import annotations

import math
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

# Mesh axes whose devices jointly hold the expert population.
EXPERT_GROUP: Tuple[str, ...] = ("data", "expert", "seq")


def group_size(group: Sequence[str] = EXPERT_GROUP) -> int:
    """Size of the expert group inside a shard_map body."""
    from .collectives import axis_size
    return axis_size(tuple(group))


def switch_moe_local(x, wg, w1, w2, *, group: Sequence[str] = EXPERT_GROUP,
                     capacity_factor: float = 2.0):
    """Per-device Switch-MoE FFN body (call inside shard_map).

    x  : (T, d) local tokens (any leading dims flattened by the caller).
    wg : (d, E) router weights, replicated over the expert group.
    w1 : (E_local, d, f_local) expert up-proj (f sharded over "model").
    w2 : (E_local, f_local, d) expert down-proj.

    Returns (y, aux) where y is (T, d) and aux is the Switch
    load-balancing loss term (local; pmean it over the group).
    """
    g = group_size(group)
    e_local = w1.shape[0]
    n_exp = g * e_local
    t, d = x.shape
    cap = max(1, int(math.ceil(t * capacity_factor / n_exp)))

    logits = x @ wg                                   # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    gate = jnp.max(probs, axis=-1)                    # (T,)
    eidx = jnp.argmax(probs, axis=-1)                 # (T,)
    onehot = jax.nn.one_hot(eidx, n_exp, dtype=x.dtype)

    # Position of each token in its expert's queue; drop overflow (> cap).
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0   # (T, E), -1 if unrouted
    keep = onehot * (pos < cap)
    pos_oh = jax.nn.one_hot(jnp.clip(pos, 0, cap - 1).astype(jnp.int32),
                            cap, dtype=x.dtype)       # (T, E, C)
    dispatch = keep[:, :, None] * pos_oh              # (T, E, C) 0/1
    combine = dispatch * gate[:, None, None]

    # Switch aux loss: E * sum_e(frac_tokens_e * mean_prob_e).
    density = jnp.mean(onehot, axis=0)
    density_proxy = jnp.mean(probs, axis=0)
    aux = n_exp * jnp.sum(density * density_proxy)

    # Dispatch: (E, C, d) → all_to_all → (E_local, G*C, d): every device
    # now holds all tokens routed to its local experts.
    xd = jnp.einsum("td,tec->ecd", x, dispatch)
    xd = jax.lax.all_to_all(xd, tuple(group), 0, 1, tiled=True)

    h = jnp.einsum("ecd,edf->ecf", xd, w1)
    h = jax.nn.gelu(h)
    y = jnp.einsum("ecf,efd->ecd", h, w2)
    y = jax.lax.psum(y, "model")                      # un-shard f (Megatron)

    # Return trip + weighted combine back into token order.
    y = jax.lax.all_to_all(y, tuple(group), 1, 0, tiled=True)
    y = jnp.einsum("ecd,tec->td", y, combine)
    return y, aux


def scale_expert_grads(grads, scale_keys, group: Sequence[str] = EXPERT_GROUP,
                       dense_axes: Sequence[str] = None):
    """Inside shard_map: fix up a grad pytree dict where `scale_keys` are
    expert-sharded (divide by group size — AD already summed cross-device
    contributions through the all_to_all transpose) and the rest are
    replicated (pmean over dense_axes, default the expert group)."""
    if dense_axes is None:
        dense_axes = tuple(group)
    g = group_size(group)
    out = {}
    for k, v in grads.items():
        if k in scale_keys:
            out[k] = jax.tree_util.tree_map(lambda a: a / g, v)
        else:
            out[k] = jax.tree_util.tree_map(
                lambda a: jax.lax.pmean(a, tuple(dense_axes)), v)
    return out
