"""Symbolic RNN cells.

Reimplementation of python/mxnet/rnn/rnn_cell.py (SURVEY §2.4): RNN/LSTM/GRU
cells with ``unroll()``, Sequential/Bidirectional composition, Dropout/
Zoneout/Residual modifiers, and FusedRNNCell. Where the reference's
FusedRNNCell maps to the cuDNN RNN kernel (rnn_cell.py:497,
cudnn_rnn-inl.h), this one maps to the framework's fused scan-based `RNN`
op (ops/rnn_fused.py) — the TPU analogue: one lax.scan over time with the
per-step matmuls batched onto the MXU.
"""
from __future__ import annotations

from .. import symbol
from ..base import MXNetError


def _batch_ref(sym_, batch_axis, ndim):
    """A (batch, 1) zero symbol whose batch dim tracks ``sym_``'s.

    Forward-shape-inference-friendly replacement for the reference's
    0-batch begin_state convention (rnn_cell.py state_info shape (0, H)):
    instead of an unknown dim unified by bidirectional InferShape, the
    batch size flows forward from the input symbol. XLA folds the
    slice*0 into a constant, so no runtime cost."""
    ref = sym_
    for ax in range(ndim):
        if ax != batch_axis:
            ref = symbol.slice_axis(ref, axis=ax, begin=0, end=1)
    return symbol.Reshape(ref, shape=(-1, 1)) * 0


def _zeros_like_batch(ref_n1):
    """begin_state func: zeros of state_info shape, 0-dims = batch."""

    def func(name=None, shape=None, **kw):
        s = tuple(shape)
        rshape = tuple(-1 if d == 0 else 1 for d in s)
        z = symbol.Reshape(ref_n1, shape=rshape)
        return symbol.broadcast_to(z, shape=s)

    return func


class RNNParams:
    """Container for shared cell parameters (reference rnn_cell.py RNNParams)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = symbol.Variable(name, **kwargs)
        return self._params[name]


class BaseRNNCell:
    """(reference rnn_cell.py BaseRNNCell)."""

    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    def __call__(self, inputs, states):
        raise NotImplementedError()

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_info(self):
        raise NotImplementedError()

    @property
    def state_shape(self):
        return [ele["shape"] for ele in self.state_info]

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, func=symbol.zeros, **kwargs):
        """(reference rnn_cell.py begin_state)."""
        assert not self._modified
        states = []
        for info in self.state_info:
            self._init_counter += 1
            if info is None:
                state = func(name="%sbegin_state_%d" % (self._prefix, self._init_counter),
                             **kwargs)
            else:
                kwargs.update(info)
                state = func(name="%sbegin_state_%d" % (self._prefix, self._init_counter),
                             **kwargs)
            states.append(state)
        return states

    def unpack_weights(self, args):
        """Split fused gate weights into per-gate arrays (reference
        rnn_cell.py unpack_weights)."""
        args = args.copy()
        if not self._gate_names:
            return args
        h = self._num_hidden
        for group_name in ["i2h", "h2h"]:
            weight = args.pop("%s%s_weight" % (self._prefix, group_name))
            bias = args.pop("%s%s_bias" % (self._prefix, group_name))
            for j, gate in enumerate(self._gate_names):
                wname = "%s%s%s_weight" % (self._prefix, group_name, gate)
                args[wname] = weight[j * h : (j + 1) * h].copy()
                bname = "%s%s%s_bias" % (self._prefix, group_name, gate)
                args[bname] = bias[j * h : (j + 1) * h].copy()
        return args

    def pack_weights(self, args):
        from .. import ndarray as nd

        args = args.copy()
        if not self._gate_names:
            return args
        for group_name in ["i2h", "h2h"]:
            weight = []
            bias = []
            for gate in self._gate_names:
                wname = "%s%s%s_weight" % (self._prefix, group_name, gate)
                weight.append(args.pop(wname))
                bname = "%s%s%s_bias" % (self._prefix, group_name, gate)
                bias.append(args.pop(bname))
            args["%s%s_weight" % (self._prefix, group_name)] = nd.concatenate(weight)
            args["%s%s_bias" % (self._prefix, group_name)] = nd.concatenate(bias)
        return args

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        """Unroll over time (reference rnn_cell.py unroll)."""
        self.reset()
        axis = layout.find("T")
        if inputs is None:
            inputs = [
                symbol.Variable("%st%d_data" % (input_prefix, i)) for i in range(length)
            ]
        elif isinstance(inputs, symbol.Symbol):
            assert len(inputs) == 1
            inputs = symbol.SliceChannel(
                inputs, axis=axis, num_outputs=length, squeeze_axis=1
            )
            inputs = [inputs[i] for i in range(length)]
        if begin_state is None:
            begin_state = self.begin_state(
                func=_zeros_like_batch(_batch_ref(inputs[0], 0, 2)))
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        if merge_outputs:
            outputs = [symbol.expand_dims(i, axis=axis) for i in outputs]
            outputs = symbol.Concat(*outputs, dim=axis)
        return outputs, states


class RNNCell(BaseRNNCell):
    """Vanilla tanh/relu RNN cell (reference rnn_cell.py RNNCell)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW, bias=self._iB,
                                    num_hidden=self._num_hidden, name="%si2h" % name)
        h2h = symbol.FullyConnected(data=states[0], weight=self._hW, bias=self._hB,
                                    num_hidden=self._num_hidden, name="%sh2h" % name)
        output = symbol.Activation(i2h + h2h, act_type=self._activation,
                                   name="%sout" % name)
        return output, [output]


class LSTMCell(BaseRNNCell):
    """LSTM cell (reference rnn_cell.py LSTMCell); gate order i,f,g,o."""

    def __init__(self, num_hidden, prefix="lstm_", params=None, forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        from ..initializer import LSTMBias

        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias", init=LSTMBias(forget_bias=forget_bias))
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [
            {"shape": (0, self._num_hidden), "__layout__": "NC"},
            {"shape": (0, self._num_hidden), "__layout__": "NC"},
        ]

    @property
    def _gate_names(self):
        return ["_i", "_f", "_c", "_o"]

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW, bias=self._iB,
                                    num_hidden=self._num_hidden * 4,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(data=states[0], weight=self._hW, bias=self._hB,
                                    num_hidden=self._num_hidden * 4,
                                    name="%sh2h" % name)
        gates = i2h + h2h
        slice_gates = symbol.SliceChannel(gates, num_outputs=4,
                                          name="%sslice" % name)
        in_gate = symbol.Activation(slice_gates[0], act_type="sigmoid",
                                    name="%si" % name)
        forget_gate = symbol.Activation(slice_gates[1], act_type="sigmoid",
                                        name="%sf" % name)
        in_transform = symbol.Activation(slice_gates[2], act_type="tanh",
                                         name="%sc" % name)
        out_gate = symbol.Activation(slice_gates[3], act_type="sigmoid",
                                     name="%so" % name)
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * symbol.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU cell (reference rnn_cell.py GRUCell); gate order r,z,o."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ["_r", "_z", "_o"]

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        prev_state_h = states[0]
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW, bias=self._iB,
                                    num_hidden=self._num_hidden * 3,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(data=prev_state_h, weight=self._hW,
                                    bias=self._hB, num_hidden=self._num_hidden * 3,
                                    name="%sh2h" % name)
        i2h_r, i2h_z, i2h = symbol.SliceChannel(i2h, num_outputs=3,
                                                name="%si2h_slice" % name)
        h2h_r, h2h_z, h2h = symbol.SliceChannel(h2h, num_outputs=3,
                                                name="%sh2h_slice" % name)
        reset_gate = symbol.Activation(i2h_r + h2h_r, act_type="sigmoid",
                                       name="%sr_act" % name)
        update_gate = symbol.Activation(i2h_z + h2h_z, act_type="sigmoid",
                                        name="%sz_act" % name)
        next_h_tmp = symbol.Activation(i2h + reset_gate * h2h, act_type="tanh",
                                       name="%sh_act" % name)
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * prev_state_h
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Fused multi-layer RNN over the whole sequence.

    The reference maps this to the cuDNN RNN kernel (rnn_cell.py:497,
    cudnn_rnn-inl.h:22). Here `unroll` emits the framework `RNN` op, whose
    impl is a lax.scan with gate matmuls fused per step (ops/rnn_fused.py) —
    the TPU-native equivalent. `unfuse()` returns the explicit-cell stack.
    """

    def __init__(self, num_hidden, num_layers=1, mode="lstm", bidirectional=False,
                 dropout=0.0, get_next_state=False, forget_bias=1.0,
                 prefix=None, params=None):
        if prefix is None:
            prefix = "%s_" % mode
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._directions = 2 if bidirectional else 1
        # tag the packed blob with the FusedRNN initializer so generic
        # initializers (Xavier etc.) route through it — the reference does
        # exactly this (rnn_cell.py FusedRNNCell: params.get('parameters',
        # init=init.FusedRNN(None, ...)))
        from .. import initializer as _init

        self._parameter = self.params.get(
            "parameters",
            init=_init.FusedRNN(None, num_hidden=num_hidden,
                                num_layers=num_layers, mode=mode,
                                bidirectional=bidirectional,
                                forget_bias=forget_bias))

    @property
    def state_info(self):
        b = self._directions
        n = (self._mode == "lstm") + 1
        return [
            {"shape": (b * self._num_layers, 0, self._num_hidden),
             "__layout__": "LNC"}
            for _ in range(n)
        ]

    @property
    def _gate_names(self):
        return {"rnn_relu": [""], "rnn_tanh": [""],
                "lstm": ["_i", "_f", "_c", "_o"],
                "gru": ["_r", "_z", "_o"]}[self._mode]

    @property
    def _num_gates(self):
        return len(self._gate_names)

    def _slice_weights(self, arr, li, lh):
        """Slice the packed parameter blob into per-layer matrices
        (reference rnn_cell.py FusedRNNCell._slice_weights)."""
        args = {}
        gate_names = self._gate_names
        directions = self._directions
        b = len(gate_names)
        p = 0
        for layer in range(self._num_layers):
            for direction in range(directions):
                for gate in gate_names:
                    name = "%s%s%d_i2h%s_weight" % (
                        self._prefix, "lr"[direction], layer, gate)
                    size = b and self._num_hidden * (lh if layer > 0 else li) // b * b // b
                    ni = lh * directions if layer > 0 else li
                    size = self._num_hidden * ni
                    args[name] = arr[p : p + size].reshape((self._num_hidden, ni))
                    p += size
                for gate in gate_names:
                    name = "%s%s%d_h2h%s_weight" % (
                        self._prefix, "lr"[direction], layer, gate)
                    size = self._num_hidden * lh
                    args[name] = arr[p : p + size].reshape((self._num_hidden, lh))
                    p += size
        for layer in range(self._num_layers):
            for direction in range(directions):
                for part in ("i2h", "h2h"):
                    for gate in gate_names:
                        name = "%s%s%d_%s%s_bias" % (
                            self._prefix, "lr"[direction], layer, part, gate)
                        args[name] = arr[p : p + self._num_hidden]
                        p += self._num_hidden
        return args

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        self.reset()
        axis = layout.find("T")
        if inputs is None:
            inputs = [symbol.Variable("%st%d_data" % (input_prefix, i))
                      for i in range(length)]
        if isinstance(inputs, list):
            inputs = [symbol.expand_dims(i, axis=1) for i in inputs]
            inputs = symbol.Concat(*inputs, dim=1)
            axis = 1
        if axis == 1:  # NTC -> TNC for the fused kernel (time-major scan)
            inputs = symbol.SwapAxis(inputs, dim1=0, dim2=1)
        if begin_state is None:
            begin_state = self.begin_state(
                func=_zeros_like_batch(_batch_ref(inputs, 1, 3)))
        states = begin_state
        rnn_kwargs = dict(
            data=inputs, parameters=self._parameter, state=states[0],
            state_size=self._num_hidden, num_layers=self._num_layers,
            bidirectional=self._bidirectional, p=self._dropout,
            state_outputs=self._get_next_state, mode=self._mode,
            name=self._prefix + "rnn",
        )
        if self._mode == "lstm":
            rnn_kwargs["state_cell"] = states[1]
        rnn = symbol.RNN(**rnn_kwargs)
        if not self._get_next_state:
            outputs, states = rnn, []
        elif self._mode == "lstm":
            outputs, states = rnn[0], [rnn[1], rnn[2]]
        else:
            outputs, states = rnn[0], [rnn[1]]
        if axis == 1:
            outputs = symbol.SwapAxis(outputs, dim1=0, dim2=1)
        if merge_outputs is False:
            outputs = symbol.SliceChannel(outputs, axis=axis, num_outputs=length,
                                          squeeze_axis=1)
            outputs = [outputs[i] for i in range(length)]
        return outputs, states

    def unfuse(self):
        """Equivalent explicit-cell stack (reference rnn_cell.py unfuse)."""
        stack = SequentialRNNCell()
        get_cell = {
            "rnn_relu": lambda cell_prefix: RNNCell(self._num_hidden,
                                                    activation="relu",
                                                    prefix=cell_prefix),
            "rnn_tanh": lambda cell_prefix: RNNCell(self._num_hidden,
                                                    activation="tanh",
                                                    prefix=cell_prefix),
            "lstm": lambda cell_prefix: LSTMCell(self._num_hidden,
                                                 prefix=cell_prefix),
            "gru": lambda cell_prefix: GRUCell(self._num_hidden,
                                               prefix=cell_prefix),
        }[self._mode]
        for i in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    get_cell("%sl%d_" % (self._prefix, i)),
                    get_cell("%sr%d_" % (self._prefix, i)),
                    output_prefix="%sbi_%d_" % (self._prefix, i)))
            else:
                stack.add(get_cell("%sl%d_" % (self._prefix, i)))
            if self._dropout > 0 and i != self._num_layers - 1:
                stack.add(DropoutCell(self._dropout,
                                      prefix="%s_dropout%d_" % (self._prefix, i)))
        return stack


class SequentialRNNCell(BaseRNNCell):
    """(reference rnn_cell.py SequentialRNNCell)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._override_cell_params = params is not None
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)
        if self._override_cell_params:
            assert cell._own_params
            cell.params._params.update(self.params._params)
            self.params._params.update(cell.params._params)

    @property
    def state_info(self):
        return sum([c.state_info for c in self._cells], [])

    def begin_state(self, **kwargs):
        assert not self._modified
        return sum([c.begin_state(**kwargs) for c in self._cells], [])

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._cells:
            n = len(cell.state_info)
            state = states[p : p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.append(state)
        return inputs, sum(next_states, [])


class DropoutCell(BaseRNNCell):
    """(reference rnn_cell.py DropoutCell)."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix, params)
        self.dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            inputs = symbol.Dropout(data=inputs, p=self.dropout)
        return inputs, states


class ModifierCell(BaseRNNCell):
    """(reference rnn_cell.py ModifierCell)."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, func=symbol.zeros, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin

    def unpack_weights(self, args):
        return self.base_cell.unpack_weights(args)

    def pack_weights(self, args):
        return self.base_cell.pack_weights(args)

    def __call__(self, inputs, states):
        raise NotImplementedError()


class ZoneoutCell(ModifierCell):
    """(reference rnn_cell.py ZoneoutCell)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, FusedRNNCell), \
            "FusedRNNCell doesn't support zoneout. Please unfuse first."
        assert not isinstance(base_cell, BidirectionalCell), \
            "BidirectionalCell doesn't support zoneout since it doesn't support step. " \
            "Please add ZoneoutCell to the cells underneath instead."
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def reset(self):
        super().reset()
        self.prev_output = None

    def __call__(self, inputs, states):
        cell, p_outputs, p_states = self.base_cell, self.zoneout_outputs, self.zoneout_states
        next_output, next_states = cell(inputs, states)
        mask = lambda p, like: symbol.Dropout(
            symbol.ones_like(like), p=p
        )
        prev_output = self.prev_output if self.prev_output is not None \
            else symbol.zeros((0, 0))
        output = (
            symbol.where(mask(p_outputs, next_output), next_output, prev_output)
            if p_outputs != 0.0 else next_output
        )
        states_out = (
            [symbol.where(mask(p_states, new_s), new_s, old_s)
             for new_s, old_s in zip(next_states, states)]
            if p_states != 0.0 else next_states
        )
        self.prev_output = output
        return output, states_out


class ResidualCell(ModifierCell):
    """(reference rnn_cell.py ResidualCell)."""

    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = symbol.elemwise_add(output, inputs, name="%s_plus_residual" % output.name)
        return output, states


class BidirectionalCell(BaseRNNCell):
    """(reference rnn_cell.py BidirectionalCell)."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__("", params)
        self._output_prefix = output_prefix
        self._override_cell_params = params is not None
        if self._override_cell_params:
            assert l_cell._own_params and r_cell._own_params
            l_cell.params._params.update(self.params._params)
            r_cell.params._params.update(self.params._params)
        self.params._params.update(l_cell.params._params)
        self.params._params.update(r_cell.params._params)
        self._cells = [l_cell, r_cell]

    def unpack_weights(self, args):
        return _cells_unpack_weights(self._cells, args)

    def pack_weights(self, args):
        return _cells_pack_weights(self._cells, args)

    def __call__(self, inputs, states):
        raise NotImplementedError("Bidirectional cannot be stepped. Please use unroll")

    @property
    def state_info(self):
        return sum([c.state_info for c in self._cells], [])

    def begin_state(self, **kwargs):
        assert not self._modified
        return sum([c.begin_state(**kwargs) for c in self._cells], [])

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        self.reset()
        if inputs is None:
            inputs = [symbol.Variable("%st%d_data" % (input_prefix, i))
                      for i in range(length)]
        elif isinstance(inputs, symbol.Symbol):
            assert len(inputs) == 1
            axis = layout.find("T")
            inputs = symbol.SliceChannel(inputs, axis=axis, num_outputs=length,
                                         squeeze_axis=1)
            inputs = [inputs[i] for i in range(length)]
        if begin_state is None:
            begin_state = self.begin_state(
                func=_zeros_like_batch(_batch_ref(inputs[0], 0, 2)))
        states = begin_state
        l_cell, r_cell = self._cells
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs,
            begin_state=states[: len(l_cell.state_info)],
            layout=layout, merge_outputs=False)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=states[len(l_cell.state_info):],
            layout=layout, merge_outputs=False)
        outputs = [
            symbol.Concat(l_o, r_o, dim=1,
                          name="%st%d" % (self._output_prefix, i))
            for i, (l_o, r_o) in enumerate(zip(l_outputs, reversed(r_outputs)))
        ]
        if merge_outputs:
            # (N, T, 2H) stacking, same convention as BaseRNNCell.unroll
            axis = layout.find("T")
            outputs = [symbol.expand_dims(o, axis=axis) for o in outputs]
            outputs = symbol.Concat(*outputs, dim=axis)
        states = l_states + r_states
        return outputs, states


def _cells_unpack_weights(cells, args):
    for cell in cells:
        args = cell.unpack_weights(args)
    return args


def _cells_pack_weights(cells, args):
    for cell in cells:
        args = cell.pack_weights(args)
    return args
