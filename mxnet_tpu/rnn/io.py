"""RNN data iterators (reference python/mxnet/rnn/io.py)."""
from __future__ import annotations

import bisect
import random

import numpy as np

from .. import ndarray as nd
from ..io import DataBatch, DataDesc, DataIter


def encode_sentences(sentences, vocab=None, invalid_label=-1, invalid_key="\n",
                     start_label=0):
    """Encode sentences to int arrays, building a vocab (reference
    rnn/io.py encode_sentences)."""
    idx = start_label
    if vocab is None:
        vocab = {invalid_key: invalid_label}
        new_vocab = True
    else:
        new_vocab = False
    res = []
    for sent in sentences:
        coded = []
        for word in sent:
            if word not in vocab:
                assert new_vocab, "Unknown token %s" % word
                if idx == invalid_label:
                    idx += 1
                vocab[word] = idx
                idx += 1
            coded.append(vocab[word])
        res.append(coded)
    return res, vocab


class BucketSentenceIter(DataIter):
    """Bucketed iterator over variable-length sequences (reference
    rnn/io.py BucketSentenceIter): sentences grouped into length buckets,
    each batch padded to its bucket length and tagged with bucket_key for
    BucketingModule."""

    def __init__(self, sentences, batch_size, buckets=None, invalid_label=-1,
                 data_name="data", label_name="softmax_label", dtype="float32",
                 sequence_labels=None):
        """``sequence_labels``: optional per-SENTENCE scalar labels
        (classification over variable-length text, e.g. the text-CNN
        example). Default None keeps the language-model convention
        (label = the sentence shifted left by one)."""
        super().__init__()
        if not buckets:
            buckets = [
                i for i, j in enumerate(np.bincount([len(s) for s in sentences]))
                if j >= batch_size
            ]
        buckets.sort()
        ndiscard = 0
        self.data = [[] for _ in buckets]
        self._seq_labels = ([[] for _ in buckets]
                            if sequence_labels is not None else None)
        for si, sent in enumerate(sentences):
            buck = bisect.bisect_left(buckets, len(sent))
            if buck == len(buckets):
                ndiscard += 1
                continue
            buff = np.full((buckets[buck],), invalid_label, dtype=dtype)
            buff[: len(sent)] = sent
            self.data[buck].append(buff)
            if self._seq_labels is not None:
                self._seq_labels[buck].append(sequence_labels[si])
        self.data = [np.asarray(i, dtype=dtype) for i in self.data]
        if self._seq_labels is not None:
            self._seq_labels = [np.asarray(i, dtype=dtype)
                                for i in self._seq_labels]
        if ndiscard:
            print("WARNING: discarded %d sentences longer than the largest bucket." % ndiscard)

        self.batch_size = batch_size
        self.buckets = buckets
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.invalid_label = invalid_label
        self.nddata = []
        self.ndlabel = []
        self.major_axis = 0
        self.default_bucket_key = max(buckets)

        self.provide_data = [DataDesc(data_name, (batch_size, self.default_bucket_key))]
        self.provide_label = [DataDesc(
            label_name, (batch_size,) if self._seq_labels is not None
            else (batch_size, self.default_bucket_key))]

        self.idx = []
        for i, buck in enumerate(self.data):
            self.idx.extend([(i, j) for j in range(0, len(buck) - batch_size + 1, batch_size)])
        self.curr_idx = 0
        self.reset()

    def reset(self):
        self.curr_idx = 0
        random.shuffle(self.idx)
        if self._seq_labels is None:
            for buck in self.data:
                np.random.shuffle(buck)
        # (sequence-labels mode shuffles data and labels with one
        # permutation below instead)
        self.nddata = []
        self.ndlabel = []
        for bi, buck in enumerate(self.data):
            if self._seq_labels is not None:
                # shuffle data and per-sentence labels with ONE perm
                perm = np.random.permutation(len(buck)) if len(buck) else []
                buck = buck[perm]
                self.data[bi] = buck
                self._seq_labels[bi] = self._seq_labels[bi][perm]
                label = self._seq_labels[bi]
            else:
                label = np.empty_like(buck)
                label[:, :-1] = buck[:, 1:]
                label[:, -1] = self.invalid_label
            self.nddata.append(nd.array(buck, dtype=self.dtype))
            self.ndlabel.append(nd.array(label, dtype=self.dtype))

    def next(self):
        if self.curr_idx == len(self.idx):
            raise StopIteration
        i, j = self.idx[self.curr_idx]
        self.curr_idx += 1
        data = self.nddata[i][j : j + self.batch_size]
        label = self.ndlabel[i][j : j + self.batch_size]
        return DataBatch(
            [data], [label], pad=0,
            bucket_key=self.buckets[i],
            provide_data=[DataDesc(self.data_name, data.shape)],
            provide_label=[DataDesc(self.label_name, label.shape)],
        )
