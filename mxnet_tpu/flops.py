"""Analytic FLOP accounting for symbolic graphs.

The reference publishes throughput (img/s) only; the north-star target for
this repo is stated as MFU (BASELINE.md), which needs a *defensible* FLOP
model. This module implements the standard accounting used by the scaling
literature:

- 1 MAC = 2 FLOPs,
- forward cost = sum over matmul-bearing ops (Convolution, Deconvolution,
  FullyConnected, dot, batch_dot, RNN); elementwise/norm/pool ops are
  excluded (they are bandwidth- not FLOP-bound and conventionally omitted
  — the same convention under which ResNet-50 is quoted at ~4.1 GFLOPs
  forward per 224x224 image),
- training step cost = 3x forward (backward does ~2x the forward matmul
  work: grad wrt inputs + grad wrt weights).

`count_flops(sym, **shapes)` walks the graph with inferred shapes
(symbol.get_internals + infer_shape, the nnvm InferShape analogue) and
returns forward FLOPs. MFU = achieved FLOP/s / nominal peak FLOP/s of the
chip at the compute precision (chip_peak_flops).
"""
from __future__ import annotations

from typing import Dict, Tuple


def _prod(shape) -> int:
    out = 1
    for s in shape:
        out *= int(s)
    return out


# Nominal peak dense bf16 FLOP/s per chip, by jax device_kind. Public
# figures from the TPU product tables (per chip, not per core).
CHIP_PEAK_BF16 = {
    "TPU v2": 46e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5": 459e12,        # v5p
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,   # Trillium
    "TPU v6e": 918e12,
    "TPU7x": 2307e12,
}


#: peak multiplier vs bf16 by compute precision: the MXU runs 8-bit
#: operands (int8, fp8) at double rate, so an MFU quoted against the
#: bf16 peak would flatter quantized kernels by 2x.
PRECISION_PEAK_MULT = {"bf16": 1.0, "float32": 1.0, "f32": 1.0,
                       "int8": 2.0, "fp8": 2.0, "fp8_e4m3": 2.0}


def chip_peak_flops(device=None, precision: str = "bf16"
                    ) -> Tuple[float, str]:
    """(nominal peak FLOP/s at ``precision``, device_kind) for a jax
    device. ``precision`` int8/fp8 doubles the bf16 figure (the MXU's
    double-rate 8-bit path) — quantized-matmul MFU must be quoted
    against THIS peak, not the bf16 one, to stay honest.

    Returns (0.0, kind) when the chip is unknown (e.g. CPU backend) — MFU
    is then not computable and callers should report throughput only.
    """
    import jax

    mult = PRECISION_PEAK_MULT.get(str(precision).lower())
    if mult is None:
        raise ValueError("unknown compute precision %r (have %s)"
                         % (precision, sorted(PRECISION_PEAK_MULT)))
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", str(device))
    if kind in CHIP_PEAK_BF16:
        return CHIP_PEAK_BF16[kind] * mult, kind
    # longest-prefix match on the device kind only ("TPU v5 lite core"
    # -> "TPU v5 lite", never "TPU v5 lite" -> the v5p "TPU v5" entry)
    best = ""
    for key in CHIP_PEAK_BF16:
        if kind.startswith(key) and len(key) > len(best):
            best = key
    if best:
        return CHIP_PEAK_BF16[best] * mult, kind
    return 0.0, kind


def count_flops(sym, **known_shapes) -> Dict[str, float]:
    """Forward-pass FLOPs of `sym` at the given input shapes.

    Returns {"total": fwd_flops, "<op_type>": flops_by_op_type...}.
    Counts 2*MACs for Convolution/Deconvolution/FullyConnected/dot/
    batch_dot/RNN; everything else contributes 0 (stated convention, see
    module docstring).
    """
    internals = sym.get_internals()
    _, out_shapes, _ = internals.infer_shape(**known_shapes)
    shape_of = {}
    for (node, idx), shp in zip(internals._entries, out_shapes):
        if shp is not None:
            shape_of[(id(node), idx)] = tuple(shp)

    by_type: Dict[str, float] = {}
    total = 0.0
    for node in sym._nodes():
        if node.is_var:
            continue
        opname = node.op.name
        in_shapes = [shape_of.get((id(c), i)) for c, i in node.inputs]
        out0 = shape_of.get((id(node), 0))
        f = _node_flops(opname, node.attrs, in_shapes, out0)
        if f:
            by_type[opname] = by_type.get(opname, 0.0) + f
            total += f
    by_type["total"] = total
    # low-precision share, separated so MFU can be quoted per precision:
    # 8-bit matmuls against the double-rate peak, the rest against bf16
    by_type["total_lowbit"] = by_type.get("QuantizedFullyConnected", 0.0)
    return by_type


def _node_flops(opname, attrs, in_shapes, out_shape) -> float:
    if out_shape is None:
        return 0.0
    if opname == "Convolution":
        # weight: (num_filter, C/groups, *kernel); every output element
        # accumulates prod(weight.shape[1:]) MACs.
        w = in_shapes[1]
        if w is None:
            return 0.0
        macs = _prod(out_shape) * _prod(w[1:])
        bias = 0 if str(attrs.get("no_bias", False)) in ("True", "true", "1") \
            else _prod(out_shape)
        return 2.0 * macs + bias
    if opname == "Deconvolution":
        # gradient-of-conv: every *input* element is multiplied into
        # prod(weight.shape[1:]) output taps.
        data, w = in_shapes[0], in_shapes[1]
        if data is None or w is None:
            return 0.0
        return 2.0 * _prod(data) * _prod(w[1:])
    if opname in ("FullyConnected", "QuantizedFullyConnected"):
        # QuantizedFullyConnected: identical MAC count at 8-bit operand
        # width — it lands in its own by_type bucket, and MFU for that
        # share must be quoted against chip_peak_flops(precision="int8")
        # (the double-rate peak), keeping quantized MFU honest.
        w = in_shapes[1]
        if w is None:
            return 0.0
        k = int(w[-1])
        macs = _prod(out_shape) * k
        bias = 0 if str(attrs.get("no_bias", False)) in ("True", "true", "1") \
            else _prod(out_shape)
        return 2.0 * macs + bias
    if opname in ("dot", "batch_dot"):
        a = in_shapes[0]
        if a is None:
            return 0.0
        ta = str(attrs.get("transpose_a", False)) in ("True", "true", "1")
        ka = int(a[-2]) if ta else int(a[-1])
        return 2.0 * _prod(out_shape) * ka
    if opname == "MultiHeadAttention":
        # two matmuls per head — scores (Tq·Tk·Dh) and weighted values —
        # = 4·N·H·Tq·Tk·Dh; causal counts the USEFUL (unmasked) half,
        # matching how the flash kernels skip it and how docs/perf.md
        # credits attention micros. Projections are separate FC nodes.
        q = in_shapes[0]
        k = in_shapes[1] if len(in_shapes) > 1 else None
        if q is None or k is None:
            return 0.0
        n, tq, dmq = int(q[0]), int(q[1]), int(q[2])
        tk = int(k[1])
        causal = str(attrs.get("causal", False)) in ("True", "true", "1")
        f = 4.0 * n * tq * tk * dmq  # H·Dh == dmq (query width)
        if causal:
            # useful (unmasked) count: query row i sees keys
            # [0, i + tk - tq], i.e. max(0, tk - tq + 1 + i) of them.
            # tq <= tk: every row sees >= 1 key, closed form
            # tq*(tk - (tq-1)/2); tq > tk: the first tq-tk rows see
            # nothing and the rest see 1..tk (clamping matters — the
            # unclamped form goes NEGATIVE). ~f/2 at tq == tk; > f/2
            # for cross-length causal (tq < tk with key offset).
            if tq <= tk:
                rows = tq * (tk - (tq - 1) / 2.0)
            else:
                rows = tk * (tk + 1) / 2.0
            return f * rows / (tq * tk)
        return f
    if opname == "RNN":
        # fused multi-layer RNN: dominated by 8 gate matmuls per LSTM step
        # (4 gates x {input, hidden}). Use weight blob size as MAC count
        # per timestep per batch row: total = 2 * T * N * prod(weights).
        data = in_shapes[0]
        w = in_shapes[1]
        if data is None or w is None:
            return 0.0
        t, n = int(data[0]), int(data[1])
        return 2.0 * t * n * _prod(w)
    return 0.0


def training_flops(fwd_flops: float) -> float:
    """Standard training-step accounting: backward = 2x forward matmul
    work, so one optimizer step = 3x forward FLOPs."""
    return 3.0 * fwd_flops
