"""Post-training quantization (PTQ) for the inference surfaces.

``mxnet_tpu.quant`` turns loaded f32 checkpoints into low-precision
serving artifacts without retraining (ROADMAP "Quantized inference:
int8/fp8 weights + low-precision KV"):

- **Weights**: per-channel symmetric int8 or fp8-e4m3
  (``quantize_params`` — the math is ``ops.contrib.quantize_symmetric``,
  the same implementation behind the MXNet-parity ``contrib.quantize``
  op). Quantized weights travel as program *arguments* next to their
  ``<name>_scale`` arrays, exactly like ``DecodePrograms`` passes f32
  params today — so progcache keys stay weight-independent and a warm
  restart disk-loads quantized programs the same way it disk-loads f32
  ones (entries are stored under ``kind="quant"``).
- **Matmuls**: ``ops.matrix.quantized_matmul`` — either a native
  int8×int8 ``dot_general`` with dynamic per-row activation
  quantization (the MXU's double-rate int8 path; ``act_dtype="int8"``,
  the default) or dequant-on-load into a bf16/f32 GEMM
  (``act_dtype="bf16"``/``"float32"``; always used for fp8 weights).
- **Models**: ``quantize_decode_model`` rewrites a ``DecodeModel``'s
  projection/FFN/head weights in place of the f32 ones;
  ``QuantizedPredictor`` is the fixed-shape serving twin — params become
  program arguments (dequant-on-load inside the program, so XLA fuses
  the scale multiply into the GEMM read).

The default-OFF contract: nothing in this module runs unless a
``MXNET_QUANT_*`` knob or an explicit config asks for it, and the f32
paths it hooks are bitwise untouched when it is off (same pattern as
``MXNET_DECODE_PAGED``).
"""
from __future__ import annotations

import dataclasses
import os
import threading
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import predict as predict_mod
from . import progcache
from . import telemetry as _telemetry
from .analysis import compile_witness as _witness
from .base import MXNetError
from .ops.contrib import dequantize_symmetric, quantize_symmetric

#: canonical weight formats -> element bytes
WEIGHT_DTYPES = {"int8": 1, "fp8_e4m3": 1}
#: canonical KV-cache dtypes -> element bytes
KV_DTYPES = {"float32": 4, "bfloat16": 2, "int8": 1}

_WEIGHT_ALIASES = {"int8": "int8", "fp8": "fp8_e4m3", "fp8_e4m3": "fp8_e4m3",
                   "float8_e4m3": "fp8_e4m3"}
_ACT_ALIASES = {"int8": "int8", "bf16": "bf16", "bfloat16": "bf16",
                "f32": "float32", "fp32": "float32", "float32": "float32"}
_KV_ALIASES = {"f32": "float32", "fp32": "float32", "float32": "float32",
               "bf16": "bfloat16", "bfloat16": "bfloat16", "int8": "int8"}


def normalize_weight_dtype(name: str) -> str:
    try:
        return _WEIGHT_ALIASES[str(name).strip().lower()]
    except KeyError:
        raise MXNetError(
            "MXNET_QUANT_WEIGHT_DTYPE must be one of %s, got %r"
            % (sorted(set(_WEIGHT_ALIASES)), name))


def normalize_kv_dtype(name: str) -> str:
    """Canonicalize an ``MXNET_DECODE_KV_DTYPE`` spelling
    (f32|bf16|int8, long forms accepted)."""
    try:
        return _KV_ALIASES[str(name).strip().lower()]
    except KeyError:
        raise MXNetError(
            "MXNET_DECODE_KV_DTYPE must be one of %s, got %r"
            % (sorted(set(_KV_ALIASES)), name))


@dataclasses.dataclass(frozen=True)
class QuantConfig:
    """Weight-quantization knobs (``MXNET_QUANT_*`` env defaults read at
    construction, docs/env_var.md).

    ``weight_dtype``: int8 | fp8_e4m3. ``act_dtype`` selects the matmul
    strategy for int8 weights: "int8" (default — dynamic activation
    quantization + native int8 matmul) or "bf16"/"float32"
    (dequant-on-load). fp8 weights always run dequant-on-load.
    """
    weight_dtype: str = dataclasses.field(
        default_factory=lambda: os.environ.get(
            "MXNET_QUANT_WEIGHT_DTYPE", "int8"))
    act_dtype: str = dataclasses.field(
        default_factory=lambda: os.environ.get(
            "MXNET_QUANT_ACT_DTYPE", "int8"))

    def __post_init__(self):
        object.__setattr__(self, "weight_dtype",
                           normalize_weight_dtype(self.weight_dtype))
        act = str(self.act_dtype).strip().lower()
        if act not in _ACT_ALIASES:
            raise MXNetError(
                "MXNET_QUANT_ACT_DTYPE must be one of %s, got %r"
                % (sorted(set(_ACT_ALIASES)), self.act_dtype))
        object.__setattr__(self, "act_dtype", _ACT_ALIASES[act])


# --- telemetry (quant_params_bytes{dtype=...}, docs/observability.md) ------
_bytes_lock = threading.Lock()
_params_bytes: Dict[str, int] = {}


def _account_params_bytes(dtype: str, nbytes: int):
    with _bytes_lock:
        _params_bytes[dtype] = _params_bytes.get(dtype, 0) + int(nbytes)
        total = _params_bytes[dtype]
    _telemetry.registry.gauge(
        "quant_params_bytes", labels={"dtype": dtype},
        help="bytes held in quantized weight arrays, by target dtype"
    ).set(total)


def quant_params_bytes() -> Dict[str, int]:
    """Quantized-weight bytes accounted so far, by target dtype."""
    with _bytes_lock:
        return dict(_params_bytes)


# --- the PTQ pass ----------------------------------------------------------
def quantize_weight(w, weight_dtype: str = "int8", axis=0):
    """Per-channel symmetric quantization of one weight array. Returns
    ``(q, scale)`` with ``scale`` squeezed to the kept channel axes
    (e.g. (O, I) -> scale (O,); stacked (L, O, I) -> (L, O)). One math
    implementation: ``ops.contrib.quantize_symmetric``."""
    weight_dtype = normalize_weight_dtype(weight_dtype)
    q, scale = quantize_symmetric(jnp.asarray(w), weight_dtype, axis=axis)
    keep = sorted({a % q.ndim for a in
                   (axis if isinstance(axis, (tuple, list)) else (axis,))})
    return q, scale.reshape(tuple(q.shape[a] for a in keep))


def dequantize_weight(q, scale):
    """Widen a quantized weight back to f32: inverse of
    :func:`quantize_weight` (scale re-broadcast over the reduced axes —
    channel axes are assumed LEADING, the (L?, O, I) layouts used
    here)."""
    s = jnp.asarray(scale)
    s = s.reshape(s.shape + (1,) * (q.ndim - s.ndim))
    return dequantize_symmetric(q, s)


#: DecodeModel matmul weights the PTQ pass rewrites, with their channel
#: axes ((L, O, I) stacked -> (0, 1); flat (O, I) -> 0). embed stays f32
#: (it is a gather table, not a GEMM operand); norms/biases are tiny.
DECODE_QUANT_WEIGHTS = {
    "wq": (0, 1), "wk": (0, 1), "wv": (0, 1), "wo": (0, 1),
    "w1": (0, 1), "w2": (0, 1), "pred_w": 0,
}


def quantize_params(params: Dict[str, jnp.ndarray], names_axes: Dict,
                    weight_dtype: str = "int8") -> Dict[str, jnp.ndarray]:
    """Quantize ``names_axes`` entries of a param dict, returning a new
    dict where each quantized ``name`` is joined by ``name_scale`` —
    scales ride as sibling *arguments*, never closure constants, so
    program cache keys stay weight-independent."""
    weight_dtype = normalize_weight_dtype(weight_dtype)
    out = dict(params)
    qbytes = 0
    for name, axis in names_axes.items():
        if name not in params:
            raise MXNetError("quantize_params: no param %r" % name)
        q, scale = quantize_weight(params[name], weight_dtype, axis)
        out[name] = q
        out[name + "_scale"] = scale
        qbytes += int(np.prod(q.shape)) * WEIGHT_DTYPES[weight_dtype]
    _account_params_bytes(weight_dtype, qbytes)
    return out


def quantize_decode_model(model, config: Optional[QuantConfig] = None):
    """PTQ over a ``DecodeModel``: projection/FFN/head weights become
    int8/fp8 program arguments with per-channel scales; the returned
    model builds programs whose matmuls route through
    ``ops.matrix.quantized_matmul`` (``config.act_dtype`` strategy)."""
    from .serving.generate.model import DecodeModel

    config = config or QuantConfig()
    params = quantize_params(model.params, DECODE_QUANT_WEIGHTS,
                             config.weight_dtype)
    qm = DecodeModel(params, model.spec)
    qm.quant_act = config.act_dtype
    return qm


# --- quantized fixed-shape predictor ---------------------------------------
def quantizable_weights(symbol) -> List[str]:
    """Names of weight params feeding FullyConnected/Convolution weight
    slots — the GEMM operands worth quantizing. Channel axis is 0 (the
    (O, I...) orientation both ops use)."""
    names = []
    for node in symbol._nodes():
        if node.is_var or node.op.name not in ("FullyConnected",
                                               "Convolution"):
            continue
        if len(node.inputs) > 1:
            child, _idx = node.inputs[1]
            if child.is_var and child.name not in names:
                names.append(child.name)
    return names


class QuantizedPredictor(predict_mod.Predictor):
    """Predictor twin whose params are program ARGUMENTS (quantized
    weights + scales), not closure constants.

    The compiled program dequantizes each weight on load (the scale
    multiply fuses into the GEMM read), so accuracy tracks per-channel
    PTQ while weight bytes shrink 4x (int8/fp8). Because weights are
    arguments, the progcache key comes from the LOWERED StableHLO text —
    weight-independent, like ``DecodePrograms`` — and entries are stored
    under ``kind="quant"``.
    """

    def __init__(self, symbol_json: str, params,
                 input_shapes: Dict[str, tuple], dtype="float32",
                 device=None, qconfig: Optional[QuantConfig] = None):
        self._qconfig = qconfig or QuantConfig()
        super().__init__(symbol_json, params, input_shapes, dtype, device)

    def _quantize_params(self):
        """name -> f32 array | (q, scale) for every arg param, built once
        and shared across reshapes (the BucketCache ladder)."""
        qnames = set(quantizable_weights(self._symbol))
        qvals: Dict[str, object] = {}
        qbytes = 0
        for n, a in self._arg_params.items():
            w = a._data
            if n in qnames and w.ndim >= 2:
                q, scale = quantize_weight(
                    w, self._qconfig.weight_dtype, axis=0)
                qvals[n] = {"q": q, "scale": scale}
                qbytes += int(np.prod(q.shape)) * \
                    WEIGHT_DTYPES[self._qconfig.weight_dtype]
            else:
                qvals[n] = w
        _account_params_bytes(self._qconfig.weight_dtype, qbytes)
        return qvals

    def _compile(self):
        if not hasattr(self, "_qvals"):
            self._qvals = self._quantize_params()
        eval_fn = self._symbol.build_eval()
        input_names = self._input_names

        def fwd(qparams, aux_vals, *input_arrays):
            args = {}
            for n, v in qparams.items():
                if isinstance(v, dict):
                    # dequant-on-load: XLA fuses the widen+scale into the
                    # consuming GEMM's operand read
                    args[n] = dequantize_weight(v["q"], v["scale"])
                else:
                    args[n] = v
            args.update(dict(zip(input_names, input_arrays)))
            outs, _ = eval_fn(args, aux_vals, False, jax.random.PRNGKey(0))
            return tuple(outs)

        self._aux_vals = {n: a._data for n, a in self._aux_params.items()}
        self._jitted = jax.jit(fwd)
        aval = lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)  # noqa: E731
        qp_avals = jax.tree_util.tree_map(aval, self._qvals)
        aux_avals = jax.tree_util.tree_map(aval, self._aux_vals)
        in_specs = [jax.ShapeDtypeStruct(self._input_shapes[n],
                                         jnp.dtype(self._dtype))
                    for n in input_names]
        with self._device_scope():
            self._lowered = self._jitted.lower(qp_avals, aux_avals,
                                               *in_specs)
            cache_key = None
            if progcache.enabled():
                cache_key = progcache.lowered_key(
                    self._lowered.as_text(), donate=(),
                    extra="quant_predictor:%s:%s"
                    % (self._qconfig.weight_dtype, self._qconfig.act_dtype))
                loaded = progcache.load(cache_key, kind="quant")
                if loaded is not None:
                    self._exec = loaded
                    self.progcache_source = "disk"
                    predict_mod._DISK_LOAD_COUNT += 1
                    return
            self._exec = self._lowered.compile()
        predict_mod._COMPILE_COUNT += 1
        _witness.record_compile(
            "quant", key=cache_key or "",
            shapes=repr(sorted(self._input_shapes.items())))
        self.progcache_source = "compile"
        if cache_key is not None:
            progcache.store(cache_key, self._exec, note="quant_predictor",
                            kind="quant")

    def forward(self, **inputs):
        """MXPredForward over the argument-passing program (params +
        scales are leading args; same locking contract as Predictor)."""
        with self._run_lock:
            for k, v in inputs.items():
                self.set_input(k, v)
            vals = []
            for n in self._input_names:
                if self._inputs[n] is None:
                    raise MXNetError("input %r not set" % n)
                vals.append(
                    self._inputs[n]._data.astype(jnp.dtype(self._dtype)))
        with self._device_scope():
            if self._device is not None:
                vals = [jax.device_put(v, self._device) for v in vals]
            outs = self._exec(self._qvals, self._aux_vals, *vals)
        result = [predict_mod.NDArray(o) for o in outs]
        with self._run_lock:
            self._outputs = result
        return result

    def reshape(self, new_input_shapes: Dict[str, tuple],
                device=None) -> "QuantizedPredictor":
        """MXPredReshape sharing weights AND their quantization — the
        BucketCache ladder quantizes once, not once per bucket."""
        p = QuantizedPredictor.__new__(QuantizedPredictor)
        p._symbol = self._symbol
        p._arg_params = self._arg_params
        p._aux_params = self._aux_params
        p._input_names = list(new_input_shapes)
        p._input_shapes = {k: tuple(v) for k, v in new_input_shapes.items()}
        p._dtype = self._dtype
        p._device = device if device is not None else self._device
        p._inputs = {n: None for n in p._input_shapes}
        p._outputs = []
        p._run_lock = threading.RLock()
        p._qconfig = self._qconfig
        p._qvals = self._qvals
        fp = getattr(self, "_progcache_model_fp", None)
        if fp is not None:
            p._progcache_model_fp = fp
        p._compile()
        return p

    def export(self, path: str):
        raise MXNetError(
            "QuantizedPredictor.export is not supported — export the f32 "
            "Predictor and quantize at load time instead")


def quantize_predictor(predictor: predict_mod.Predictor,
                       config: Optional[QuantConfig] = None
                       ) -> QuantizedPredictor:
    """PTQ over an existing Predictor: rebind the same symbol/params as a
    :class:`QuantizedPredictor` at the same shapes/device."""
    return QuantizedPredictor(
        predictor._symbol.tojson(),
        {n: a for n, a in predictor._arg_params.items()} |
        {"aux:%s" % n: a for n, a in predictor._aux_params.items()},
        predictor._input_shapes, dtype=predictor._dtype,
        device=predictor._device, qconfig=config)
