"""Run PyTorch modules / criteria / functions as framework ops.

Capability parity with the reference's Torch plugin (python/mxnet/torch.py
Torch function+criterion wrappers, and plugin/torch/torch_module.cc's
TorchModule op — SURVEY §2.4, §2.5). The reference embeds a Lua Torch7
interpreter behind a native op; here the foreign-kernel seam is the Custom
op bridge (operator.py → jax.pure_callback), so a `torch.nn.Module`
executes on host inside an otherwise jit-compiled graph, with backward
supplied by torch autograd.

    import mxnet_tpu as mx
    import torch as th

    op = mx.torch.module_op(th.nn.Conv2d(3, 8, 3, padding=1), "th_conv")
    y = mx.nd.Custom(x, op_type=op)            # imperative
    s = mx.sym.Custom(data=d, op_type=op)      # symbolic

Everything is gated on torch being importable; the module degrades to a
clear error otherwise (the reference's plugin is likewise opt-in via
TORCH_PATH, make/config.mk).
"""
from __future__ import annotations

import threading
from typing import Callable, List, Optional, Sequence

import numpy as np

from .base import MXNetError
from . import operator as _operator
from . import ndarray as nd

try:  # torch (CPU build) is an optional host-side dependency
    import torch as _th
except ImportError:  # pragma: no cover
    _th = None


def _require_torch():
    if _th is None:  # pragma: no cover
        raise MXNetError(
            "mxnet_tpu.torch requires PyTorch; install torch (CPU is "
            "sufficient — it only runs host-side kernels)")
    return _th


# XLA's CPU runtime may invoke host callbacks concurrently from several
# execution threads, but torch autograd state (module parameters, .grad
# accumulation, tensor version counters) is not safe under concurrent
# forward/backward of the SAME module — symptoms range from
# "cannot call bump_version() on undefined tensor" to segfaults. One
# process-wide lock serializes every torch-op callback.
_TH_LOCK = threading.RLock()


def _to_torch(a: np.ndarray, requires_grad: bool):
    t = _th.from_numpy(np.ascontiguousarray(a))
    if requires_grad and t.is_floating_point():
        t = t.clone().requires_grad_(True)
    return t


class _TorchModuleOp(_operator.CustomOp):
    """CustomOp executing a torch.nn.Module; backward via torch autograd."""

    def __init__(self, module):
        self.module = module

    def forward(self, is_train, req, in_data, out_data, aux):
        with _TH_LOCK:
            xs = [_to_torch(np.asarray(x), False) for x in in_data]
            with _th.no_grad():
                out = self.module(*xs)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            for i, (dst, src) in enumerate(zip(out_data, outs)):
                self.assign(dst,
                            req[i] if isinstance(req, (list, tuple)) else req,
                            src.detach().numpy())

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        with _TH_LOCK:
            xs = [_to_torch(np.asarray(x), True) for x in in_data]
            out = self.module(*xs)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            gs = [_th.from_numpy(np.ascontiguousarray(np.asarray(g)))
                  for g in out_grad[:len(outs)]]
            _th.autograd.backward(list(outs), gs)
            for i, (dst, x) in enumerate(zip(in_grad, xs)):
                g = x.grad
                r = req[i] if isinstance(req, (list, tuple)) else req
                self.assign(dst, r,
                            g.numpy() if g is not None
                            else np.zeros_like(np.asarray(in_data[i])))
        # torch-side parameters train in place with torch's own grads; an
        # explicit torch optimizer step is the user's choice (the reference
        # likewise leaves Torch module weights to Torch, torch_module.cc)


class _TorchFunctionOp(_operator.CustomOp):
    """CustomOp for a pure torch function (autograd.grad for backward)."""

    def __init__(self, fn, num_outputs):
        self.fn = fn
        self.num_outputs = num_outputs

    def forward(self, is_train, req, in_data, out_data, aux):
        with _TH_LOCK:
            xs = [_to_torch(np.asarray(x), False) for x in in_data]
            with _th.no_grad():
                out = self.fn(*xs)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            for i, (dst, src) in enumerate(zip(out_data, outs)):
                r = req[i] if isinstance(req, (list, tuple)) else req
                self.assign(dst, r, src.detach().numpy())

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        with _TH_LOCK:
            xs = [_to_torch(np.asarray(x), True) for x in in_data]
            out = self.fn(*xs)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            gs = [_th.from_numpy(np.ascontiguousarray(np.asarray(g)))
                  for g in out_grad[:len(outs)]]
            diff = [x for x in xs if x.requires_grad]
            grads = (_th.autograd.grad(list(outs), diff, gs,
                                       allow_unused=True)
                     if diff else ())
            it = iter(grads)
            for i, (dst, x) in enumerate(zip(in_grad, xs)):
                r = req[i] if isinstance(req, (list, tuple)) else req
                if x.requires_grad:
                    g = next(it)
                    self.assign(dst, r,
                                g.numpy() if g is not None
                                else np.zeros_like(np.asarray(in_data[i])))
                else:
                    self.assign(dst, r,
                                np.zeros_like(np.asarray(in_data[i])))


def _infer_by_tracing(module_or_fn, in_shape, num_outputs):
    th = _require_torch()
    xs = [th.zeros(tuple(s)) for s in in_shape]
    with th.no_grad():
        out = module_or_fn(*xs)
    outs = out if isinstance(out, (tuple, list)) else (out,)
    return [list(o.shape) for o in outs[:num_outputs]]


def module_op(module, name: str, n_inputs: int = 1,
              num_outputs: int = 1) -> str:
    """Register `module` (a torch.nn.Module) as Custom op type `name`.
    Returns the op_type string for nd/sym.Custom. Output shapes are
    inferred by tracing the module on zeros (the reference's TorchModule
    declares them manually)."""
    _require_torch()
    mod = module

    @_operator.register(name)
    class _Prop(_operator.CustomOpProp):  # noqa: N801
        def __init__(self):
            super().__init__(need_top_grad=True)

        def list_arguments(self):
            return ["data%d" % i for i in range(n_inputs)]

        def list_outputs(self):
            return (["output"] if num_outputs == 1 else
                    ["output%d" % i for i in range(num_outputs)])

        def infer_shape(self, in_shape):
            out = _infer_by_tracing(mod, in_shape, num_outputs)
            return in_shape, out, []

        def create_operator(self, ctx, in_shapes, in_dtypes):
            return _TorchModuleOp(mod)

    return name


def function_op(fn: Callable, name: str, n_inputs: int = 1,
                num_outputs: int = 1) -> str:
    """Register a pure torch function (e.g. `torch.special.logit`, or any
    composition) as Custom op type `name` — the reference's torch function
    wrappers (python/mxnet/torch.py)."""
    _require_torch()

    @_operator.register(name)
    class _Prop(_operator.CustomOpProp):  # noqa: N801
        def __init__(self):
            super().__init__(need_top_grad=True)

        def list_arguments(self):
            return ["data%d" % i for i in range(n_inputs)]

        def list_outputs(self):
            return (["output"] if num_outputs == 1 else
                    ["output%d" % i for i in range(num_outputs)])

        def infer_shape(self, in_shape):
            out = _infer_by_tracing(fn, in_shape, num_outputs)
            return in_shape, out, []

        def create_operator(self, ctx, in_shapes, in_dtypes):
            return _TorchFunctionOp(fn, num_outputs)

    return name


def criterion_op(criterion, name: str) -> str:
    """Register a torch criterion (loss(input, target) -> scalar) as a
    2-input Custom op (the reference's TorchCriterion wrappers)."""
    _require_torch()

    def fn(x, t):
        return criterion(x, t)

    @_operator.register(name)
    class _Prop(_operator.CustomOpProp):  # noqa: N801
        def __init__(self):
            super().__init__(need_top_grad=False)

        def list_arguments(self):
            return ["data", "label"]

        def list_outputs(self):
            return ["output"]

        def infer_shape(self, in_shape):
            return in_shape, [[1]], []

        def create_operator(self, ctx, in_shapes, in_dtypes):
            return _TorchCriterionOp(criterion)

    return name


class _TorchCriterionOp(_operator.CustomOp):
    def __init__(self, criterion):
        self.criterion = criterion

    def forward(self, is_train, req, in_data, out_data, aux):
        # under _TH_LOCK like the module/function ops: torch callbacks may
        # be replayed from concurrent engine workers and libtorch autograd
        # state is not re-entrant from our side
        with _TH_LOCK:
            x = _to_torch(np.asarray(in_data[0]), False)
            t = _to_torch(np.asarray(in_data[1]), False)
            with _th.no_grad():
                loss = self.criterion(x, t)
            self.assign(out_data[0],
                        req[0] if isinstance(req, (list, tuple)) else req,
                        np.asarray([float(loss)], np.float32))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        with _TH_LOCK:
            x = _to_torch(np.asarray(in_data[0]), True)
            t = _to_torch(np.asarray(in_data[1]), False)
            loss = self.criterion(x, t)
            loss.backward()
            r0 = req[0] if isinstance(req, (list, tuple)) else req
            self.assign(in_grad[0], r0, x.grad.numpy())
            if len(in_grad) > 1:
                r1 = req[1] if isinstance(req, (list, tuple)) else req
                self.assign(in_grad[1], r1,
                            np.zeros_like(np.asarray(in_data[1])))
