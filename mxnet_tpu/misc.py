"""Deprecated learning-rate scheduler interface.

Capability parity with python/mxnet/misc.py (reference :7-56): the
pre-`lr_scheduler.py` scheduler classes, kept for old user code. New code
should use :mod:`mxnet_tpu.lr_scheduler`.
"""
from __future__ import annotations


class LearningRateScheduler(object):
    """Base class of the deprecated scheduler interface
    (reference misc.py:7-23)."""

    def __init__(self):
        self.base_lr = 0.01

    def __call__(self, iteration):
        raise NotImplementedError("must override this")


class FactorScheduler(LearningRateScheduler):
    """Reduce lr by factor every ``step`` iterations
    (reference misc.py:24-56)."""

    def __init__(self, step, factor=0.1):
        super().__init__()
        if step < 1:
            raise ValueError("Schedule step must be greater or equal than 1")
        if factor >= 1.0:
            raise ValueError("Factor must be less than 1 to make lr reduce")
        self.step = step
        self.factor = factor
        self.old_lr = None

    def __call__(self, iteration):
        import logging
        lr = self.base_lr * (self.factor ** (iteration // self.step))
        if lr != self.old_lr:
            self.old_lr = lr
            logging.info("At Iteration [%d]: Swith to new learning rate %.5f",
                         iteration, lr)
        return lr
