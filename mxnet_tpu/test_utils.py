"""Testing utilities.

TPU-native port of the reference's verification harness
(python/mxnet/test_utils.py): numeric gradient checking by central
differences (test_utils.py:360 check_numeric_gradient), symbolic
forward/backward checks (:473, :538), and cross-device consistency
(:705 check_consistency) where the "devices" are XLA cpu/tpu backends.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from . import ndarray as nd
from .context import Context, cpu, default_context
from .base import MXNetError


def default_dtype():
    return np.float32


def rand_ndarray(shape, ctx=None, dtype=np.float32):
    return nd.array(np.random.uniform(-1.0, 1.0, size=shape).astype(dtype), ctx=ctx)


def same(a, b):
    return np.array_equal(a, b)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-8, names=("a", "b")):
    a = a.asnumpy() if isinstance(a, nd.NDArray) else np.asarray(a)
    b = b.asnumpy() if isinstance(b, nd.NDArray) else np.asarray(b)
    np.testing.assert_allclose(a, b, rtol=rtol, atol=atol,
                               err_msg="%s != %s" % names)


def _as_shape_dict(sym, location):
    if isinstance(location, dict):
        return {k: np.asarray(v.asnumpy() if isinstance(v, nd.NDArray) else v, dtype=np.float32)
                if not isinstance(v, np.ndarray) else v for k, v in location.items()}
    names = sym.list_arguments()
    return dict(zip(names, [np.asarray(v.asnumpy() if isinstance(v, nd.NDArray) else v) for v in location]))


def _bind(sym, location, aux=None, grad_req="write", ctx=None):
    ctx = ctx or default_context()
    args = {k: nd.array(v, ctx=ctx) for k, v in location.items()}
    grads = {k: nd.zeros(v.shape, ctx=ctx) for k, v in location.items()} if grad_req != "null" else None
    aux_states = {k: nd.array(v, ctx=ctx) for k, v in (aux or {}).items()}
    if aux_states:
        missing = [n for n in sym.list_auxiliary_states() if n not in aux_states]
    else:
        aux_names = sym.list_auxiliary_states()
        if aux_names:
            shapes = {k: v.shape for k, v in location.items()}
            _, _, aux_shapes = sym.infer_shape(**shapes)
            aux_states = {n: nd.zeros(s, ctx=ctx) for n, s in zip(aux_names, aux_shapes)}
    return sym.bind(ctx, args, grads, grad_req, aux_states)


def check_symbolic_forward(sym, location, expected, rtol=1e-4, atol=1e-6,
                           aux_states=None, ctx=None):
    """Run forward and compare against expected numpy outputs
    (reference test_utils.py:473)."""
    location = _as_shape_dict(sym, location)
    exe = _bind(sym, location, aux_states, "null", ctx)
    outputs = exe.forward(is_train=False)
    for out, exp in zip(outputs, expected):
        assert_almost_equal(out, exp, rtol, atol)
    return [o.asnumpy() for o in outputs]


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-4,
                            atol=1e-6, aux_states=None, grad_req="write", ctx=None):
    """Run backward with given head grads and compare input grads
    (reference test_utils.py:538)."""
    location = _as_shape_dict(sym, location)
    exe = _bind(sym, location, aux_states, grad_req, ctx)
    exe.forward(is_train=True)
    exe.backward([nd.array(g) for g in out_grads])
    if isinstance(expected, dict):
        for name, exp in expected.items():
            assert_almost_equal(exe.grad_dict[name], exp, rtol, atol, names=(name, "expected"))
    else:
        for g, exp in zip(exe.grad_arrays, expected):
            if exp is not None:
                assert_almost_equal(g, exp, rtol, atol)
    return {k: v.asnumpy() for k, v in exe.grad_dict.items()}


def check_numeric_gradient(sym, location, aux_states=None, numeric_eps=1e-3,
                           rtol=1e-2, atol=None, grad_nodes=None, ctx=None):
    """Central-difference gradient check (reference test_utils.py:360).

    Computes analytic grads via the executor's fused backward, then perturbs
    each input elementwise to form the numeric estimate.
    """
    location = _as_shape_dict(sym, location)
    grad_nodes = grad_nodes or list(location.keys())
    exe = _bind(sym, location, aux_states, grad_req={"write": "write"} and
                {k: ("write" if k in grad_nodes else "null") for k in location}, ctx=ctx)
    exe.forward(is_train=True)
    out_shapes = [o.shape for o in exe.outputs]
    head_grads = [nd.array(np.random.normal(0, 0.01, size=s).astype(np.float32)) for s in out_shapes]
    exe.backward(head_grads)
    analytic = {k: exe.grad_dict[k].asnumpy().copy() for k in grad_nodes}

    def eval_sum(loc):
        exe2 = _bind(sym, loc, aux_states, "null", ctx)
        outs = exe2.forward(is_train=True)
        return sum(float(np.sum(o.asnumpy() * g.asnumpy())) for o, g in zip(outs, head_grads))

    for name in grad_nodes:
        base_val = location[name]
        numeric = np.zeros_like(base_val, dtype=np.float64)
        flat = base_val.reshape(-1)
        num_flat = numeric.reshape(-1)
        for i in range(flat.size):
            old = flat[i]
            flat[i] = old + numeric_eps
            fplus = eval_sum(location)
            flat[i] = old - numeric_eps
            fminus = eval_sum(location)
            flat[i] = old
            num_flat[i] = (fplus - fminus) / (2 * numeric_eps)
        a = analytic[name]
        atol_eff = atol if atol is not None else 1e-3
        np.testing.assert_allclose(
            a, numeric.astype(a.dtype), rtol=rtol, atol=atol_eff,
            err_msg="numeric gradient mismatch for %s" % name,
        )


def check_consistency(sym, ctx_list, scale=1.0, grad_req="write", rtol=1e-3, atol=1e-4):
    """Run the same symbol on several contexts and compare outputs & grads
    (reference test_utils.py:705) — cpu vs tpu backends here."""
    shapes = ctx_list[0]["shapes"] if "shapes" in ctx_list[0] else None
    results = []
    for spec in ctx_list:
        ctx = spec["ctx"]
        shape_kwargs = {k: v for k, v in spec.items() if k != "ctx"}
        arg_shapes, _, aux_shapes = sym.infer_shape(**shape_kwargs)
        rng = np.random.RandomState(0)
        location = {
            n: (rng.normal(0, scale, size=s)).astype(np.float32)
            for n, s in zip(sym.list_arguments(), arg_shapes)
        }
        exe = _bind(sym, location, None, grad_req, ctx)
        exe.forward(is_train=True)
        exe.backward([nd.array(np.ones(o.shape, np.float32), ctx=ctx) for o in exe.outputs])
        results.append((
            [o.asnumpy() for o in exe.outputs],
            {k: v.asnumpy() for k, v in exe.grad_dict.items()},
        ))
    ref_outs, ref_grads = results[0]
    for outs, grads in results[1:]:
        for a, b in zip(ref_outs, outs):
            np.testing.assert_allclose(a, b, rtol=rtol, atol=atol)
        for k in ref_grads:
            np.testing.assert_allclose(ref_grads[k], grads[k], rtol=rtol, atol=atol)
    return results


def synthetic_digits(n, flat=True, noise=0.3, seed=0, num_classes=10):
    """Seeded MNIST-stand-in: 10 gaussian blobs in 28x28 pixel space
    (zero-egress CI has no real MNIST; the reference's convergence bars
    — tests/python/train/test_mlp.py:65 acc>0.95 — are applied to this
    deterministic task instead). Returns (X, y): X is (n, 784) when
    flat else (n, 1, 28, 28), y is int labels. Shared by the
    train_mnist example, tests/test_convergence.py, and
    tests/test_models.py so the task cannot drift between them."""
    rng = np.random.RandomState(seed)
    centers = rng.uniform(0, 1, (num_classes, 28 * 28)).astype(np.float32)
    y = rng.randint(0, num_classes, n)
    X = centers[y] + noise * rng.randn(n, 28 * 28).astype(np.float32)
    if not flat:
        X = X.reshape(n, 1, 28, 28)
    return X, y
