"""Legacy data-parallel executor management (pre-Module API).

Parity with python/mxnet/executor_manager.py (SURVEY §2.4): the
`FeedForward` estimator's device-management layer — `_split_input_slice`
(workload-weighted batch slicing, executor_manager.py:14) and
`DataParallelExecutorManager` which binds one executor per context and
fans a batch out / gradients back.

TPU-native note: the modern path (module/executor_group.py) shards the
batch over a jax mesh in ONE executor, which splits evenly by
construction; non-uniform work_load_list values are therefore reported
(warning + even slices) rather than honored — on a homogeneous TPU mesh
uneven device weighting has no use. `_split_input_slice` itself keeps the
reference's exact weighted-slice arithmetic for callers that shard on the
host. Binding delegates to DataParallelExecutorGroup.
"""
from __future__ import annotations

import logging
from typing import List, Optional, Sequence

import numpy as np

from .base import MXNetError
from .io import DataDesc
from .module.executor_group import DataParallelExecutorGroup


def _split_input_slice(batch_size: int,
                       work_load_list: Sequence[float]) -> List[slice]:
    """Split batch_size into per-device slices proportional to the
    workload weights (reference _split_input_slice,
    executor_manager.py:14-43). Raises if a device would get 0 rows."""
    total = sum(work_load_list)
    if total <= 0:
        raise MXNetError("invalid work load list %r" % (work_load_list,))
    slices = []
    start = 0
    acc = 0.0
    for i, w in enumerate(work_load_list):
        acc += w
        end = (batch_size if i == len(work_load_list) - 1
               else int(round(batch_size * acc / total)))
        if end <= start:
            raise MXNetError(
                "too many slices: batch size %d cannot cover workload %r"
                % (batch_size, work_load_list))
        slices.append(slice(start, end))
        start = end
    return slices


def _check_arguments(symbol):
    """Reject duplicated argument/aux names (reference _check_arguments)."""
    arg_names = symbol.list_arguments()
    if len(set(arg_names)) != len(arg_names):
        dup = [n for n in arg_names if arg_names.count(n) > 1]
        raise MXNetError("find duplicated argument name %r" % (dup,))
    aux_names = symbol.list_auxiliary_states()
    if len(set(aux_names)) != len(aux_names):
        dup = [n for n in aux_names if aux_names.count(n) > 1]
        raise MXNetError("find duplicated auxiliary name %r" % (dup,))


class DataParallelExecutorManager:
    """Helper to manage multiple executors for data parallelism (reference
    executor_manager.py:195 DataParallelExecutorManager). Used by the
    legacy FeedForward path; Module uses DataParallelExecutorGroup
    directly."""

    def __init__(self, symbol, ctx, train_data, arg_names=None,
                 param_names=None, aux_names=None, work_load_list=None,
                 logger=None, sym_gen=None):
        self.logger = logger or logging
        self.symbol = symbol
        self.ctx = ctx if isinstance(ctx, (list, tuple)) else [ctx]
        self.sym_gen = sym_gen
        _check_arguments(symbol)

        if work_load_list is None:
            work_load_list = [1.0] * len(self.ctx)
        if len(work_load_list) != len(self.ctx):
            raise MXNetError("Invalid setting for work load.")
        self.work_load_list = list(work_load_list)

        batch_size = train_data.provide_data[0][1][0] \
            if not hasattr(train_data.provide_data[0], "shape") \
            else train_data.provide_data[0].shape[0]
        if len(set(self.work_load_list)) > 1:
            self.logger.warning(
                "non-uniform work_load_list %r is not honored: the mesh-"
                "sharded executor splits the batch evenly across devices",
                self.work_load_list)
            self.slices = _split_input_slice(batch_size,
                                             [1.0] * len(self.ctx))
        else:
            self.slices = _split_input_slice(batch_size, self.work_load_list)

        self.arg_names = arg_names or symbol.list_arguments()
        self.aux_names = aux_names or symbol.list_auxiliary_states()
        data_names = [d[0] if isinstance(d, tuple) else d.name
                      for d in train_data.provide_data]
        label_names = [d[0] if isinstance(d, tuple) else d.name
                       for d in train_data.provide_label]
        if param_names is None:
            param_names = [n for n in self.arg_names
                           if n not in data_names + label_names]
        self.param_names = list(param_names)

        def _desc(d):
            if isinstance(d, tuple):
                return DataDesc(d[0], d[1])
            return d

        self.execgrp = DataParallelExecutorGroup(
            symbol, self.ctx, self.work_load_list,
            [_desc(d) for d in train_data.provide_data],
            [_desc(d) for d in train_data.provide_label],
            self.param_names, for_training=True, inputs_need_grad=False,
            logger=self.logger)
        self._monitor = None

    # ---- parameter plumbing (reference :268-306) -------------------------
    def install_monitor(self, monitor):
        self._monitor = monitor
        self.execgrp.install_monitor(monitor)

    def set_params(self, arg_params, aux_params):
        self.execgrp.set_params(arg_params, aux_params)

    def copy_to(self, arg_params, aux_params):
        """Copy current (possibly averaged over devices) params out."""
        self.execgrp.get_params(arg_params, aux_params)

    @property
    def param_arrays(self):
        return self.execgrp.param_arrays

    @property
    def grad_arrays(self):
        return self.execgrp.grad_arrays

    @property
    def aux_arrays(self):
        return self.execgrp.aux_arrays if hasattr(self.execgrp, "aux_arrays") \
            else []

    # ---- per-batch flow (reference :308-343) -----------------------------
    def load_data_batch(self, data_batch):
        # the actual host->device transfer happens once, inside
        # execgrp.forward (executor_group._load_data)
        self._cur_batch = data_batch

    def forward(self, is_train=False):
        self.execgrp.forward(self._cur_batch, is_train=is_train)

    def backward(self):
        self.execgrp.backward()

    def update_metric(self, metric, labels):
        self.execgrp.update_metric(metric, labels)
