"""Runtime-compiled user kernels (mx.rtc).

TPU-native redesign of the reference's NVRTC wrapper (include/mxnet/mxrtc.h,
src/common/mxrtc.cc, python/mxnet/rtc.py — SURVEY §2.1 #31): the reference
compiles user CUDA C strings to device kernels at runtime, cached by source.
The TPU-native analogue compiles user **Pallas** kernel source at runtime:
the user hands over Python source defining a function ``kernel(...)`` whose
parameters are input refs followed by output refs; we exec it, wrap it in
``pl.pallas_call`` (interpret mode off-TPU), jit, and cache by source hash —
the same cache-by-source discipline as MXRtc (mxrtc.h:26-40).

    rtc = mx.rtc.Rtc('axpy', ['x', 'y'], ['out'], '''
    def kernel(x_ref, y_ref, out_ref):
        out_ref[...] = x_ref[...] * 2.0 + y_ref[...]
    ''')
    rtc.push([x, y], [out])     # reference Rtc.push(ins, outs, grid, block)

Plain-jax fallback: source may instead define ``fn(*arrays) -> arrays`` and
be created with ``mode='jax'`` — runtime codegen without the kernel DSL.
"""
from __future__ import annotations

import hashlib
import textwrap
from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from .base import MXNetError
from .ndarray import NDArray

_CACHE: Dict[str, "Rtc"] = {}


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


class Rtc:
    """A runtime-compiled kernel (reference python/mxnet/rtc.py Rtc).

    ``input_names``/``output_names`` document the signature; the compiled
    callable takes ``len(input_names)`` arrays and writes
    ``len(output_names)`` outputs whose shapes/dtypes are taken from the
    ``outputs`` NDArrays passed to :meth:`push` (the reference also sizes
    outputs from the bound NDArrays, mxrtc.h Push)."""

    def __init__(self, name: str, input_names: Sequence[str],
                 output_names: Sequence[str], src: str, mode: str = "pallas"):
        self.name = name
        self.input_names = list(input_names)
        self.output_names = list(output_names)
        self.src = textwrap.dedent(src)
        self.mode = mode
        if mode not in ("pallas", "jax"):
            raise MXNetError("rtc mode must be 'pallas' or 'jax'")
        ns: Dict = {"jnp": jnp, "jax": jax}
        if mode == "pallas":
            from jax.experimental import pallas as pl

            ns["pl"] = pl
        try:
            exec(compile(self.src, "<mx.rtc:%s>" % name, "exec"), ns)
        except Exception as e:
            raise MXNetError("rtc source failed to compile: %s" % e) from e
        entry = "kernel" if mode == "pallas" else "fn"
        if entry not in ns:
            raise MXNetError(
                "rtc source must define a function named %r" % entry)
        self._user_fn = ns[entry]
        self._compiled: Dict[Tuple, "jax.stages.Wrapped"] = {}

    def _get_compiled(self, out_specs):
        key = tuple(out_specs)
        fn = self._compiled.get(key)
        if fn is not None:
            return fn
        if self.mode == "pallas":
            from jax.experimental import pallas as pl

            user = self._user_fn
            call = pl.pallas_call(
                user,
                out_shape=[jax.ShapeDtypeStruct(s, d) for s, d in out_specs],
                interpret=not _on_tpu(),
            )
            fn = jax.jit(lambda *ins: call(*ins))
        else:
            fn = jax.jit(self._user_fn)
        self._compiled[key] = fn
        return fn

    def push(self, ins: Sequence[NDArray], outs: Sequence[NDArray],
             grid_dims=None, block_dims=None):
        """Run the kernel (reference Rtc.push). ``grid_dims``/``block_dims``
        are accepted for API parity and ignored — grid/tiling on TPU comes
        from the kernel's own pallas grid spec, not a launch config."""
        if len(ins) != len(self.input_names):
            raise MXNetError("%s expects %d inputs, got %d"
                             % (self.name, len(self.input_names), len(ins)))
        if len(outs) != len(self.output_names):
            raise MXNetError("%s expects %d outputs, got %d"
                             % (self.name, len(self.output_names), len(outs)))
        out_specs = [(tuple(o.shape), o._data.dtype) for o in outs]
        fn = self._get_compiled(out_specs)
        results = fn(*[x._data for x in ins])
        if not isinstance(results, (list, tuple)):
            results = [results]
        for o, r in zip(outs, results):
            o._data = r
        return outs


def create(name: str, input_names, output_names, src: str,
           mode: str = "pallas") -> Rtc:
    """Compile (or fetch cached) — reference MXRtcCreate + source cache."""
    key = hashlib.sha1(
        ("%s|%s|%s" % (name, mode, src)).encode()).hexdigest()
    rtc = _CACHE.get(key)
    if rtc is None:
        rtc = Rtc(name, input_names, output_names, src, mode)
        _CACHE[key] = rtc
    return rtc
