"""Host-side dependency engine.

Python surface over the native scheduler (native/engine.cc) — the
TPU-native counterpart of the reference's Engine singleton
(include/mxnet/engine.h:75-250, src/engine/threaded_engine*.cc,
SURVEY §2.1 #1-5).

Division of labor (SURVEY §7): *device* work is ordered by XLA's async
runtime — jax.Array dispatch is already the reference NDArray's
engine-var pipelining (`.block_until_ready()` ≡ WaitToRead). This engine
orders the HOST work XLA cannot see: checkpoint/file IO, data-pipeline
stages, parameter-server-style updates, metric sinks. Semantics are the
reference's: closures tagged with const (read) / mutable (write) variable
sets; conflicting ops serialize in push order, independent ops run
concurrently on a native worker pool.

Selection mirrors MXNET_ENGINE_TYPE (src/engine/engine.cc:13-38):
``ThreadedEngine`` (default) or ``NaiveEngine`` (fully synchronous, for
debugging — the reference's own advice, threaded_engine.h:326-338).

    from mxnet_tpu import engine
    v = engine.new_variable()
    engine.push(lambda: write_file(...), mutable_vars=[v])
    engine.push(lambda: read_file(...), const_vars=[v])   # ordered after
    engine.wait_for_all()
"""
from __future__ import annotations

import ctypes
import json
import os
import threading
import traceback
from typing import Callable, Dict, Optional, Sequence

from . import telemetry as _telemetry
from .base import MXNetError

_OPR_FN = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_void_p)
_DEL_FN = ctypes.CFUNCTYPE(None, ctypes.c_void_p)


def _load_native() -> Optional[ctypes.CDLL]:
    from . import native as _native

    # reuse the shared build machinery; the engine lib sits next to the io lib
    so = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                      "native", "libmxtpu_engine.so")
    if not os.path.exists(so):
        try:
            import subprocess

            subprocess.run(["make", "-C", os.path.dirname(so),
                            "libmxtpu_engine.so"], check=True,
                           capture_output=True, timeout=120)
        except Exception:
            return None
    try:
        lib = ctypes.CDLL(so)
    except OSError:
        return None
    lib.mxe_create.restype = ctypes.c_void_p
    lib.mxe_create.argtypes = [ctypes.c_int, ctypes.c_int]
    lib.mxe_destroy.argtypes = [ctypes.c_void_p]
    lib.mxe_new_var.restype = ctypes.c_int64
    lib.mxe_new_var.argtypes = [ctypes.c_void_p]
    lib.mxe_delete_var.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.mxe_push.argtypes = [
        ctypes.c_void_p, _OPR_FN, ctypes.c_void_p, _DEL_FN,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
    lib.mxe_opr_complete.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.mxe_wait_for_var.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.mxe_wait_for_all.argtypes = [ctypes.c_void_p]
    lib.mxe_pending.restype = ctypes.c_int
    lib.mxe_pending.argtypes = [ctypes.c_void_p]
    lib.mxe_set_profiling.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.mxe_dump_profile.restype = ctypes.c_int64
    lib.mxe_dump_profile.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_int64]
    return lib


class NativeEngine:
    """ctypes wrapper over native/engine.cc."""

    def __init__(self, num_workers=0, engine_type="ThreadedEngine"):
        self._lib = _load_native()
        if self._lib is None:
            raise MXNetError("native engine library unavailable")
        etype = 1 if engine_type == "NaiveEngine" else 0
        self._h = self._lib.mxe_create(num_workers, etype)
        self._pending: Dict[int, tuple] = {}
        self._pending_lock = threading.Lock()
        self._next_key = [1]
        # single C trampoline for every op; param = key into _pending
        self._trampoline = _OPR_FN(self._dispatch)
        self._no_del = ctypes.cast(None, _DEL_FN)

    def _dispatch(self, param, on_complete):
        key = int(param)
        with self._pending_lock:
            fn, is_async, name, t_q, const_vars, mutable_vars = \
                self._pending.pop(key)
        # t_q was stamped at push time iff the engine span domain was on;
        # queue wait = dispatch time - push time. Worker thread identity
        # rides for free on the per-thread telemetry buffer; an async op's
        # end() records the completing thread as end_tid.
        span_args = None
        if t_q and _telemetry.enabled("engine"):
            span_args = {"queue_us": (_telemetry.clock_ns() - t_q) // 1000,
                         "const_vars": list(const_vars),
                         "mutable_vars": list(mutable_vars)}
        tok = None
        try:
            if is_async:
                h = ctypes.c_void_p(on_complete)
                if span_args is not None:
                    tok = _telemetry.begin(name, domain="engine", **span_args)

                def complete(_h=h, _tok=tok):
                    _telemetry.end(_tok)
                    self._lib.mxe_opr_complete(self._h, _h)

                fn(complete)
            else:
                if span_args is not None:
                    with _telemetry.span(name, domain="engine", **span_args):
                        fn()
                else:
                    fn()
        except Exception:  # never let an exception cross the C boundary
            traceback.print_exc()
            if is_async:
                _telemetry.end(tok, error=True)
                self._lib.mxe_opr_complete(self._h, ctypes.c_void_p(on_complete))

    def new_variable(self) -> int:
        return self._lib.mxe_new_var(self._h)

    def delete_variable(self, var: int):
        self._lib.mxe_delete_var(self._h, var)

    def _push(self, fn, const_vars, mutable_vars, priority, name, is_async):
        const_vars, mutable_vars = _dedup(const_vars, mutable_vars)
        t_q = _telemetry.clock_ns() if _telemetry.enabled("engine") else 0
        with self._pending_lock:
            key = self._next_key[0]
            self._next_key[0] += 1
            self._pending[key] = (fn, is_async, name, t_q,
                                  tuple(const_vars), tuple(mutable_vars))
        c = (ctypes.c_int64 * max(len(const_vars), 1))(*const_vars)
        m = (ctypes.c_int64 * max(len(mutable_vars), 1))(*mutable_vars)
        self._lib.mxe_push(self._h, self._trampoline, ctypes.c_void_p(key),
                           self._no_del, c, len(const_vars), m,
                           len(mutable_vars), priority, name.encode(),
                           1 if is_async else 0)

    def push(self, fn: Callable[[], None], const_vars: Sequence[int] = (),
             mutable_vars: Sequence[int] = (), priority: int = 0,
             name: str = "op"):
        """PushSync (engine.h:198-208): fn runs on a worker; completion is
        automatic on return."""
        self._push(fn, const_vars, mutable_vars, priority, name, False)

    def push_async(self, fn: Callable[[Callable[[], None]], None],
                   const_vars: Sequence[int] = (),
                   mutable_vars: Sequence[int] = (), priority: int = 0,
                   name: str = "op"):
        """PushAsync (engine.h:158-170): fn receives an ``on_complete``
        callable it must invoke (from any thread) when the op finishes."""
        self._push(fn, const_vars, mutable_vars, priority, name, True)

    def wait_for_var(self, var: int):
        self._lib.mxe_wait_for_var(self._h, var)

    def wait_for_all(self):
        self._lib.mxe_wait_for_all(self._h)

    def pending(self) -> int:
        return self._lib.mxe_pending(self._h)

    def set_profiling(self, on: bool):
        self._lib.mxe_set_profiling(self._h, int(on))

    def dump_profile(self) -> dict:
        n = self._lib.mxe_dump_profile(self._h, None, 0)
        buf = ctypes.create_string_buffer(n + 16)
        self._lib.mxe_dump_profile(self._h, buf, n + 16)
        return json.loads(buf.value.decode())

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.mxe_destroy(self._h)
                self._h = None
        except Exception:
            pass


class PythonEngine:
    """Pure-Python fallback honoring the API. ``NaiveEngine`` (the default
    here) runs everything inline, like naive_engine.cc. ``ThreadedEngine``
    drains a FIFO on one daemon worker: ops still run in push order
    (conservative — as if every op conflicted on a variable), but the
    pushing thread is NOT blocked, so host pipelines (async checkpoint
    writes, the serving batcher/dispatch split) overlap with the caller
    even when the native library is unavailable."""

    def __init__(self, num_workers=0, engine_type="NaiveEngine"):
        self._next = 1
        self._prof = []
        self._profiling = False
        self._queue = None
        if engine_type != "NaiveEngine":
            import queue

            self._queue = queue.Queue()
            threading.Thread(target=self._worker, daemon=True,
                             name="mxtpu-py-engine").start()

    def _worker(self):
        while True:
            fn = self._queue.get()
            try:
                fn()
            except Exception:  # never kill the worker loop
                traceback.print_exc()
            finally:
                self._queue.task_done()

    def new_variable(self):
        self._next += 1
        return self._next - 1

    def delete_variable(self, var):
        pass

    def _run_profiled(self, fn, name, t_q=0):
        import time

        t0 = time.time()
        if t_q and _telemetry.enabled("engine"):
            with _telemetry.span(
                    name, domain="engine",
                    queue_us=(_telemetry.clock_ns() - t_q) // 1000):
                fn()
        else:
            fn()
        if self._profiling:
            self._prof.append({"name": name, "ph": "X", "pid": 0, "tid": 0,
                               "ts": int(t0 * 1e6),
                               "dur": int((time.time() - t0) * 1e6)})

    def push(self, fn, const_vars=(), mutable_vars=(), priority=0, name="op"):
        t_q = _telemetry.clock_ns() if _telemetry.enabled("engine") else 0
        if self._queue is not None:
            self._queue.put(lambda: self._run_profiled(fn, name, t_q))
        else:
            self._run_profiled(fn, name, t_q)

    def push_async(self, fn, const_vars=(), mutable_vars=(), priority=0,
                   name="op"):
        t_q = _telemetry.clock_ns() if _telemetry.enabled("engine") else 0

        def run():
            done = threading.Event()
            fn(done.set)
            done.wait()  # hold the FIFO slot until on_complete fires

        if self._queue is not None:
            self._queue.put(lambda: self._run_profiled(run, name, t_q))
        else:
            self._run_profiled(run, name, t_q)

    def wait_for_var(self, var):
        # conservative: the FIFO admits no reordering, so draining it is a
        # correct (if coarse) WaitForVar
        if self._queue is not None:
            self._queue.join()

    def wait_for_all(self):
        if self._queue is not None:
            self._queue.join()

    def pending(self):
        return self._queue.unfinished_tasks if self._queue is not None else 0

    def set_profiling(self, on):
        self._profiling = bool(on)

    def dump_profile(self):
        return {"traceEvents": list(self._prof)}


def _dedup(const_vars, mutable_vars):
    """DeduplicateVarHandle (engine.h:231-249): drop repeats; a var that is
    both read and mutated is tracked as mutable only."""
    mut = list(dict.fromkeys(mutable_vars))
    mset = set(mut)
    const = [v for v in dict.fromkeys(const_vars) if v not in mset]
    return const, mut


_engine = None
_engine_lock = threading.Lock()


def get() -> "NativeEngine | PythonEngine":
    """Engine.Get() singleton (engine.h:211). Type from MXNET_ENGINE_TYPE."""
    global _engine
    with _engine_lock:
        if _engine is None:
            etype = os.environ.get("MXNET_ENGINE_TYPE", "ThreadedEngine")
            workers = int(os.environ.get("MXNET_CPU_WORKER_NTHREADS", "0"))
            try:
                _engine = NativeEngine(workers, etype)
            except MXNetError:
                _engine = PythonEngine(workers, etype)
        return _engine


# module-level conveniences mirroring the reference's C API surface
def new_variable():
    return get().new_variable()


def delete_variable(var):
    get().delete_variable(var)


def push(fn, const_vars=(), mutable_vars=(), priority=0, name="op"):
    counted = _inflight_begin(tuple(const_vars) + tuple(mutable_vars))
    if counted:
        fn = _wrap_inflight_sync(fn, counted)
    get().push(fn, const_vars, mutable_vars, priority, name)


def push_async(fn, const_vars=(), mutable_vars=(), priority=0, name="op"):
    counted = _inflight_begin(tuple(const_vars) + tuple(mutable_vars))
    if counted:
        fn = _wrap_inflight_async(fn, counted)
    get().push_async(fn, const_vars, mutable_vars, priority, name)


def wait_for_var(var):
    get().wait_for_var(var)


def wait_for_all():
    with _telemetry.span("engine.wait_for_all", domain="engine"):
        get().wait_for_all()
    _raise_pending_file_error()


class Fence:
    """Handle returned by :func:`fence` — a pushed barrier op.

    ``wait()`` blocks until every op enqueued BEFORE the fence on the
    fenced vars has fully completed — including async ops, whose
    completion is their host ``on_complete`` callback firing. That is the
    happens-before edge ``nd.waitall()`` does NOT provide (it drains the
    device queue; host callbacks may still be in flight) and that a
    per-var ``wait_for_var`` loop provides only one var at a time.
    """

    def __init__(self, event: threading.Event, n_vars: int):
        self._event = event
        self.n_vars = n_vars

    def done(self) -> bool:
        """True once the barrier op has run (non-blocking probe)."""
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> "Fence":
        """Block for the barrier; raises MXNetError on timeout."""
        with _telemetry.span("engine.fence.wait", domain="engine",
                             n_vars=self.n_vars):
            reached = self._event.wait(timeout)
        if not reached:
            raise MXNetError(
                "engine fence over %d var(s) not reached after %.3fs"
                % (self.n_vars, timeout))
        return self


def fence(vars: Sequence[int], priority: int = 0,
          name: str = "fence") -> Fence:
    """Push a barrier op ordered after everything enqueued on ``vars``.

    The barrier reads every var (``const_vars``), so the engine schedules
    it only once all prior writers — sync or async — have completed.
    Returns immediately with a :class:`Fence`; call ``.wait()`` for the
    blocking edge, or poll ``.done()`` to overlap host work::

        f = engine.fence([var_a, var_b], name="ckpt_fence")
        ...                      # overlapped host work
        f.wait()                 # ops on var_a/var_b happened-before here
    """
    ev = threading.Event()
    vs = list(vars)
    get().push(ev.set, const_vars=vs, priority=priority, name=name)
    return Fence(ev, len(vs))


# --- per-var in-flight accounting --------------------------------------------
# Opt-in queued-or-running op counts per engine variable, the signal a
# load-aware dispatcher needs (serving's least-outstanding-work router reads
# its replica vars through this): a var registered with track_inflight() has
# every module-level push/push_async mentioning it counted at push time and
# released when the op completes (sync: fn returned; async: on_complete
# fired). Untracked vars pay nothing — one dict probe per push.
_inflight: Dict[int, int] = {}
_inflight_lock = threading.Lock()


def track_inflight(var: int):
    """Register ``var`` for in-flight accounting (idempotent)."""
    with _inflight_lock:
        _inflight.setdefault(int(var), 0)


def untrack_inflight(var: int):
    """Stop accounting for ``var`` and drop its counter."""
    with _inflight_lock:
        _inflight.pop(int(var), None)


def var_inflight(var: int) -> int:
    """Ops queued or running that mention ``var`` (0 if untracked)."""
    with _inflight_lock:
        return _inflight.get(int(var), 0)


def _inflight_begin(vars) -> tuple:
    """Count the push against every tracked var; returns the vars counted
    (empty tuple => nothing tracked, no completion bookkeeping needed)."""
    if not _inflight:  # racy read is fine: tracking starts before pushing
        return ()
    counted = []
    with _inflight_lock:
        for v in vars:
            if v in _inflight:
                _inflight[v] += 1
                counted.append(v)
    return tuple(counted)


def _inflight_end(counted: tuple):
    with _inflight_lock:
        for v in counted:
            if v in _inflight:
                _inflight[v] -= 1


def _wrap_inflight_sync(fn, counted):
    def run():
        try:
            fn()
        finally:
            _inflight_end(counted)
    return run


def _wrap_inflight_async(fn, counted):
    def run(on_complete):
        released = []  # once-guard: the engine's error path may re-complete

        def done():
            if not released:
                released.append(1)
                _inflight_end(counted)
            on_complete()

        try:
            fn(done)
        except BaseException:
            # the engine completes an op whose fn raised without calling
            # our done(); release here so the counter can never leak high
            if not released:
                released.append(1)
                _inflight_end(counted)
            raise
    return run


# --- file-write routing ------------------------------------------------------
# Checkpoint/state blob writes ride the engine with one write-var per file
# path (the reference's NDArray save-through-engine: every host mutation of
# a named resource is an engine op, kvstore_dist.h:233-241 being the PS
# analogue). Writers push with the path's var mutable; readers wait on the
# var, so an in-flight async checkpoint is never half-read.
_file_vars: Dict[str, int] = {}
_file_pending: Dict[str, int] = {}  # writes queued-or-running per path
_file_waiting: Dict[str, int] = {}  # waiters pinning the var per path
_file_errs: Dict[str, BaseException] = {}
_file_lock = threading.Lock()


def file_var(path: str) -> int:
    """The engine write-var owning ``path`` (created on first use)."""
    path = os.path.abspath(path)
    with _file_lock:
        v = _file_vars.get(path)
        if v is None:
            v = get().new_variable()
            _file_vars[path] = v
        return v


def push_file_write(path: str, fn: Callable[[], None], wait: bool = True,
                    name: Optional[str] = None):
    """Run ``fn`` (which writes ``path``) as an engine op holding the
    path's write-var. ``wait=False`` returns immediately — the write
    overlaps whatever the caller does next. A failed async write
    surfaces at the next ``wait_for_file(path)``, OR at the next
    ``push_file_write``/``wait_for_all`` on ANY path (per-epoch
    checkpoints use distinct filenames, so surfacing must not be
    per-path-only — a full disk would otherwise lose every later
    checkpoint silently)."""
    apath = os.path.abspath(path)
    _raise_pending_file_error()
    eng = get()
    with _file_lock:
        var = _file_vars.get(apath)
        if var is None:
            var = eng.new_variable()
            _file_vars[apath] = var
        # counted under the SAME lock acquisition that resolved the var,
        # so wait_for_file can never retire a var with a write en route
        _file_pending[apath] = _file_pending.get(apath, 0) + 1

    def run():
        try:
            fn()
        except BaseException as e:  # surface at the next sync point
            with _file_lock:
                _file_errs[apath] = e
        finally:
            with _file_lock:
                _file_pending[apath] -= 1

    eng.push(run, mutable_vars=[var],
             name=name or ("file_write:%s" % os.path.basename(apath)))
    if wait:
        wait_for_file(apath)


def _raise_pending_file_error():
    with _file_lock:
        if not _file_errs:
            return
        path, err = next(iter(_file_errs.items()))
        del _file_errs[path]
    raise err


def _retire_file_var(apath: str, var: int):
    """Drop the path's var ONLY if no write is queued/in flight, no other
    waiter holds it, and the mapping is unchanged (guards the concurrent
    writer AND concurrent waiter races); the native delete is itself
    ordered after the var's enqueued ops."""
    with _file_lock:
        if (_file_pending.get(apath, 0) != 0
                or _file_waiting.get(apath, 0) != 0
                or _file_vars.get(apath) is not var):
            return
        del _file_vars[apath]
        _file_pending.pop(apath, None)
    get().delete_variable(var)


def wait_for_file(path: str):
    """Block until every pending engine op on ``path`` finished; re-raise
    the first failure recorded for it. Once drained (and only if no new
    write or other waiter raced in), the path's engine var is retired so
    long runs with per-epoch filenames don't grow the var table without
    bound."""
    apath = os.path.abspath(path)
    with _file_lock:
        var = _file_vars.get(apath)
        if var is not None:
            # pin: a concurrent wait_for_file must not retire+delete the
            # var between our lookup and the native wait
            _file_waiting[apath] = _file_waiting.get(apath, 0) + 1
    if var is not None:
        try:
            get().wait_for_var(var)
        finally:
            with _file_lock:
                _file_waiting[apath] -= 1
                if _file_waiting[apath] == 0:
                    del _file_waiting[apath]  # no unbounded per-path table
        _retire_file_var(apath, var)
    with _file_lock:
        err = _file_errs.pop(apath, None)
    if err is not None:
        raise err


def wait_for_all_files():
    """Drain every pending file write and surface the first failure —
    call at end-of-training when using async_write."""
    with _file_lock:
        pending = list(_file_vars)
    first_err = None
    for apath in pending:
        try:
            wait_for_file(apath)
        except BaseException as e:
            # drain EVERY path before surfacing: a caller that catches the
            # error must still find the other checkpoints fully written
            if first_err is None:
                first_err = e
    if first_err is not None:
        raise first_err
    _raise_pending_file_error()


# queue depth for the metrics registry — the callback reads the module
# global at scrape time and never instantiates an engine itself
_telemetry.registry.gauge(
    "engine_pending_ops",
    fn=lambda: _engine.pending() if _engine is not None else 0,
    help="ops queued or running on the host dependency engine")
