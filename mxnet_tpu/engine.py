"""Host-side dependency engine.

Python surface over the native scheduler (native/engine.cc) — the
TPU-native counterpart of the reference's Engine singleton
(include/mxnet/engine.h:75-250, src/engine/threaded_engine*.cc,
SURVEY §2.1 #1-5).

Division of labor (SURVEY §7): *device* work is ordered by XLA's async
runtime — jax.Array dispatch is already the reference NDArray's
engine-var pipelining (`.block_until_ready()` ≡ WaitToRead). This engine
orders the HOST work XLA cannot see: checkpoint/file IO, data-pipeline
stages, parameter-server-style updates, metric sinks. Semantics are the
reference's: closures tagged with const (read) / mutable (write) variable
sets; conflicting ops serialize in push order, independent ops run
concurrently on a native worker pool.

Selection mirrors MXNET_ENGINE_TYPE (src/engine/engine.cc:13-38):
``ThreadedEngine`` (default) or ``NaiveEngine`` (fully synchronous, for
debugging — the reference's own advice, threaded_engine.h:326-338).

    from mxnet_tpu import engine
    v = engine.new_variable()
    engine.push(lambda: write_file(...), mutable_vars=[v])
    engine.push(lambda: read_file(...), const_vars=[v])   # ordered after
    engine.wait_for_all()
"""
from __future__ import annotations

import ctypes
import functools
import json
import logging
import os
import threading
import traceback
import types
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from . import telemetry as _telemetry
from .base import MXNetError

_OPR_FN = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_void_p)
_DEL_FN = ctypes.CFUNCTYPE(None, ctypes.c_void_p)

# --- op-error observation -----------------------------------------------------
# The engine NEVER lets an op exception escape the worker (it would cross the
# C boundary / kill the worker loop); by default a failed op prints its
# traceback and the run continues. A process-wide handler lets supervision
# layers (mxnet_tpu.resilience) OBSERVE those swallowed failures — e.g. to
# count injected faults or trigger a restore — without changing engine
# semantics. Plain module global, set once at startup: no lock needed.
_op_error_handler: Optional[Callable[[str, BaseException], None]] = None


def set_error_handler(fn: Optional[Callable[[str, BaseException], None]]):
    """Install ``fn(op_name, exc)`` to observe engine-op exceptions (which
    are otherwise only printed). Pass ``None`` to reset. Returns the
    previously installed handler. The handler runs ON the engine worker —
    it must be fast and must not raise (a raising handler is swallowed)."""
    global _op_error_handler
    prev = _op_error_handler
    _op_error_handler = fn
    return prev


def _notify_op_error(name: str, exc: BaseException):
    h = _op_error_handler
    if h is not None:
        try:
            h(name, exc)
        except Exception:  # an observing hook must never break dispatch
            traceback.print_exc()


def _load_native() -> Optional[ctypes.CDLL]:
    from . import native as _native

    # reuse the shared build machinery; the engine lib sits next to the io lib
    so = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                      "native", "libmxtpu_engine.so")
    if not os.path.exists(so):
        try:
            import subprocess

            subprocess.run(["make", "-C", os.path.dirname(so),
                            "libmxtpu_engine.so"], check=True,
                           capture_output=True, timeout=120)
        except Exception:
            return None
    try:
        lib = ctypes.CDLL(so)
    except OSError:
        return None
    lib.mxe_create.restype = ctypes.c_void_p
    lib.mxe_create.argtypes = [ctypes.c_int, ctypes.c_int]
    lib.mxe_destroy.argtypes = [ctypes.c_void_p]
    lib.mxe_new_var.restype = ctypes.c_int64
    lib.mxe_new_var.argtypes = [ctypes.c_void_p]
    lib.mxe_delete_var.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.mxe_push.argtypes = [
        ctypes.c_void_p, _OPR_FN, ctypes.c_void_p, _DEL_FN,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
    lib.mxe_opr_complete.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.mxe_wait_for_var.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.mxe_wait_for_all.argtypes = [ctypes.c_void_p]
    lib.mxe_pending.restype = ctypes.c_int
    lib.mxe_pending.argtypes = [ctypes.c_void_p]
    lib.mxe_set_profiling.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.mxe_dump_profile.restype = ctypes.c_int64
    lib.mxe_dump_profile.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_int64]
    return lib


class NativeEngine:
    """ctypes wrapper over native/engine.cc."""

    def __init__(self, num_workers=0, engine_type="ThreadedEngine"):
        self._lib = _load_native()
        if self._lib is None:
            raise MXNetError("native engine library unavailable")
        etype = 1 if engine_type == "NaiveEngine" else 0
        self._h = self._lib.mxe_create(num_workers, etype)
        self._pending: Dict[int, tuple] = {}
        self._pending_lock = threading.Lock()
        self._next_key = [1]
        # single C trampoline for every op; param = key into _pending
        self._trampoline = _OPR_FN(self._dispatch)
        self._no_del = ctypes.cast(None, _DEL_FN)

    def _dispatch(self, param, on_complete):
        key = int(param)
        with self._pending_lock:
            fn, is_async, name, t_q, const_vars, mutable_vars = \
                self._pending.pop(key)
        # t_q was stamped at push time iff the engine span domain was on;
        # queue wait = dispatch time - push time. Worker thread identity
        # rides for free on the per-thread telemetry buffer; an async op's
        # end() records the completing thread as end_tid.
        span_args = None
        if t_q and _telemetry.enabled("engine"):
            span_args = {"queue_us": (_telemetry.clock_ns() - t_q) // 1000,
                         "const_vars": list(const_vars),
                         "mutable_vars": list(mutable_vars)}
        tok = None
        try:
            if is_async:
                h = ctypes.c_void_p(on_complete)
                if span_args is not None:
                    tok = _telemetry.begin(name, domain="engine", **span_args)

                def complete(_h=h, _tok=tok):
                    _telemetry.end(_tok)
                    self._lib.mxe_opr_complete(self._h, _h)

                fn(complete)
            else:
                if span_args is not None:
                    with _telemetry.span(name, domain="engine", **span_args):
                        fn()
                else:
                    fn()
        except Exception as e:  # never let an exception cross the C boundary
            traceback.print_exc()
            _notify_op_error(name, e)
            if is_async:
                _telemetry.end(tok, error=True)
                self._lib.mxe_opr_complete(self._h, ctypes.c_void_p(on_complete))

    def new_variable(self) -> int:
        return self._lib.mxe_new_var(self._h)

    def delete_variable(self, var: int):
        self._lib.mxe_delete_var(self._h, var)

    def _push(self, fn, const_vars, mutable_vars, priority, name, is_async):
        const_vars, mutable_vars = _dedup(const_vars, mutable_vars)
        t_q = _telemetry.clock_ns() if _telemetry.enabled("engine") else 0
        with self._pending_lock:
            key = self._next_key[0]
            self._next_key[0] += 1
            self._pending[key] = (fn, is_async, name, t_q,
                                  tuple(const_vars), tuple(mutable_vars))
        c = (ctypes.c_int64 * max(len(const_vars), 1))(*const_vars)
        m = (ctypes.c_int64 * max(len(mutable_vars), 1))(*mutable_vars)
        self._lib.mxe_push(self._h, self._trampoline, ctypes.c_void_p(key),
                           self._no_del, c, len(const_vars), m,
                           len(mutable_vars), priority, name.encode(),
                           1 if is_async else 0)

    def push(self, fn: Callable[[], None], const_vars: Sequence[int] = (),
             mutable_vars: Sequence[int] = (), priority: int = 0,
             name: str = "op"):
        """PushSync (engine.h:198-208): fn runs on a worker; completion is
        automatic on return."""
        self._push(fn, const_vars, mutable_vars, priority, name, False)

    def push_async(self, fn: Callable[[Callable[[], None]], None],
                   const_vars: Sequence[int] = (),
                   mutable_vars: Sequence[int] = (), priority: int = 0,
                   name: str = "op"):
        """PushAsync (engine.h:158-170): fn receives an ``on_complete``
        callable it must invoke (from any thread) when the op finishes."""
        self._push(fn, const_vars, mutable_vars, priority, name, True)

    def wait_for_var(self, var: int):
        self._lib.mxe_wait_for_var(self._h, var)

    def wait_for_all(self):
        self._lib.mxe_wait_for_all(self._h)

    def pending(self) -> int:
        return self._lib.mxe_pending(self._h)

    def set_profiling(self, on: bool):
        self._lib.mxe_set_profiling(self._h, int(on))

    def dump_profile(self) -> dict:
        n = self._lib.mxe_dump_profile(self._h, None, 0)
        buf = ctypes.create_string_buffer(n + 16)
        self._lib.mxe_dump_profile(self._h, buf, n + 16)
        return json.loads(buf.value.decode())

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.mxe_destroy(self._h)
                self._h = None
        except Exception:
            pass


class PythonEngine:
    """Pure-Python fallback honoring the API. ``NaiveEngine`` (the default
    here) runs everything inline, like naive_engine.cc. ``ThreadedEngine``
    drains a FIFO on one daemon worker: ops still run in push order
    (conservative — as if every op conflicted on a variable), but the
    pushing thread is NOT blocked, so host pipelines (async checkpoint
    writes, the serving batcher/dispatch split) overlap with the caller
    even when the native library is unavailable."""

    def __init__(self, num_workers=0, engine_type="NaiveEngine"):
        self._next = 1
        self._prof = []
        self._profiling = False
        self._queue = None
        if engine_type != "NaiveEngine":
            import queue

            self._queue = queue.Queue()
            threading.Thread(target=self._worker, daemon=True,
                             name="mxtpu-py-engine").start()

    def _worker(self):
        while True:
            fn, name = self._queue.get()
            try:
                fn()
            except Exception as e:  # never kill the worker loop
                traceback.print_exc()
                _notify_op_error(name, e)
            finally:
                self._queue.task_done()

    def new_variable(self):
        self._next += 1
        return self._next - 1

    def delete_variable(self, var):
        pass

    def _run_profiled(self, fn, name, t_q=0):
        import time

        t0 = time.time()
        if t_q and _telemetry.enabled("engine"):
            with _telemetry.span(
                    name, domain="engine",
                    queue_us=(_telemetry.clock_ns() - t_q) // 1000):
                fn()
        else:
            fn()
        if self._profiling:
            self._prof.append({"name": name, "ph": "X", "pid": 0, "tid": 0,
                               "ts": int(t0 * 1e6),
                               "dur": int((time.time() - t0) * 1e6)})

    def push(self, fn, const_vars=(), mutable_vars=(), priority=0, name="op"):
        t_q = _telemetry.clock_ns() if _telemetry.enabled("engine") else 0
        if self._queue is not None:
            self._queue.put((lambda: self._run_profiled(fn, name, t_q), name))
        else:
            self._run_profiled(fn, name, t_q)

    def push_async(self, fn, const_vars=(), mutable_vars=(), priority=0,
                   name="op"):
        t_q = _telemetry.clock_ns() if _telemetry.enabled("engine") else 0

        def run():
            done = threading.Event()
            fn(done.set)
            done.wait()  # hold the FIFO slot until on_complete fires

        if self._queue is not None:
            self._queue.put((lambda: self._run_profiled(run, name, t_q), name))
        else:
            self._run_profiled(run, name, t_q)

    def wait_for_var(self, var):
        # conservative: the FIFO admits no reordering, so draining it is a
        # correct (if coarse) WaitForVar
        if self._queue is not None:
            self._queue.join()

    def wait_for_all(self):
        if self._queue is not None:
            self._queue.join()

    def pending(self):
        return self._queue.unfinished_tasks if self._queue is not None else 0

    def set_profiling(self, on):
        self._profiling = bool(on)

    def dump_profile(self):
        return {"traceEvents": list(self._prof)}


def _dedup(const_vars, mutable_vars):
    """DeduplicateVarHandle (engine.h:231-249): drop repeats; a var that is
    both read and mutated is tracked as mutable only."""
    mut = list(dict.fromkeys(mutable_vars))
    mset = set(mut)
    const = [v for v in dict.fromkeys(const_vars) if v not in mset]
    return const, mut


_engine = None
_engine_lock = threading.Lock()


def get() -> "NativeEngine | PythonEngine":
    """Engine.Get() singleton (engine.h:211). Type from MXNET_ENGINE_TYPE."""
    global _engine
    with _engine_lock:
        if _engine is None:
            etype = os.environ.get("MXNET_ENGINE_TYPE", "ThreadedEngine")
            workers = int(os.environ.get("MXNET_CPU_WORKER_NTHREADS", "0"))
            try:
                _engine = NativeEngine(workers, etype)
            except MXNetError:
                _engine = PythonEngine(workers, etype)
        return _engine


# module-level conveniences mirroring the reference's C API surface
def new_variable():
    v = get().new_variable()
    if _san is not None:
        _san.on_new(v)
    return v


def delete_variable(var):
    if _san is not None:
        _san.on_delete(var)
    get().delete_variable(var)


def push(fn, const_vars=(), mutable_vars=(), priority=0, name="op"):
    if _san is not None:
        _san.on_push(fn, const_vars, mutable_vars, name)
    counted = _inflight_begin(tuple(const_vars) + tuple(mutable_vars))
    if counted:
        fn = _wrap_inflight_sync(fn, counted)
    get().push(fn, const_vars, mutable_vars, priority, name)


def push_async(fn, const_vars=(), mutable_vars=(), priority=0, name="op"):
    if _san is not None:
        _san.on_push(fn, const_vars, mutable_vars, name)
    counted = _inflight_begin(tuple(const_vars) + tuple(mutable_vars))
    if counted:
        fn = _wrap_inflight_async(fn, counted)
    get().push_async(fn, const_vars, mutable_vars, priority, name)


def wait_for_var(var):
    get().wait_for_var(var)
    if _san is not None:
        _san.on_sync((int(var),))


def wait_for_all():
    with _telemetry.span("engine.wait_for_all", domain="engine"):
        get().wait_for_all()
    if _san is not None:
        _san.on_sync(None)
    _raise_pending_file_error()


class Fence:
    """Handle returned by :func:`fence` — a pushed barrier op.

    ``wait()`` blocks until every op enqueued BEFORE the fence on the
    fenced vars has fully completed — including async ops, whose
    completion is their host ``on_complete`` callback firing. That is the
    happens-before edge ``nd.waitall()`` does NOT provide (it drains the
    device queue; host callbacks may still be in flight) and that a
    per-var ``wait_for_var`` loop provides only one var at a time.
    """

    def __init__(self, event: threading.Event, n_vars: int,
                 fence_vars: Sequence[int] = ()):
        self._event = event
        self.n_vars = n_vars
        self._fence_vars = tuple(fence_vars)

    def done(self) -> bool:
        """True once the barrier op has run (non-blocking probe)."""
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> "Fence":
        """Block for the barrier; raises MXNetError on timeout."""
        with _telemetry.span("engine.fence.wait", domain="engine",
                             n_vars=self.n_vars):
            reached = self._event.wait(timeout)
        if not reached:
            raise MXNetError(
                "engine fence over %d var(s) not reached after %.3fs"
                % (self.n_vars, timeout))
        if _san is not None and self._fence_vars:
            # the fence completed: every DECLARED access enqueued before it
            # on these vars happened-before this point
            _san.on_sync(self._fence_vars)
        return self


def fence(vars: Sequence[int], priority: int = 0,
          name: str = "fence") -> Fence:
    """Push a barrier op ordered after everything enqueued on ``vars``.

    The barrier reads every var (``const_vars``), so the engine schedules
    it only once all prior writers — sync or async — have completed.
    Returns immediately with a :class:`Fence`; call ``.wait()`` for the
    blocking edge, or poll ``.done()`` to overlap host work::

        f = engine.fence([var_a, var_b], name="ckpt_fence")
        ...                      # overlapped host work
        f.wait()                 # ops on var_a/var_b happened-before here
    """
    ev = threading.Event()
    vs = list(vars)
    if _san is not None:
        _san.on_fence(vs, name)
    get().push(ev.set, const_vars=vs, priority=priority, name=name)
    return Fence(ev, len(vs), fence_vars=vs)


# --- capture/replay of steady-state dispatch sequences -----------------------
# PyGraph-style (PAPERS.md): the per-op host cost of dynamic dispatch —
# _dedup, the pending-table lock, the ctypes marshalling, the native
# scheduler walk — is paid once during a short warmup, then the whole
# sequence replays as ONE engine submission whose internal ordering comes
# from a precomputed edge list.

_log = logging.getLogger("mxnet_tpu")


def capture_enabled() -> bool:
    """True when ``MXNET_ENGINE_CAPTURE`` opts steady-state callers
    (``Module.fit_step``, serving dispatch) into capture/replay. Read at
    point of use so tests and dryruns can flip it mid-process."""
    return os.environ.get("MXNET_ENGINE_CAPTURE", "0").lower() \
        not in ("0", "", "false", "off")


def capture_warmup() -> int:
    """Warmup iterations before a sequence is eligible to replay
    (``MXNET_ENGINE_CAPTURE_WARMUP``, default 3, floor 2 — stability is
    meaningless with a single observation)."""
    try:
        n = int(os.environ.get("MXNET_ENGINE_CAPTURE_WARMUP", "3"))
    except ValueError:
        n = 3
    return max(2, n)


def fuse_enabled() -> bool:
    """True when ``MXNET_ENGINE_FUSE`` opts stable captured sequences into
    trace-and-fuse: the recorded op stream is lowered into ONE jitted XLA
    program (requires capture — a sequence that never stabilizes has
    nothing to fuse). Read at point of use, like :func:`capture_enabled`."""
    return os.environ.get("MXNET_ENGINE_FUSE", "0").lower() \
        not in ("0", "", "false", "off")


class _FuseBail(Exception):
    """Per-iteration fuse bail (feed drift, executable failure before any
    side effect): the iteration falls back to replay-style execution."""


class _FuseIneligible(Exception):
    """The recorded sequence cannot be fused at all (an op lacks traceable
    metadata, or the metadata contradicts the declared var sets)."""


class FuseOp:
    """Traceable metadata for one captured push (trace-and-fuse).

    A push site that wants its op fused passes ``fuse=FuseOp(...)`` to
    :meth:`CapturedSequence.push`/``push_async``. The eager closure still
    runs during warmup/replay/bail; once the sequence stabilizes with
    every slot carrying a FuseOp, :class:`FusedSequence` stages the
    ``jax_fn``s into one jitted program and the closures stop running.

    - ``jax_fn(*registers, *feeds) -> tuple(out registers)``: pure,
      traceable. Registers are arbitrary pytrees keyed by engine var —
      the op consumes its ``in_vars``' registers (in order) plus the
      per-iteration ``feed`` values, and produces one register per
      ``out_vars`` entry.
    - ``in_vars``/``out_vars``: engine vars read/written. Must be covered
      by the push's declared const/mutable sets (the pre-resolved
      RAW/WAR/WAW edges are the fused program's dependency structure).
    - ``feed``: per-iteration concrete inputs — a tuple, or a zero-arg
      callable returning one (evaluated inside the fused engine op).
      Shapes/dtypes must stay stable; drift bails the iteration to
      replay.
    - ``init``: dict var -> value-or-callable seeding the register of a
      var that is read before it is written (live-in). Evaluated once at
      staging time, after a quiescing fence.
    - ``writeback``: host callable receiving ``{var: final value}`` for
      this op's out_vars after each fused iteration — the hook that keeps
      consumer-visible state (param snapshots, serving responses) in sync
      so a later bail resumes correctly. Runs on the engine worker,
      inside the fused push.
    - ``fingerprint``: stable content hash of the computation for the
      progcache key; ``None`` means "hash the lowered program text".
    """

    __slots__ = ("jax_fn", "in_vars", "out_vars", "feed", "init",
                 "writeback", "fingerprint")

    def __init__(self, jax_fn, in_vars: Sequence[int] = (),
                 out_vars: Sequence[int] = (), feed=(), init=None,
                 writeback=None, fingerprint: Optional[str] = None):
        self.jax_fn = jax_fn
        self.in_vars = tuple(int(v) for v in in_vars)
        self.out_vars = tuple(int(v) for v in out_vars)
        self.feed = feed
        self.init = init or {}
        self.writeback = writeback
        self.fingerprint = fingerprint


# process-wide trace-and-fuse accounting: the dict is the test/dryrun
# surface (always on), the registry counters the telemetry export
_fuse_stats = {"runs": 0, "bails": 0, "ineligible": 0, "compiles": 0,
               "disk_loads": 0}
_fused_runs_counter = _telemetry.registry.counter(
    "engine_fused_runs_total",
    help="captured-sequence iterations executed as one fused XLA program")
_fuse_bails_counter = _telemetry.registry.counter(
    "engine_fuse_bails_total",
    help="trace-and-fuse bails back to replay (ineligible sequence, "
         "staging failure, feed drift, runtime error)")


def fused_stats() -> Dict[str, int]:
    """Snapshot of trace-and-fuse counters (runs, bails, ineligible,
    compiles, disk_loads) since process start / last reset."""
    return dict(_fuse_stats)


def fused_stats_reset():
    for k in _fuse_stats:
        _fuse_stats[k] = 0


def _count_fuse_bail(kind: str):
    _fuse_stats["bails"] += 1
    if kind == "ineligible":
        _fuse_stats["ineligible"] += 1
    _fuse_bails_counter.inc()


def _sharding_sig(leaf):
    """Stable signature of a committed jax.sharding, or None.

    Sharded carries (MXNET_SHARDED_UPDATE stages 1-3) lower into the
    fused program with their NamedSharding baked into the executable, so
    the placement must be part of the staging aval: a progcache entry
    serialized for one mesh/spec must never be handed a differently
    placed carry, and a placement change must re-stage rather than feed
    a stale program. Single-device / uncommitted / non-jax leaves all
    map to None so the unsharded path's keys are unchanged.
    """
    sh = getattr(leaf, "sharding", None)
    if sh is None:
        return None
    try:
        import jax
        if not isinstance(sh, jax.sharding.NamedSharding):
            return None
        mesh = sh.mesh
        return (tuple(mesh.axis_names), tuple(mesh.devices.shape),
                str(sh.spec))
    except Exception:
        return None


class FusedSequence:
    """One stable :class:`CapturedSequence` lowered into ONE jitted XLA
    program (``MXNET_ENGINE_FUSE``; ROADMAP trace-and-fuse).

    Construction runs on the sequence's driving thread at the first ready
    ``end_step`` and performs the whole staging pipeline:

    1. **Quiesce**: fence the union var set so every warmup iteration's
       effects are settled before live-in registers are seeded.
    2. **Liveness** over the per-op ``in_vars``/``out_vars``: a var read
       before its first write is *carried* (live-in AND live-out — its
       register threads across iterations and is seeded from
       ``FuseOp.init``); a var written then only consumed inside the
       iteration is an *intermediate* (donated, dead at iteration end,
       DCE'd by XLA unless a writeback needs it). Var ids are normalized
       to sequence-local indices so the staged program — and its cache
       key — are process-independent.
    3. **Stitch**: each op's ``jax_fn`` is staged in recorded order,
       consuming registers exactly along the pre-resolved RAW/WAR/WAW
       edges, into one function ``(carry, feeds) -> (carry', mats)``
       jitted with the carry donated.
    4. **Cache**: the executable is keyed in progcache by the capture
       signature — sha1 over per-op fingerprints, the edge set and in/out
       avals (plus the lowered text when an op has no explicit
       fingerprint) — so a warm restart disk-loads it with zero fresh
       compiles (``kind="fused"`` in the entry meta).

    Per iteration, :meth:`run_iteration` (on the engine worker, inside
    the single ``fused:<name>`` push) evaluates the fresh ``FuseOp``
    feeds, checks their avals against the staged ones (drift raises
    :class:`_FuseBail` BEFORE the executable runs — the iteration is then
    replayed untouched), executes the program, and runs the writebacks.
    """

    def __init__(self, name: str, ops: List[tuple], fuses: List[FuseOp],
                 union: Tuple[tuple, tuple]):
        import jax  # deferred: the engine itself must import without jax

        self.name = name
        u_const, u_mut = union
        # 1. quiesce: warmup iterations still in flight wrote the state
        # the init callables are about to read
        fence(list(u_const) + list(u_mut),
              name="fuse_stage:%s" % name).wait(120)
        declared_mut = [set(int(v) for v in sig[4]) for sig, _ in ops]
        declared_all = [set(int(v) for v in sig[3]) | declared_mut[i]
                        for i, (sig, _) in enumerate(ops)]
        for i, f in enumerate(fuses):
            if not set(f.in_vars) <= declared_all[i]:
                raise _FuseIneligible(
                    "op %d (%s) fuse metadata reads vars outside its "
                    "declared set" % (i, ops[i][0][1]))
            if not set(f.out_vars) <= declared_mut[i]:
                raise _FuseIneligible(
                    "op %d (%s) fuse metadata writes vars outside its "
                    "declared mutable set" % (i, ops[i][0][1]))
        # 2. liveness under normalized (process-independent) var indices
        var_idx: Dict[int, int] = {}
        for v in list(u_const) + list(u_mut):
            var_idx[int(v)] = len(var_idx)
        first: Dict[int, str] = {}
        order: List[int] = []
        for f in fuses:
            for v in f.in_vars:
                if v not in first:
                    first[v] = "r"
                    order.append(v)
            for v in f.out_vars:
                if v not in first:
                    first[v] = "w"
                    order.append(v)
        carried = tuple(v for v in order if first[v] == "r")
        wb_ops = tuple(i for i, f in enumerate(fuses)
                       if f.writeback is not None)
        mat_vars = tuple(v for i in wb_ops for v in fuses[i].out_vars
                         if v not in carried)
        carry0 = {}
        for v in carried:
            src = None
            for f in fuses:
                if v in f.init:
                    src = f.init[v]
                    break
            if src is None:
                raise _FuseIneligible(
                    "live-in var %d has no FuseOp.init seed" % v)
            carry0[var_idx[v]] = src() if callable(src) else src
        self._var_idx = var_idx
        self._carried_idx = tuple(var_idx[v] for v in carried)
        self._mat_idx = tuple(sorted(var_idx[v] for v in set(mat_vars)))
        self._wb_ops = wb_ops
        self._in_idx = tuple(tuple(var_idx[v] for v in f.in_vars)
                             for f in fuses)
        self._out_idx = tuple(tuple(var_idx[v] for v in f.out_vars)
                              for f in fuses)
        self._out_vars = tuple(f.out_vars for f in fuses)
        # 3. staged feeds: evaluated once here (they double as the lowering
        # example args and the aval reference for drift checks), then the
        # first run_iteration consumes them instead of re-evaluating
        feeds0, defs, avals = [], [], []
        for i, f in enumerate(fuses):
            fv = tuple(f.feed()) if callable(f.feed) else tuple(f.feed)
            leaves, treedef = jax.tree_util.tree_flatten(fv)
            feeds0.append(fv)
            defs.append(treedef)
            avals.append(tuple(self._aval(l) for l in leaves))
        self._feed_defs = tuple(defs)
        self._feed_avals = tuple(avals)
        self._pending_feeds: Optional[tuple] = tuple(feeds0)
        jax_fns = tuple(f.jax_fn for f in fuses)
        in_idx, out_idx = self._in_idx, self._out_idx
        carried_idx, mat_idx = self._carried_idx, self._mat_idx
        names = tuple(sig[1] for sig, _ in ops)

        def fused(carry, feeds):
            regs = dict(carry)
            for i, fn in enumerate(jax_fns):
                res = fn(*[regs[k] for k in in_idx[i]], *feeds[i])
                if not isinstance(res, (tuple, list)):
                    res = (res,)
                if len(res) != len(out_idx[i]):
                    raise _FuseIneligible(
                        "op %d (%s) jax_fn returned %d value(s) for %d "
                        "out var(s)" % (i, names[i], len(res),
                                        len(out_idx[i])))
                for k, val in zip(out_idx[i], res):
                    regs[k] = val
            # materialized registers BEFORE the carry: with the carry
            # donated, XLA pairs donated buffers to outputs in flattened
            # output order, and the unfused step emits its outputs ahead
            # of the updated params/states — matching that order keeps
            # the fused program's buffer aliasing (and therefore its CPU
            # SPMD codegen) bitwise-identical to the replay arm's.
            return ({k: regs[k] for k in mat_idx},
                    {k: regs[k] for k in carried_idx})

        # 4. lower + compile-or-disk-load, keyed by the capture signature
        jitted = jax.jit(fused, donate_argnums=(0,))
        lowered = jitted.lower(dict(carry0), tuple(feeds0))
        sigparts = []
        for i, (sig, deps) in enumerate(ops):
            sigparts.append((sig[1], sig[0], fuses[i].fingerprint,
                             in_idx[i], out_idx[i], deps, avals[i]))
        carry_avals = tuple(
            (k, tuple(self._aval(l)
                      for l in jax.tree_util.tree_leaves(carry0[k])))
            for k in sorted(carry0))
        from . import progcache as _progcache
        from .analysis import compile_witness as _witness
        need_text = any(f.fingerprint is None for f in fuses)
        key = _progcache.fused_key(
            repr((sigparts, carry_avals)),
            lowered.as_text() if need_text else None)
        self.signature = key
        exe = (_progcache.load(key, kind="fused")
               if _progcache.enabled() else None)
        if exe is not None:
            _fuse_stats["disk_loads"] += 1
        else:
            exe = lowered.compile()
            _fuse_stats["compiles"] += 1
            _witness.record_compile("fused", key=key[:16])
            if _progcache.enabled():
                _progcache.store(key, exe, note="fused:%s" % name,
                                 kind="fused")
        self._exe = exe
        self._carry = carry0
        self._san_seen = None
        _log.info("engine fuse '%s': staged %d op(s) into one program "
                  "(%d live-in, %d materialized, key %s…)", name,
                  len(ops), len(carried), len(mat_vars), key[:12])

    @staticmethod
    def _aval(leaf):
        # (shape, dtype, sharding) — the sharding leg keys the staged
        # program (and its progcache entry) to the carry placement so
        # ZeRO stage-1/2/3 runs fuse instead of bailing; see
        # ``_sharding_sig``. None everywhere on the unsharded path.
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            return (tuple(leaf.shape), str(leaf.dtype),
                    _sharding_sig(leaf))
        import numpy as np
        a = np.asarray(leaf)
        return (tuple(a.shape), str(a.dtype), None)

    def _eval_feeds(self, fuses) -> tuple:
        import jax
        vals = []
        for i, f in enumerate(fuses):
            fv = tuple(f.feed()) if callable(f.feed) else tuple(f.feed)
            leaves, treedef = jax.tree_util.tree_flatten(fv)
            if treedef != self._feed_defs[i] or \
                    tuple(self._aval(l) for l in leaves) \
                    != self._feed_avals[i]:
                raise _FuseBail(
                    "feed for op %d drifted from the staged shapes/dtypes"
                    % i)
            vals.append(fv)
        return tuple(vals)

    def san_check(self, ops):
        """Sanitizer validation: the declared edge set (transitively, with
        program order inside the one fused push) must dominate the full
        conflict-predecessor map — the same contract replay's
        ``on_replay_child`` enforces dynamically."""
        san = _san
        if san is None or self._san_seen is san:
            return
        self._san_seen = san
        conf = _Sanitizer.replay_conflicts(ops)
        reach: List[set] = []
        for i, (_sig, deps) in enumerate(ops):
            r = set(deps)
            for d in deps:
                r |= reach[d]
            reach.append(r)
        for i, cset in enumerate(conf):
            for j in cset:
                if j not in reach[i]:
                    sig_i, sig_j = ops[i][0], ops[j][0]
                    shared = sorted(
                        ({int(v) for v in sig_i[3]}
                         | {int(v) for v in sig_i[4]})
                        & ({int(v) for v in sig_j[3]}
                           | {int(v) for v in sig_j[4]}))
                    san._emit(san._mk(
                        "fused-edge-violation",
                        shared[0] if shared else -1, sig_i[1],
                        "%s[%d]" % (self.name, i), sig_j[1],
                        "%s[%d]" % (self.name, j),
                        detail="fused program's declared edge set does "
                               "not dominate the conflict between ops "
                               "%d and %d (shared vars %r)"
                               % (i, j, shared)))

    def run_iteration(self, fuses):
        """Execute one iteration (engine worker, inside the fused push).
        Raises :class:`_FuseBail` before any side effect when the
        iteration can still be replayed; lets writeback errors propagate
        (results are already partially published — replaying would
        double-apply)."""
        feeds = self._pending_feeds
        if feeds is not None:
            self._pending_feeds = None
        else:
            feeds = self._eval_feeds(fuses)
        try:
            mats, new_carry = self._exe(self._carry, feeds)
        except Exception as e:
            raise _FuseBail("fused executable failed: %s" % e)
        self._carry = new_carry
        regs = dict(new_carry)
        regs.update(mats)
        for i in self._wb_ops:
            wb = fuses[i].writeback
            if wb is not None:
                wb({v: regs[self._var_idx[v]] for v in self._out_vars[i]
                    if self._var_idx[v] in regs})


class CapturedSequence:
    """Record a steady-state push sequence once, replay it with near-zero
    host overhead.

    Protocol — the owning thread brackets each iteration::

        cs = engine.CapturedSequence(name="fit_step")
        for batch in loader:
            cs.begin_step()
            cs.push(load_fn, mutable_vars=[data_var], name="load")
            cs.push(step_fn, const_vars=[data_var],
                    mutable_vars=[step_var], name="step")
            cs.end_step()

    For the first ``warmup`` iterations every push forwards eagerly
    through the module-level :func:`push`/:func:`push_async` (so behavior
    is identical to not capturing) while the ``(is_async, name, priority,
    const_vars, mutable_vars)`` signature stream is recorded. If all
    warmup iterations produced the SAME signature stream, the sequence
    compiles: per-op ``_dedup`` runs once, RAW/WAR/WAW edges between the
    recorded ops are resolved into a static dependency list, and the
    union of all vars becomes the replay submission's var set. If the
    stream was unstable (different ops or different var topology across
    iterations) the sequence **bails to eager** with a logged reason and
    stays eager until :meth:`invalidate` is called.

    A compiled iteration is submitted by ``end_step()`` as ONE
    module-level :func:`push_async` — so per-var in-flight accounting
    counts the replay's vars exactly once per replay, :func:`fence` over
    any of the union vars orders after the whole replay (including its
    async children's ``on_complete``), and file vars in the recorded
    signatures keep their write ordering. Inside the replay op the
    recorded ops run in recorded order on one engine worker, waiting only
    on precomputed edges to async predecessors — no per-op ``_dedup``, no
    scheduler-queue lock, no ctypes marshalling.

    If a replayed iteration deviates from the recording (different op at
    slot i, or fewer/more ops), the already-matched prefix is flushed
    eagerly in order, the rest of the iteration runs eagerly, and the
    sequence returns to capturing — a mismatch never loses or reorders
    an op.

    Threading: one thread drives ``begin_step``/``push``/``end_step``;
    :meth:`invalidate` may be called from any thread (e.g. a retune op on
    an engine worker) — it sets a flag consumed at the next
    ``begin_step``. ``_lock`` is a declared leaf (rank 100): no call
    leaves the package while it is held.
    """

    def __init__(self, name: str = "seq", warmup: Optional[int] = None,
                 fuse: Optional[bool] = None):
        self._name = name
        self._warmup = max(2, warmup) if warmup is not None \
            else capture_warmup()
        self._lock = threading.Lock()
        # state: "capture" (recording + eager), "ready" (replaying),
        # "flush" (mid-step after a mismatch: eager, not recording),
        # "eager" (bailed on unstable warmup: eager until invalidate())
        self._state = "capture"
        self._iters: List[list] = []     # signature stream per warmup iter
        self._cur: Optional[list] = None
        self._ops: Optional[List[tuple]] = None  # [(sig, deps), ...]
        self._union: Tuple[tuple, tuple] = ((), ())
        self._slots: List[Callable] = []
        self._invalid_reason: Optional[str] = None
        self.replays = 0
        self.bails = 0
        # trace-and-fuse (MXNET_ENGINE_FUSE; None = read env at use time):
        # _fuse_state is None (unstaged) / "staged" / "ineligible" / "dead";
        # _fused holds the staged FusedSequence while "staged"
        self._fuse_opt = fuse
        self._fuse_state: Optional[str] = None
        self._fused: Optional[FusedSequence] = None
        self._fuse_slots: List[Optional[FuseOp]] = []
        self.fused_runs = 0
        self.fuse_bails = 0

    @property
    def name(self) -> str:
        return self._name

    @property
    def warmup(self) -> int:
        return self._warmup

    @property
    def state(self) -> str:
        return self._state

    def invalidate(self, reason: str):
        """Discard the recording at the next ``begin_step`` (thread-safe;
        an already-submitted replay is unaffected — its vars and closures
        were frozen at submission)."""
        with self._lock:
            if self._invalid_reason is None:
                self._invalid_reason = reason

    # -- step bracketing ------------------------------------------------

    def begin_step(self):
        reason = None
        with self._lock:
            if self._invalid_reason is not None:
                reason = self._invalid_reason
                self._invalid_reason = None
                self._reset_locked()
            elif self._state == "flush":  # caller skipped end_step
                self._reset_locked()
            if self._state == "ready":
                self._slots = []
                self._fuse_slots = []
            elif self._state == "capture":
                self._cur = []
        if reason is not None:
            _log.info("engine capture '%s': invalidated (%s), recapturing",
                      self._name, reason)

    def end_step(self):
        st = self._state
        if st == "ready":
            with self._lock:
                slots, self._slots = self._slots, []
                fuses, self._fuse_slots = self._fuse_slots, []
            if len(slots) != len(self._ops):
                self._flush_eager(
                    slots, "iteration ended after %d of %d recorded ops"
                    % (len(slots), len(self._ops)))
                with self._lock:
                    self._reset_locked()
                return
            if self._fuse_wanted():
                with self._lock:
                    fstate = self._fuse_state
                if fstate is None:
                    self._stage_fuse(fuses)
                    with self._lock:
                        fstate = self._fuse_state
                if fstate == "staged":
                    if all(f is not None for f in fuses):
                        self._submit_fused(slots, fuses)
                        self.fused_runs += 1
                        return
                    # a recorded slot lost its metadata mid-stream: the
                    # staged registers would go stale — kill the program
                    # and fall through to replay
                    self._fuse_dead("a slot was pushed without fuse "
                                    "metadata", "run")
            self._submit_replay(slots)
            self.replays += 1
        elif st == "capture":
            cur, self._cur = self._cur, None
            if cur is not None:
                self._iters.append(cur)
                if len(self._iters) >= self._warmup:
                    self._compile()
        elif st == "flush":
            with self._lock:
                self._reset_locked()

    # -- pushes ---------------------------------------------------------

    def push(self, fn: Callable[[], None], const_vars: Sequence[int] = (),
             mutable_vars: Sequence[int] = (), priority: int = 0,
             name: str = "op", fuse: Optional[FuseOp] = None):
        """Sync push routed through the capture state machine. ``fuse``
        carries the op's traceable metadata (trace-and-fuse); ``None``
        marks the op non-traceable, keeping the sequence on replay."""
        self._push(False, fn, const_vars, mutable_vars, priority, name,
                   fuse)

    def push_async(self, fn: Callable[[Callable[[], None]], None],
                   const_vars: Sequence[int] = (),
                   mutable_vars: Sequence[int] = (), priority: int = 0,
                   name: str = "op", fuse: Optional[FuseOp] = None):
        """Async push routed through the capture state machine. ``fuse``
        as in :meth:`push` — a fused iteration publishes the op's effects
        through ``FuseOp.writeback`` instead of running ``fn``."""
        self._push(True, fn, const_vars, mutable_vars, priority, name,
                   fuse)

    def _push(self, is_async, fn, const_vars, mutable_vars, priority, name,
              fuse=None):
        sig = (is_async, name, int(priority),
               tuple(const_vars), tuple(mutable_vars))
        st = self._state
        if st == "ready":
            i = len(self._slots)
            if i < len(self._ops) and self._ops[i][0] == sig:
                self._slots.append(fn)
                self._fuse_slots.append(fuse)
                return
            with self._lock:
                slots, self._slots = self._slots, []
                self._fuse_slots = []
                self._state = "flush"
            self._flush_eager(
                slots, "op %d is %r, recorded %r" % (
                    i, name,
                    self._ops[i][0][1] if i < len(self._ops) else "<end>"))
        elif st == "capture":
            if self._cur is not None:
                self._cur.append(sig)
        # capture warmup, flush, and bailed-eager all forward eagerly
        if is_async:
            push_async(fn, const_vars, mutable_vars, priority, name)
        else:
            push(fn, const_vars, mutable_vars, priority, name)

    # -- internals ------------------------------------------------------

    def _reset_locked(self):
        self._state = "capture"
        self._iters = []
        self._cur = None
        self._ops = None
        self._slots = []
        self._fuse_slots = []
        self._fuse_state = None
        self._fused = None

    def _flush_eager(self, slots, why):
        """Replay deviated: run the already-matched prefix eagerly, in
        recorded order, so nothing is lost or reordered."""
        self.bails += 1
        _log.info("engine capture '%s': replay mismatch (%s); flushing %d "
                  "op(s) eagerly and recapturing", self._name, why,
                  len(slots))
        for j, fn in enumerate(slots):
            s_async, s_name, s_pri, s_const, s_mut = self._ops[j][0]
            if s_async:
                push_async(fn, s_const, s_mut, s_pri, s_name)
            else:
                push(fn, s_const, s_mut, s_pri, s_name)

    def _compile(self):
        """All warmup iterations observed: verify stability, resolve the
        dependency edges once, or bail to eager."""
        first = self._iters[0]
        if not first:
            self._iters = []  # empty steps: nothing to replay, keep looking
            return
        for k, it in enumerate(self._iters[1:], 1):
            if it != first:
                with self._lock:
                    self._state = "eager"
                    self._iters = []
                self.bails += 1
                _log.info(
                    "engine capture '%s': unstable across warmup (iteration "
                    "%d has %d op(s), first had %d; or var topology "
                    "changed) — staying eager until invalidated",
                    self._name, k, len(it), len(first))
                return
        ops = []
        last_writer: Dict[int, int] = {}
        readers_since: Dict[int, list] = {}
        union_mut: Dict[int, None] = {}
        union_const: Dict[int, None] = {}
        for i, sig in enumerate(first):
            const, mut = _dedup(sig[3], sig[4])  # per-op _dedup, done ONCE
            deps = set()
            for v in const:
                if v in last_writer:
                    deps.add(last_writer[v])            # RAW
            for v in mut:
                if v in last_writer:
                    deps.add(last_writer[v])            # WAW
                deps.update(readers_since.get(v, ()))   # WAR
            for v in const:
                readers_since.setdefault(v, []).append(i)
                union_const.setdefault(v)
            for v in mut:
                last_writer[v] = i
                readers_since[v] = []
                union_mut.setdefault(v)
            ops.append((sig, tuple(sorted(deps))))
        u_mut = tuple(union_mut)
        u_const = tuple(v for v in union_const if v not in union_mut)
        with self._lock:
            self._ops = ops
            self._union = (u_const, u_mut)
            self._iters = []
            self._state = "ready"
        _log.info("engine capture '%s': captured %d op(s) over %d vars, "
                  "replaying", self._name, len(ops),
                  len(u_const) + len(u_mut))

    def _submit_replay(self, slots):
        """Submit one iteration as a single module-level push_async. The
        union var set makes fence()/in-flight/file-var semantics hold for
        the whole sequence; inside, ops run in recorded order waiting
        only on precomputed edges to async predecessors."""
        ops = self._ops
        seq_name = self._name

        def replay(on_complete, _slots=slots, _ops=ops):
            tok = _telemetry.begin("engine.replay", domain="engine",
                                   ops=len(_ops), sequence=seq_name) \
                if _telemetry.enabled("engine") else None
            self._replay_children(_slots, _ops, seq_name)
            if tok is not None:
                _telemetry.end(tok)
            on_complete()

        push_async(replay, self._union[0], self._union[1],
                   name="replay:%s" % seq_name)

    @staticmethod
    def _replay_children(slots, ops, seq_name):
        """Run one iteration's recorded ops in order on the current engine
        worker, waiting only on the precomputed edges to async
        predecessors — the body of a replay submission, shared with the
        fused path's bail-to-replay fallback."""
        on_engine = _telemetry.enabled("engine")
        san = _san  # read once per replay: tests may toggle mid-run
        conf = san.replay_conflicts(ops) if san is not None else None
        events: List[Optional[threading.Event]] = [None] * len(ops)
        for i, (sig, deps) in enumerate(ops):
            is_async, opname = sig[0], sig[1]
            for d in deps:
                ev = events[d]
                if ev is not None:  # sync deps completed in program order
                    ev.wait()
            if conf is not None:
                # after the declared-edge waits, every conflicting
                # predecessor must already be done — or an edge is missing
                san.on_replay_child(seq_name, i, ops, conf, events)
            fn = slots[i]
            try:
                if is_async:
                    done_ev = threading.Event()
                    events[i] = done_ev
                    if on_engine:
                        optok = _telemetry.begin(opname, domain="engine",
                                                 replay=True)

                        def done(_ev=done_ev, _t=optok):
                            _telemetry.end(_t)
                            _ev.set()
                    else:
                        done = done_ev.set
                    fn(done)
                else:
                    if on_engine:
                        with _telemetry.span(opname, domain="engine",
                                             replay=True):
                            fn()
                    else:
                        fn()
            except Exception as e:  # mirror _dispatch: never escape the op
                traceback.print_exc()
                _notify_op_error(opname, e)
                if events[i] is not None:
                    events[i].set()
        # the submission completes only when every child has: that is
        # what keeps fence()/in-flight release correct under replay
        for ev in events:
            if ev is not None:
                ev.wait()

    # -- trace-and-fuse -------------------------------------------------

    def _fuse_wanted(self) -> bool:
        return self._fuse_opt if self._fuse_opt is not None \
            else fuse_enabled()

    def _fuse_dead(self, why: str, kind: str):
        with self._lock:
            self._fused = None
            self._fuse_state = "dead"
        self.fuse_bails += 1
        _count_fuse_bail(kind)
        _log.info("engine fuse '%s': %s; falling back to replay until the "
                  "sequence recaptures", self._name, why)

    def _stage_fuse(self, fuses):
        """First ready iteration with fusing requested: lower the recorded
        sequence into a FusedSequence, or mark why it cannot be."""
        try:
            missing = [i for i, f in enumerate(fuses) if f is None]
            if missing:
                raise _FuseIneligible(
                    "op(s) %s (%s) carry no traceable metadata"
                    % (missing,
                       ", ".join(self._ops[i][0][1] for i in missing)))
            prog = FusedSequence(self._name, self._ops, fuses, self._union)
        except _FuseIneligible as e:
            with self._lock:
                self._fuse_state = "ineligible"
            self.fuse_bails += 1
            _count_fuse_bail("ineligible")
            _log.info("engine fuse '%s': ineligible (%s); staying on "
                      "replay", self._name, e)
        except Exception:
            with self._lock:
                self._fuse_state = "dead"
            self.fuse_bails += 1
            _count_fuse_bail("stage")
            _log.warning("engine fuse '%s': staging failed; staying on "
                         "replay", self._name, exc_info=True)
        else:
            with self._lock:
                self._fused = prog
                self._fuse_state = "staged"

    def _submit_fused(self, slots, fuses):
        """Submit one iteration as a single module-level push_async running
        the staged program — same union var set as replay, so fences,
        in-flight accounting (one count) and async-completion semantics
        are unchanged. A pre-execution bail replays the iteration's
        recorded closures inline on the same worker."""
        prog = self._fused
        ops = self._ops
        seq_name = self._name
        prog.san_check(ops)

        def fused_run(on_complete, _slots=slots, _fuses=fuses, _prog=prog,
                      _ops=ops):
            tok = _telemetry.begin("engine.fused_run", domain="engine",
                                   ops=len(_ops), sequence=seq_name,
                                   signature=_prog.signature[:12]) \
                if _telemetry.enabled("engine") else None
            try:
                _prog.run_iteration(_fuses)
                _fuse_stats["runs"] += 1
                _fused_runs_counter.inc()
            except _FuseBail as e:
                # nothing was published yet: the iteration replays whole
                self._fuse_dead("bailed (%s)" % e, "run")
                self._replay_children(_slots, _ops, seq_name)
            except Exception as e:
                # a writeback failed mid-publish: replaying could double-
                # apply effects, so surface it like any failed engine op
                self._fuse_dead("writeback failed (%s)" % e, "error")
                traceback.print_exc()
                _notify_op_error("fused:%s" % seq_name, e)
            finally:
                if tok is not None:
                    _telemetry.end(tok)
                on_complete()

        push_async(fused_run, self._union[0], self._union[1],
                   name="fused:%s" % seq_name)


# --- happens-before sanitizer (MXNET_ENGINE_SANITIZER) -----------------------
# Dynamic half of mxnet_tpu.analysis.racecheck: with MXNET_ENGINE_SANITIZER=1
# (or sanitizer_enable()), every module-level push is checked against shadow
# epochs per engine var. Host state registered with guard_state(obj, var) is
# found by a bounded reachability scan over the pushed fn (closure cells,
# defaults, functools.partial, bound-method instances — one helper level
# deep); reaching it without declaring its var, while a prior access is not
# yet settled by a fence/wait on that var, is a race: the engine has no edge
# ordering the two ops. Checks run at push time only — op fns execute exactly
# as without the sanitizer (so MXNET_FAULT_PLAN composes untouched). Replays
# additionally validate that CapturedSequence's pre-resolved edge set
# dominates the conflict set: when a child starts, every conflicting async
# predecessor's done-event must already be set (declared edges + program
# order make that transitively true iff no edge is missing).
#
# Disabled path: `_san` stays None and every hook is one global load + branch.
_san = None
_san_lock = threading.Lock()  # leaf (rank 100): guards shadow tables only


def _san_site() -> str:
    """First stack frame outside this file — the user-visible push site."""
    for fr in reversed(traceback.extract_stack(limit=12)[:-2]):
        if not fr.filename.endswith("engine.py"):
            return "%s:%d" % (os.path.basename(fr.filename), fr.lineno)
    return "<engine>"


class _ShadowVar:
    __slots__ = ("epoch", "decl_epoch", "synced", "last", "deleted")

    def __init__(self):
        self.epoch = 0       # every tracked access, declared or undeclared
        self.decl_epoch = 0  # high-water mark of declared accesses only
        self.synced = 0      # decl_epoch as of the last fence/wait on the var
        self.last = None     # (op, site, mode, declared-var frozenset)
        self.deleted = None  # site of delete_variable once deleted

    def settled(self) -> bool:
        return self.epoch <= self.synced


class _Sanitizer:
    """Shadow-state tracker behind the module-level engine API."""

    MAX_REPORTS = 1000

    def __init__(self):
        self._vars: Dict[int, _ShadowVar] = {}
        # id(obj) -> (obj, var, desc); strong refs so ids are never reused
        self._guards: Dict[int, Tuple[object, int, str]] = {}
        self.reports: List[dict] = []

    # -- guard registry ------------------------------------------------------
    def guard(self, obj, var, desc):
        with _san_lock:
            self._guards[id(obj)] = (obj, int(var), desc)

    def unguard(self, obj):
        with _san_lock:
            self._guards.pop(id(obj), None)

    def _reachable_guards(self, fn):
        """Guarded objects reachable from a pushed callable. Lock-free: only
        dict probes on the guard registry (GIL-atomic)."""
        found, seen = [], set()
        stack = [(fn, 2)]
        budget = 256
        while stack and budget:
            obj, depth = stack.pop()
            oid = id(obj)
            if oid in seen:
                continue
            seen.add(oid)
            budget -= 1
            hit = self._guards.get(oid)
            if hit is not None and hit[0] is obj:
                found.append((hit[1], hit[2]))
                continue
            if depth <= 0:
                continue
            if isinstance(obj, functools.partial):
                stack.append((obj.func, depth))
                stack.extend((a, depth) for a in obj.args)
                stack.extend((v, depth) for v in obj.keywords.values())
            elif isinstance(obj, (list, tuple, set, frozenset)):
                stack.extend((e, depth) for e in list(obj)[:32])
            elif isinstance(obj, dict):
                stack.extend((v, depth) for v in list(obj.values())[:32])
            elif isinstance(obj, (types.ModuleType, type)):
                pass  # never walk module/class namespaces
            else:
                inst = getattr(obj, "__self__", None)
                if inst is not None and not isinstance(
                        inst, (types.ModuleType, type)):
                    stack.append((inst, depth - 1))
                f = getattr(obj, "__func__", obj)
                cells = getattr(f, "__closure__", None)
                if cells:
                    for c in cells:
                        try:
                            stack.append((c.cell_contents, depth - 1))
                        except ValueError:  # empty cell
                            pass
                dfl = getattr(f, "__defaults__", None)
                if dfl:
                    stack.extend((v, depth - 1) for v in dfl)
                code = getattr(f, "__code__", None)
                gl = getattr(f, "__globals__", None)
                if code is not None and gl is not None:
                    # module-global state (and global helpers) the fn names
                    for nm in code.co_names[:32]:
                        if nm in gl:
                            stack.append((gl[nm], depth - 1))
                if not callable(obj):
                    d = getattr(obj, "__dict__", None)
                    if isinstance(d, dict):
                        stack.extend(
                            (v, depth - 1) for v in list(d.values())[:64])
        return found

    # -- hooks (called from the module-level wrappers) -----------------------
    def on_new(self, var):
        with _san_lock:
            self._vars.pop(int(var), None)

    def on_delete(self, var):
        site = _san_site()
        with _san_lock:
            self._vars.setdefault(int(var), _ShadowVar()).deleted = site

    def on_sync(self, vars):
        """A wait completed: declared accesses on `vars` (all vars if None)
        happened-before this point. Undeclared epochs stay unsettled — a
        fence only covers ops the engine knew about."""
        with _san_lock:
            if vars is None:
                cells = list(self._vars.values())
            else:
                cells = [self._vars[v] for v in (int(x) for x in vars)
                         if v in self._vars]
            for cell in cells:
                cell.synced = cell.decl_epoch

    def on_fence(self, vars, name):
        site = _san_site()
        out = []
        with _san_lock:
            for v in (int(x) for x in vars):
                cell = self._vars.get(v)
                if cell is not None and cell.deleted is not None:
                    out.append(self._mk(
                        "var-use-after-delete", v, name, site,
                        "delete_variable", cell.deleted,
                        detail="fence names var %d after deletion" % v))
        for rep in out:
            self._emit(rep)

    def on_push(self, fn, const_vars, mutable_vars, name):
        site = _san_site()
        mut = {int(v) for v in mutable_vars}
        declared = {int(v) for v in const_vars} | mut
        touched = self._reachable_guards(fn)
        out = []
        with _san_lock:
            for v in sorted(declared):
                cell = self._vars.get(v)
                if cell is not None and cell.deleted is not None:
                    out.append(self._mk(
                        "var-use-after-delete", v, name, site,
                        "delete_variable", cell.deleted,
                        detail="op declares var %d after deletion" % v))
            for v, desc in touched:
                if v in declared:
                    continue  # ordered: the engine sees this access
                cell = self._vars.setdefault(v, _ShadowVar())
                last = cell.last
                # a shared declared var with the previous access orders the
                # two ops even though this one skips the guard var
                if not cell.settled() and last is not None \
                        and not (declared & last[3]):
                    out.append(self._mk(
                        "undeclared-var-access", v, name, site,
                        last[0], last[1],
                        detail="op reaches state %r guarded by var %d "
                               "without declaring it" % (desc, v)))
                cell.epoch += 1
                cell.last = (name, site, "undeclared", frozenset(declared))
            for v in sorted(declared):
                cell = self._vars.setdefault(v, _ShadowVar())
                last = cell.last
                if not cell.settled() and last is not None \
                        and last[2] == "undeclared" \
                        and not (declared & last[3]):
                    out.append(self._mk(
                        "undeclared-var-access", v, name, site,
                        last[0], last[1],
                        detail="declared access races the earlier "
                               "undeclared access to var %d" % v))
                cell.epoch += 1
                cell.decl_epoch = cell.epoch
                cell.last = (name, site,
                             "write" if v in mut else "read",
                             frozenset(declared))
        for rep in out:
            self._emit(rep)

    # -- replay validation ---------------------------------------------------
    @staticmethod
    def replay_conflicts(ops):
        """Full conflict-predecessor map over a captured sequence: for each
        child, every earlier child sharing a var with at least one writer.
        The pre-resolved edge set must dominate this."""
        conf = []
        writers: Dict[int, List[int]] = {}
        readers: Dict[int, List[int]] = {}
        for i, (sig, _deps) in enumerate(ops):
            const, mutv = _dedup(sig[3], sig[4])
            c = set()
            for v in const:
                c.update(writers.get(v, ()))
            for v in mutv:
                c.update(writers.get(v, ()))
                c.update(readers.get(v, ()))
            conf.append(tuple(sorted(c)))
            for v in const:
                readers.setdefault(v, []).append(i)
            for v in mutv:
                writers.setdefault(v, []).append(i)
                readers[v] = []
        return conf

    def on_replay_child(self, seq, i, ops, conf, events):
        for j in conf[i]:
            ev = events[j]
            if ev is None or ev.is_set():
                continue  # sync child (done in program order) or completed
            sig_i, sig_j = ops[i][0], ops[j][0]
            shared = sorted(
                ({int(v) for v in sig_i[3]} | {int(v) for v in sig_i[4]})
                & ({int(v) for v in sig_j[3]} | {int(v) for v in sig_j[4]}))
            self._emit(self._mk(
                "replay-edge-violation", shared[0] if shared else -1,
                sig_i[1], "%s[%d]" % (seq, i), sig_j[1], "%s[%d]" % (seq, j),
                detail="replay child %d starts before conflicting async "
                       "child %d completed (shared vars %r): pre-resolved "
                       "edges do not dominate the access set" % (i, j,
                                                                 shared)))

    # -- reporting -----------------------------------------------------------
    @staticmethod
    def _mk(rule, var, op, site, other_op, other_site, detail=""):
        return {"rule": rule, "var": int(var), "op": op, "site": site,
                "other_op": other_op, "other_site": other_site,
                "detail": detail,
                "stack": "".join(traceback.format_stack(limit=8)[:-2])}

    def _emit(self, rep):
        with _san_lock:
            if len(self.reports) < self.MAX_REPORTS:
                self.reports.append(rep)
        # counter/log have their own locking: keep them OUTSIDE _san_lock
        _san_counter.inc()
        _log.error(
            "engine sanitizer [%s] var %d: op '%s' at %s vs op '%s' at %s"
            " — %s", rep["rule"], rep["var"], rep["op"], rep["site"],
            rep["other_op"], rep["other_site"], rep["detail"])


_san_counter = _telemetry.registry.counter(
    "engine_sanitizer_reports_total",
    help="Races reported by the engine happens-before sanitizer")


def sanitizer_enabled() -> bool:
    return _san is not None


def sanitizer_enable(on: bool = True):
    """Turn the happens-before sanitizer on (fresh shadow state) or off at
    runtime; the import-time switch is MXNET_ENGINE_SANITIZER=1."""
    global _san
    _san = _Sanitizer() if on else None


def sanitizer_reports() -> List[dict]:
    """Snapshot of race reports since the sanitizer was (re-)enabled."""
    if _san is None:
        return []
    with _san_lock:
        return list(_san.reports)


def sanitizer_clear():
    """Drop accumulated reports; shadow epochs and guards are kept."""
    if _san is not None:
        with _san_lock:
            del _san.reports[:]


def guard_state(obj, var, name: Optional[str] = None):
    """Register ``obj`` (host container/buffer) as engine state ordered by
    ``var``: any pushed fn that can reach ``obj`` without declaring ``var``
    races every unsettled access. No-op while the sanitizer is off."""
    if _san is not None:
        _san.guard(obj, var, name or type(obj).__name__)
    return obj


def unguard_state(obj):
    if _san is not None:
        _san.unguard(obj)


if os.environ.get("MXNET_ENGINE_SANITIZER", "0").strip().lower() \
        not in ("", "0", "false", "off"):
    _san = _Sanitizer()


# --- per-var in-flight accounting --------------------------------------------
# Opt-in queued-or-running op counts per engine variable, the signal a
# load-aware dispatcher needs (serving's least-outstanding-work router reads
# its replica vars through this): a var registered with track_inflight() has
# every module-level push/push_async mentioning it counted at push time and
# released when the op completes (sync: fn returned; async: on_complete
# fired). Untracked vars pay nothing — one dict probe per push.
_inflight: Dict[int, int] = {}
_inflight_lock = threading.Lock()


def track_inflight(var: int):
    """Register ``var`` for in-flight accounting (idempotent)."""
    with _inflight_lock:
        _inflight.setdefault(int(var), 0)


def untrack_inflight(var: int):
    """Stop accounting for ``var`` and drop its counter."""
    with _inflight_lock:
        _inflight.pop(int(var), None)


def var_inflight(var: int) -> int:
    """Ops queued or running that mention ``var`` (0 if untracked)."""
    with _inflight_lock:
        return _inflight.get(int(var), 0)


def _inflight_begin(vars) -> tuple:
    """Count the push against every tracked var; returns the vars counted
    (empty tuple => nothing tracked, no completion bookkeeping needed)."""
    if not _inflight:  # racy read is fine: tracking starts before pushing
        return ()
    counted = []
    with _inflight_lock:
        for v in vars:
            if v in _inflight:
                _inflight[v] += 1
                counted.append(v)
    return tuple(counted)


def _inflight_end(counted: tuple):
    with _inflight_lock:
        for v in counted:
            if v in _inflight:
                _inflight[v] -= 1


def _wrap_inflight_sync(fn, counted):
    def run():
        try:
            fn()
        finally:
            _inflight_end(counted)
    return run


def _wrap_inflight_async(fn, counted):
    def run(on_complete):
        released = []  # once-guard: the engine's error path may re-complete

        def done():
            if not released:
                released.append(1)
                _inflight_end(counted)
            on_complete()

        try:
            fn(done)
        except BaseException:
            # the engine completes an op whose fn raised without calling
            # our done(); release here so the counter can never leak high
            if not released:
                released.append(1)
                _inflight_end(counted)
            raise
    return run


# --- file-write routing ------------------------------------------------------
# Checkpoint/state blob writes ride the engine with one write-var per file
# path (the reference's NDArray save-through-engine: every host mutation of
# a named resource is an engine op, kvstore_dist.h:233-241 being the PS
# analogue). Writers push with the path's var mutable; readers wait on the
# var, so an in-flight async checkpoint is never half-read.
_file_vars: Dict[str, int] = {}
_file_pending: Dict[str, int] = {}  # writes queued-or-running per path
_file_waiting: Dict[str, int] = {}  # waiters pinning the var per path
_file_errs: Dict[str, BaseException] = {}
_file_lock = threading.Lock()


def file_var(path: str) -> int:
    """The engine write-var owning ``path`` (created on first use)."""
    path = os.path.abspath(path)
    with _file_lock:
        v = _file_vars.get(path)
        if v is None:
            v = get().new_variable()
            _file_vars[path] = v
        return v


def push_file_write(path: str, fn: Callable[[], None], wait: bool = True,
                    name: Optional[str] = None,
                    after_paths: Sequence[str] = ()):
    """Run ``fn`` (which writes ``path``) as an engine op holding the
    path's write-var. ``wait=False`` returns immediately — the write
    overlaps whatever the caller does next. A failed async write
    surfaces at the next ``wait_for_file(path)``, OR at the next
    ``push_file_write``/``wait_for_all`` on ANY path (per-epoch
    checkpoints use distinct filenames, so surfacing must not be
    per-path-only — a full disk would otherwise lose every later
    checkpoint silently).

    ``after_paths`` orders this write AFTER every previously enqueued
    write on those paths (their file-vars become const deps): the
    commit-manifest-after-all-shards edge sharded checkpoints need —
    the manifest op cannot run until every shard op finished, so a
    crash at any point leaves either no manifest or a manifest whose
    shards are all fully on disk."""
    apath = os.path.abspath(path)
    _raise_pending_file_error()
    eng = get()
    deps = []
    with _file_lock:
        var = _file_vars.get(apath)
        if var is None:
            var = eng.new_variable()
            _file_vars[apath] = var
        # counted under the SAME lock acquisition that resolved the var,
        # so wait_for_file can never retire a var with a write en route
        _file_pending[apath] = _file_pending.get(apath, 0) + 1
        dep_paths = []
        for p in after_paths:
            ap = os.path.abspath(p)
            if ap == apath:
                continue
            dv = _file_vars.get(ap)
            if dv is None:
                continue  # nothing ever written there: no edge needed
            deps.append(dv)
            dep_paths.append(ap)
            # pin the dep vars against retirement until this op completes
            # (a const reader is invisible to _file_pending otherwise)
            _file_pending[ap] = _file_pending.get(ap, 0) + 1

    def run():
        try:
            fn()
        except BaseException as e:  # surface at the next sync point
            with _file_lock:
                _file_errs[apath] = e
        finally:
            with _file_lock:
                _file_pending[apath] -= 1
                for ap in dep_paths:
                    _file_pending[ap] -= 1

    eng.push(run, const_vars=deps, mutable_vars=[var],
             name=name or ("file_write:%s" % os.path.basename(apath)))
    if wait:
        wait_for_file(apath)


def _raise_pending_file_error():
    with _file_lock:
        if not _file_errs:
            return
        path, err = next(iter(_file_errs.items()))
        del _file_errs[path]
    raise err


def _retire_file_var(apath: str, var: int):
    """Drop the path's var ONLY if no write is queued/in flight, no other
    waiter holds it, and the mapping is unchanged (guards the concurrent
    writer AND concurrent waiter races); the native delete is itself
    ordered after the var's enqueued ops."""
    with _file_lock:
        if (_file_pending.get(apath, 0) != 0
                or _file_waiting.get(apath, 0) != 0
                or _file_vars.get(apath) is not var):
            return
        del _file_vars[apath]
        _file_pending.pop(apath, None)
    get().delete_variable(var)


def wait_for_file(path: str):
    """Block until every pending engine op on ``path`` finished; re-raise
    the first failure recorded for it. Once drained (and only if no new
    write or other waiter raced in), the path's engine var is retired so
    long runs with per-epoch filenames don't grow the var table without
    bound."""
    apath = os.path.abspath(path)
    with _file_lock:
        var = _file_vars.get(apath)
        if var is not None:
            # pin: a concurrent wait_for_file must not retire+delete the
            # var between our lookup and the native wait
            _file_waiting[apath] = _file_waiting.get(apath, 0) + 1
    if var is not None:
        try:
            get().wait_for_var(var)
        finally:
            with _file_lock:
                _file_waiting[apath] -= 1
                if _file_waiting[apath] == 0:
                    del _file_waiting[apath]  # no unbounded per-path table
        _retire_file_var(apath, var)
    with _file_lock:
        err = _file_errs.pop(apath, None)
    if err is not None:
        raise err


def wait_for_all_files():
    """Drain every pending file write and surface the first failure —
    call at end-of-training when using async_write."""
    with _file_lock:
        pending = list(_file_vars)
    first_err = None
    for apath in pending:
        try:
            wait_for_file(apath)
        except BaseException as e:
            # drain EVERY path before surfacing: a caller that catches the
            # error must still find the other checkpoints fully written
            if first_err is None:
                first_err = e
    if first_err is not None:
        raise first_err
    _raise_pending_file_error()


# queue depth for the metrics registry — the callback reads the module
# global at scrape time and never instantiates an engine itself
_telemetry.registry.gauge(
    "engine_pending_ops",
    fn=lambda: _engine.pending() if _engine is not None else 0,
    help="ops queued or running on the host dependency engine")
