"""Monitor — per-tensor statistics during training.

Reimplementation of python/mxnet/monitor.py (SURVEY §5.1): regex-selected
per-array stats collected via the executor monitor callback
(graph_executor.cc:761-781 equivalent in executor.py).

Stat computation rides the host engine: every tap is pushed as an engine
op on a monitor-owned variable, so the training thread never pays for
``stat_func`` (reference monitor.py blocks on it inline), and draining is
one ``engine.fence([var]).wait()`` — the real happens-before edge over
all pushed taps — plus a single tree-level ``jax.block_until_ready`` for
device settlement, instead of a per-array ``wait_to_read`` loop (the
analysis suite's ``drain-as-fence`` antipattern). Ops on one variable
serialize, so ``self.queue`` needs no lock.
"""
from __future__ import annotations

import logging
import re
from math import sqrt

import jax

from . import engine
from . import ndarray as nd
from . import telemetry
from .ndarray import NDArray


class Monitor:
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def asum_stat(x):
                return nd.norm(x) / sqrt(x.size)

            stat_func = asum_stat
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort
        self._var = None  # engine variable serializing the stat taps

        def stat_helper(name, arr):
            if not self.activated or not self.re_prog.match(name):
                return
            self._push_stat(self.step, name, arr)

        # executors probe this to skip the (costly) internal-output
        # evaluation entirely on batches where the monitor is idle
        stat_helper.is_active = lambda: self.activated
        self.stat_helper = stat_helper

    def _stat_var(self):
        if self._var is None:
            self._var = engine.new_variable()
        return self._var

    def _push_stat(self, step, name, arr):
        """Queue one stat computation on an engine worker. ``arr`` wraps an
        immutable jax.Array, so the deferred read is a consistent
        snapshot; the monitor var orders taps in push order."""
        def compute(step=step, name=name, arr=arr):
            with telemetry.span("monitor.stat", domain="monitor",
                                stat=name):
                self.queue.append((step, name, self.stat_func(arr)))

        engine.push(compute, mutable_vars=[self._stat_var()],
                    name="monitor_stat")

    def _drain(self):
        """Fence the monitor var (all pushed taps have appended to
        ``queue``) and settle the executors' device arrays in one call."""
        with telemetry.span("monitor.drain", domain="monitor",
                            n_exes=len(self.exes)):
            if self._var is not None:
                engine.fence([self._var], name="monitor_fence").wait()
            arrs = [a._data for exe in self.exes for a in exe.arg_arrays]
            if arrs:
                jax.block_until_ready(arrs)

    def install(self, exe):
        exe.set_monitor_callback(self.stat_helper)
        self.exes.append(exe)

    def tic(self):
        if self.step % self.interval == 0:
            self._drain()
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        if not self.activated:
            return []
        for exe in self.exes:
            for name, array in zip(exe._symbol.list_arguments(),
                                   exe.arg_arrays):
                if self.re_prog.match(name):
                    self._push_stat(self.step, name, array)
        self._drain()
        self.activated = False
        res = []
        if self.sort:
            self.queue.sort(key=lambda x: x[1])
        for n, k, v_list in self.queue:
            if isinstance(v_list, NDArray):
                v_list = [v_list]
            assert isinstance(v_list, list)
            s = ""
            for v in v_list:
                assert isinstance(v, NDArray)
                if v.shape == (1,) or v.shape == ():
                    s += str(v.asscalar()) + "\t"
                else:
                    s += str(v.asnumpy()) + "\t"
            res.append((n, k, s))
        self.queue = []
        return res

    def toc_print(self):
        res = self.toc()
        for n, k, v in res:
            logging.info("Batch: %7d %30s %s", n, k, v)
