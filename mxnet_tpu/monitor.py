"""Monitor — per-tensor statistics during training.

Reimplementation of python/mxnet/monitor.py (SURVEY §5.1): regex-selected
per-array stats collected via the executor monitor callback
(graph_executor.cc:761-781 equivalent in executor.py)."""
from __future__ import annotations

import logging
import re
from math import sqrt

from . import ndarray as nd
from .ndarray import NDArray


class Monitor:
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def asum_stat(x):
                return nd.norm(x) / sqrt(x.size)

            stat_func = asum_stat
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort

        def stat_helper(name, arr):
            if not self.activated or not self.re_prog.match(name):
                return
            self.queue.append((self.step, name, self.stat_func(arr)))

        # executors probe this to skip the (costly) internal-output
        # evaluation entirely on batches where the monitor is idle
        stat_helper.is_active = lambda: self.activated
        self.stat_helper = stat_helper

    def install(self, exe):
        exe.set_monitor_callback(self.stat_helper)
        self.exes.append(exe)

    def tic(self):
        if self.step % self.interval == 0:
            for exe in self.exes:
                for array in exe.arg_arrays:
                    array.wait_to_read()
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        if not self.activated:
            return []
        for exe in self.exes:
            for array in exe.arg_arrays:
                array.wait_to_read()
        for exe in self.exes:
            for name, array in zip(exe._symbol.list_arguments(), exe.arg_arrays):
                if self.re_prog.match(name):
                    self.queue.append((self.step, name, self.stat_func(array)))
        self.activated = False
        res = []
        if self.sort:
            self.queue.sort(key=lambda x: x[1])
        for n, k, v_list in self.queue:
            if isinstance(v_list, NDArray):
                v_list = [v_list]
            assert isinstance(v_list, list)
            s = ""
            for v in v_list:
                assert isinstance(v, NDArray)
                if v.shape == (1,) or v.shape == ():
                    s += str(v.asscalar()) + "\t"
                else:
                    s += str(v.asnumpy()) + "\t"
            res.append((n, k, s))
        self.queue = []
        return res

    def toc_print(self):
        res = self.toc()
        for n, k, v in res:
            logging.info("Batch: %7d %30s %s", n, k, v)
