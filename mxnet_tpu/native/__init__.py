"""ctypes bindings for the native data-plane library (native/recordio.cc).

The flat-C-ABI + ctypes boundary mirrors the reference's C API discipline
(include/mxnet/c_api.h ↔ python/mxnet/base.py ctypes loading). The library
is built on demand with `make -C native`; all callers degrade to the pure-
Python path when the toolchain or libjpeg is unavailable.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SO = os.path.join(_REPO, "native", "libmxtpu_io.so")

_lib = None
_tried = False


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    src = os.path.join(os.path.dirname(_SO), "recordio.cc")
    stale = (os.path.exists(_SO) and os.path.exists(src)
             and os.path.getmtime(src) > os.path.getmtime(_SO))
    if not os.path.exists(_SO) or stale:
        try:
            subprocess.run(["make", "-C", os.path.dirname(_SO)], check=True,
                           capture_output=True, timeout=120)
        except Exception:
            if stale:  # keep using the older (but loadable) build
                pass
            else:
                return None
    try:
        lib = ctypes.CDLL(_SO)
        _bind(lib)
    except (OSError, AttributeError):
        # missing file OR a prebuilt .so lacking even the core symbols:
        # degrade to the pure-Python path rather than crash
        return None
    _lib = lib
    return _lib


def _bind(lib: ctypes.CDLL) -> None:
    lib.mxio_reader_open.restype = ctypes.c_void_p
    lib.mxio_reader_open.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.mxio_reader_next.restype = ctypes.c_int
    lib.mxio_reader_next.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_uint64)]
    lib.mxio_reader_reset.argtypes = [ctypes.c_void_p]
    lib.mxio_reader_close.argtypes = [ctypes.c_void_p]
    lib.mxio_writer_open.restype = ctypes.c_void_p
    lib.mxio_writer_open.argtypes = [ctypes.c_char_p]
    lib.mxio_writer_write.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(ctypes.c_uint8),
                                      ctypes.c_uint64]
    lib.mxio_writer_close.argtypes = [ctypes.c_void_p]
    lib.mxio_imgloader_create.restype = ctypes.c_void_p
    lib.mxio_imgloader_create.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
        ctypes.c_int, ctypes.c_int, ctypes.c_uint64, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.POINTER(ctypes.c_int)]
    # aug transforms are newer symbols: bind optionally so a stale prebuilt
    # .so (no toolchain to rebuild) keeps its reader/writer/loader usable
    try:
        lib.mxio_aug_rotate.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_int,
            ctypes.c_float, ctypes.c_int, ctypes.POINTER(ctypes.c_uint8)]
        lib.mxio_aug_hsl.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int]
        lib._mxtpu_has_aug = True
    except AttributeError:
        lib._mxtpu_has_aug = False
    try:
        lib.mxio_imgloader_create2.restype = ctypes.c_void_p
        lib.mxio_imgloader_create2.argtypes = \
            list(lib.mxio_imgloader_create.argtypes) + [ctypes.c_int]
        lib._mxtpu_has_label_width = True
    except AttributeError:
        lib._mxtpu_has_label_width = False
    try:
        lib.mxio_im2rec.restype = ctypes.c_int64
        lib.mxio_im2rec.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int]
        lib._mxtpu_has_im2rec = True
    except AttributeError:
        lib._mxtpu_has_im2rec = False
    lib.mxio_imgloader_next.restype = ctypes.c_int
    lib.mxio_imgloader_next.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_float)]
    lib.mxio_imgloader_reset.argtypes = [ctypes.c_void_p]
    lib.mxio_imgloader_destroy.argtypes = [ctypes.c_void_p]


def available() -> bool:
    return load() is not None


def aug_rotate(img: np.ndarray, angle: float, fill: int = 255) -> np.ndarray:
    """Native rotation transform on an (H, W, 3) uint8 RGB array (exported
    for golden tests vs image.rotate_image)."""
    lib = load()
    if lib is None or not getattr(lib, "_mxtpu_has_aug", False):
        raise RuntimeError("native io library unavailable (or too old "
                           "for aug transforms)")
    img = np.ascontiguousarray(img, np.uint8)
    h, w = img.shape[:2]
    out = np.empty_like(img)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.mxio_aug_rotate(img.ctypes.data_as(u8p), w, h,
                        ctypes.c_float(angle), fill,
                        out.ctypes.data_as(u8p))
    return out


def aug_hsl(img: np.ndarray, dh: int, ds: int, dl: int) -> np.ndarray:
    """Native HLS-space jitter on an (H, W, 3) uint8 RGB array (exported
    for golden tests vs image.hsl_shift)."""
    lib = load()
    if lib is None or not getattr(lib, "_mxtpu_has_aug", False):
        raise RuntimeError("native io library unavailable (or too old "
                           "for aug transforms)")
    out = np.ascontiguousarray(img, np.uint8).copy()
    h, w = out.shape[:2]
    lib.mxio_aug_hsl(out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                     w, h, dh, ds, dl)
    return out


def im2rec_pack(lst_path, root, rec_path, idx_path, resize=0, quality=95,
                nthreads=4):
    """Multithreaded .lst -> .rec/.idx packer (the reference's C++
    tools/im2rec.cc). Returns the number of records written. Ordered
    output: byte-identical regardless of thread count."""
    lib = load()
    if lib is None or not getattr(lib, "_mxtpu_has_im2rec", False):
        raise RuntimeError("native io library unavailable (or too old "
                           "for im2rec)")
    n = lib.mxio_im2rec(str(lst_path).encode(), str(root).encode(),
                        str(rec_path).encode(), str(idx_path).encode(),
                        int(resize), int(quality), int(nthreads))
    if n < 0:
        raise IOError("mxio_im2rec failed (unreadable .lst or unwritable "
                      "output paths)")
    return int(n)


class NativeRecordReader:
    """Sharded sequential reader over a .rec file (native)."""

    def __init__(self, path, part_index=0, num_parts=1):
        lib = load()
        if lib is None:
            raise RuntimeError("native io library unavailable")
        self._lib = lib
        self._h = lib.mxio_reader_open(path.encode(), part_index, num_parts)
        if not self._h:
            raise IOError("cannot open %s" % path)

    def read(self):
        data = ctypes.POINTER(ctypes.c_uint8)()
        length = ctypes.c_uint64()
        if not self._lib.mxio_reader_next(self._h, ctypes.byref(data),
                                          ctypes.byref(length)):
            return None
        return ctypes.string_at(data, length.value)

    def reset(self):
        self._lib.mxio_reader_reset(self._h)

    def close(self):
        if self._h:
            self._lib.mxio_reader_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeImageLoader:
    """Threaded JPEG-decoding batch loader (native ImageRecordIOParser2
    analogue). Yields (data (N,C,H,W) float32, labels (N,), n_valid)."""

    def __init__(self, path, batch_size, data_shape, nthreads=4,
                 rand_crop=False, rand_mirror=False, mean_rgb=None,
                 std_rgb=None, part_index=0, num_parts=1, seed=0,
                 resize_shorter=0, queue_depth=2, shuffle_buffer=0,
                 max_rotate_angle=0, rotate=-1, fill_value=255,
                 random_h=0, random_s=0, random_l=0, label_width=1):
        lib = load()
        if lib is None:
            raise RuntimeError("native io library unavailable")
        self._lib = lib
        c, h, w = data_shape
        mean = (ctypes.c_float * 3)(*(mean_rgb or (0.0, 0.0, 0.0)))
        std = (ctypes.c_float * 3)(*(std_rgb or (1.0, 1.0, 1.0)))
        wants_aug = (max_rotate_angle > 0 or rotate > 0 or random_h
                     or random_s or random_l)
        if wants_aug and not getattr(lib, "_mxtpu_has_aug", False):
            # old prebuilt .so: it would silently drop these params — fall
            # back to the Python reader, which honors them
            raise RuntimeError("native io library too old for aug params")
        aug = (ctypes.c_int * 6)(int(max_rotate_angle), int(rotate),
                                 int(fill_value), int(random_h),
                                 int(random_s), int(random_l))
        self.batch_size = batch_size
        self.data_shape = data_shape
        self.label_width = int(label_width)
        self._data = np.empty((batch_size, c, h, w), np.float32)
        if self.label_width > 1:
            if not getattr(lib, "_mxtpu_has_label_width", False):
                # old prebuilt .so would silently read zeros for packed
                # labels — fall back to the Python reader, which honors it
                raise RuntimeError("native io library too old for "
                                   "label_width")
            self._labels = np.empty((batch_size, self.label_width),
                                    np.float32)
            self._h = lib.mxio_imgloader_create2(
                path.encode(), batch_size, h, w, c, nthreads,
                int(rand_crop), int(rand_mirror), mean, std,
                part_index, num_parts, seed, resize_shorter, queue_depth,
                shuffle_buffer, aug, self.label_width)
        else:
            self._labels = np.empty((batch_size,), np.float32)
            self._h = lib.mxio_imgloader_create(
                path.encode(), batch_size, h, w, c, nthreads,
                int(rand_crop), int(rand_mirror), mean, std,
                part_index, num_parts, seed, resize_shorter, queue_depth,
                shuffle_buffer, aug)
        if not self._h:
            raise IOError("cannot open %s" % path)

    def next_batch(self):
        n = self._lib.mxio_imgloader_next(
            self._h,
            self._data.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            self._labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        if n == 0:
            return None
        return self._data, self._labels, n

    def reset(self):
        self._lib.mxio_imgloader_reset(self._h)

    def close(self):
        if self._h:
            self._lib.mxio_imgloader_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
