"""Reference-ecosystem file interop: read (and write) the reference
framework's serialized model formats, so checkpoints from its model zoo
load directly into this framework.

Two stable public formats (SURVEY §2.6 "deployment story"):

1. **Symbol JSON** — the nnvm graph dump written by the reference's
   ``Symbol.save``: ``nodes`` (op/name/attr/inputs), ``arg_nodes``,
   ``heads``, with per-version quirks normalized by its legacy upgrader
   (/root/reference/src/nnvm/legacy_json_util.cc):
   - pre-0.9 graphs omit auxiliary-state inputs (BatchNorm moving
     stats): they are re-created as ``<node>_<auxname>`` variables
     (UpgradeJSON_000800_000900, legacy_json_util.cc:115-133);
   - "hidden" attribute keys (``lr_mult``/``wd_mult``/``ctx_group``/
     ``force_mirroring``, c_api_symbolic.cc:20-22) appear bare or
     arg-scoped (``weight_lr_mult``) in old files and must not reach the
     op's parameter parser (UpgradeJSON_FixParsing);
   - ``argmin/argmax`` with ``axis="-1"`` predate the optional-axis
     semantics and mean "flatten" (UpgradeJSON_000904_000905).
   Node attr dicts are stored under ``attr`` (0.9.x) or ``attrs``
   (1.x); both are accepted, as are 2- and 3-element input entries.

2. **.params blob** — the dmlc-stream NDArray container
   (src/ndarray/ndarray.cc:616-700): uint64 magic ``0x112`` + uint64
   reserved, a ``vector<NDArray>`` (uint64 count, then per array:
   TShape as uint32 ndim + per-dim extents, Context as int32 dev_type +
   int32 dev_id, int32 type_flag, raw bytes) and a ``vector<string>``
   of names (uint64 count, uint64 length + bytes each). Newer (1.x)
   files tag each array with NDARRAY_V1/V2 magics and widen dims to
   int64 (V2 adds an int32 storage-type field); all three layouts are
   read by sniffing the record's first uint32.

``mxnet_tpu.ndarray.load`` and ``mxnet_tpu.symbol.load_json`` detect
these formats automatically, so ``model.load_checkpoint`` works on a
reference-written checkpoint pair unchanged.
"""
from __future__ import annotations

import json
import struct
from typing import Dict, List, Tuple

import numpy as np

NDLIST_MAGIC = 0x112
_NDARRAY_V1_MAGIC = 0xF993FAC8
_NDARRAY_V2_MAGIC = 0xF993FAC9

# type_flag <-> numpy dtype (mshadow/base.h kFloat32... order)
_TYPE_FLAGS = {0: np.float32, 1: np.float64, 2: np.float16, 3: np.uint8,
               4: np.int32, 5: np.int8, 6: np.int64}
_FLAG_OF = {np.dtype(v).name: k for k, v in _TYPE_FLAGS.items()}


class _Reader:
    def __init__(self, data: bytes):
        self.d = data
        self.o = 0

    def take(self, n):
        if self.o + n > len(self.d):
            raise ValueError("reference .params blob truncated at byte %d"
                             % self.o)
        b = self.d[self.o:self.o + n]
        self.o += n
        return b

    def u32(self):
        return struct.unpack("<I", self.take(4))[0]

    def i32(self):
        return struct.unpack("<i", self.take(4))[0]

    def u64(self):
        return struct.unpack("<Q", self.take(8))[0]

    def peek_u32(self):
        if self.o + 4 > len(self.d):
            raise ValueError("reference .params blob truncated at byte %d"
                             % self.o)
        return struct.unpack("<I", self.d[self.o:self.o + 4])[0]


def _read_one_ndarray(r: _Reader) -> np.ndarray:
    first = r.peek_u32()
    if first in (_NDARRAY_V1_MAGIC, _NDARRAY_V2_MAGIC):
        r.u32()
        if first == _NDARRAY_V2_MAGIC:
            stype = r.i32()
            if stype != 0:  # kDefaultStorage
                raise ValueError("sparse reference NDArray (stype %d) not "
                                 "supported" % stype)
        ndim = r.u32()
        shape = tuple(struct.unpack("<%dq" % ndim, r.take(8 * ndim)))
    else:
        # legacy (<=0.11): TShape = uint32 ndim + uint32 extents
        ndim = r.u32()
        shape = tuple(struct.unpack("<%dI" % ndim, r.take(4 * ndim)))
    if ndim == 0:
        return np.zeros((), np.float32)
    r.i32()  # Context dev_type (always saved from CPU copy)
    r.i32()  # Context dev_id
    flag = r.i32()
    if flag not in _TYPE_FLAGS:
        raise ValueError("unknown reference dtype flag %d" % flag)
    dt = np.dtype(_TYPE_FLAGS[flag])
    n = int(np.prod(shape, dtype=np.int64))
    return np.frombuffer(r.take(n * dt.itemsize), dt).reshape(shape).copy()


def is_reference_params(head: bytes) -> bool:
    """First 8 bytes == the dmlc NDArray-list magic?"""
    return (len(head) >= 8
            and struct.unpack("<Q", head[:8])[0] == NDLIST_MAGIC)


def load_params(fname_or_bytes):
    """Read a reference ``.params`` blob. Returns a dict name->NDArray
    when the file carries names (``arg:``/``aux:`` prefixes preserved,
    exactly what model.load_checkpoint splits), else a list."""
    from . import ndarray as nd

    if isinstance(fname_or_bytes, bytes):
        data = fname_or_bytes
    else:
        with open(fname_or_bytes, "rb") as f:
            data = f.read()
    r = _Reader(data)
    if r.u64() != NDLIST_MAGIC:
        raise ValueError("not a reference NDArray file (bad magic)")
    r.u64()  # reserved
    arrays = [_read_one_ndarray(r) for _ in range(r.u64())]
    n_names = r.u64()
    names = [r.take(r.u64()).decode() for _ in range(n_names)]
    if names and len(names) != len(arrays):
        raise ValueError("reference .params name/array count mismatch")
    if names:
        return {k: nd.array(v) for k, v in zip(names, arrays)}
    return [nd.array(v) for v in arrays]


def save_params(fname: str, data) -> None:
    """Write the legacy dmlc blob (the layout of ndarray.cc:616-639 /
    675-683) so artifacts round-trip back into the reference ecosystem."""
    if isinstance(data, dict):
        names = list(data.keys())
        arrays = [np.asarray(data[k]._data if hasattr(data[k], "_data")
                             else data[k]) for k in names]
    else:
        names = []
        arrays = [np.asarray(v._data if hasattr(v, "_data") else v)
                  for v in data]
    out = [struct.pack("<QQ", NDLIST_MAGIC, 0),
           struct.pack("<Q", len(arrays))]
    for a in arrays:
        if a.dtype.name not in _FLAG_OF:
            raise ValueError("dtype %s has no reference type flag (cast "
                             "bf16 etc. first)" % a.dtype)
        out.append(struct.pack("<I", a.ndim))
        out.append(struct.pack("<%dI" % a.ndim, *a.shape))
        out.append(struct.pack("<ii", 1, 0))       # Context: cpu(0)
        out.append(struct.pack("<i", _FLAG_OF[a.dtype.name]))
        out.append(np.ascontiguousarray(a).tobytes())
    out.append(struct.pack("<Q", len(names)))
    for nm in names:
        b = nm.encode()
        out.append(struct.pack("<Q", len(b)) + b)
    with open(fname, "wb") as f:
        f.write(b"".join(out))


# --- symbol JSON ----------------------------------------------------------

_HIDDEN_KEYS = ("ctx_group", "lr_mult", "wd_mult", "force_mirroring",
                "mirror_stage", "init")
# legacy -> current op-name aliases seen in old zoo files
_OP_ALIASES = {"ElementWiseSum": "add_n"}


def _split_attrs(op, raw: Dict[str, str]):
    """Separate a reference node's attr dict into (param attrs for
    op.parse_attrs, misc attrs, arg-scoped hidden keys). Mirrors
    UpgradeJSON_FixParsing: hidden keys — bare (``lr_mult``), arg-scoped
    (``weight_lr_mult``, to be relocated onto the named input variable),
    or already ``__wrapped__`` — and anything the parameter struct
    doesn't know must not reach the parser. For variable nodes
    (op=None) every non-hidden attr is a user attribute and stays in
    misc verbatim (the reference's AttrScope storage)."""
    params, misc, arg_scoped = {}, {}, []
    known = set(op.param_spec or ()) if op is not None else set()
    for k, v in raw.items():
        if k.startswith("__") and k.endswith("__"):
            misc[k] = v
            continue
        hit = next((h for h in _HIDDEN_KEYS
                    if k == h or k.endswith("_" + h)), None)
        if hit is not None:
            if k == hit or op is None:
                misc["__%s__" % k] = v
            else:
                # weight_lr_mult on a Conv node belongs to the `weight`
                # input variable as __lr_mult__
                arg_scoped.append((k[:-(len(hit) + 1)], hit, v))
            continue
        if op is not None and k in known:
            params[k] = v
        else:
            # variables: user attrs verbatim; ops: num_args on variadic
            # ops (input count speaks) or attrs from newer reference
            # versions — keep, don't reject
            misc[k] = v
    return params, misc, arg_scoped


def load_symbol_json(json_str):
    """Build a Symbol from reference symbol JSON (any version the
    reference's own legacy upgrader accepts — see module docstring).
    Accepts the raw string or an already-parsed dict."""
    from .base import coerce_attr
    from .ops.registry import get_op
    from . import symbol as sym_mod

    data = (json_str if isinstance(json_str, dict)
            else json.loads(json_str))
    # graphs without a version stamp are pre-0.9 (the reference treats
    # absent as 0 and runs every upgrader)
    ver_attr = (data.get("attrs") or {}).get("mxnet_version")
    version = int(ver_attr[1]) if ver_attr else 0
    jnodes = data["nodes"]
    nodes: List[sym_mod._Node] = []  # indexed like the JSON node list
    for jn in jnodes:
        raw = dict(jn.get("attrs") or jn.get("attr") or jn.get("param")
                   or {})
        name = jn["name"]
        if jn["op"] == "null":
            params, misc, _ = _split_attrs(None, raw)
            misc.update(params)  # defensive: op=None routes all to misc
            nodes.append(sym_mod._Node(None, name, {}, [], False, misc))
            continue
        op = get_op(_OP_ALIASES.get(jn["op"], jn["op"]))
        params, misc, arg_scoped = _split_attrs(op, raw)
        # argmin/argmax axis=-1 predates optional axis and means
        # "flatten" ONLY in pre-0.9.5 files (UpgradeJSON_000904_000905
        # is gated on the version; 1.x uses -1 = last axis)
        if (version < 905 and op.name in ("argmax", "argmin")
                and params.get("axis") == "-1"):
            del params["axis"]
        attrs = op.parse_attrs({k: coerce_attr(v)
                                for k, v in params.items()})
        inputs = [(nodes[e[0]], e[1]) for e in jn["inputs"]]
        node = sym_mod._Node(op, name, attrs, inputs, False, misc)
        # pre-0.9 JSON omits aux-state inputs: recreate them as
        # <node>_<auxname> variables inheriting the node's attrs
        # (UpgradeJSON_000800_000900 + DefaultVarName). Synthesized vars
        # are reachable through node.inputs — they need no slot in
        # `nodes`, which mirrors the JSON indexing for input/head refs.
        aux_names = () if op.variadic else op.get_aux_names(attrs)
        n_args = len(inputs) if op.variadic else len(op.get_arg_names(attrs))
        while len(node.inputs) < n_args + len(aux_names):
            var = sym_mod._Node(
                None, "%s_%s" % (name, aux_names[len(node.inputs) - n_args]),
                {}, [], True, {})
            node.inputs.append((var, 0))
        # mark this op's aux inputs (reference: FMutateInputs positions)
        for child, _ in node.inputs[len(node.inputs) - len(aux_names):]:
            if child.is_var:
                child.is_aux = True
        # relocate arg-scoped hidden keys onto the named input variable
        # (UpgradeJSON_FixParsing's second branch); unmatched names fall
        # back to the op node's misc under the original key (mutate
        # node.misc_attrs, NOT the local dict: _Node replaces a falsy
        # misc with a fresh one at construction)
        if arg_scoped:
            argn = list(op.get_arg_names(attrs)) if not op.variadic else []
            for aname, hid, v in arg_scoped:
                if aname in argn and node.inputs[argn.index(aname)][0].is_var:
                    node.inputs[argn.index(aname)][0].misc_attrs[
                        "__%s__" % hid] = v
                else:
                    node.misc_attrs["%s_%s" % (aname, hid)] = v
        nodes.append(node)
    heads = data.get("heads", data.get("head"))
    entries = [(nodes[e[0]], e[1]) for e in heads]
    return sym_mod.Symbol(entries)


def is_reference_symbol_json(data: dict) -> bool:
    """Our own schema stamps attrs.mxnet_tpu_version; the reference's
    doesn't."""
    attrs = data.get("attrs") or {}
    return "nodes" in data and "mxnet_tpu_version" not in attrs


# write side -----------------------------------------------------------------

_REV_OP_ALIASES = {v: k for k, v in _OP_ALIASES.items()}


def _ref_attr_str(v) -> str:
    """Python attr value -> the reference's dmlc::Parameter string
    spelling ("(5,5)" tuples without spaces, "True"/"False", "None") —
    the forms `coerce_attr` and the reference's own parsers both read."""
    if v is None:
        return "None"
    if isinstance(v, bool):
        return "True" if v else "False"
    if isinstance(v, (tuple, list)):
        return "(%s)" % ",".join(_ref_attr_str(x) for x in v)
    return str(v)


def save_symbol_json(sym, indent: int = 2) -> str:
    """Emit reference-format symbol JSON (``nodes``/``arg_nodes``/
    ``node_row_ptr``/``heads`` + ``attrs.mxnet_version`` — the schema
    the reference's Symbol.save writes and legacy_json_util.cc reads;
    the write-side complement of :func:`load_symbol_json`, closing the
    ecosystem round trip the .params side already has).

    Aux-state inputs are emitted as ordinary variable nodes (the >=0.9
    convention); the reader reconstructs their aux positions from the
    op's own aux-name list. Hidden keys (``__lr_mult__`` etc.) are
    written wrapped, as the reference's C API stores them. Ops that
    exist only in this framework serialize under their own names — this
    repo's reader round-trips them; the reference era would reject them
    exactly as it rejects any unknown op."""
    nodes = sym._nodes()
    idx = {id(n): i for i, n in enumerate(nodes)}
    jnodes = []
    for n in nodes:
        jn = {"op": ("null" if n.is_var
                     else _REV_OP_ALIASES.get(n.op.name, n.op.name)),
              "name": n.name,
              "inputs": [[idx[id(c)], i, 0] for c, i in n.inputs]}
        attr = {}
        if not n.is_var:
            for k, v in n.attrs.items():
                attr[k] = _ref_attr_str(v)
            if n.op.variadic:
                # the reference's variadic ops carry their input count
                attr["num_args"] = str(len(n.inputs))
        for k, v in (n.misc_attrs or {}).items():
            attr[k] = v if isinstance(v, str) else _ref_attr_str(v)
        if attr:
            jn["attr"] = attr
        jnodes.append(jn)
    # node_row_ptr[i+1] = node_row_ptr[i] + num_outputs(node i): the
    # entry-index table graph-runtime consumers use — a flat +1 per node
    # would mis-index any graph with a multi-output op (SliceChannel...)
    row_ptr = [0]
    for n in nodes:
        n_out = 1 if n.is_var else n.op.get_num_outputs(n.attrs)
        row_ptr.append(row_ptr[-1] + n_out)
    return json.dumps(
        {
            "nodes": jnodes,
            "arg_nodes": [i for i, n in enumerate(nodes) if n.is_var],
            "node_row_ptr": row_ptr,
            "heads": [[idx[id(n)], i, 0] for n, i in sym._entries],
            "attrs": {"mxnet_version": ["int", 905]},
        },
        indent=indent,
    )
