"""mxnet_tpu.serving — dynamic-batching inference server.

The inference-workload half of the deployment story (docs/deployment.md
"Serving"): concurrent single-example requests coalesce into micro-batches
(``BatchFormer``), pad to the nearest configured batch bucket, and run
through a bucketed compile cache (``BucketCache`` — one XLA program per
bucket, parameters shared) dispatched via the host engine
(``InferenceServer``), with QPS/latency/occupancy/cache metrics
(``ServingMetrics``). Failures are structured ``ServingError``s.

The ``generate`` subpackage adds the autoregressive-decode workload on
the same server: continuous batching with iteration-level scheduling
(``DecodeScheduler``), slot-allocated KV slabs behind engine vars
(``KVCacheManager``), and a bounded fixed-shape program set
(``DecodePrograms``). Front door: ``InferenceServer.generate()`` /
``submit_stream()`` when constructed with ``decode=GenerateConfig(...)``.

    from mxnet_tpu import serving

    srv = serving.create_server("ckpt/m", epoch=1,
                                example_shapes={"data": (3, 224, 224)},
                                config=serving.ServingConfig(buckets=(1, 4, 8)))
    with srv:
        out = srv.predict(data=img[None])          # sync
        req = srv.submit(data=img[None])           # async future
        out = req.get(timeout=1.0)
    print(srv.metrics.get_name_value())
"""
from .batcher import (PRIORITY_BATCH, PRIORITY_INTERACTIVE, BatchFormer,
                      Request, ServingError)
from .bucket_cache import BucketCache
from .frontend import FrontendConfig, HttpFrontend
from .generate import (DecodeModel, DecodePrograms, DecodeScheduler,
                       DecodeSpec, GenerateConfig, KVCacheManager,
                       PagedDecodePrograms, PagedKVCacheManager,
                       TokenStream)
from .metrics import ServingBatchEndParam, ServingMetrics
from .server import InferenceServer, ServingConfig, create_server
from .staging import StagingPool
from .tuner import BucketTuner

__all__ = [
    "BatchFormer", "Request", "ServingError", "BucketCache",
    "PRIORITY_INTERACTIVE", "PRIORITY_BATCH",
    "FrontendConfig", "HttpFrontend",
    "ServingBatchEndParam", "ServingMetrics", "InferenceServer",
    "ServingConfig", "create_server", "StagingPool", "BucketTuner",
    "DecodeModel", "DecodeSpec", "DecodePrograms", "KVCacheManager",
    "PagedDecodePrograms", "PagedKVCacheManager",
    "DecodeScheduler", "GenerateConfig", "TokenStream",
]
