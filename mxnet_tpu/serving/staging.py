"""Reusable host staging buffers for zero-copy batch assembly.

The original dispatch path built every padded micro-batch from scratch:
``np.concatenate`` over the request rows plus an ``np.zeros`` pad block —
two fresh allocations and a full copy per dispatch, all on the engine
worker's critical path. ``StagingPool`` keeps ONE long-lived buffer per
(bucket, input) and writes request rows directly into it, so steady-state
assembly allocates nothing and touches only the real rows plus whatever
stale tail must be re-zeroed.

Correctness of the tail rests on a single invariant, maintained per
bucket: *after every fill, rows >= the filled count are zero.* A fresh
buffer starts all-zero (filled = 0); a fill writing ``r`` real rows only
needs to zero ``[r, prev_filled)`` — rows past ``prev_filled`` are
already zero by induction. A bimodal mix alternating 6-row and 1-row
batches therefore zeroes 5 rows instead of memsetting the whole bucket,
and the common monotone case zeroes nothing.

Reuse is safe because ``Predictor.forward`` copies host arrays to device
(``nd.array``) before the XLA call returns control: by the time the next
fill for this replica runs (serialized behind the same replica engine
var), the device owns its own copy and the staging rows are dead.

``_lock`` guards only the buffer table (creation / ``retain``); buffer
CONTENTS are never touched under it — fills are serialized per replica by
the engine var. Leaf rank 100 in analysis.LOCK_HIERARCHY: nothing is
called and no other lock is taken while held.
"""
from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Tuple

import numpy as np


class StagingPool:
    """Per-replica pool of reusable (bucket, input) staging buffers."""

    def __init__(self, example_shapes: Dict[str, tuple]):
        self._example_shapes = {n: tuple(s)
                                for n, s in example_shapes.items()}
        self._lock = threading.Lock()
        # (bucket, name) -> buffer; bucket -> rows filled at last dispatch
        self._buffers: Dict[Tuple[int, str], np.ndarray] = {}
        self._filled: Dict[int, int] = {}
        self.allocations = 0  # buffers ever created (bench/test probe)

    def _buffer(self, bucket: int, name: str,
                dtype: np.dtype) -> np.ndarray:
        with self._lock:
            buf = self._buffers.get((bucket, name))
            if buf is None or buf.dtype != dtype:
                # fresh all-zero buffer satisfies the filled-watermark
                # invariant at ANY watermark, so _filled is left alone
                # (other inputs of this bucket may have live buffers)
                buf = np.zeros((bucket,) + self._example_shapes[name],
                               dtype=dtype)
                self._buffers[(bucket, name)] = buf
                self.allocations += 1
            return buf

    def fill(self, batch, bucket: int,
             input_names: Iterable[str]) -> Dict[str, np.ndarray]:
        """Assemble the padded feed for ``batch`` in the bucket's staging
        buffers and return {name: buffer} (the buffers themselves — the
        caller must be done with them before the next fill for this
        replica, which the replica engine var guarantees)."""
        rows = sum(r.rows for r in batch)
        feed = {}
        for name in input_names:
            dtype = np.result_type(*[r.inputs[name].dtype for r in batch])
            buf = self._buffer(bucket, name, dtype)
            off = 0
            for r in batch:
                arr = r.inputs[name]
                buf[off:off + r.rows] = arr
                off += r.rows
            feed[name] = buf
        prev = self._filled.get(bucket, 0)
        if prev > rows:
            for name in input_names:
                self._buffers[(bucket, name)][rows:prev] = 0
        self._filled[bucket] = rows
        return feed

    def retain(self, buckets: Iterable[int]) -> List[int]:
        """Drop buffers for buckets not in ``buckets`` (called after a
        ladder swap retires programs). Returns the dropped buckets."""
        keep = set(int(b) for b in buckets)
        with self._lock:
            drop = sorted(set(b for b, _ in self._buffers) - keep)
            for b, name in list(self._buffers):
                if b not in keep:
                    del self._buffers[(b, name)]
            for b in drop:
                self._filled.pop(b, None)
        return drop

    def buffer_count(self) -> int:
        with self._lock:
            return len(self._buffers)
