"""In-process dynamic-batching inference server.

Pipeline (each stage a host-engine op or dedicated thread, so they
overlap — the engine.py division of labor applied to serving):

    clients --submit--> BatchFormer (bounded queue, deadlines)
                            |  former loop (thread): coalesce + pick bucket
                            v
             engine.push_async(dispatch, mutable_vars=[replica.var])
                            |  engine worker: pad -> compiled XLA program
                            v
                 per-request result futures + ServingMetrics

Dispatches to the SAME replica serialize on its engine variable (XLA
programs on one device must anyway); dispatches to DIFFERENT replicas run
concurrently on the native engine's worker pool — round-robin data
parallelism over replica executors. The batch former keeps coalescing the
next micro-batch while the engine runs the current one.

Configuration comes from ``ServingConfig`` with ``MXNET_SERVING_*`` env
defaults (docs/env_var.md; knob trade-offs in docs/deployment.md).
"""
from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

import jax.numpy as jnp

from .. import engine
from .. import predict as predict_mod
from .. import progcache as _progcache
from .. import telemetry
from ..telemetry import context as trace_context
from ..telemetry import flight as _flight
from .batcher import BatchFormer, Request, ServingError
from .bucket_cache import BucketCache
from .generate import (DecodeModel, DecodeScheduler, DecodeSpec,
                       GenerateConfig, TokenStream)
from .metrics import ServingBatchEndParam, ServingMetrics
from .staging import StagingPool
from .tuner import BucketTuner


def _env_buckets() -> tuple:
    raw = os.environ.get("MXNET_SERVING_BUCKETS", "1,4,8")
    return tuple(int(x) for x in raw.replace(" ", "").split(",") if x)


@dataclass
class ServingConfig:
    """Batch-former / queue / replica / hot-path knobs (env defaults read
    at construction, docs/env_var.md; tuning guide in docs/deployment.md)."""
    buckets: Sequence[int] = field(default_factory=_env_buckets)
    max_delay_ms: float = field(default_factory=lambda: float(
        os.environ.get("MXNET_SERVING_MAX_DELAY_MS", "2.0")))
    queue_depth: int = field(default_factory=lambda: int(
        os.environ.get("MXNET_SERVING_QUEUE_DEPTH", "256")))
    timeout_ms: float = field(default_factory=lambda: float(
        os.environ.get("MXNET_SERVING_TIMEOUT_MS", "1000")))
    replicas: int = field(default_factory=lambda: int(
        os.environ.get("MXNET_SERVING_REPLICAS", "1")))
    warm: bool = field(default_factory=lambda: bool(int(
        os.environ.get("MXNET_SERVING_WARM", "0"))))
    # --- hot-path knobs (this PR's tentpole; docs/deployment.md) ---------
    #: adaptive bucket ladders: a BucketTuner re-derives the ladder from
    #: the observed request-size histogram every retune_interval batches
    adaptive: bool = field(default_factory=lambda: bool(int(
        os.environ.get("MXNET_SERVING_ADAPTIVE", "0"))))
    #: max compiled programs per replica an adaptive ladder may use
    program_budget: int = field(default_factory=lambda: int(
        os.environ.get("MXNET_SERVING_PROGRAM_BUDGET", "8")))
    #: cross-bucket coalescing: pack toward the largest ladder bucket that
    #: is >= this percent full (0 disables; 100 = only full buckets)
    coalesce_fill_pct: float = field(default_factory=lambda: float(
        os.environ.get("MXNET_SERVING_COALESCE_FILL_PCT", "0")))
    #: replica routing: "rr" round-robin, or "least_loaded" = fewest
    #: outstanding engine ops on the replica's var (engine.var_inflight)
    router: str = field(default_factory=lambda: os.environ.get(
        "MXNET_SERVING_ROUTER", "rr"))
    #: assemble batches in reusable per-(replica, bucket) staging buffers
    #: instead of per-dispatch np.zeros + concatenate
    zero_copy: bool = field(default_factory=lambda: bool(int(
        os.environ.get("MXNET_SERVING_ZERO_COPY", "1"))))
    #: batches between retune passes (adaptive only)
    retune_interval: int = field(default_factory=lambda: int(
        os.environ.get("MXNET_SERVING_RETUNE_INTERVAL", "64")))
    #: min observed requests before the tuner will propose a ladder
    retune_min_samples: int = field(default_factory=lambda: int(
        os.environ.get("MXNET_SERVING_RETUNE_MIN_SAMPLES", "64")))
    #: engine capture/replay of the steady-state dispatch submission —
    #: one CapturedSequence per (replica, nominal bucket), invalidated by
    #: adaptive ladder swaps (engine.CapturedSequence, docs/perf.md)
    capture: bool = field(default_factory=lambda: engine.capture_enabled())
    #: trace-and-fuse the captured dispatch (MXNET_ENGINE_FUSE; requires
    #: ``capture``): a stable per-(replica, bucket) sequence lowers into
    #: ONE fused XLA program, bailing back to replay when acquire()
    #: resolves a different bucket/program than the staged one
    fuse: bool = field(default_factory=lambda: engine.fuse_enabled())
    #: post-training weight quantization for the replicas: "" (off,
    #: default — f32 path bitwise untouched) | int8 | fp8_e4m3. Each
    #: replica binds a mxnet_tpu.quant.QuantizedPredictor; the whole
    #: bucket ladder shares ONE quantization pass (docs/deployment.md
    #: "Quantized serving").
    quant_weights: str = field(default_factory=lambda: os.environ.get(
        "MXNET_QUANT_WEIGHT_DTYPE", ""))


class _Replica:
    __slots__ = ("index", "cache", "var", "staging", "dispatched",
                 "captures")

    def __init__(self, index: int, cache: BucketCache, var: int,
                 staging: StagingPool):
        self.index = index
        self.cache = cache
        self.var = var
        self.staging = staging
        self.dispatched = 0
        # bucket -> CapturedSequence (ServingConfig.capture); written by
        # the former thread, invalidated+cleared by retune/stop
        self.captures: Dict[int, "engine.CapturedSequence"] = {}


class InferenceServer:
    """Dynamic-batching server over bucketed Predictor executors.

    ``symbol``: Symbol, symbol-JSON string, or path. ``params``: params
    path or dict (Predictor semantics). ``example_shapes``: per-example
    input shapes WITHOUT the batch axis, e.g. ``{"data": (3, 224, 224)}``.
    ``devices``: optional jax devices, one replica pinned per device
    (round-robin dispatch); default all replicas on the default device.
    """

    def __init__(self, symbol, params, example_shapes: Dict[str, tuple],
                 dtype: str = "float32",
                 config: Optional[ServingConfig] = None,
                 batch_end_callback: Optional[Callable] = None,
                 devices: Optional[Sequence] = None,
                 decode: Optional[GenerateConfig] = None):
        self.config = config or ServingConfig()
        if not self.config.buckets:
            raise ServingError("no buckets configured")
        self._example_shapes = {n: tuple(s)
                                for n, s in example_shapes.items()}
        self._input_names = list(self._example_shapes)
        self._dtype = dtype
        self._batch_end_callback = batch_end_callback
        symbol_json = symbol.tojson() if hasattr(symbol, "tojson") else symbol

        n_rep = max(1, int(self.config.replicas))
        if devices is not None and len(devices) < n_rep:
            raise ServingError("need %d devices for %d replicas, got %d"
                               % (n_rep, n_rep, len(devices)))
        if self.config.router not in ("rr", "least_loaded"):
            raise ServingError(
                "MXNET_SERVING_ROUTER must be 'rr' or 'least_loaded', got %r"
                % (self.config.router,))
        if not 0.0 <= float(self.config.coalesce_fill_pct) <= 100.0:
            raise ServingError("coalesce_fill_pct must be in [0, 100]")
        ladder = tuple(sorted(set(int(b) for b in self.config.buckets)))
        smallest = ladder[0]
        self._replicas: List[_Replica] = []
        for i in range(n_rep):
            dev = devices[i] if devices is not None else None
            base = predict_mod.Predictor(
                symbol_json, params,
                {n: (smallest,) + s for n, s in self._example_shapes.items()},
                dtype=dtype, device=dev)
            if self.config.quant_weights:
                base = base.quantize(self.config.quant_weights)
            cache = BucketCache(base, self.config.buckets, device=dev)
            var = engine.new_variable()
            # opt this var into the engine's per-var in-flight accounting:
            # the least-loaded router reads it, and router_inflight_replica<N>
            # gauges expose it
            engine.track_inflight(var)
            self._replicas.append(_Replica(
                i, cache, var, StagingPool(self._example_shapes)))
        self._rr = 0

        # the live ladder (read lock-free by the former/dispatch: tuple
        # rebind is atomic) + its version, bumped by every adaptive swap
        self._ladder = ladder
        self._ladder_version = 0
        self._tuner: Optional[BucketTuner] = None
        self._tuner_var: Optional[int] = None
        if self.config.adaptive:
            if self.config.program_budget < 1:
                raise ServingError("program_budget must be >= 1")
            self._tuner = BucketTuner(
                max_batch=ladder[-1],
                program_budget=self.config.program_budget,
                min_samples=self.config.retune_min_samples)
            # retunes serialize on a dedicated engine var (background op,
            # off the dispatch hot path)
            self._tuner_var = engine.new_variable()

        self.metrics = ServingMetrics(
            cache_stats_fn=self._cache_stats,
            router_inflight_fn=self._router_inflight,
            ladder_version_fn=lambda: self._ladder_version)
        # continuous-batching decode (serving/generate): the scheduler
        # builds its own fixed-shape program set from the SAME loaded
        # weights the fixed-path predictors use, with its own per-replica
        # KV engine vars — the two workloads share the engine worker pool
        # and the telemetry registry but never each other's state
        self._decode: Optional[DecodeScheduler] = None
        if decode is not None:
            base = self._replicas[0].cache._base
            dm = DecodeModel.from_arg_params(
                base._arg_params,
                DecodeSpec(num_heads=decode.num_heads,
                           num_kv_heads=decode.num_kv_heads,
                           rope_base=decode.rope_base), dtype=dtype)
            self._decode = DecodeScheduler(dm, decode, replicas=n_rep)

        self._former = self._make_former()
        self._nbatch = 0
        self._thread: Optional[threading.Thread] = None
        self._started = False
        # With the persistent progcache enabled, a restarted server warms
        # its whole ladder before accepting traffic — each bucket build is
        # a disk load, not a compile, so this is seconds, not a compile
        # storm. It first adopts the ladder a previous process tuned
        # (progcache.save_ladder via set_ladder) so the restart lands on
        # the tuned rungs, not the configured defaults. config.warm keeps
        # its compile-eagerly meaning when the cache is off.
        if self.config.warm or _progcache.enabled():
            budget = (self.config.program_budget
                      if self.config.adaptive else None)
            for rep in self._replicas:
                if _progcache.enabled():
                    rep.cache.restore_ladder(budget)
                rep.cache.warm()
            if _progcache.enabled():
                self._ladder = tuple(self._replicas[0].cache.buckets)

    def _make_former(self) -> BatchFormer:
        former = BatchFormer(
            max_batch=max(self.config.buckets),
            max_delay_ms=self.config.max_delay_ms,
            queue_depth=self.config.queue_depth,
            error_hook=self.metrics.record_error,
            buckets_fn=lambda: self._ladder,
            coalesce_fill=self.config.coalesce_fill_pct / 100.0)
        # replica count divides the reject-early backlog estimate:
        # dispatches to different replicas run concurrently
        former.parallelism = len(self._replicas)
        self.metrics._queue_depth_fn = former.depth
        return former

    # --- cache stats aggregated over replicas -----------------------------
    def _cache_stats(self) -> Dict:
        agg = {"hits": 0, "misses": 0, "compiles": 0, "disk_hits": 0,
               "cache_hits": 0}
        for rep in self._replicas:
            s = rep.cache.stats()
            for k in agg:
                agg[k] += s[k]
        return agg

    def _router_inflight(self) -> List[int]:
        """Per-replica outstanding engine-op counts (the router's signal
        and the router_inflight_replica<N> gauges)."""
        return [engine.var_inflight(rep.var) if rep.var is not None else 0
                for rep in self._replicas]

    # --- lifecycle --------------------------------------------------------
    def start(self) -> "InferenceServer":
        """Start (or restart) the former loop. A stopped server restarts
        cleanly: close() is permanent on a BatchFormer, so a fresh one is
        built, and replica engine variables deleted by stop() are
        re-issued."""
        if self._started:
            return self
        if self._former.closed():
            self._former = self._make_former()
            for rep in self._replicas:
                if rep.var is None:
                    rep.var = engine.new_variable()
                    engine.track_inflight(rep.var)
            if self._tuner is not None and self._tuner_var is None:
                self._tuner_var = engine.new_variable()
        self._started = True
        self._thread = threading.Thread(target=self._former_loop,
                                        daemon=True, name="serving-former")
        self._thread.start()
        if self._decode is not None:
            self._decode.start()
        return self

    def stop(self, drain: bool = True,
             deadline_ms: Optional[float] = None):
        """Stop the server. ``drain=True`` is the graceful path: new
        submits fail immediately with code ``shutting_down`` while
        everything already queued keeps being served — up to
        ``deadline_ms`` (default ``MXNET_SERVING_DRAIN_DEADLINE_MS``;
        unset = drain fully), after which still-queued requests fail
        with ``shutting_down`` too. ``drain=False`` fails queued
        requests right away with a ``shutdown`` ServingError. In-flight
        dispatches always finish either way. Once ``stop`` returns the
        server is plain stopped: later submits raise ``shutdown``."""
        if self._decode is not None:
            # token streams drain (or fail) on the same policy as the
            # queued fixed-shape requests, under the same deadline
            self._decode.stop(drain=drain, deadline_ms=deadline_ms)
        if not self._started:
            self._former.close()
            self._former.fail_pending()
            return
        if not drain:
            self._former.close()
            self._former.fail_pending()
            self._thread.join()
        else:
            if deadline_ms is None:
                env = os.environ.get("MXNET_SERVING_DRAIN_DEADLINE_MS", "")
                deadline_ms = float(env) if env else None
            self._former.close(code="shutting_down")
            self._thread.join(None if deadline_ms is None
                              else max(0.0, deadline_ms) / 1e3)
            if self._thread.is_alive():
                # deadline passed: give up on what is still queued
                # (in-flight batches below still complete on their vars)
                self._former.fail_pending(
                    code="shutting_down",
                    msg="drain deadline (%g ms) passed with the request "
                        "still queued" % deadline_ms)
                self._thread.join()
            # drain over: submits now race a *stopped* server, not a
            # draining one — re-stamp the terminal code
            self._former.close(code="shutdown")
        for rep in self._replicas:
            engine.wait_for_var(rep.var)
            engine.untrack_inflight(rep.var)
            engine.delete_variable(rep.var)
            rep.var = None
            # recorded sequences reference the deleted var; start()
            # issues a fresh var, so capture re-warms from scratch
            rep.captures.clear()
        if self._tuner_var is not None:
            engine.wait_for_var(self._tuner_var)
            engine.delete_variable(self._tuner_var)
            self._tuner_var = None
        self._started = False

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop(drain=not any(exc))

    # --- client surface ---------------------------------------------------
    def submit(self, timeout_ms: Optional[float] = None,
               priority: object = 0,
               request_id: Optional[str] = None,
               **inputs) -> Request:
        """Enqueue one request (arrays WITH a leading batch axis; 1-row
        requests are the common case). Returns a Request future —
        ``req.get()`` blocks for the result. Raises ServingError
        immediately on backpressure (``queue_full``), an infeasible
        deadline (``deadline_exceeded`` — reject-early) or shutdown.
        ``priority`` is the QoS class — ``"interactive"``/0 (default,
        dispatched first) or ``"batch"``/1 (rides in leftover batch
        budget). ``request_id`` is an opaque correlation id carried on
        the Request and telemetry."""
        pri = {"interactive": 0, "batch": 1}.get(priority, priority)
        rows = None
        feed = {}
        for name in self._input_names:
            if name not in inputs:
                raise ServingError("missing input %r (need %s)"
                                   % (name, self._input_names))
            arr = np.asarray(inputs[name])
            want = self._example_shapes[name]
            if arr.ndim != len(want) + 1 or tuple(arr.shape[1:]) != want:
                raise ServingError(
                    "input %r shape %s != (rows,)+%s"
                    % (name, arr.shape, want))
            if rows is None:
                rows = arr.shape[0]
            elif arr.shape[0] != rows:
                raise ServingError("inconsistent row counts across inputs")
            feed[name] = arr
        if rows < 1:
            raise ServingError("empty request")
        max_rows = max(self.config.buckets)
        if rows > max_rows:
            raise ServingError(
                "request of %d rows exceeds the largest bucket (%d)"
                % (rows, max_rows), "too_large")
        t = self.config.timeout_ms if timeout_ms is None else timeout_ms
        deadline = (time.monotonic() + t / 1e3) if t and t > 0 else None
        # the trace context rides ON the request — the thread-local set
        # by the HTTP edge doesn't survive the former/engine thread hops
        trace = trace_context.current_context()
        req = Request(feed, rows, deadline, priority=pri,
                      request_id=request_id, trace=trace)
        if trace is not None and telemetry.enabled("serving"):
            telemetry.instant("serving.submit", domain="serving", rows=rows,
                              priority=req.priority, **trace.stamps())
        else:
            telemetry.instant("serving.submit", domain="serving", rows=rows,
                              priority=req.priority, request_id=request_id)
        self.metrics.record_submit(rows)
        try:
            self._former.submit(req)
        except ServingError as e:
            self.metrics.record_error(e.code)
            raise
        return req

    def predict(self, timeout_ms: Optional[float] = None,
                **inputs) -> List[np.ndarray]:
        """Synchronous convenience: submit + wait."""
        req = self.submit(timeout_ms=timeout_ms, **inputs)
        # grace over the queue deadline so a request failed by the former
        # surfaces its own (structured) error rather than a wait_timeout
        t = self.config.timeout_ms if timeout_ms is None else timeout_ms
        wait = (t / 1e3 + 60.0) if t and t > 0 else None
        return req.get(wait)

    # --- autoregressive decode (serving/generate) -------------------------
    def submit_stream(self, prompt: Sequence[int],
                      max_new_tokens: Optional[int] = None,
                      timeout_ms: Optional[float] = None,
                      temperature: float = 0.0,
                      seed: Optional[int] = None,
                      request_id: Optional[str] = None) -> TokenStream:
        """Enqueue one generate request; returns a :class:`TokenStream`
        that yields token ids as the continuous-batching scheduler decodes
        them. ``timeout_ms`` is a whole-stream deadline (queued OR
        decoding; default none — decode requests outlive the fixed-path
        ``timeout_ms`` scale by design). ``temperature`` 0 is greedy;
        > 0 samples per-stream with a ``seed``-deterministic rng.
        Raises ServingError with the batcher's structured codes
        (``queue_full``, ``too_large``, ``shutting_down``, ``shutdown``,
        ``deadline_exceeded``, ...)."""
        if self._decode is None:
            raise ServingError(
                "decode is not configured — construct the server with "
                "decode=GenerateConfig(num_heads=...)")
        if not self._started:
            raise ServingError("server not started", "shutdown")
        trace = trace_context.current_context()
        if trace is not None and telemetry.enabled("serving"):
            telemetry.instant("serving.submit_stream", domain="serving",
                              prompt=len(prompt), **trace.stamps())
        else:
            telemetry.instant("serving.submit_stream", domain="serving",
                              prompt=len(prompt), request_id=request_id)
        try:
            return self._decode.submit(prompt, max_new_tokens,
                                       timeout_ms=timeout_ms,
                                       temperature=temperature, seed=seed,
                                       request_id=request_id, trace=trace)
        except ServingError as e:
            self.metrics.record_error(e.code)
            raise

    def generate(self, prompt: Sequence[int],
                 max_new_tokens: Optional[int] = None,
                 timeout_ms: Optional[float] = None,
                 temperature: float = 0.0,
                 seed: Optional[int] = None) -> List[int]:
        """Synchronous convenience: submit_stream + wait for the full
        token list."""
        stream = self.submit_stream(prompt, max_new_tokens,
                                    timeout_ms=timeout_ms,
                                    temperature=temperature, seed=seed)
        wait = None if timeout_ms is None else timeout_ms / 1e3 + 60.0
        return stream.tokens(wait)

    def decode_stats(self) -> Dict:
        """Decode-side counters: fresh compiles, progcache disk hits,
        steps taken, queued/active stream counts."""
        if self._decode is None:
            raise ServingError("decode is not configured")
        return self._decode.stats()

    # --- former loop + dispatch -------------------------------------------
    def _former_loop(self):
        while True:
            with telemetry.span("serving.form_batch", domain="serving") as sp:
                batch = self._former.next_batch()
                if batch is not None:
                    sp.annotate(n_requests=len(batch))
            if batch is None:
                return
            if telemetry.enabled("serving"):
                # queue time per request: submitted is time.monotonic(),
                # the same clock the tracer stamps in, so the span is exact
                for r in batch:
                    extra = (r.trace.child().stamps()
                             if r.trace is not None else {})
                    telemetry.complete("serving.queued", domain="serving",
                                       start_ns=int(r.submitted * 1e9),
                                       rows=r.rows, **extra)
            rep = self._pick_replica()
            self._nbatch += 1
            nbatch = self._nbatch
            dispatch = (lambda done, batch=batch, rep=rep, nbatch=nbatch:
                        self._dispatch(batch, rep, nbatch, done))
            if self.config.capture:
                self._push_captured(rep, batch, dispatch, nbatch)
            else:
                engine.push_async(
                    dispatch, mutable_vars=[rep.var],
                    name="serving_dispatch_r%d" % rep.index)
            if (self._tuner is not None and self.config.retune_interval > 0
                    and nbatch % self.config.retune_interval == 0):
                self._push_retune()

    def _push_captured(self, rep: _Replica, batch: List[Request],
                       dispatch: Callable, nbatch: int):
        """Dispatch through the replica's per-bucket CapturedSequence
        (ServingConfig.capture). The NOMINAL bucket — smallest current
        ladder rung holding the batch — keys the sequence, so each
        steady-state shape replays its own recording; ``acquire()`` still
        chooses the real bucket atomically at run time, so a ladder swap
        mid-flight never strands a request (its sequence is merely
        invalidated back to warmup by ``_retune_op``). Only the former
        thread writes ``rep.captures``."""
        ladder = self._ladder  # atomic tuple snapshot
        rows = sum(r.rows for r in batch)
        bucket = next((b for b in ladder if b >= rows), ladder[-1])
        cs = rep.captures.get(bucket)
        if cs is None:
            cs = engine.CapturedSequence(
                name="serving_r%d_b%d" % (rep.index, bucket),
                fuse=True if self.config.fuse else None)
            rep.captures[bucket] = cs
        fuse = (self._fuse_dispatch_op(rep, bucket, batch, nbatch)
                if self.config.fuse else None)
        cs.begin_step()
        cs.push_async(dispatch, mutable_vars=(rep.var,),
                      name="serving_dispatch_r%d" % rep.index, fuse=fuse)
        cs.end_step()

    def _fuse_dispatch_op(self, rep: _Replica, bucket: int,
                          batch: List[Request], nbatch: int):
        """Traceable metadata for one captured dispatch (ServingConfig.fuse;
        engine.FuseOp): the nominal bucket predictor's jitted forward is
        the staged computation, the per-iteration feed re-runs the atomic
        ``acquire()`` + pad on the engine worker and bails to replay when
        it resolves a different bucket or program than the staged one, and
        the writeback publishes results exactly like ``_dispatch``'s
        post-forward tail. None when the executor exposes no traceable
        forward (keeps the sequence on replay)."""
        exe = rep.cache.prepare(bucket)
        jitted = getattr(exe, "_jitted", None)
        if jitted is None:
            return None
        names = self._input_names
        dtype = jnp.dtype(getattr(exe, "_dtype", self._dtype))
        fp = getattr(exe, "_progcache_model_fp", None)

        def fwd_fn(*vals, _jit=jitted):
            return (tuple(_jit(*vals)),)

        def feed(_batch=batch, _exe=exe, _bucket=bucket):
            # any failure here happened BEFORE any result was published:
            # converting it to a bail makes the whole iteration replay
            # through _dispatch, whose handler owns request error delivery
            try:
                rows = sum(r.rows for r in _batch)
                b, got = rep.cache.acquire(rows)
                if got is not _exe:
                    raise engine._FuseBail(
                        "bucket drift: acquire() resolved b%d, staged b%d"
                        % (b, _bucket))
                if self.config.zero_copy:
                    fd = rep.staging.fill(_batch, b, names)
                else:
                    fd = {}
                    for name in names:
                        cat = np.concatenate(
                            [r.inputs[name] for r in _batch], axis=0)
                        if b > rows:
                            pad = np.zeros(
                                (b - rows,) + cat.shape[1:], cat.dtype)
                            cat = np.concatenate([cat, pad], axis=0)
                        fd[name] = cat
                return tuple(jnp.asarray(fd[n]).astype(dtype)
                             for n in names)
            except engine._FuseBail:
                raise
            except BaseException as e:
                raise engine._FuseBail("dispatch feed failed: %s: %s"
                                       % (type(e).__name__, e))

        def writeback(d, _batch=batch, _nbatch=nbatch, _bucket=bucket):
            outs = d[rep.var]
            try:
                self._publish_outputs(_batch, rep, _nbatch, _bucket,
                                      sum(r.rows for r in _batch), outs)
            except BaseException as e:  # mirror _dispatch's error contract
                err = e if isinstance(e, ServingError) else ServingError(
                    "dispatch failed: %s: %s" % (type(e).__name__, e),
                    "dispatch_error")
                self.metrics.record_error(err.code)
                for r in _batch:
                    if not r.done():
                        r.set_error(err)
                        _flight.request_end(r.trace, ok=False,
                                            code=err.code,
                                            latency_ms=r.latency_ms,
                                            request_id=r.request_id)

        return engine.FuseOp(
            fwd_fn, out_vars=(rep.var,), feed=feed, writeback=writeback,
            fingerprint=(None if fp is None
                         else "serving:%s:b%d:%s:%r" % (fp, bucket,
                                                        dtype, names)))

    def _pick_replica(self) -> _Replica:
        """Routing policy. ``rr``: classic round-robin. ``least_loaded``:
        the replica with the fewest outstanding engine ops on its var
        (queued + running dispatches, engine.var_inflight) — a stalled
        replica keeps absorbing nothing while healthy ones drain the
        queue, which bounds p99 where round-robin lets one slow replica
        inflate it. Round-robin start index breaks ties so equal-load
        replicas still rotate."""
        reps = self._replicas
        start = self._rr % len(reps)
        self._rr += 1
        if self.config.router != "least_loaded" or len(reps) == 1:
            return reps[start]
        best, best_load = None, None
        for i in range(len(reps)):
            rep = reps[(start + i) % len(reps)]
            load = engine.var_inflight(rep.var)
            if best_load is None or load < best_load:
                best, best_load = rep, load
        return best

    # --- adaptive ladder retune -------------------------------------------
    def _push_retune(self):
        engine.push(self._retune_op, mutable_vars=[self._tuner_var],
                    name="serving_retune")

    def retune_now(self, wait: bool = True):
        """Run one tuner pass now (bench/tests; the periodic path pushes
        the same op every ``retune_interval`` batches). Serialized on the
        tuner engine var like every retune."""
        if self._tuner is None:
            raise ServingError(
                "adaptive tuning is disabled (ServingConfig.adaptive)")
        if self._tuner_var is None:
            raise ServingError("server is stopped", "shutdown")
        self._push_retune()
        if wait:
            engine.fence([self._tuner_var]).wait()

    def _retune_op(self):
        """One tuner pass (runs on an engine worker, off the hot path):
        propose a ladder from the observed size histogram; if it clears
        the hysteresis bar, compile-ahead-warm every new bucket, THEN swap
        each replica's ladder atomically and retire old programs LRU. The
        former/dispatch never blocks on any of this — they read the old
        ladder until the rebind, and acquire() makes choose+fetch atomic
        against the swap, so no in-flight request can fail."""
        try:
            ladder = self._tuner.propose(
                self.metrics.request_size_histogram(), self._ladder)
            if ladder is None:
                return
            with telemetry.span("serving.retune", domain="serving",
                                ladder=str(ladder)):
                for rep in self._replicas:
                    for b in ladder:
                        rep.cache.prepare(b)  # warm BEFORE the swap
                for rep in self._replicas:
                    rep.cache.set_ladder(
                        ladder, budget=self._tuner.program_budget)
                    rep.staging.retain(ladder)
                self._ladder = tuple(ladder)
                self._ladder_version += 1
                # captured dispatch sequences recorded against the old
                # ladder re-warm against the new one; a replay already
                # submitted keeps running (acquire() is swap-atomic)
                for rep in self._replicas:
                    for cs in list(rep.captures.values()):
                        cs.invalidate("ladder swap v%d"
                                      % self._ladder_version)
                    rep.captures.clear()
            telemetry.instant("serving.ladder_swap", domain="serving",
                              version=self._ladder_version,
                              ladder=str(ladder))
        except BaseException:
            # a failed retune must never take the serving path down;
            # traffic continues on the current ladder
            logging.getLogger("mxnet_tpu").exception(
                "serving ladder retune failed (keeping ladder %s)",
                self._ladder)

    def _dispatch(self, batch: List[Request], rep: _Replica, nbatch: int,
                  on_complete: Callable[[], None]):
        # entered/exited manually so the span brackets the whole dispatch
        # (success and failure paths) without re-nesting the handler
        sp = telemetry.span("serving.dispatch", domain="serving",
                            nbatch=nbatch, replica=rep.index)
        sp.__enter__()
        t0 = time.monotonic()
        try:
            rows = sum(r.rows for r in batch)
            # choose-and-fetch under one cache lock hold: atomic against a
            # concurrent adaptive ladder swap
            bucket, exe = rep.cache.acquire(rows)
            if telemetry.enabled("serving"):
                now = time.monotonic()
                margins = [(r.deadline - now) * 1e3 for r in batch
                           if r.deadline is not None]
                sp.annotate(bucket=bucket, rows=rows,
                            deadline_margin_ms=(round(min(margins), 3)
                                                if margins else None))
                # batch-level span: link every member request's trace so
                # each request's assembled tree includes the batch it rode
                tids = [r.trace.trace_id for r in batch
                        if r.trace is not None]
                if tids:
                    sp.annotate(trace_ids=tids,
                                span_id=trace_context.mint_span_id())
            with telemetry.span("serving.pad", domain="serving",
                                bucket=bucket, rows=rows):
                if self.config.zero_copy:
                    # rows land directly in the replica's reusable staging
                    # buffer (safe: dispatches to this replica serialize
                    # on its engine var, and forward copies host->device
                    # before returning)
                    feed = rep.staging.fill(batch, bucket,
                                            self._input_names)
                else:
                    feed = {}
                    for name in self._input_names:
                        cat = np.concatenate(
                            [r.inputs[name] for r in batch], axis=0)
                        if bucket > rows:
                            pad = np.zeros(
                                (bucket - rows,) + cat.shape[1:], cat.dtype)
                            cat = np.concatenate([cat, pad], axis=0)
                        feed[name] = cat
            with telemetry.span("serving.forward", domain="serving",
                                bucket=bucket):
                outs = [o.asnumpy() for o in exe.forward(**feed)]
            self._publish_outputs(batch, rep, nbatch, bucket, rows, outs)
            # feed the reject-early estimator with the observed service
            # time (handoff -> results published); successes only, so a
            # failure storm doesn't poison the feasibility EWMA
            self._former.note_dispatch(time.monotonic() - t0)
        except BaseException as e:
            err = e if isinstance(e, ServingError) else ServingError(
                "dispatch failed: %s: %s" % (type(e).__name__, e),
                "dispatch_error")
            self.metrics.record_error(err.code)
            for r in batch:
                if not r.done():
                    r.set_error(err)
                    _flight.request_end(r.trace, ok=False, code=err.code,
                                        latency_ms=r.latency_ms,
                                        request_id=r.request_id)
        finally:
            sp.__exit__(None, None, None)
            on_complete()

    def _publish_outputs(self, batch: List[Request], rep: _Replica,
                         nbatch: int, bucket: int, rows: int, outs):
        """Post-forward publication tail shared by ``_dispatch`` and the
        fused writeback: batch-axis check, per-request result slicing,
        metrics and the batch_end_callback. Raises on contract violations
        — the caller owns request error delivery."""
        outs = [np.asarray(o) for o in outs]
        for o in outs:
            if o.shape[:1] != (bucket,):
                raise ServingError(
                    "output batch axis %s != bucket %d — serving "
                    "requires batch-major outputs" % (o.shape, bucket))
        offset = 0
        lats = []
        for r in batch:
            r.set_result([o[offset:offset + r.rows] for o in outs])
            offset += r.rows
            lats.append(r.latency_ms)
            self.metrics.observe_latency(
                r.latency_ms,
                r.trace.trace_id if r.trace is not None else None)
            _flight.request_end(r.trace, ok=True, latency_ms=r.latency_ms,
                                kind="predict", request_id=r.request_id)
        rep.dispatched += 1
        self.metrics.record_batch(rows, bucket, lats)
        if self._batch_end_callback is not None:
            # every request already completed: a raising user callback
            # must not be recorded as a dispatch failure
            try:
                self._batch_end_callback(ServingBatchEndParam(
                    nbatch=nbatch, bucket=bucket, rows=rows,
                    replica=rep.index,
                    latency_ms=sum(lats) / len(lats), occupancy=rows,
                    metrics=self.metrics))
            except Exception:
                logging.getLogger("mxnet_tpu").exception(
                    "serving batch_end_callback raised (batch %d)",
                    nbatch)

    # --- readiness --------------------------------------------------------
    def warm(self):
        """Compile (or progcache-disk-load) every rung of every replica's
        ladder now. Idempotent; the HTTP front-end calls it from a
        background thread so ``/readyz`` flips only once no request can
        hit a cold compile."""
        for rep in self._replicas:
            rep.cache.warm()

    def ready(self) -> bool:
        """True once the server is started AND every replica holds a
        program for every rung of the live ladder — the ``/readyz``
        predicate: traffic admitted now will not stall on a compile."""
        if not self._started:
            return False
        for rep in self._replicas:
            s = rep.cache.stats()
            if not set(s["buckets"]) <= set(s["compiled"]):
                return False
        return True

    # --- introspection ----------------------------------------------------
    def get_metrics(self):
        """metric.py-style (names, values) snapshot."""
        return self.metrics.get()

    def cache_stats(self) -> Dict:
        return self._cache_stats()

    def replica_dispatch_counts(self) -> List[int]:
        return [rep.dispatched for rep in self._replicas]

    def current_ladder(self) -> tuple:
        """The live bucket ladder (changes under adaptive tuning)."""
        return self._ladder

    @property
    def ladder_version(self) -> int:
        """0 for the static ladder; +1 per adaptive swap."""
        return self._ladder_version

    def router_inflight(self) -> List[int]:
        """Per-replica outstanding engine-op counts (router's live view)."""
        return self._router_inflight()


def create_server(prefix: str, epoch: int, example_shapes: Dict[str, tuple],
                  dtype: str = "float32", **kwargs) -> InferenceServer:
    """Server straight from a training checkpoint pair (predict.create
    analogue): ``prefix-symbol.json`` + ``prefix-%04d.params``."""
    return InferenceServer("%s-symbol.json" % prefix,
                           "%s-%04d.params" % (prefix, epoch),
                           example_shapes, dtype=dtype, **kwargs)
