"""Continuous batching for autoregressive decode (KV-cache serving).

The LLM-inference workload on top of the serving stack: an Orca-style
:class:`DecodeScheduler` re-forms the decode batch every step as
sequences finish, :class:`KVCacheManager` owns slot-allocated KV slabs
behind engine mutable vars, and :class:`DecodePrograms` bounds XLA
compiles to (prefill ladder + decode step + admit) per replica via the
persistent program cache. Front door: ``InferenceServer.generate()`` /
``submit_stream()`` (serving/server.py).
"""
from .kv_cache import AdmitPlan, KVCacheManager
from .model import DecodeModel, DecodeSpec
from .paged import PagedKVCacheManager
from .programs import DecodePrograms, PagedDecodePrograms
from .scheduler import DecodeScheduler, GenerateConfig
from .spec import SpecDecoder, accept_greedy, accept_sampled, sample_token
from .stream import TokenStream

__all__ = [
    "AdmitPlan", "DecodeModel", "DecodeSpec", "DecodePrograms",
    "KVCacheManager", "PagedDecodePrograms", "PagedKVCacheManager",
    "DecodeScheduler", "GenerateConfig", "SpecDecoder", "TokenStream",
    "accept_greedy", "accept_sampled", "sample_token",
]
