"""Paged KV cache: block pool + per-sequence block tables + prefix reuse.

The vLLM-style replacement for ``KVCacheManager``'s one-contiguous-lane-
per-slot layout: the two per-replica slabs become a pool of fixed-size
**blocks** of ``block_tokens`` KV rows each (``MXNET_DECODE_BLOCK_TOKENS``,
default 16), and every sequence owns a **block table** — a fixed-width
``(max_blocks,)`` int32 vector naming the physical block holding each
logical ``block_tokens``-token span of its context. Admission is governed
by **free-block count** instead of free-slot count, so memory (not the
slot dimension of the decode program) is what caps co-residency, and a
short sequence no longer reserves ``max_context`` worth of slab.

Prefix reuse (``MXNET_DECODE_PREFIX_SHARE``, default on): every admitted
prompt registers its token blocks under a chained content hash. A later
prompt whose leading blocks hash-match **shares** those physical blocks
(refcount++) instead of re-prefilling them — the shared-system-prompt
traffic shape materializes the prefix ONCE. A *partially* filled prompt
block can be shared too: the joiner's first divergent write would land
inside it, so the admit program **copy-on-write forks** it — copies the
shared block into a private one from the joiner's own reservation, then
writes there. Sharers only ever read shared blocks; every write target is
private by construction, which is what keeps paged token streams
bitwise-identical to the unpaged path.

No mid-stream eviction, ever: admission reserves every block the
sequence can touch through ``min(prompt + max_new, capacity)`` up front,
so a running sequence never allocates — a waiting prefill is admitted
only when retirement frees blocks.

Lock discipline: ``_lock`` is a LEAF (rank 100 in ``LOCK_HIERARCHY``) —
it guards the block table / free-list / refcount / prefix-registry
bookkeeping only. Engine pushes, device calls, and telemetry increments
all happen outside the hold; the slabs themselves are serialized by the
engine var exactly like the unpaged manager.
"""
from __future__ import annotations

import hashlib
import threading
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ... import engine as _engine
from ..batcher import ServingError
from .kv_cache import AdmitPlan
from .programs import PagedDecodePrograms

#: physical block 0 is the reserved /dev/null block: inactive lanes and
#: padded prefill positions write into it, and it is never read unmasked.
TRASH_BLOCK = 0


def _chain_hash(prev: str, tokens: Sequence[int]) -> str:
    """Content hash of one token block, chained on its prefix's hash —
    equal chains <=> equal token prefixes, block-aligned."""
    h = hashlib.sha1()
    h.update(prev.encode())
    h.update(b"|")
    h.update(",".join(str(int(t)) for t in tokens).encode())
    return h.hexdigest()


class PagedKVCacheManager:
    """Block allocator + paged slab holder for one replica's decode state.

    Same surface the scheduler drives on the unpaged ``KVCacheManager``
    (``try_admit``/``free``/``advance``/``length``/``owner``/
    ``active_slots``/``occupancy_pct``/``step_arrays``/``swap_slabs``/
    ``reset``/``kv_bytes``), plus the block-pool introspection the
    telemetry gauges export (``blocks_free``/``blocks_total``).
    """

    def __init__(self, programs: PagedDecodePrograms, replica: int = 0,
                 prefix_share: bool = True):
        self.programs = programs
        self.replica = replica
        self.slots = programs.slots
        self.capacity = programs.capacity
        self.block_tokens = programs.block_tokens
        self.max_blocks = programs.max_blocks
        self.num_blocks = programs.num_blocks
        self.prefix_share = bool(prefix_share)
        self.var = _engine.new_variable()
        _engine.track_inflight(self.var)
        self.k_slab, self.v_slab = programs.fresh_slabs()
        # int8 KV: per-position f32 scale slabs (L, NB+1, T), CoW-copied
        # and scattered by the same programs that move the value blocks
        scales = programs.fresh_scale_slabs()
        self.k_scale, self.v_scale = scales if scales else (None, None)
        self._lock = threading.Lock()
        self._lengths = np.zeros(self.slots, np.int32)
        self._owner: List[Optional[object]] = [None] * self.slots
        self._free_slots: deque = deque(range(self.slots))
        # block pool: ids 1..num_blocks (0 = trash), O(1) alloc/free
        self._free_blocks: deque = deque(range(1, self.num_blocks + 1))
        self._ref = np.zeros(self.num_blocks + 1, np.int32)
        self._tables = np.zeros((self.slots, self.max_blocks), np.int32)
        # admission reservation in blocks, per slot: truncate() keeps
        # table entries inside it by default (no-mid-stream-eviction —
        # the sequence may grow back into them without allocating)
        self._reserved = np.zeros(self.slots, np.int32)
        # prefix registry: chained hash -> full block id, and
        # chained hash -> (block id, partial token tuple); _block_keys is
        # the reverse map so a freed block unregisters its entries.
        self._full_index: Dict[str, int] = {}
        self._partial_index: Dict[str, Tuple[int, Tuple[int, ...]]] = {}
        self._block_keys: Dict[int, List[Tuple[str, str]]] = {}
        # monotonic counters, mirrored into telemetry by the scheduler
        self.prefix_hits = 0
        self.prefix_tokens_saved = 0
        self.cow_forks = 0

    # --- admission (host-only, leaf lock) --------------------------------
    def try_admit(self, owner, prompt: Sequence[int],
                  max_new: int) -> Optional[AdmitPlan]:
        """Claim a slot AND every block ``owner`` can ever write, sharing
        hash-matched prefix blocks; None if slots or blocks are exhausted
        (the caller requeues — admission waits on retirement, a running
        sequence is never evicted)."""
        prompt = [int(t) for t in prompt]
        n = len(prompt)
        if n >= self.capacity:
            raise ServingError(
                "prompt length %d leaves no kv capacity (max_context %d)"
                % (n, self.capacity), code="too_large")
        T = self.block_tokens
        max_len = min(n + int(max_new), self.capacity)
        nb = -(-max_len // T)                  # blocks this stream can touch
        with self._lock:
            if not self._free_slots:
                return None
            # --- prefix match (full blocks, then one partial block) ------
            shared: List[int] = []
            chain = "root"
            p_full = 0
            fork_src = TRASH_BLOCK
            p_part = 0
            if self.prefix_share:
                while (len(shared) + 1) * T <= n - 1:
                    h = _chain_hash(chain, prompt[len(shared) * T:
                                                  (len(shared) + 1) * T])
                    bid = self._full_index.get(h)
                    if bid is None:
                        break
                    shared.append(bid)
                    chain = h
                p_full = len(shared) * T
                ent = self._partial_index.get(chain)
                if ent is not None:
                    bid, toks = ent
                    tail = prompt[p_full:]
                    # leave >= 1 token to prefill: the admit program is
                    # also how the stream gets its first logits
                    lim = min(len(toks), len(tail) - 1)
                    while p_part < lim and toks[p_part] == tail[p_part]:
                        p_part += 1
                    if p_part > 0:
                        fork_src = bid
            ctx_len = p_full + p_part
            first_new = len(shared)            # boundary block index
            need = nb - first_new
            if need > len(self._free_blocks):
                return None                    # wait for retirement
            slot = self._free_slots.popleft()
            table = np.zeros(self.max_blocks, np.int32)
            for idx, bid in enumerate(shared):
                table[idx] = bid
                self._ref[bid] += 1
            for k in range(need):
                bid = self._free_blocks.popleft()
                table[first_new + k] = bid
                self._ref[bid] = 1
            # CoW target: the divergent write lands inside the boundary
            # block, which is this stream's own first private block
            fork_dst = int(table[first_new]) if p_part > 0 else TRASH_BLOCK
            self._register_prompt(prompt, table)
            self._owner[slot] = owner
            self._lengths[slot] = n
            self._tables[slot] = table
            self._reserved[slot] = nb
            if ctx_len > 0:
                self.prefix_hits += 1
                self.prefix_tokens_saved += ctx_len
            if fork_dst != TRASH_BLOCK:
                self.cow_forks += 1
        return AdmitPlan(slot=slot, suffix=prompt[ctx_len:],
                         ctx_len=ctx_len, table=table,
                         fork_src=int(fork_src), fork_dst=int(fork_dst))

    def _register_prompt(self, prompt: Sequence[int], table: np.ndarray):
        """Index this prompt's token blocks for later sharers (lock held).
        Only PROMPT tokens are registered — generated tokens land at
        offsets beyond the registered span, so entries stay valid for the
        block's whole lifetime. First registration wins."""
        if not self.prefix_share:
            return
        T = self.block_tokens
        n = len(prompt)
        chain = "root"
        j = 0
        while (j + 1) * T <= n:
            blk = tuple(int(t) for t in prompt[j * T:(j + 1) * T])
            prev = chain
            chain = _chain_hash(prev, blk)
            bid = int(table[j])
            if bid != TRASH_BLOCK:
                if chain not in self._full_index:
                    self._full_index[chain] = bid
                    self._block_keys.setdefault(bid, []).append(
                        ("full", chain))
                # alias the full block into the partial index too, so a
                # prompt that is a proper PREFIX of it can still share
                # (capped token-wise at admission, resolved by CoW fork)
                if prev not in self._partial_index:
                    self._partial_index[prev] = (bid, blk)
                    self._block_keys.setdefault(bid, []).append(
                        ("partial", prev))
            j += 1
        rem = tuple(int(t) for t in prompt[j * T:])
        if rem:
            bid = int(table[j])
            if bid != TRASH_BLOCK and chain not in self._partial_index:
                self._partial_index[chain] = (bid, rem)
                self._block_keys.setdefault(bid, []).append(
                    ("partial", chain))

    def free(self, slot: int):
        """Release a retired sequence's slot and decref its blocks; a
        block freed to zero refcount returns to the pool and drops out of
        the prefix registry."""
        with self._lock:
            table = self._tables[slot]
            for bid in sorted({int(b) for b in table if b != TRASH_BLOCK}):
                self._ref[bid] -= 1
                if self._ref[bid] <= 0:
                    self._ref[bid] = 0
                    self._free_blocks.append(bid)
                    for kind, key in self._block_keys.pop(bid, []):
                        index = (self._full_index if kind == "full"
                                 else self._partial_index)
                        index.pop(key, None)
            self._tables[slot] = 0
            self._owner[slot] = None
            self._lengths[slot] = 0
            self._reserved[slot] = 0
            self._free_slots.append(slot)

    # --- bookkeeping shared with the unpaged surface ----------------------
    def advance(self, slot: int) -> int:
        with self._lock:
            self._lengths[slot] += 1
            return int(self._lengths[slot])

    def length(self, slot: int) -> int:
        with self._lock:
            return int(self._lengths[slot])

    def truncate(self, slot: int, new_len: int, release: bool = False):
        """Rewind ``slot`` to ``new_len`` tokens — the speculative-decode
        reject path, a pure block-table/length edit with no KV copies.

        Table entries wholly past the new length are decref'd exactly
        like ``free``: a refcounted shared-prefix block another sequence
        still holds survives untouched, while a private speculative-tail
        block drops to zero refs and returns to the pool. By default
        entries inside the admission reservation are KEPT — the sequence
        may grow back into them and must never allocate mid-stream;
        ``release=True`` drops them too and shrinks the reservation
        (explicit early-shrink, e.g. tests). Idempotent: a released
        entry is already trash on the second call."""
        T = self.block_tokens
        new_len = int(new_len)
        keep = -(-new_len // T)            # blocks still (partly) in use
        with self._lock:
            if self._owner[slot] is None:
                return
            self._lengths[slot] = new_len
            floor = keep if release \
                else max(keep, int(self._reserved[slot]))
            if release and keep < self._reserved[slot]:
                self._reserved[slot] = keep
            table = self._tables[slot]
            for idx in range(floor, self.max_blocks):
                bid = int(table[idx])
                if bid == TRASH_BLOCK:
                    continue
                table[idx] = TRASH_BLOCK
                self._ref[bid] -= 1
                if self._ref[bid] <= 0:
                    self._ref[bid] = 0
                    self._free_blocks.append(bid)
                    for kind, key in self._block_keys.pop(bid, []):
                        index = (self._full_index if kind == "full"
                                 else self._partial_index)
                        index.pop(key, None)

    def owner(self, slot: int):
        with self._lock:
            return self._owner[slot]

    def active_slots(self) -> List[int]:
        with self._lock:
            return [i for i in range(self.slots)
                    if self._owner[i] is not None]

    def occupancy_pct(self) -> float:
        with self._lock:
            used = sum(1 for o in self._owner if o is not None)
        return 100.0 * used / self.slots

    def blocks_total(self) -> int:
        return self.num_blocks

    def blocks_free(self) -> int:
        with self._lock:
            return len(self._free_blocks)

    def step_arrays(self):
        """(lengths, tables) snapshots for the next decode step: inactive
        rows run with length 0 and an all-trash table — their lanes write
        into block 0 and read nothing unmasked."""
        with self._lock:
            lengths = self._lengths.copy()
            tables = self._tables.copy()
        return lengths, tables

    # --- slab plumbing (scheduler thread only) ---------------------------
    def swap_slabs(self, k_slab, v_slab, k_scale=None, v_scale=None):
        self.k_slab, self.v_slab = k_slab, v_slab
        if k_scale is not None:
            self.k_scale, self.v_scale = k_scale, v_scale

    def reset(self):
        """Fresh slabs + empty bookkeeping (server restart / poisoned
        step recovery)."""
        with self._lock:
            self._lengths[:] = 0
            self._owner = [None] * self.slots
            self._free_slots = deque(range(self.slots))
            self._free_blocks = deque(range(1, self.num_blocks + 1))
            self._ref[:] = 0
            self._tables[:] = 0
            self._reserved[:] = 0
            self._full_index.clear()
            self._partial_index.clear()
            self._block_keys.clear()
        self.k_slab, self.v_slab = self.programs.fresh_slabs()
        scales = self.programs.fresh_scale_slabs()
        self.k_scale, self.v_scale = scales if scales else (None, None)

    def kv_bytes(self) -> int:
        return self.programs.kv_bytes()
