"""Speculative decoding for the bounded-program decode engine.

Draft-k-then-verify (Leviathan et al., *Fast Inference from Transformers
via Speculative Decoding*): per scheduler iteration the DRAFT model —
``quantize_decode_model``'s int8 rewrite of the target by default — runs
``spec_tokens`` fixed-shape decode steps (the ordinary step program,
built from the draft's params via ``DecodePrograms(step_model=...)``),
then ONE fixed-shape verify program scores all k+1 window positions with
the TARGET model at once. Standard rejection sampling accepts 0..k draft
tokens plus a correction/bonus token, so the emitted stream follows the
target model's distribution EXACTLY regardless of draft quality (greedy
degenerates to longest-matching-prefix, which is what makes spec streams
token-identical to vanilla decode — the CI gate).

Program accounting: the draft step REPLACES the vanilla decode step (the
target never needs a 1-token program — the verify's accept-0 case IS a
vanilla step), so the paged program set stays at ladder + 2 and the
unpaged at ladder + 3 (its standalone admit rides along). Both are
progcache-keyed like everything else; a warm restart compiles nothing.

KV discipline: draft steps write draft-model K/V into the live slabs at
window positions (write position clamped to capacity − 1); the verify
attends under a strict per-row ``< length`` mask — the draft scratch is
invisible to it — and rewrites every window position with target-exact
K/V. After the verify the slabs hold target K/V through every committed
position, so rewind-on-reject is a pure host-side bookkeeping edit:
``truncate()`` on the cache manager (paged: a block-table/length edit;
unpaged: a length rollback), never a KV copy. ``keff`` additionally
clamps acceptance to the paged admission reservation, so a sequence
never allocates a block mid-stream — exactly the vanilla invariant.

Everything here runs inside the ONE engine op the scheduler pushes per
replica per iteration (``decode.draft``/``decode.verify`` spans nest
under ``decode.step``), so capture, sanitizer, fault plans and
``stop(drain=True)`` compose unchanged.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from ... import engine as _engine
from ... import telemetry as _telemetry
from ..batcher import ServingError


# --- sampling / acceptance math (host-side, f64) --------------------------
def _softmax64(logits, temperature: float) -> np.ndarray:
    """f64 softmax on the host — the one place sampling probabilities are
    computed, so vanilla and speculative paths share identical math."""
    z = np.asarray(logits, np.float64) / max(float(temperature), 1e-8)
    z = z - z.max()
    e = np.exp(z)
    return e / e.sum()


def _draw(probs: np.ndarray, rng) -> int:
    """One inverse-CDF draw (clamped against fp round-off in the cumsum
    tail)."""
    u = rng.random_sample()
    idx = int(np.searchsorted(np.cumsum(probs), u, side="right"))
    return min(idx, len(probs) - 1)


def sample_token(logits, temperature: float, rng) -> int:
    """Greedy argmax at temperature 0 (or without an rng), else one draw
    from the f64 softmax — shared by vanilla and speculative paths."""
    if temperature <= 0.0 or rng is None:
        return int(np.asarray(logits).argmax())
    return _draw(_softmax64(logits, temperature), rng)


def accept_greedy(draft: List[int], vlogits,
                  k_eff: int) -> Tuple[int, List[int]]:
    """Longest-matching-prefix acceptance: greedy rejection sampling
    degenerates to comparing each draft token with the target argmax.
    Returns ``(accepted, emitted)`` with ``len(emitted) == accepted + 1``
    — the final token is the target's correction (first mismatch) or
    bonus (whole window accepted), so every iteration advances ≥ 1
    token. ``k_eff == 0`` is exactly one vanilla greedy step."""
    emitted: List[int] = []
    for j in range(int(k_eff)):
        t = int(np.asarray(vlogits[j]).argmax())
        emitted.append(t)
        if t != int(draft[j]):
            return j, emitted
    emitted.append(int(np.asarray(vlogits[int(k_eff)]).argmax()))
    return int(k_eff), emitted


def accept_sampled(draft: List[int], draft_probs, vlogits, k_eff: int,
                   temperature: float, rng) -> Tuple[int, List[int]]:
    """Leviathan rejection sampling: accept draft ``d_j`` w.p.
    ``min(1, p[d]/q[d])``; the first rejection resamples from the
    residual ``max(p − q, 0)`` (falling back to ``p`` if the residual
    vanishes numerically); a fully-accepted window earns a bonus draw
    from the target's last position. The emitted marginals equal the
    target model's distribution exactly, regardless of draft quality."""
    emitted: List[int] = []
    for j in range(int(k_eff)):
        p = _softmax64(vlogits[j], temperature)
        q = np.asarray(draft_probs[j], np.float64)
        d = int(draft[j])
        if rng.random_sample() < min(1.0, p[d] / max(q[d], 1e-300)):
            emitted.append(d)
            continue
        resid = np.maximum(p - q, 0.0)
        s = resid.sum()
        emitted.append(_draw(resid / s if s > 0.0 else p, rng))
        return j, emitted
    emitted.append(_draw(_softmax64(vlogits[int(k_eff)], temperature), rng))
    return int(k_eff), emitted


# --- the scheduler's speculative step loop --------------------------------
class SpecDecoder:
    """One instance per ``DecodeScheduler`` when ``GenerateConfig.spec``
    is on. Owns no state beyond the back-reference — all bookkeeping
    stays on the scheduler and cache managers, so stats, drain and the
    poisoned-step recovery path are the vanilla code paths."""

    def __init__(self, sched):
        self.sched = sched
        self.k = int(sched.config.spec_tokens)

    def step_all(self):
        """One draft-k-then-verify iteration on every occupied replica:
        ONE engine op per replica (k draft dispatches + the verify +
        host acceptance, all inside), one fence, then commit — truncate
        the cache to the accepted length and emit 1..k+1 tokens."""
        sched = self.sched
        k = self.k
        cap = sched.programs.capacity
        stepped = []          # (replica, [active...], holder)
        touched = []
        with sched._cond:
            by_rep: Dict[int, list] = {}
            for (rep, _slot), a in sched._active.items():
                by_rep.setdefault(rep, []).append(a)
        for rep, actives in sorted(by_rep.items()):
            actives.sort(key=lambda a: a.slot)
            cache = sched.caches[rep]
            n0 = np.zeros(cache.slots, np.int32)
            t0 = np.zeros(cache.slots, np.int32)
            keff = np.zeros(cache.slots, np.int32)
            for a in actives:
                n0[a.slot] = cache.length(a.slot)
                t0[a.slot] = a.last_token
                # emit ≤ keff+1 tokens: stay within max_new_tokens AND
                # within capacity/admission reservation, so accepted
                # positions never need a block beyond what try_admit
                # reserved (rewind is then a pure length edit)
                remaining = a.stream.max_new_tokens - a.generated
                keff[a.slot] = max(0, min(k, remaining - 1,
                                          cap - 1 - int(n0[a.slot])))
            active = n0 > 0
            tables = cache.step_arrays()[1] if sched.config.paged else None
            # per-row sampling context, consumed inside the op — safe:
            # the scheduler fences before touching these streams again
            samplers = {a.slot: (a.temperature, a.rng) for a in actives
                        if a.temperature > 0.0 and a.rng is not None}
            holder: Dict[str, object] = {}
            stepped.append((rep, actives, holder))
            touched.append(cache.var)

            def op(cache=cache, n0=n0, t0=t0, keff=keff, tables=tables,
                   samplers=samplers, active=active, holder=holder):
                try:
                    with _telemetry.span("decode.step", domain="serving",
                                         rows=int(active.sum()),
                                         spec=k):
                        self._speculate(cache, n0, t0, keff, tables,
                                        samplers, active, holder)
                except Exception as e:          # noqa: BLE001
                    holder["error"] = e

            cs = sched._captures[rep] if rep < len(sched._captures) \
                else None
            if cs is not None:
                cs.begin_step()
                cs.push(op, mutable_vars=[cache.var], name="decode.step")
                cs.end_step()
            else:
                _engine.push(op, mutable_vars=[cache.var],
                             name="decode.step")
        if not stepped:
            return
        _engine.fence(touched).wait()
        sched.steps += 1
        for rep, actives, holder in stepped:
            err = holder.get("error")
            if err is not None:
                # donation may have consumed the slabs mid-iteration —
                # rebuild the replica (the vanilla recovery path)
                for a in actives:
                    sched._retire(a, error=ServingError(
                        "decode step failed: %s" % err,
                        code="dispatch_error"))
                sched.caches[rep].reset()
                continue
            res = holder["res"]
            cache = sched.caches[rep]
            for a in actives:
                base, kk, acc, emitted = res[a.slot]
                # commit: KV through base+acc is target-exact (verify
                # rewrote the window); the reject rewind is this ONE
                # host edit — paged drops only entries past the
                # admission reservation (none in steady state)
                cache.truncate(a.slot, base + 1 + acc)
                sched.seq_steps += 1
                sched.step_tokens += len(emitted)
                sched.drafted_tokens += kk
                sched.accepted_tokens += acc
                for m, tok in enumerate(emitted):
                    if not sched._emit(a, tok, length=base + 1 + m):
                        break

    def _speculate(self, cache, n0, t0, keff, tables, samplers, active,
                   holder):
        """The device phase (engine worker thread): k draft steps, one
        verify, host acceptance. Every array is (slots,) or (slots, W)
        regardless of occupancy or accept counts — fixed shapes, so the
        program set never grows past draft step + verify."""
        sched = self.sched
        programs = sched.programs
        k = self.k
        cap = programs.capacity
        W = k + 1
        wtok = np.zeros((cache.slots, W), np.int32)
        wtok[:, 0] = t0
        qprobs: Dict[Tuple[int, int], np.ndarray] = {}
        cur = t0.copy()
        with _telemetry.span("decode.draft", domain="serving", k=k):
            for j in range(k):
                # clamp the write position to cap-1: a row nearing
                # capacity parks tail drafts on the last position (the
                # verify rewrites it target-exact; keff already keeps
                # anything ACCEPTED strictly below capacity)
                lens_j = np.where(active, np.minimum(n0 + j, cap - 1),
                                  0).astype(np.int32)
                if tables is not None:
                    out = programs.decode(
                        cache.k_slab, cache.v_slab, tables, lens_j, cur,
                        ks_slab=cache.k_scale, vs_slab=cache.v_scale)
                else:
                    out = programs.decode(
                        cache.k_slab, cache.v_slab, lens_j, cur,
                        ks_slab=cache.k_scale, vs_slab=cache.v_scale)
                cache.swap_slabs(*out[1:])
                logits = np.asarray(out[0])
                cur = logits.argmax(axis=-1).astype(np.int32)
                for slot, (temp, rng) in sorted(samplers.items()):
                    # rng draws only for lanes the row can accept —
                    # keff-excess lanes stay argmax (no stream drift)
                    if j < int(keff[slot]):
                        q = _softmax64(logits[slot], temp)
                        qprobs[(slot, j)] = q
                        cur[slot] = _draw(q, rng)
                wtok[:, j + 1] = cur
        vlens = np.where(active, n0, 0).astype(np.int32)
        with _telemetry.span("decode.verify", domain="serving", window=W):
            if tables is not None:
                out = programs.verify(
                    cache.k_slab, cache.v_slab, tables, vlens, wtok,
                    ks_slab=cache.k_scale, vs_slab=cache.v_scale)
            else:
                out = programs.verify(
                    cache.k_slab, cache.v_slab, vlens, wtok,
                    ks_slab=cache.k_scale, vs_slab=cache.v_scale)
            cache.swap_slabs(*out[1:])
            vlogits = np.asarray(out[0])           # (slots, W, V)
        res: Dict[int, Tuple[int, int, int, List[int]]] = {}
        for slot in np.nonzero(active)[0]:
            slot = int(slot)
            kk = int(keff[slot])
            draft = [int(wtok[slot, j + 1]) for j in range(kk)]
            ctx = samplers.get(slot)
            if ctx is None:
                acc, emitted = accept_greedy(draft, vlogits[slot], kk)
            else:
                temp, rng = ctx
                acc, emitted = accept_sampled(
                    draft, [qprobs[(slot, j)] for j in range(kk)],
                    vlogits[slot], kk, temp, rng)
            res[slot] = (int(n0[slot]), kk, acc, emitted)
        holder["res"] = res
