"""Fixed-shape program set for continuous-batching decode.

The whole subsystem compiles exactly three kinds of XLA program per
(replica, slot-capacity) configuration, and nothing else, no matter how
requests arrive:

- one **prefill** program per prompt-length bucket in the ladder
  (batch 1, padded to the bucket, emits slab-capacity K/V),
- ONE **decode** program (batch = all slots, one token each, slabs
  donated — the steady-state step, compiled once, replayed forever),
- ONE **admit** program (dynamic-slice a prefilled sequence's K/V into
  its allocated slot row, slabs donated).

That bound is what `dryrun_decode` asserts: fresh compiles ≤ ladder size
+ 2 per replica. Every program goes through ``progcache`` keyed by its
LOWERED StableHLO text (the executor's train-step idiom — weights are
program *arguments* here, so the key is weight-independent and a warm
restart disk-loads the whole set), with the same stale-executable
fallback: a cached program that fails to run is dropped and the plain
``jax.jit`` path recompiles, never failing the request.
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ... import progcache as _progcache
from ...analysis import compile_witness as _witness
from ..batcher import ServingError
from .model import KV_SLAB_DTYPES, DecodeModel

log = logging.getLogger("mxnet_tpu")


def _avals(tree):
    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree)


class _Compiled:
    """One AOT program: progcache-persisted executable with jit fallback.

    ``counters`` is the owning DecodePrograms — fresh XLA compiles and
    progcache disk hits are tallied there so CI can gate the bound.
    """

    def __init__(self, fn, donate: Sequence[int], note: str, avals,
                 counters: "DecodePrograms"):
        self._jit = jax.jit(fn, donate_argnums=tuple(donate))
        self._exec = None
        self.source = "jit"
        with _witness.surface(counters._witness_scope):
            try:
                lowered = self._jit.lower(*avals)
                key = None
                if _progcache.enabled():
                    key = _progcache.lowered_key(
                        lowered.as_text(), donate=tuple(donate), extra=note)
                    exe = _progcache.load(key, kind="decode")
                    if exe is not None:
                        self._exec, self.source = exe, "disk"
                        counters.disk_hits += 1
                        return
                self._exec = lowered.compile()
                self.source = "compile"
                counters.compiles += 1
                _witness.record_compile("decode", key=note)
                if key is not None:
                    _progcache.store(key, self._exec, note=note,
                                     kind="decode")
            except Exception:
                # anything going sideways in lowering/AOT pins the plain-jit
                # path; its first call is still one fresh compile
                log.warning("generate: AOT path failed for %s; using plain "
                            "jit", note, exc_info=True)
                self._exec = None
                counters.compiles += 1
                _witness.record_compile("decode", key=note + ":jit_fallback")

    def __call__(self, *args):
        if self._exec is not None:
            try:
                return self._exec(*args)
            except Exception:
                # stale/incompatible disk-loaded executable: drop it and
                # recompile via jit (args are intact — argument processing
                # precedes donation)
                log.warning("generate: cached program unusable; recompiling",
                            exc_info=True)
                self._exec = None
        return self._jit(*args)


class DecodePrograms:
    """The compiled program set for one model at one slot/capacity config.

    Thread-safety: construction and ``prefill``'s lazy per-bucket build
    happen on the scheduler thread only; the compiled callables themselves
    are pure and safe to invoke from engine worker threads.
    """

    def __init__(self, model: DecodeModel, slots: int, capacity: int,
                 prefill_buckets: Sequence[int],
                 kv_dtype: str = "float32",
                 step_model: Optional[DecodeModel] = None):
        buckets = sorted({int(b) for b in prefill_buckets})
        if not buckets:
            raise ServingError("decode: empty prefill bucket ladder")
        if buckets[-1] > capacity:
            raise ServingError(
                "decode: prefill bucket %d exceeds kv capacity %d"
                % (buckets[-1], capacity))
        if kv_dtype not in KV_SLAB_DTYPES:
            raise ServingError("decode: unknown kv_dtype %r (have %s)"
                               % (kv_dtype, sorted(KV_SLAB_DTYPES)))
        self.model = model
        # the model whose forward IS the decode-step program. Defaults to
        # ``model``; speculative decoding passes the DRAFT model here, so
        # the vanilla 1-token step is never built — the draft step takes
        # its slot in the program set and the verify program doubles as
        # the target's step (accept-0 ≡ one vanilla step). That is what
        # keeps the paged spec set at ladder + 2.
        self.step_model = step_model or model
        self.slots = int(slots)
        self.capacity = int(capacity)
        self.buckets: List[int] = buckets
        self.kv_dtype = kv_dtype
        self.compiles = 0    # fresh XLA compiles (the CI-gated bound)
        self.disk_hits = 0   # progcache warm loads
        # per-instance compile-witness scope: every _Compiled build tags
        # its fresh compiles / disk loads with it, so the witness ledger
        # splits per program set (scheduler.stats reads it back)
        self._witness_scope = _witness.new_scope()
        self._params_avals = _avals(model.params)
        self._step_params_avals = _avals(self.step_model.params)
        self._prefill: Dict[int, _Compiled] = {}
        self._verify: Optional[_Compiled] = None
        self.spec_window = 0
        elem = KV_SLAB_DTYPES[kv_dtype]
        slab = jax.ShapeDtypeStruct(
            model.kv_slab_shape(self.slots, self.capacity), elem)
        ints = lambda n: jax.ShapeDtypeStruct((n,), jnp.int32)  # noqa: E731
        kv_new = jax.ShapeDtypeStruct(
            model.kv_slab_shape(1, self.capacity), elem)
        if kv_dtype == "int8":
            # scale slabs ride as extra donated args right after the value
            # slabs, so the steady-state step still allocates only logits
            sslab = jax.ShapeDtypeStruct(
                model.kv_scale_slab_shape(self.slots, self.capacity),
                jnp.float32)
            snew = jax.ShapeDtypeStruct(
                model.kv_scale_slab_shape(1, self.capacity), jnp.float32)
            self._decode = _Compiled(
                self.step_model.build_decode(self.slots, self.capacity,
                                             kv_dtype),
                donate=(1, 2, 3, 4), note="decode_step_kv_int8",
                avals=(self._step_params_avals, slab, slab, sslab, sslab,
                       ints(self.slots), ints(self.slots)),
                counters=self)
            self._admit = _Compiled(
                model.build_admit(self.slots, self.capacity, kv_dtype),
                donate=(0, 1, 2, 3), note="decode_admit_kv_int8",
                avals=(slab, slab, sslab, sslab, kv_new, kv_new, snew,
                       snew, jax.ShapeDtypeStruct((), jnp.int32)),
                counters=self)
        else:
            self._decode = _Compiled(
                self.step_model.build_decode(self.slots, self.capacity,
                                             kv_dtype),
                donate=(1, 2),
                note="decode_step" if kv_dtype == "float32"
                else "decode_step_kv_%s" % kv_dtype,
                avals=(self._step_params_avals, slab, slab,
                       ints(self.slots), ints(self.slots)),
                counters=self)
            self._admit = _Compiled(
                model.build_admit(self.slots, self.capacity, kv_dtype),
                donate=(0, 1),
                note="decode_admit" if kv_dtype == "float32"
                else "decode_admit_kv_%s" % kv_dtype,
                avals=(slab, slab, kv_new, kv_new,
                       jax.ShapeDtypeStruct((), jnp.int32)),
                counters=self)

    # --- shapes -----------------------------------------------------------
    def fresh_slabs(self) -> Tuple[jnp.ndarray, jnp.ndarray]:
        shape = self.model.kv_slab_shape(self.slots, self.capacity)
        elem = KV_SLAB_DTYPES[self.kv_dtype]
        return jnp.zeros(shape, elem), jnp.zeros(shape, elem)

    def fresh_scale_slabs(self) -> Optional[Tuple[jnp.ndarray, jnp.ndarray]]:
        """f32 per-position scale slabs (int8 KV only, else None)."""
        if self.kv_dtype != "int8":
            return None
        shape = self.model.kv_scale_slab_shape(self.slots, self.capacity)
        return jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32)

    def kv_bytes(self) -> int:
        """Bytes in the K+V slabs INCLUDING int8 scale slabs — the honest
        number for byte-equivalent pool comparisons."""
        shape = self.model.kv_slab_shape(self.slots, self.capacity)
        elem = jnp.dtype(KV_SLAB_DTYPES[self.kv_dtype]).itemsize
        total = 2 * int(np.prod(shape)) * elem
        if self.kv_dtype == "int8":
            sshape = self.model.kv_scale_slab_shape(self.slots,
                                                    self.capacity)
            total += 2 * int(np.prod(sshape)) * 4
        return total

    def bucket_for(self, prompt_len: int) -> Optional[int]:
        """Smallest ladder bucket holding the prompt, or None (too long)."""
        for b in self.buckets:
            if prompt_len <= b:
                return b
        return None

    def warm(self):
        """Build every prefill bucket up front (server start option)."""
        for b in self.buckets:
            self._prefill_for(b)

    def ensure_prefill(self, prompt_len: int):
        """Build (or no-op) the bucket program for ``prompt_len`` on the
        CALLING thread — the scheduler uses this so engine workers only
        ever invoke already-built programs."""
        bucket = self.bucket_for(prompt_len)
        if bucket is not None:
            self._prefill_for(bucket)

    def _prefill_for(self, bucket: int) -> _Compiled:
        prog = self._prefill.get(bucket)
        if prog is None:
            prog = _Compiled(
                self.model.build_prefill(bucket, self.capacity,
                                         self.kv_dtype), donate=(),
                note="decode_prefill_%d" % bucket
                if self.kv_dtype == "float32"
                else "decode_prefill_%d_kv_%s" % (bucket, self.kv_dtype),
                avals=(self._params_avals,
                       jax.ShapeDtypeStruct((1, bucket), jnp.int32),
                       jax.ShapeDtypeStruct((1,), jnp.int32)),
                counters=self)
            self._prefill[bucket] = prog
        return prog

    # --- execution --------------------------------------------------------
    def prefill(self, token_ids: Sequence[int]):
        """Run one prompt through its bucket's prefill program.

        Returns (last_logits (V,) ndarray-backed jax array,
        k_new, v_new (L, 1, Hkv, C, Dh)); int8 KV appends the (L, 1, C)
        ks_new, vs_new scale rows.
        """
        n = len(token_ids)
        bucket = self.bucket_for(n)
        if bucket is None:
            raise ServingError(
                "prompt length %d exceeds largest prefill bucket %d"
                % (n, self.buckets[-1]), code="too_large")
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :n] = np.asarray(token_ids, np.int32)
        out = self._prefill_for(bucket)(
            self.model.params, jnp.asarray(toks),
            jnp.asarray([n], jnp.int32))
        return (out[0][0],) + tuple(out[1:])

    def decode(self, k_slab, v_slab, lengths, tokens, ks_slab=None,
               vs_slab=None):
        """One step for every slot. ``lengths``/``tokens``: (slots,) i32
        (inactive slots: length 0, token 0 — lanes wasted, never wrong).
        Donates the slabs (and int8 scale slabs); use the returned ones.
        Returns (logits, k, v) or (logits, k, v, ks, vs) for int8 KV.
        Runs ``step_model`` — the DRAFT model under speculative decoding,
        identical to ``model`` otherwise."""
        if self.kv_dtype == "int8":
            return self._decode(self.step_model.params, k_slab, v_slab,
                                ks_slab, vs_slab,
                                jnp.asarray(lengths, jnp.int32),
                                jnp.asarray(tokens, jnp.int32))
        return self._decode(self.step_model.params, k_slab, v_slab,
                            jnp.asarray(lengths, jnp.int32),
                            jnp.asarray(tokens, jnp.int32))

    # --- speculative decode (serving/generate/spec.py) --------------------
    def enable_verify(self, window: int):
        """Build the ONE extra spec program: a fixed-shape W-position
        verify forward of the TARGET model (W = spec_tokens + 1),
        progcache-keyed like everything else. Idempotent per window."""
        W = int(window)
        if self._verify is not None and self.spec_window == W:
            return
        elem = KV_SLAB_DTYPES[self.kv_dtype]
        slab = jax.ShapeDtypeStruct(
            self.model.kv_slab_shape(self.slots, self.capacity), elem)
        ints = jax.ShapeDtypeStruct((self.slots,), jnp.int32)
        wtoks = jax.ShapeDtypeStruct((self.slots, W), jnp.int32)
        if self.kv_dtype == "int8":
            sslab = jax.ShapeDtypeStruct(
                self.model.kv_scale_slab_shape(self.slots, self.capacity),
                jnp.float32)
            self._verify = _Compiled(
                self.model.build_verify(self.slots, self.capacity, W,
                                        self.kv_dtype),
                donate=(1, 2, 3, 4),
                note="decode_verify_w%d_kv_int8" % W,
                avals=(self._params_avals, slab, slab, sslab, sslab, ints,
                       wtoks),
                counters=self)
        else:
            self._verify = _Compiled(
                self.model.build_verify(self.slots, self.capacity, W,
                                        self.kv_dtype),
                donate=(1, 2),
                note="decode_verify_w%d" % W if self.kv_dtype == "float32"
                else "decode_verify_w%d_kv_%s" % (W, self.kv_dtype),
                avals=(self._params_avals, slab, slab, ints, wtoks),
                counters=self)
        self.spec_window = W

    def verify(self, k_slab, v_slab, lengths, wtokens, ks_slab=None,
               vs_slab=None):
        """Score a (slots, W) draft window against the TARGET model in one
        program: returns (logits (B, W, V), k, v[, ks, vs]) with the
        window's target-exact k/v scattered over the draft scratch
        (slabs donated)."""
        if self.kv_dtype == "int8":
            return self._verify(self.model.params, k_slab, v_slab,
                                ks_slab, vs_slab,
                                jnp.asarray(lengths, jnp.int32),
                                jnp.asarray(wtokens, jnp.int32))
        return self._verify(self.model.params, k_slab, v_slab,
                            jnp.asarray(lengths, jnp.int32),
                            jnp.asarray(wtokens, jnp.int32))

    def admit(self, k_slab, v_slab, k_new, v_new, slot: int, ks_slab=None,
              vs_slab=None, ks_new=None, vs_new=None):
        """Slot a prefilled sequence's K/V into the slabs (donates slabs).
        Returns (k, v) or (k, v, ks, vs) for int8 KV."""
        if self.kv_dtype == "int8":
            return self._admit(k_slab, v_slab, ks_slab, vs_slab, k_new,
                               v_new, ks_new, vs_new,
                               jnp.asarray(slot, jnp.int32))
        return self._admit(k_slab, v_slab, k_new, v_new,
                           jnp.asarray(slot, jnp.int32))


class PagedDecodePrograms(DecodePrograms):
    """Program set for block/paged KV decode (``MXNET_DECODE_PAGED=1``).

    Two program kinds, both progcache-keyed by lowered StableHLO exactly
    like the unpaged set, so the paged bound is even TIGHTER than the
    unpaged one: the bucketed **paged-prefill** ladder (gather cached
    prefix through the block table + chunked prefill + CoW fork + suffix
    scatter, all in ONE donated program per rung — there is no separate
    admit program) and ONE **paged decode** step (scatter each row's new
    k/v into its private block, gather per-row dense views through the
    tables, mask by length). Steady state compiles nothing, and a warm
    restart disk-loads the whole set.
    """

    def __init__(self, model: DecodeModel, slots: int, capacity: int,
                 prefill_buckets: Sequence[int], block_tokens: int,
                 num_blocks: int, kv_dtype: str = "float32",
                 step_model: Optional[DecodeModel] = None):
        buckets = sorted({int(b) for b in prefill_buckets})
        if not buckets:
            raise ServingError("decode: empty prefill bucket ladder")
        if buckets[-1] > capacity:
            raise ServingError(
                "decode: prefill bucket %d exceeds kv capacity %d"
                % (buckets[-1], capacity))
        if block_tokens < 1:
            raise ServingError("decode: block_tokens must be >= 1")
        if num_blocks < 1:
            raise ServingError("decode: need at least one usable KV block")
        if kv_dtype not in KV_SLAB_DTYPES:
            raise ServingError("decode: unknown kv_dtype %r (have %s)"
                               % (kv_dtype, sorted(KV_SLAB_DTYPES)))
        self.model = model
        self.step_model = step_model or model    # draft model under spec
        self.slots = int(slots)
        self.capacity = int(capacity)
        self.buckets: List[int] = buckets
        self.kv_dtype = kv_dtype
        self.block_tokens = int(block_tokens)
        # MB = per-sequence table width; gathered views are MB*T wide, so
        # every position < capacity is addressable through a table
        self.max_blocks = -(-self.capacity // self.block_tokens)
        self.num_blocks = int(num_blocks)        # usable (excludes trash)
        self.compiles = 0
        self.disk_hits = 0
        self._witness_scope = _witness.new_scope()
        self._params_avals = _avals(model.params)
        self._step_params_avals = _avals(self.step_model.params)
        self._prefill: Dict[int, _Compiled] = {}
        self._verify: Optional[_Compiled] = None
        self.spec_window = 0
        slab = jax.ShapeDtypeStruct(
            model.paged_slab_shape(self.num_blocks + 1, self.block_tokens),
            KV_SLAB_DTYPES[kv_dtype])
        self._slab_aval = slab
        self._sslab_aval = None
        ints = lambda n: jax.ShapeDtypeStruct((n,), jnp.int32)  # noqa: E731
        tables = jax.ShapeDtypeStruct((self.slots, self.max_blocks),
                                      jnp.int32)
        if kv_dtype == "int8":
            self._sslab_aval = jax.ShapeDtypeStruct(
                model.paged_scale_slab_shape(self.num_blocks + 1,
                                             self.block_tokens),
                jnp.float32)
            self._decode = _Compiled(
                self.step_model.build_paged_decode(
                    self.slots, self.block_tokens, self.max_blocks,
                    kv_dtype),
                donate=(1, 2, 3, 4), note="paged_decode_step_kv_int8",
                avals=(self._step_params_avals, slab, slab,
                       self._sslab_aval, self._sslab_aval, tables,
                       ints(self.slots), ints(self.slots)),
                counters=self)
        else:
            self._decode = _Compiled(
                self.step_model.build_paged_decode(
                    self.slots, self.block_tokens, self.max_blocks,
                    kv_dtype),
                donate=(1, 2),
                note="paged_decode_step" if kv_dtype == "float32"
                else "paged_decode_step_kv_%s" % kv_dtype,
                avals=(self._step_params_avals, slab, slab, tables,
                       ints(self.slots), ints(self.slots)),
                counters=self)
        self._admit = None      # folded into the paged-prefill programs

    # --- shapes -----------------------------------------------------------
    def fresh_slabs(self) -> Tuple[jnp.ndarray, jnp.ndarray]:
        shape = self.model.paged_slab_shape(self.num_blocks + 1,
                                            self.block_tokens)
        elem = KV_SLAB_DTYPES[self.kv_dtype]
        return jnp.zeros(shape, elem), jnp.zeros(shape, elem)

    def fresh_scale_slabs(self) -> Optional[Tuple[jnp.ndarray, jnp.ndarray]]:
        if self.kv_dtype != "int8":
            return None
        shape = self.model.paged_scale_slab_shape(self.num_blocks + 1,
                                                  self.block_tokens)
        return jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32)

    def kv_bytes(self) -> int:
        shape = self.model.paged_slab_shape(self.num_blocks + 1,
                                            self.block_tokens)
        elem = jnp.dtype(KV_SLAB_DTYPES[self.kv_dtype]).itemsize
        total = 2 * int(np.prod(shape)) * elem
        if self.kv_dtype == "int8":
            sshape = self.model.paged_scale_slab_shape(self.num_blocks + 1,
                                                       self.block_tokens)
            total += 2 * int(np.prod(sshape)) * 4
        return total

    def _prefill_for(self, bucket: int) -> _Compiled:
        prog = self._prefill.get(bucket)
        if prog is None:
            scalar = jax.ShapeDtypeStruct((), jnp.int32)
            common = (jax.ShapeDtypeStruct((self.max_blocks,), jnp.int32),
                      scalar,
                      jax.ShapeDtypeStruct((1, bucket), jnp.int32),
                      jax.ShapeDtypeStruct((1,), jnp.int32),
                      scalar, scalar)
            if self.kv_dtype == "int8":
                avals = (self._params_avals, self._slab_aval,
                         self._slab_aval, self._sslab_aval,
                         self._sslab_aval) + common
                donate = (1, 2, 3, 4)
                note = "paged_prefill_%d_kv_int8" % bucket
            else:
                avals = (self._params_avals, self._slab_aval,
                         self._slab_aval) + common
                donate = (1, 2)
                note = "paged_prefill_%d" % bucket \
                    if self.kv_dtype == "float32" \
                    else "paged_prefill_%d_kv_%s" % (bucket, self.kv_dtype)
            prog = _Compiled(
                self.model.build_paged_prefill(bucket, self.block_tokens,
                                               self.max_blocks,
                                               self.kv_dtype),
                donate=donate, note=note, avals=avals, counters=self)
            self._prefill[bucket] = prog
        return prog

    # --- execution --------------------------------------------------------
    def paged_prefill(self, k_slab, v_slab, table, ctx_len: int,
                      suffix: Sequence[int], fork_src: int, fork_dst: int,
                      ks_slab=None, vs_slab=None):
        """Prefill ``suffix`` against the ``ctx_len``-token cached prefix
        reachable through ``table``, scattering the suffix k/v into its
        blocks (slabs donated). Returns (last_logits (V,), k, v) — int8
        KV appends the updated ks, vs scale slabs."""
        n = len(suffix)
        bucket = self.bucket_for(n)
        if bucket is None:
            raise ServingError(
                "suffix length %d exceeds largest prefill bucket %d"
                % (n, self.buckets[-1]), code="too_large")
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :n] = np.asarray(suffix, np.int32)
        common = (jnp.asarray(table, jnp.int32),
                  jnp.asarray(ctx_len, jnp.int32), jnp.asarray(toks),
                  jnp.asarray([n], jnp.int32),
                  jnp.asarray(fork_src, jnp.int32),
                  jnp.asarray(fork_dst, jnp.int32))
        if self.kv_dtype == "int8":
            out = self._prefill_for(bucket)(
                self.model.params, k_slab, v_slab, ks_slab, vs_slab,
                *common)
        else:
            out = self._prefill_for(bucket)(
                self.model.params, k_slab, v_slab, *common)
        return (out[0][0],) + tuple(out[1:])

    def prefill(self, token_ids: Sequence[int]):
        raise ServingError("paged decode has no standalone prefill — "
                           "use paged_prefill (admit is folded in)")

    def admit(self, *a, **kw):
        raise ServingError("paged decode has no standalone admit — "
                           "the paged-prefill program scatters in place")

    def decode(self, k_slab, v_slab, tables, lengths, tokens,
               ks_slab=None, vs_slab=None):
        """One step for every slot, indexed through the block tables.
        Donates the slabs; use the returned ones. int8 KV takes and
        returns the scale slabs after the value slabs. Runs
        ``step_model`` (the draft under speculative decoding)."""
        if self.kv_dtype == "int8":
            return self._decode(self.step_model.params, k_slab, v_slab,
                                ks_slab, vs_slab,
                                jnp.asarray(tables, jnp.int32),
                                jnp.asarray(lengths, jnp.int32),
                                jnp.asarray(tokens, jnp.int32))
        return self._decode(self.step_model.params, k_slab, v_slab,
                            jnp.asarray(tables, jnp.int32),
                            jnp.asarray(lengths, jnp.int32),
                            jnp.asarray(tokens, jnp.int32))

    def enable_verify(self, window: int):
        """Paged spec verify: ladder + draft step + this = ladder + 2 —
        the CI-gated spec program bound (there is no separate admit)."""
        W = int(window)
        if self._verify is not None and self.spec_window == W:
            return
        ints = jax.ShapeDtypeStruct((self.slots,), jnp.int32)
        wtoks = jax.ShapeDtypeStruct((self.slots, W), jnp.int32)
        tables = jax.ShapeDtypeStruct((self.slots, self.max_blocks),
                                      jnp.int32)
        if self.kv_dtype == "int8":
            self._verify = _Compiled(
                self.model.build_paged_verify(
                    self.slots, self.block_tokens, self.max_blocks, W,
                    self.kv_dtype),
                donate=(1, 2, 3, 4),
                note="paged_verify_w%d_kv_int8" % W,
                avals=(self._params_avals, self._slab_aval,
                       self._slab_aval, self._sslab_aval,
                       self._sslab_aval, tables, ints, wtoks),
                counters=self)
        else:
            self._verify = _Compiled(
                self.model.build_paged_verify(
                    self.slots, self.block_tokens, self.max_blocks, W,
                    self.kv_dtype),
                donate=(1, 2),
                note="paged_verify_w%d" % W if self.kv_dtype == "float32"
                else "paged_verify_w%d_kv_%s" % (W, self.kv_dtype),
                avals=(self._params_avals, self._slab_aval,
                       self._slab_aval, tables, ints, wtoks),
                counters=self)
        self.spec_window = W

    def verify(self, k_slab, v_slab, tables, lengths, wtokens,
               ks_slab=None, vs_slab=None):
        """Target-model W-position verify through the block tables
        (slabs donated) — see ``DecodePrograms.verify``."""
        if self.kv_dtype == "int8":
            return self._verify(self.model.params, k_slab, v_slab,
                                ks_slab, vs_slab,
                                jnp.asarray(tables, jnp.int32),
                                jnp.asarray(lengths, jnp.int32),
                                jnp.asarray(wtokens, jnp.int32))
        return self._verify(self.model.params, k_slab, v_slab,
                            jnp.asarray(tables, jnp.int32),
                            jnp.asarray(lengths, jnp.int32),
                            jnp.asarray(wtokens, jnp.int32))
