"""Orca-style iteration-level scheduler for continuous-batching decode.

One scheduler thread drives every replica: each loop iteration it
(1) expires/admits waiting prefills into freed slots, (2) pushes ONE
fixed-shape decode step per occupied replica onto the engine
(``mutable_vars=[kv var]`` — the engine's dependency ordering serializes
step N+1 after step N and after any admits between them), (3) fences,
samples greedily on the host, streams tokens out, and retires finished
sequences — so the batch is re-formed **every step** as sequences finish
and new ones join mid-flight.

Compile discipline: all device work goes through the fixed
``DecodePrograms`` set (prefill ladder + one decode step + one admit per
replica), so steady state compiles nothing regardless of traffic shape.
The decode-step push is optionally routed through an
``engine.CapturedSequence`` per replica (``MXNET_ENGINE_CAPTURE`` /
``GenerateConfig.capture``): its signature is occupancy-independent, so
the steady-state step replays with near-zero host dispatch overhead.

Paged mode (``MXNET_DECODE_PAGED=1``, PR 13): the same loop drives
``PagedDecodePrograms`` + ``PagedKVCacheManager`` — admission goes
through ``try_admit`` (block reservation + prefix-hash lookup, returning
an ``AdmitPlan``), the prefill op becomes one fused paged-prefill
program (CoW fork + cached-prefix attention + suffix scatter), and the
decode step carries each row's block table as an extra fixed-shape arg.
The unpaged path is untouched and remains the bitwise-reference arm.

Lock discipline (declared in ``analysis/lockorder.py``):
``DecodeScheduler._cond`` has rank 50 — engine pushes and fences
(``engine._engine_lock``, rank 20) NEVER happen while it is held;
``TokenStream._cond``, ``KVCacheManager._lock`` and
``PagedKVCacheManager._lock`` are leaves (rank 100) and may be taken
under it.
"""
from __future__ import annotations

import dataclasses
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ... import engine as _engine
from ... import telemetry as _telemetry
from ...telemetry import context as _trace_context
from ...telemetry import flight as _flight
from ..metrics import latency_histogram as _latency_histogram
from ...analysis import compile_witness as _witness
from ..batcher import ServingError
from .kv_cache import KVCacheManager
from .model import DecodeModel
from .paged import PagedKVCacheManager
from .programs import DecodePrograms, PagedDecodePrograms
from .spec import SpecDecoder, sample_token
from .stream import TokenStream


def _env_int(name, default):
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_flag(name, default):
    return os.environ.get(name, default).lower() \
        not in ("0", "", "false", "off")


def _env_buckets():
    raw = os.environ.get("MXNET_DECODE_PREFILL_BUCKETS", "8,16,32")
    try:
        return tuple(sorted({int(b) for b in raw.split(",") if b.strip()}))
    except ValueError:
        return (8, 16, 32)


def _env_eos():
    raw = os.environ.get("MXNET_DECODE_EOS", "")
    try:
        return int(raw) if raw.strip() else None
    except ValueError:
        return None


@dataclasses.dataclass
class GenerateConfig:
    """Decode-side knobs; every default reads its ``MXNET_DECODE_*`` env
    var at construction time (docs/env_var.md has the table). Head counts
    have no env default — they are architecture facts of the checkpoint."""
    num_heads: int
    num_kv_heads: int = 0
    slots: int = dataclasses.field(
        default_factory=lambda: _env_int("MXNET_DECODE_SLOTS", 4))
    max_context: int = dataclasses.field(
        default_factory=lambda: _env_int("MXNET_DECODE_MAX_CONTEXT", 64))
    prefill_buckets: Tuple[int, ...] = dataclasses.field(
        default_factory=_env_buckets)
    max_new_tokens: int = dataclasses.field(
        default_factory=lambda: _env_int("MXNET_DECODE_MAX_NEW_TOKENS", 32))
    queue_depth: int = dataclasses.field(
        default_factory=lambda: _env_int("MXNET_DECODE_QUEUE_DEPTH", 64))
    eos_id: Optional[int] = dataclasses.field(default_factory=_env_eos)
    capture: bool = dataclasses.field(
        default_factory=lambda: os.environ.get(
            "MXNET_DECODE_CAPTURE", "0").lower()
        not in ("0", "", "false", "off"))
    rope_base: float = 10000.0
    # paged KV (PR 13): block pool + prefix reuse; 0 blocks = auto-size
    # to byte parity with the unpaged config (slots * ceil(capacity/T))
    paged: bool = dataclasses.field(
        default_factory=lambda: _env_flag("MXNET_DECODE_PAGED", "0"))
    block_tokens: int = dataclasses.field(
        default_factory=lambda: _env_int("MXNET_DECODE_BLOCK_TOKENS", 16))
    num_blocks: int = dataclasses.field(
        default_factory=lambda: _env_int("MXNET_DECODE_BLOCKS", 0))
    prefix_share: bool = dataclasses.field(
        default_factory=lambda: _env_flag("MXNET_DECODE_PREFIX_SHARE", "1"))
    # low-precision serving (PR 14): KV slab dtype (f32|bf16|int8 —
    # normalized by mxnet_tpu.quant at scheduler construction) and weight
    # PTQ opt-in ("" = off; "int8"/"fp8" quantizes the DecodeModel)
    kv_dtype: str = dataclasses.field(
        default_factory=lambda: os.environ.get(
            "MXNET_DECODE_KV_DTYPE", "f32"))
    quant_weights: str = dataclasses.field(
        default_factory=lambda: os.environ.get(
            "MXNET_QUANT_WEIGHT_DTYPE", ""))
    # speculative decoding (PR 16): draft-k-then-verify. spec_tokens = k
    # drafted per iteration; spec_draft picks the int8 self-draft
    # ("int8", quantize_decode_model) or the same-precision model
    # ("self" — the upper bound on acceptance, no quality gap)
    spec: bool = dataclasses.field(
        default_factory=lambda: _env_flag("MXNET_DECODE_SPEC", "0"))
    spec_tokens: int = dataclasses.field(
        default_factory=lambda: _env_int("MXNET_DECODE_SPEC_TOKENS", 4))
    spec_draft: str = dataclasses.field(
        default_factory=lambda: os.environ.get(
            "MXNET_DECODE_SPEC_DRAFT", "int8"))


class _Active:
    """One sequence occupying a slot. ``temperature``/``rng`` carry the
    per-stream sampling context (temperature 0 = greedy, rng unused —
    a private RandomState per stream keeps draws deterministic per seed
    and independent of scheduling order across streams)."""
    __slots__ = ("stream", "replica", "slot", "last_token", "generated",
                 "temperature", "rng")

    def __init__(self, stream, replica, slot, last_token, generated,
                 temperature=0.0, rng=None):
        self.stream = stream
        self.replica = replica
        self.slot = slot
        self.last_token = last_token
        self.generated = generated
        self.temperature = temperature
        self.rng = rng


class DecodeScheduler:
    """Continuous-batching decode over one model across N replica slabs."""

    def __init__(self, model: DecodeModel, config: GenerateConfig,
                 replicas: int = 1):
        from ... import quant as _quant   # lazy — avoids an import cycle

        self.config = config
        kv_dtype = _quant.normalize_kv_dtype(config.kv_dtype)
        self.kv_dtype = kv_dtype
        if config.quant_weights and "wq_scale" not in model.params:
            model = _quant.quantize_decode_model(
                model, _quant.QuantConfig(
                    weight_dtype=config.quant_weights))
        self.model = model
        draft = None
        if config.spec:
            if config.spec_tokens < 1:
                raise ServingError("decode: spec_tokens must be >= 1")
            if config.spec_draft not in ("int8", "self"):
                raise ServingError(
                    "decode: unknown spec_draft %r (want int8|self)"
                    % config.spec_draft)
            if config.spec_draft == "int8" \
                    and "wq_scale" not in model.params:
                draft = _quant.quantize_decode_model(
                    model, _quant.QuantConfig(weight_dtype="int8"))
            else:
                # "self", or the target is already int8-quantized: the
                # draft IS the target — the step program is then byte-
                # identical to vanilla decode and shares its progcache
                # entry
                draft = model
        if config.paged:
            blocks = config.num_blocks or config.slots * (
                -(-config.max_context // config.block_tokens))
            self.programs: DecodePrograms = PagedDecodePrograms(
                model, config.slots, config.max_context,
                config.prefill_buckets, config.block_tokens, blocks,
                kv_dtype=kv_dtype, step_model=draft)
        else:
            self.programs = DecodePrograms(model, config.slots,
                                           config.max_context,
                                           config.prefill_buckets,
                                           kv_dtype=kv_dtype,
                                           step_model=draft)
        self._spec: Optional[SpecDecoder] = None
        if config.spec:
            self.programs.enable_verify(config.spec_tokens + 1)
            self._spec = SpecDecoder(self)
        self.replicas = int(replicas)
        self.caches: List[KVCacheManager] = []
        self._cond = threading.Condition()       # rank 50
        self._queue: deque = deque()             # (stream, prompt tokens)
        self._active: Dict[Tuple[int, int], _Active] = {}
        self._state = "stopped"                  # running|draining|stopped
        self._thread: Optional[threading.Thread] = None
        self._captures: List[Optional[_engine.CapturedSequence]] = []
        self.steps = 0
        # speculative-decode accounting (spec off: drafted stays 0 and
        # step_tokens == seq_steps, i.e. tokens/step is exactly 1.0)
        self.seq_steps = 0        # per-sequence step iterations
        self.step_tokens = 0      # tokens emitted by step iterations
        self.drafted_tokens = 0   # draft lanes eligible for acceptance
        self.accepted_tokens = 0  # draft lanes the target accepted
        reg = _telemetry.registry
        self._m_tokens = reg.counter(
            "decode_tokens_total", help="tokens emitted by decode streams")
        # explicit .set() (not fn=) — get_or_create would pin a stale
        # callback to a dead scheduler across server restarts
        self._m_occ = reg.gauge(
            "decode_batch_occupancy_pct",
            help="decode slots occupied, % (mean over replicas)")
        self._m_kv = reg.gauge(
            "kv_bytes", help="bytes held in decode KV slabs")
        # split-by-dtype twin of kv_bytes (the unlabeled gauge keeps its
        # historical meaning; capacity planning reads the labeled series)
        self._m_kv_dtype = reg.gauge(
            "kv_bytes", labels={"dtype": kv_dtype},
            help="bytes held in decode KV slabs")
        self._m_blocks_free = reg.gauge(
            "kv_blocks_free", labels={"decode_kv_dtype": kv_dtype},
            help="free KV blocks in the paged pool (sum over replicas)")
        self._m_blocks_total = reg.gauge(
            "kv_blocks_total", labels={"decode_kv_dtype": kv_dtype},
            help="usable KV blocks in the paged pool (sum over replicas)")
        self._m_prefix_hits = reg.counter(
            "decode_prefix_hits_total",
            help="admissions that reused a shared KV prefix")
        self._m_prefix_saved = reg.counter(
            "decode_prefix_tokens_saved_total",
            help="prompt tokens served from shared prefix blocks "
                 "instead of being re-prefilled")
        # explicit .set() from the scheduler loop, same staleness
        # rationale as decode_batch_occupancy_pct above
        self._m_accept_rate = reg.gauge(
            "decode_spec_accept_rate",
            help="speculative drafts accepted by the target model, "
                 "fraction of drafted tokens (0 when spec is off)")
        self._m_tokens_per_step = reg.gauge(
            "decode_tokens_per_step",
            help="tokens emitted per sequence per decode iteration "
                 "(vanilla decode: exactly 1.0)")

    # --- lifecycle --------------------------------------------------------
    def start(self):
        with self._cond:
            if self._state != "stopped":
                return
            self._state = "running"
        if self.config.paged:
            self.caches = [
                PagedKVCacheManager(self.programs, i,
                                    prefix_share=self.config.prefix_share)
                for i in range(self.replicas)]
            self._m_blocks_total.set(
                sum(c.blocks_total() for c in self.caches))
            self._m_blocks_free.set(
                sum(c.blocks_free() for c in self.caches))
        else:
            self.caches = [KVCacheManager(self.programs, i)
                           for i in range(self.replicas)]
        use_capture = self.config.capture or _engine.capture_enabled()
        self._captures = [
            _engine.CapturedSequence(name="decode_step_r%d" % i)
            if use_capture else None for i in range(self.replicas)]
        kv_total = sum(c.kv_bytes() for c in self.caches)
        self._m_kv.set(kv_total)
        self._m_kv_dtype.set(kv_total)
        self._thread = threading.Thread(target=self._loop,
                                        name="decode-scheduler", daemon=True)
        self._thread.start()

    def stop(self, drain: bool = False, deadline_ms: Optional[float] = None):
        """Stop the scheduler. ``drain=True`` finishes in-flight and queued
        streams first (refusing new submits, code ``shutting_down``);
        ``drain=False`` fails everything immediately (code ``shutdown``)."""
        with self._cond:
            if self._state == "stopped" and self._thread is None:
                return
            self._state = "draining" if drain else "stopped"
            self._cond.notify_all()
        t = self._thread
        if t is not None:
            timeout = None if deadline_ms is None else deadline_ms / 1000.0
            t.join(timeout)
            if t.is_alive():
                # drain deadline passed: force the loop out
                with self._cond:
                    self._state = "stopped"
                    self._cond.notify_all()
                t.join()
        self._thread = None
        code = "shutting_down" if drain else "shutdown"
        leftovers: List[TokenStream] = []
        with self._cond:
            self._state = "stopped"
            while self._queue:
                leftovers.append(self._queue.popleft()[0])
            actives, self._active = list(self._active.values()), {}
        for a in actives:
            self.caches[a.replica].free(a.slot)
            leftovers.append(a.stream)
        for s in leftovers:
            s._fail(ServingError("decode scheduler stopped", code=code))
        for cs in self._captures:
            if cs is not None:
                cs.invalidate("scheduler stopped")
        if self.caches:
            _engine.fence([c.var for c in self.caches]).wait()
            for c in self.caches:
                _engine.delete_variable(c.var)
        self.caches = []
        self._m_occ.set(0.0)

    # --- submission -------------------------------------------------------
    def submit(self, prompt: Sequence[int],
               max_new_tokens: Optional[int] = None,
               timeout_ms: Optional[float] = None,
               temperature: float = 0.0,
               seed: Optional[int] = None,
               request_id: Optional[str] = None,
               trace=None) -> TokenStream:
        """Queue one prompt. ``temperature`` 0 (default) is greedy —
        bitwise the historical behavior; > 0 samples from the softmax
        with a per-stream RandomState seeded by ``seed`` (deterministic
        per seed, independent of co-resident streams). ``request_id``
        is carried on the TokenStream and annotated on decode spans so
        an HTTP SSE stream correlates with scheduler work."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ServingError("empty prompt", code="too_large")
        if self.programs.bucket_for(len(prompt)) is None:
            raise ServingError(
                "prompt length %d exceeds largest prefill bucket %d"
                % (len(prompt), self.programs.buckets[-1]), code="too_large")
        if len(prompt) >= self.programs.capacity:
            raise ServingError(
                "prompt length %d leaves no kv capacity (max_context %d)"
                % (len(prompt), self.programs.capacity), code="too_large")
        max_new = int(max_new_tokens or self.config.max_new_tokens)
        if max_new < 1:
            raise ServingError("max_new_tokens must be >= 1",
                               code="too_large")
        deadline = None if timeout_ms is None \
            else time.monotonic() + timeout_ms / 1000.0
        stream = TokenStream(len(prompt), max_new, deadline,
                             request_id=request_id,
                             trace=(trace if trace is not None else
                                    _trace_context.current_context()))
        temperature = float(temperature)
        rng = np.random.RandomState(seed) if temperature > 0.0 else None
        with self._cond:
            if self._state == "draining":
                raise ServingError("server is draining",
                                   code="shutting_down")
            if self._state != "running":
                raise ServingError("decode scheduler not running",
                                   code="shutdown")
            if len(self._queue) >= self.config.queue_depth:
                raise ServingError("decode queue full", code="queue_full")
            self._queue.append((stream, prompt, temperature, rng))
            self._cond.notify_all()
        return stream

    # --- scheduler loop ---------------------------------------------------
    def _loop(self):
        while True:
            with self._cond:
                while (self._state == "running" and not self._queue
                       and not self._active):
                    self._cond.wait(0.1)
                if self._state == "stopped":
                    return
                if (self._state == "draining" and not self._queue
                        and not self._active):
                    return
            self._expire_and_cancel()
            self._admit_waiting()
            self._step_all()
            occ = [c.occupancy_pct() for c in self.caches]
            self._m_occ.set(sum(occ) / max(1, len(occ)))
            if self.config.paged and self.caches:
                self._m_blocks_free.set(
                    sum(c.blocks_free() for c in self.caches))
            if self.seq_steps:
                self._m_tokens_per_step.set(
                    self.step_tokens / self.seq_steps)
            if self.drafted_tokens:
                self._m_accept_rate.set(
                    self.accepted_tokens / self.drafted_tokens)

    def _expire_and_cancel(self):
        now = time.monotonic()
        expired: List[TokenStream] = []
        cancelled: List[TokenStream] = []
        with self._cond:
            keep: deque = deque()
            for item in self._queue:
                s = item[0]
                if s.cancelled:
                    cancelled.append(s)
                elif s.deadline is not None and now > s.deadline:
                    expired.append(s)
                else:
                    keep.append(item)
            self._queue = keep
        for s in cancelled:
            s._finish("cancelled")
            self._stream_end(s, ok=True, code="cancelled")
        for s in expired:
            s._fail(ServingError("expired before a decode slot freed",
                                 code="deadline_exceeded"))
            self._stream_end(s, ok=False, code="deadline_exceeded",
                             queued=True)
        # active sequences: retire cancelled/expired before the next step
        for key, a in list(self._active.items()):
            if a.stream.cancelled:
                self._retire(a, reason="cancelled")
            elif a.stream.deadline is not None and now > a.stream.deadline:
                self._retire(a, error=ServingError(
                    "deadline exceeded mid-stream",
                    code="deadline_exceeded"))

    def _stream_end(self, stream: TokenStream, ok: bool,
                    code: Optional[str] = None, queued: bool = False):
        """Observability tail for one finished stream: the registry
        latency histogram (trace-id exemplar), the flight recorder's
        completed-request ring, and the deadline-miss bundle trigger.
        Called with no scheduler locks held."""
        lat_ms = (time.monotonic() - stream.submitted) * 1e3
        tr = stream.trace
        if (queued and tr is not None
                and _telemetry.enabled("serving")):
            # a stream that died waiting never got its queued span —
            # stamp one now so its flight timeline is complete
            _telemetry.complete("serving.queued", domain="serving",
                                start_ns=int(stream.submitted * 1e9),
                                tokens=stream.prompt_len, error=code,
                                **tr.child().stamps())
        if ok:
            _latency_histogram().observe(
                lat_ms, exemplar=tr.trace_id if tr is not None else None)
        _flight.request_end(tr, ok=ok, code=code, latency_ms=lat_ms,
                            kind="generate", request_id=stream.request_id)
        if code == "deadline_exceeded":
            _flight.on_anomaly("deadline_miss", tr,
                               request_id=stream.request_id,
                               latency_ms=lat_ms, kind="generate")

    def _retire(self, a: _Active, reason: Optional[str] = None,
                error: Optional[ServingError] = None):
        self.caches[a.replica].free(a.slot)
        with self._cond:
            self._active.pop((a.replica, a.slot), None)
        if error is not None:
            a.stream._fail(error)
            self._stream_end(a.stream, ok=False, code=error.code)
        else:
            a.stream._finish(reason or "eos")
            self._stream_end(a.stream, ok=True, code=reason or "eos")

    def _pick_replica(self) -> Optional[int]:
        best, best_free = None, 0
        for i, c in enumerate(self.caches):
            free = c.slots - len(c.active_slots())
            if free > best_free:
                best, best_free = i, free
        return best

    def _admit_waiting(self):
        """Prefill waiting prompts into free slots (unpaged) / free blocks
        (paged). Each admission is one engine op on the target replica's
        kv var (prefill → slot insert → first-token sample), fenced as a
        group so fresh sequences join the very next decode step. Paged
        plans may reuse a cached prefix: the op runs only the suffix, and
        a copy-on-write fork (fused into the same program) privatizes a
        partially-shared boundary block first."""
        admitted = []         # (active, holder)
        touched = []
        while True:
            rep = self._pick_replica()
            if rep is None:
                break
            with self._cond:
                if not self._queue:
                    break
                stream, prompt, temp, rng = self._queue.popleft()
            cache = self.caches[rep]
            plan = cache.try_admit(stream, prompt, stream.max_new_tokens)
            if plan is None:      # slots/blocks exhausted — wait for
                with self._cond:  # retirement, never evict mid-stream
                    self._queue.appendleft((stream, prompt, temp, rng))
                break
            # build the bucket's prefill program here (scheduler thread)
            # so the engine op never mutates the program dict — two
            # replicas' workers could otherwise race the lazy build
            self.programs.ensure_prefill(len(plan.suffix))
            if plan.ctx_len:
                self._m_prefix_hits.inc()
                self._m_prefix_saved.inc(plan.ctx_len)
            # trace plumbing: the queued span closes at admission; the
            # serving.dispatch span brackets push -> first token (stamped
            # post-fence); the prefill span nests under it via ts
            tr = stream.trace
            dctx, ts = None, None
            if tr is not None and _telemetry.enabled("serving"):
                _telemetry.complete("serving.queued", domain="serving",
                                    start_ns=int(stream.submitted * 1e9),
                                    tokens=len(prompt),
                                    **tr.child().stamps())
                dctx = tr.child()
                ts = dctx.child().stamps()
            holder: Dict[str, object] = {}
            admitted.append((_Active(stream, rep, plan.slot, 0, 0,
                                     temperature=temp, rng=rng), holder,
                             dctx, _telemetry.clock_ns()))
            touched.append(cache.var)

            if self.config.paged:
                def op(cache=cache, plan=plan, holder=holder,
                       rid=stream.request_id, ts=ts):
                    def run():
                        out = self.programs.paged_prefill(
                            cache.k_slab, cache.v_slab, plan.table,
                            plan.ctx_len, plan.suffix,
                            plan.fork_src, plan.fork_dst,
                            ks_slab=cache.k_scale, vs_slab=cache.v_scale)
                        cache.swap_slabs(*out[1:])
                        # sampled post-fence on the scheduler thread —
                        # the stream's rng is never touched off-thread
                        holder["logits"] = np.asarray(out[0])
                    try:
                        with _telemetry.span(
                                "decode.prefill", domain="serving",
                                tokens=len(plan.suffix),
                                reused=plan.ctx_len,
                                **(ts if ts is not None
                                   else {"request_id": rid})):
                            if plan.forked:
                                with _telemetry.span(
                                        "decode.cow_fork", domain="serving",
                                        src=plan.fork_src,
                                        dst=plan.fork_dst):
                                    run()
                            else:
                                run()
                    except Exception as e:      # noqa: BLE001
                        holder["error"] = e
            else:
                def op(cache=cache, plan=plan, holder=holder,
                       rid=stream.request_id, ts=ts):
                    try:
                        with _telemetry.span("decode.prefill",
                                             domain="serving",
                                             tokens=len(plan.suffix),
                                             **(ts if ts is not None
                                                else {"request_id": rid})):
                            pre = self.programs.prefill(plan.suffix)
                            if len(pre) == 5:   # int8 KV: + scale rows
                                last, k_new, v_new, ks_new, vs_new = pre
                                out = self.programs.admit(
                                    cache.k_slab, cache.v_slab, k_new,
                                    v_new, plan.slot,
                                    ks_slab=cache.k_scale,
                                    vs_slab=cache.v_scale,
                                    ks_new=ks_new, vs_new=vs_new)
                            else:
                                last, k_new, v_new = pre
                                out = self.programs.admit(
                                    cache.k_slab, cache.v_slab, k_new,
                                    v_new, plan.slot)
                            cache.swap_slabs(*out)
                            holder["logits"] = np.asarray(last)
                    except Exception as e:      # noqa: BLE001
                        holder["error"] = e

            _engine.push(op, mutable_vars=[cache.var], name="decode.prefill")
        if not admitted:
            return
        _engine.fence(touched).wait()
        for a, holder, dctx, t0 in admitted:
            err = holder.get("error")
            if err is not None:
                self.caches[a.replica].free(a.slot)
                a.stream._fail(ServingError(
                    "prefill failed: %s" % err, code="dispatch_error"))
                self._stream_end(a.stream, ok=False, code="dispatch_error")
                continue
            if dctx is not None:
                # the decode-path dispatch span: push -> first token,
                # parent of the prefill span recorded on the engine worker
                _telemetry.complete("serving.dispatch", domain="serving",
                                    start_ns=t0, kind="prefill",
                                    replica=a.replica, **dctx.stamps())
            with self._cond:
                self._active[(a.replica, a.slot)] = a
            self._emit(a, sample_token(holder["logits"], a.temperature,
                                       a.rng))

    def _emit(self, a: _Active, token: int, length: Optional[int] = None
              ) -> bool:
        """Deliver one sampled token; retire the sequence if done and
        return False once it has retired (the speculative path stops
        emitting a window's remaining tokens on eos). ``length`` is the
        committed kv length AFTER this token's predecessor landed —
        speculative emits pass it explicitly because the cache already
        holds the whole accepted run."""
        a.last_token = token
        a.generated += 1
        a.stream._emit(token)
        self._m_tokens.inc()
        eos = self.config.eos_id
        if eos is not None and token == eos:
            self._retire(a, reason="eos")
            return False
        if a.generated >= a.stream.max_new_tokens:
            self._retire(a, reason="max_tokens")
            return False
        if length is None:
            length = self.caches[a.replica].length(a.slot)
        if length >= self.programs.capacity:
            # the next step would write at kv position == capacity (the
            # write position IS the current length)
            self._retire(a, reason="capacity")
            return False
        return True

    def _step_all(self):
        """One decode step on every replica with occupied slots: push all
        step ops, fence once, then sample/stream on the host. With
        ``GenerateConfig.spec`` the iteration is the draft-k-then-verify
        loop in spec.py instead (same push/fence/emit skeleton, 1..k+1
        tokens per sequence)."""
        if self._spec is not None:
            self._spec.step_all()
            return
        stepped = []          # (replica, [active...], holder)
        touched = []
        with self._cond:
            by_rep: Dict[int, List[_Active]] = {}
            for (rep, _slot), a in self._active.items():
                by_rep.setdefault(rep, []).append(a)
        for rep, actives in sorted(by_rep.items()):
            cache = self.caches[rep]
            lengths = np.zeros(cache.slots, np.int32)
            tokens = np.zeros(cache.slots, np.int32)
            for a in actives:
                lengths[a.slot] = cache.length(a.slot)
                tokens[a.slot] = a.last_token
            # paged rows index kv through their block tables (freed rows
            # are all-trash: they write block 0 and read nothing unmasked)
            tables = cache.step_arrays()[1] if self.config.paged else None
            holder: Dict[str, object] = {}
            stepped.append((rep, actives, holder))
            touched.append(cache.var)
            # batch-level span: link every co-resident stream's trace so
            # each request's tree shows the decode steps it shared
            step_stamps = None
            if _telemetry.enabled("serving"):
                tids = [a.stream.trace.trace_id for a in actives
                        if a.stream.trace is not None]
                if tids:
                    step_stamps = {
                        "trace_ids": tids,
                        "span_id": _trace_context.mint_span_id()}

            def op(cache=cache, lengths=lengths, tokens=tokens,
                   tables=tables, holder=holder, ts=step_stamps):
                try:
                    with _telemetry.span("decode.step", domain="serving",
                                         rows=int((lengths > 0).sum()),
                                         **(ts or {})):
                        if tables is not None:
                            out = self.programs.decode(
                                cache.k_slab, cache.v_slab, tables,
                                lengths, tokens, ks_slab=cache.k_scale,
                                vs_slab=cache.v_scale)
                        else:
                            out = self.programs.decode(
                                cache.k_slab, cache.v_slab, lengths,
                                tokens, ks_slab=cache.k_scale,
                                vs_slab=cache.v_scale)
                        cache.swap_slabs(*out[1:])
                        holder["logits"] = np.asarray(out[0])
                except Exception as e:          # noqa: BLE001
                    holder["error"] = e

            cs = self._captures[rep] if rep < len(self._captures) else None
            if cs is not None:
                cs.begin_step()
                cs.push(op, mutable_vars=[cache.var], name="decode.step")
                cs.end_step()
            else:
                _engine.push(op, mutable_vars=[cache.var],
                             name="decode.step")
        if not stepped:
            return
        _engine.fence(touched).wait()
        self.steps += 1
        for rep, actives, holder in stepped:
            err = holder.get("error")
            if err is not None:
                # donation may have consumed the slabs — rebuild the
                # replica rather than risk stepping on poisoned state
                for a in actives:
                    self._retire(a, error=ServingError(
                        "decode step failed: %s" % err,
                        code="dispatch_error"))
                self.caches[rep].reset()
                continue
            logits = holder["logits"]
            for a in actives:
                self.caches[rep].advance(a.slot)
                self.seq_steps += 1
                self.step_tokens += 1
                self._emit(a, sample_token(logits[a.slot], a.temperature,
                                           a.rng))

    # --- introspection ----------------------------------------------------
    def stats(self) -> Dict[str, int]:
        with self._cond:
            queued = len(self._queue)
            active = len(self._active)
        # with the compile witness armed, the compile/disk split is read
        # back from the witness ledger (this program set's scope) so the
        # per-set stats and the process-wide counters share one source
        n_compiles, n_disk = self.programs.compiles, self.programs.disk_hits
        if _witness.enabled():
            sc = _witness.scope_counts(self.programs._witness_scope)
            n_compiles, n_disk = sc["compiles"], sc["disk_hits"]
        st = {"compiles": n_compiles,
              "disk_hits": n_disk,
              "steps": self.steps, "queued": queued, "active": active,
              "kv_dtype": self.kv_dtype,
              "quant_weights": self.config.quant_weights or "off",
              "seq_steps": self.seq_steps,
              "step_tokens": self.step_tokens,
              "drafted_tokens": self.drafted_tokens,
              "accepted_tokens": self.accepted_tokens,
              "spec": "%s k=%d" % (self.config.spec_draft,
                                   self.config.spec_tokens)
              if self.config.spec else "off"}
        if self.config.paged and self.caches:
            st["blocks_total"] = sum(c.blocks_total() for c in self.caches)
            st["blocks_free"] = sum(c.blocks_free() for c in self.caches)
            st["prefix_hits"] = sum(c.prefix_hits for c in self.caches)
            st["prefix_tokens_saved"] = sum(
                c.prefix_tokens_saved for c in self.caches)
            st["cow_forks"] = sum(c.cow_forks for c in self.caches)
        return st
