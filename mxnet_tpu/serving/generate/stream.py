"""Per-request token stream for ``InferenceServer.submit_stream``.

A ``TokenStream`` is the caller's half of one generate request: tokens
appear as the scheduler decodes them; iteration blocks until the next
token or end-of-stream. Finish is terminal and carries a reason
(``"eos"``, ``"max_tokens"``, ``"capacity"`` — the row hit the KV slab
capacity) or a ``ServingError`` (deadline, shutdown, cancel, dispatch
failure).

Lock discipline: ``_cond`` is a LEAF (rank 100 in LOCK_HIERARCHY) — the
scheduler emits tokens with only this lock held, never while holding its
own scheduling lock, and callers never re-enter scheduler code from
inside iteration.
"""
from __future__ import annotations

import threading
import time
from typing import Iterator, List, Optional

from ..batcher import ServingError


class TokenStream:
    """Consumer handle for one streaming generate request."""

    def __init__(self, prompt_len: int, max_new_tokens: int,
                 deadline: Optional[float] = None,
                 request_id: Optional[str] = None, trace=None):
        self.prompt_len = prompt_len
        self.max_new_tokens = max_new_tokens
        self.deadline = deadline              # time.monotonic() absolute
        self.request_id = request_id          # caller correlation id
        self.trace = trace                    # telemetry.TraceContext | None
        self.submitted = time.monotonic()
        self._cond = threading.Condition()
        self._tokens: List[int] = []
        self._read = 0
        self._done = False
        self.finish_reason: Optional[str] = None
        self._error: Optional[ServingError] = None
        self._cancelled = False

    # --- scheduler side ---------------------------------------------------
    def _emit(self, token: int):
        with self._cond:
            if self._done:
                return
            self._tokens.append(int(token))
            self._cond.notify_all()

    def _finish(self, reason: str):
        with self._cond:
            if self._done:
                return
            self._done = True
            self.finish_reason = reason
            self._cond.notify_all()

    def _fail(self, err: ServingError):
        with self._cond:
            if self._done:
                return
            self._done = True
            self.finish_reason = err.code
            self._error = err
            self._cond.notify_all()

    @property
    def cancelled(self) -> bool:
        with self._cond:
            return self._cancelled

    # --- caller side ------------------------------------------------------
    def cancel(self):
        """Stop decoding this request; the scheduler frees its slot at the
        next step. Already-produced tokens stay readable."""
        with self._cond:
            if not self._done:
                self._cancelled = True

    def next_token(self, timeout: Optional[float] = None) -> Optional[int]:
        """Next token id, or None at end of stream. Raises the stream's
        ServingError if it failed, or ``wait_timeout`` on timeout."""
        limit = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                if self._read < len(self._tokens):
                    tok = self._tokens[self._read]
                    self._read += 1
                    return tok
                if self._done:
                    if self._error is not None and \
                            self._read >= len(self._tokens):
                        raise self._error
                    return None
                rem = None if limit is None else limit - time.monotonic()
                if rem is not None and rem <= 0:
                    raise ServingError("generate stream: no token within "
                                       "timeout", code="wait_timeout")
                self._cond.wait(rem)

    def __iter__(self) -> Iterator[int]:
        while True:
            tok = self.next_token()
            if tok is None:
                return
            yield tok

    def tokens(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the stream finishes; return all generated tokens."""
        limit = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._done:
                rem = None if limit is None else limit - time.monotonic()
                if rem is not None and rem <= 0:
                    raise ServingError("generate stream: not finished "
                                       "within timeout", code="wait_timeout")
                self._cond.wait(rem)
            if self._error is not None:
                raise self._error
            return list(self._tokens)

    @property
    def done(self) -> bool:
        with self._cond:
            return self._done
