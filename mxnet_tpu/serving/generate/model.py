"""Decode-side model: the KV-cache twin of ``models/transformer.py``.

``DecodeModel`` holds the decoder-LM weights in a canonical stacked layout
(per-layer arrays stacked on a leading L axis) plus the architecture facts
the weights alone cannot carry (head counts), and builds the two pure
functions the generate subsystem compiles:

- ``prefill_fn(params, tokens (1, T), length (1,))`` — full causal forward
  over a length-bucketed padded prompt, returning the next-token logits at
  position ``length - 1`` and the prompt's K/V laid out at slab capacity
  ``(L, 1, Hkv, C, Dh)``, ready to be slotted into a replica's KV slab.
- ``decode_fn(params, k_slab, v_slab, lengths (B,), tokens (B,))`` — ONE
  token for every slot at once: write each row's new k/v at position
  ``lengths[i]``, attend over its own prefix only
  (``ops.attention.cached_attention``), return (B, V) logits plus the
  updated slabs (donated — the steady-state step allocates nothing new).

The math mirrors ``models/transformer.py`` op for op (LayerNorm eps 1e-5,
no-bias q/k/v/o, RoPE on split heads at absolute positions, exact-match
gelu FFN, biased head) so a ``DecodeModel`` built from a Predictor's
loaded checkpoint produces the same distribution the fixed-shape serving
path scores — ``tests/test_serving_generate.py`` gates prefill logits
against ``Predictor.forward`` and decode logits against re-prefill.

Row independence is the correctness keystone: every per-position op is
row-local and ``cached_attention`` masks by the row's own length, so a
sequence's logits are bitwise identical regardless of which other
sequences share the batch — the continuous-batching invariant.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.attention import cached_attention, prefix_cached_attention, rope
from ..batcher import ServingError


@dataclasses.dataclass(frozen=True)
class DecodeSpec:
    """Architecture facts not recoverable from weight shapes."""
    num_heads: int
    num_kv_heads: int = 0  # 0 = MHA (models/transformer.py convention)
    rope_base: float = 10000.0

    @property
    def hkv(self) -> int:
        return self.num_kv_heads or self.num_heads


def _ln(x, g, b, eps=1e-5):
    """ops.attention LayerNorm math (axis -1, eps 1e-5 — the op default
    models/transformer.py binds)."""
    mu = jnp.mean(x, -1, keepdims=True)
    var = jnp.var(x, -1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * g + b


class DecodeModel:
    """Canonical stacked decoder-LM weights + derived dims.

    ``params`` (all jnp arrays): embed (V, D); stacked per-layer
    ln1_g/ln1_b/ln2_g/ln2_b (L, D), wq (L, D, D), wk/wv (L, Dkv, D),
    wo (L, D, D), w1 (L, F, D), b1 (L, F), w2 (L, D, F), b2 (L, D);
    lnf_g/lnf_b (D,), pred_w (V, D), pred_b (V,). FC weights keep the
    (out, in) orientation of ops.nn.FullyConnected.
    """

    def __init__(self, params: Dict[str, jnp.ndarray], spec: DecodeSpec):
        self.params = params
        self.spec = spec
        self.vocab, self.dm = params["embed"].shape
        self.layers = params["wq"].shape[0]
        self.dff = params["w1"].shape[1]
        if self.dm % spec.num_heads:
            raise ServingError("model_dim %d not divisible by num_heads %d"
                               % (self.dm, spec.num_heads))
        self.head_dim = self.dm // spec.num_heads
        want_dkv = self.head_dim * spec.hkv
        if params["wk"].shape[1] != want_dkv:
            raise ServingError(
                "k projection rows %d != num_kv_heads*head_dim %d — wrong "
                "num_heads/num_kv_heads for these weights?"
                % (params["wk"].shape[1], want_dkv))

    # --- construction ----------------------------------------------------
    @classmethod
    def from_arg_params(cls, arg_params: Dict, spec: DecodeSpec,
                        dtype="float32") -> "DecodeModel":
        """Build from ``models/transformer.py`` checkpoint naming (the
        dict a Predictor loads: embed_weight, layer%d_q_weight, ...).
        Accepts NDArray or numpy values."""
        def get(name):
            if name not in arg_params:
                raise ServingError(
                    "decode model: checkpoint lacks %r — is this a "
                    "models/transformer.py decoder LM?" % name)
            v = arg_params[name]
            v = v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)
            return jnp.asarray(v.astype(dtype))

        n_layers = 0
        while ("layer%d_q_weight" % n_layers) in arg_params:
            n_layers += 1
        if n_layers == 0:
            raise ServingError("decode model: no layer0_q_weight in params")
        stacked: Dict[str, list] = {k: [] for k in (
            "ln1_g", "ln1_b", "wq", "wk", "wv", "wo", "ln2_g", "ln2_b",
            "w1", "b1", "w2", "b2")}
        for i in range(n_layers):
            p = "layer%d" % i
            stacked["ln1_g"].append(get(p + "_ln1_gamma"))
            stacked["ln1_b"].append(get(p + "_ln1_beta"))
            stacked["wq"].append(get(p + "_q_weight"))
            stacked["wk"].append(get(p + "_k_weight"))
            stacked["wv"].append(get(p + "_v_weight"))
            stacked["wo"].append(get(p + "_o_weight"))
            stacked["ln2_g"].append(get(p + "_ln2_gamma"))
            stacked["ln2_b"].append(get(p + "_ln2_beta"))
            stacked["w1"].append(get(p + "_ffn1_weight"))
            stacked["b1"].append(get(p + "_ffn1_bias"))
            stacked["w2"].append(get(p + "_ffn2_weight"))
            stacked["b2"].append(get(p + "_ffn2_bias"))
        params = {k: jnp.stack(v) for k, v in stacked.items()}
        params["embed"] = get("embed_weight")
        params["lnf_g"] = get("lnf_gamma")
        params["lnf_b"] = get("lnf_beta")
        params["pred_w"] = get("pred_weight")
        params["pred_b"] = get("pred_bias")
        return cls(params, spec)

    def kv_slab_shape(self, slots: int, capacity: int) -> tuple:
        """(L, slots, Hkv, C, Dh) — one of the two per-replica slabs."""
        return (self.layers, slots, self.spec.hkv, capacity, self.head_dim)

    def fingerprint_items(self):
        """(name, array) pairs in stable order, for the progcache model
        fingerprint (weights are program ARGS here, but the fingerprint
        still keys persisted metadata like ladders)."""
        return [(k, self.params[k]) for k in sorted(self.params)]

    # --- the two programs -------------------------------------------------
    def _project(self, h, l, b, t):
        """q/k/v projections of (b, t, D) -> split-head (b, {H|Hkv}, t, Dh),
        roped later (rope needs absolute positions)."""
        p, s = self.params, self.spec
        q = h @ p["wq"][l].T
        k = h @ p["wk"][l].T
        v = h @ p["wv"][l].T
        q = q.reshape(b, t, s.num_heads, self.head_dim).transpose(0, 2, 1, 3)
        k = k.reshape(b, t, s.hkv, self.head_dim).transpose(0, 2, 1, 3)
        v = v.reshape(b, t, s.hkv, self.head_dim).transpose(0, 2, 1, 3)
        return q, k, v

    def _mlp(self, x, l):
        p = self.params
        h = _ln(x, p["ln2_g"][l], p["ln2_b"][l])
        h = jax.nn.gelu(h @ p["w1"][l].T + p["b1"][l])
        return x + (h @ p["w2"][l].T + p["b2"][l])

    def _head(self, x):
        p = self.params
        x = _ln(x, p["lnf_g"], p["lnf_b"])
        return x @ p["pred_w"].T + p["pred_b"]

    def build_prefill(self, bucket: int, capacity: int):
        """Pure fn (params, tokens (1, T=bucket) i32, length (1,) i32) ->
        (logits (1, V) f32, k (L, 1, Hkv, C, Dh), v (...)). Padded
        positions >= length produce garbage kv that decode never reads
        (masked by length); the causal mask keeps them out of the
        returned last-real-position logits."""
        if bucket > capacity:
            raise ServingError("prefill bucket %d exceeds kv capacity %d"
                               % (bucket, capacity))
        spec = self.spec

        def prefill(params, tokens, length):
            self_p = DecodeModel.__new__(DecodeModel)
            self_p.params = params
            self_p.spec = spec
            self_p.vocab, self_p.dm = params["embed"].shape
            self_p.layers = params["wq"].shape[0]
            self_p.head_dim = self_p.dm // spec.num_heads
            x = jnp.take(params["embed"], tokens.astype(jnp.int32), axis=0)
            ks, vs = [], []
            for l in range(self_p.layers):
                h = _ln(x, params["ln1_g"][l], params["ln1_b"][l])
                q, k, v = self_p._project(h, l, 1, bucket)
                q, k = rope(q, base=spec.rope_base), \
                    rope(k, base=spec.rope_base)
                # same fusion seam as the serving forward path: the flash
                # kernel owns the on-TPU/shape gate and falls back to the
                # grouped einsum / reference math off it
                from ...ops.pallas import flash_attention as _fa
                att = _fa.flash_attention(q, k, v, causal=True)
                att = att.transpose(0, 2, 1, 3).reshape(1, bucket, self_p.dm)
                x = x + att @ params["wo"][l].T
                x = self_p._mlp(x, l)
                ks.append(k)
                vs.append(v)
            logits = self_p._head(x)  # (1, T, V)
            last = jnp.take_along_axis(
                logits, (length - 1).astype(jnp.int32)[:, None, None], axis=1
            )[:, 0, :]
            pad = ((0, 0), (0, 0), (0, 0), (0, capacity - bucket), (0, 0))
            k_out = jnp.pad(jnp.stack(ks), pad)   # (L, 1, Hkv, C, Dh)
            v_out = jnp.pad(jnp.stack(vs), pad)
            return last, k_out, v_out

        return prefill

    def build_decode(self, slots: int, capacity: int):
        """Pure fn (params, k_slab, v_slab, lengths (B,) i32, tokens (B,)
        i32) -> (logits (B, V), k_slab, v_slab). Slabs are meant to be
        donated by the compiler wrapper: steady state rewrites C-slices in
        place and allocates only the (B, V) logits. Inactive slots run
        with lengths pinned to 0 — wasted lanes, never wrong lanes."""
        spec = self.spec

        def decode(params, k_slab, v_slab, lengths, tokens):
            dm = params["embed"].shape[1]
            n_layers = params["wq"].shape[0]
            head_dim = dm // spec.num_heads
            lengths = lengths.astype(jnp.int32)
            x = jnp.take(params["embed"], tokens.astype(jnp.int32), axis=0)
            # rope positions: the new token sits at index `length`
            pos = lengths.reshape(slots, 1, 1)
            for l in range(n_layers):
                h = _ln(x, params["ln1_g"][l], params["ln1_b"][l])
                q = (h @ params["wq"][l].T).reshape(
                    slots, spec.num_heads, 1, head_dim)
                k_t = (h @ params["wk"][l].T).reshape(
                    slots, spec.hkv, 1, head_dim)
                v_t = (h @ params["wv"][l].T).reshape(
                    slots, spec.hkv, 1, head_dim)
                q = rope(q, positions=pos, base=spec.rope_base)
                k_t = rope(k_t, positions=pos, base=spec.rope_base)

                def write(cache, new, p):
                    # cache (Hkv, C, Dh), new (Hkv, 1, Dh): row's k/v lands
                    # at its own position p = lengths[i]
                    return jax.lax.dynamic_update_slice(cache, new, (0, p, 0))

                k_l = jax.vmap(write)(k_slab[l], k_t, lengths)
                v_l = jax.vmap(write)(v_slab[l], v_t, lengths)
                k_slab = k_slab.at[l].set(k_l)
                v_slab = v_slab.at[l].set(v_l)
                att = cached_attention(q, k_l, v_l, lengths)
                att = att.transpose(0, 2, 1, 3).reshape(slots, dm)
                x = x + att @ params["wo"][l].T
                h2 = _ln(x, params["ln2_g"][l], params["ln2_b"][l])
                h2 = jax.nn.gelu(h2 @ params["w1"][l].T + params["b1"][l])
                x = x + (h2 @ params["w2"][l].T + params["b2"][l])
            logits = _ln(x, params["lnf_g"], params["lnf_b"]) \
                @ params["pred_w"].T + params["pred_b"]
            return logits, k_slab, v_slab

        return decode

    def paged_slab_shape(self, num_blocks: int, block_tokens: int) -> tuple:
        """(L, num_blocks, Hkv, T, Dh) — one of the two paged slabs.
        ``num_blocks`` INCLUDES physical block 0, the reserved /dev/null
        block inactive lanes and padded positions write into."""
        return (self.layers, num_blocks, self.spec.hkv, block_tokens,
                self.head_dim)

    def build_paged_prefill(self, bucket: int, block_tokens: int,
                            max_blocks: int):
        """Pure fn (params, k_slab, v_slab, table (MB,) i32, ctx_len ()
        i32, tokens (1, T=bucket) i32, n (1,) i32, fork_src () i32,
        fork_dst () i32) -> (logits (1, V), k_slab, v_slab).

        The paged admit path folds THREE things into one donated-slab
        program so the program set stays (ladder + one decode):

        1. **Copy-on-write fork**: physical block ``fork_src`` is copied
           into ``fork_dst`` first (both 0 — the trash block — when no
           fork), so a suffix that diverges inside a shared prefix block
           lands in a private copy while every other sharer keeps reading
           the original.
        2. **Chunked prefill over the cached prefix**: the first
           ``ctx_len`` positions are gathered from the slab via ``table``
           (shared prefix blocks materialize ONCE and are only read
           here); the ``n`` suffix tokens attend to that prefix plus
           causally to each other, roped at absolute positions
           ``ctx_len + j``.
        3. **Admit**: each suffix position's k/v is scattered to physical
           block ``table[(ctx_len + j) // T]`` offset ``(ctx_len + j) % T``
           (padded positions j >= n go to trash block 0).
        """
        spec = self.spec
        T = int(block_tokens)
        mb = int(max_blocks)
        cap = T * mb

        def prefill(params, k_slab, v_slab, table, ctx_len, tokens, n,
                    fork_src, fork_dst):
            self_p = DecodeModel.__new__(DecodeModel)
            self_p.params = params
            self_p.spec = spec
            self_p.vocab, self_p.dm = params["embed"].shape
            self_p.layers = params["wq"].shape[0]
            self_p.head_dim = self_p.dm // spec.num_heads
            hkv = spec.hkv
            ctx_len = ctx_len.astype(jnp.int32)
            table = table.astype(jnp.int32)
            # (1) CoW fork: materialize the divergent block privately
            # before anything reads through the table (whose boundary
            # entry already names fork_dst).
            k_slab = k_slab.at[:, fork_dst].set(k_slab[:, fork_src])
            v_slab = v_slab.at[:, fork_dst].set(v_slab[:, fork_src])
            x = jnp.take(params["embed"], tokens.astype(jnp.int32), axis=0)
            j = jnp.arange(bucket, dtype=jnp.int32)
            pos = ctx_len + j                       # absolute positions
            # suffix k/v land at table[pos // T] : pos % T; padded lanes
            # (j >= n) land in trash block 0 (never read unmasked)
            phys = jnp.where(j < n[0],
                             table[jnp.clip(pos // T, 0, mb - 1)], 0)
            off = pos % T
            for l in range(self_p.layers):
                h = _ln(x, params["ln1_g"][l], params["ln1_b"][l])
                q, k, v = self_p._project(h, l, 1, bucket)
                q = rope(q, positions=pos, base=spec.rope_base)
                k = rope(k, positions=pos, base=spec.rope_base)
                # (2) gather the cached prefix through the block table
                k_ctx = k_slab[l][table].transpose(1, 0, 2, 3) \
                    .reshape(1, hkv, cap, self_p.head_dim)
                v_ctx = v_slab[l][table].transpose(1, 0, 2, 3) \
                    .reshape(1, hkv, cap, self_p.head_dim)
                att = prefix_cached_attention(q, k_ctx, v_ctx, ctx_len,
                                              k, v)
                att = att.transpose(0, 2, 1, 3).reshape(1, bucket,
                                                        self_p.dm)
                x = x + att @ params["wo"][l].T
                x = self_p._mlp(x, l)
                # (3) admit: scatter this layer's suffix k/v into place
                k_slab = k_slab.at[l, phys, :, off, :].set(
                    k[0].transpose(1, 0, 2))
                v_slab = v_slab.at[l, phys, :, off, :].set(
                    v[0].transpose(1, 0, 2))
            logits = self_p._head(x)  # (1, T, V)
            last = jnp.take_along_axis(
                logits, (n - 1).astype(jnp.int32)[:, None, None], axis=1
            )[:, 0, :]
            return last, k_slab, v_slab

        return prefill

    def build_paged_decode(self, slots: int, block_tokens: int,
                           max_blocks: int):
        """Pure fn (params, k_slab, v_slab, tables (B, MB) i32, lengths
        (B,) i32, tokens (B,) i32) -> (logits (B, V), k_slab, v_slab).

        The paged twin of ``build_decode``: each row's new k/v is
        scattered to physical block ``tables[i, lengths[i] // T]`` offset
        ``lengths[i] % T`` (the scheduler guarantees that block is
        PRIVATE to row i — copy-on-write resolves sharing before any
        write is scheduled), then attention gathers the row's dense
        (Hkv, C, Dh) view through its table and masks by length exactly
        like the unpaged step. Inactive lanes carry an all-zero table, so
        their writes land in trash block 0 — wasted lanes, never wrong
        lanes, same fixed-shape discipline as the unpaged program.
        """
        spec = self.spec
        T = int(block_tokens)
        mb = int(max_blocks)
        cap = T * mb

        def decode(params, k_slab, v_slab, tables, lengths, tokens):
            dm = params["embed"].shape[1]
            n_layers = params["wq"].shape[0]
            head_dim = dm // spec.num_heads
            hkv = spec.hkv
            lengths = lengths.astype(jnp.int32)
            tables = tables.astype(jnp.int32)
            x = jnp.take(params["embed"], tokens.astype(jnp.int32), axis=0)
            pos = lengths.reshape(slots, 1, 1)
            # write site per row: its own (always-private) block
            phys_w = jnp.take_along_axis(
                tables, jnp.clip(lengths // T, 0, mb - 1)[:, None],
                axis=1)[:, 0]
            off_w = lengths % T
            for l in range(n_layers):
                h = _ln(x, params["ln1_g"][l], params["ln1_b"][l])
                q = (h @ params["wq"][l].T).reshape(
                    slots, spec.num_heads, 1, head_dim)
                k_t = (h @ params["wk"][l].T).reshape(
                    slots, hkv, 1, head_dim)
                v_t = (h @ params["wv"][l].T).reshape(
                    slots, hkv, 1, head_dim)
                q = rope(q, positions=pos, base=spec.rope_base)
                k_t = rope(k_t, positions=pos, base=spec.rope_base)
                k_slab = k_slab.at[l, phys_w, :, off_w, :].set(
                    k_t[:, :, 0, :])
                v_slab = v_slab.at[l, phys_w, :, off_w, :].set(
                    v_t[:, :, 0, :])
                # gather each row's dense view (write first, so the new
                # token's k/v is visible to its own attention)
                k_l = k_slab[l][tables].transpose(0, 2, 1, 3, 4) \
                    .reshape(slots, hkv, cap, head_dim)
                v_l = v_slab[l][tables].transpose(0, 2, 1, 3, 4) \
                    .reshape(slots, hkv, cap, head_dim)
                att = cached_attention(q, k_l, v_l, lengths)
                att = att.transpose(0, 2, 1, 3).reshape(slots, dm)
                x = x + att @ params["wo"][l].T
                h2 = _ln(x, params["ln2_g"][l], params["ln2_b"][l])
                h2 = jax.nn.gelu(h2 @ params["w1"][l].T + params["b1"][l])
                x = x + (h2 @ params["w2"][l].T + params["b2"][l])
            logits = _ln(x, params["lnf_g"], params["lnf_b"]) \
                @ params["pred_w"].T + params["pred_b"]
            return logits, k_slab, v_slab

        return decode

    def build_admit(self, slots: int, capacity: int):
        """Pure fn (k_slab, v_slab, k_new (L,1,Hkv,C,Dh), v_new, slot i32)
        -> updated slabs (donated): slot a freshly prefilled sequence's kv
        into its allocated row."""
        def admit(k_slab, v_slab, k_new, v_new, slot):
            slot = slot.astype(jnp.int32)
            z = jnp.int32(0)
            return (jax.lax.dynamic_update_slice(k_slab, k_new,
                                                 (z, slot, z, z, z)),
                    jax.lax.dynamic_update_slice(v_slab, v_new,
                                                 (z, slot, z, z, z)))

        return admit


def infer_spec_dims(arg_params: Dict) -> Dict[str, int]:
    """Dims recoverable from a models/transformer.py checkpoint (vocab,
    model_dim, ffn_dim, layers) — head counts must come from DecodeSpec."""
    embed = arg_params["embed_weight"]
    shape = embed.shape
    n_layers = 0
    while ("layer%d_q_weight" % n_layers) in arg_params:
        n_layers += 1
    ffn1 = arg_params["layer0_ffn1_weight"]
    return {"vocab": int(shape[0]), "model_dim": int(shape[1]),
            "layers": n_layers, "ffn_dim": int(ffn1.shape[0])}
